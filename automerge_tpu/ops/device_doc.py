"""DeviceDoc: read API over a kernel-resolved op log.

The batched alternative to the host OpStore for N-way merges: build an
OpLog from many replicas' changes, run ops/merge.py once on device, then
answer reads from the resolved columns. Mirrors the reference ReadDoc
surface (reference: rust/automerge/src/read.rs:32-236) including the
historical ``*_at`` variants: ``at(heads)`` re-resolves visibility under a
clock mask (vectorized ``Clock::covers``, reference: clock.rs:71-77) while
sharing the log and the RGA element order with the current-state view —
element order depends only on the insert forest, never on the clock.

Also a patch source: ``diff(before_heads, after_heads)`` emits the same
path-qualified patches as the host differ (patches/diff.py) straight from
two clock-masked kernel resolutions, so the device merge can feed
materialized views / ``apply_patches`` without a host re-apply
(reference: rust/automerge/src/automerge/diff.rs log_diff).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.marks import Mark
from ..patches.patch import (
    DeleteMap,
    DeleteSeq,
    FlagConflict,
    IncrementPatch,
    Insert,
    Patch,
    PutMap,
    PutSeq,
    SpliceText,
)
from ..types import ObjType, is_make_action, objtype_for_action
from .merge import merge_columns
from .oplog import MAKE_ACTIONS, ACTOR_BITS, OpLog, TAG_COUNTER

_MAKE_OBJ = {0: ObjType.MAP, 2: ObjType.LIST, 4: ObjType.TEXT, 6: ObjType.TABLE}


def order_elem_rows(log: "OpLog", elem_index: np.ndarray,
                    obj_rows: np.ndarray) -> np.ndarray:
    """Element rows of one sequence object in DOCUMENT order: the insert
    rows the linearization ranked, sorted by their rank. The single
    definition of the element-order rule shared by DeviceDoc reads and
    the stale-store read path (core/bulk_load.stale_text)."""
    obj_rows = np.asarray(obj_rows, np.int64)
    erows = obj_rows[
        np.asarray(log.insert)[obj_rows] & (elem_index[obj_rows] >= 0)
    ]
    return erows[np.argsort(elem_index[erows], kind="stable")]
_OBJ_REPLACEMENT = "￼"
_PUT = 1
_INCREMENT = 5
_MARK = 7


class DeviceDoc:
    def __init__(
        self,
        log: OpLog,
        res: Dict[str, np.ndarray],
        covered: Optional[np.ndarray] = None,
        base: Optional["DeviceDoc"] = None,
    ):
        self.log = log
        self.res = res
        n = log.n
        self._base = base if base is not None else self
        self.covered = (
            covered if covered is not None else np.ones(n, np.bool_)
        )
        self.visible = res["visible"][:n]
        self.winner = res["winner"][:n]
        self.conflicts = res["conflicts"][:n]
        if base is None:
            self.elem_index = res["elem_index"][:n]
            self._views: Dict[tuple, "DeviceDoc"] = {}
            self._hash_index = {ch.hash: ch for ch in log.changes}
            self._rank_of = {a.bytes: i for i, a in enumerate(log.actors)}
            # object id -> object type, from make ops (+ root)
            self._obj_type: Dict[int, ObjType] = {0: ObjType.MAP}
            for r in np.flatnonzero(np.isin(log.action[:n], MAKE_ACTIONS)):
                self._obj_type[int(log.id_key[r])] = _MAKE_OBJ[int(log.action[r])]
            # row ranges by object
            order = np.argsort(log.obj_key[:n], kind="stable")
            self._rows_by_obj = order.astype(np.int64)
            self._obj_sorted = log.obj_key[:n][order]
            self._all_elems_cache: Dict[int, List[int]] = {}
        else:
            self.elem_index = base.elem_index
            self._obj_type = base._obj_type
            self._rows_by_obj = base._rows_by_obj
            self._obj_sorted = base._obj_sorted
        # exact int64 counter totals, host-side, gated by this view's clock
        # (the device kernel keeps the int32 fast path; reference counters
        # are i64, value.rs:369)
        self.counter_val = log.value_int.copy()
        if len(log.pred_src):
            mask = (
                (log.action[log.pred_src] == _INCREMENT)
                & (log.pred_tgt >= 0)
                & self.covered[log.pred_src]
            )
            np.add.at(
                self.counter_val,
                log.pred_tgt[mask],
                log.value_int[log.pred_src[mask]],
            )

    # -- construction -------------------------------------------------------

    @classmethod
    def merge(cls, docs: Sequence) -> "DeviceDoc":
        """N-way fan-in merge of documents (AutoDoc or Document)."""
        return cls.resolve(OpLog.from_documents(docs))

    # the outputs the read API consumes; everything else stays on device
    READ_FETCH = (
        "visible", "winner", "conflicts", "elem_index",
        "obj_vis_len", "obj_text_width",
    )
    # historical views reuse the base view's element order
    VIEW_FETCH = (
        "visible", "winner", "conflicts", "obj_vis_len", "obj_text_width",
    )

    @classmethod
    def resolve(cls, log: OpLog) -> "DeviceDoc":
        return cls(
            log,
            merge_columns(
                log.columns(), fetch=cls.READ_FETCH, n_objs=log.n_objs,
                n_props=len(log.props),
            ),
        )

    # -- historical views ---------------------------------------------------

    def current_heads(self) -> List[bytes]:
        """Change hashes no other change in the log depends on."""
        base = self._base
        deps = {d for ch in base.log.changes for d in ch.dependencies}
        return sorted(h for h in base._hash_index if h not in deps)

    def _clock_vec(self, heads: Sequence[bytes]) -> np.ndarray:
        """Dense per-actor-rank max-op vector for the clock at ``heads``
        (the ancestor traversal of change_graph.rs:128-142, host-side)."""
        base = self._base
        vec = np.zeros(len(base.log.actors), np.int64)
        stack = list(heads)
        seen = set()
        while stack:
            h = stack.pop()
            if h in seen:
                continue
            seen.add(h)
            ch = base._hash_index.get(h)
            if ch is None:
                raise KeyError(f"unknown head {h.hex()}")
            rank = base._rank_of[bytes(ch.actor)]
            if ch.max_op > vec[rank]:
                vec[rank] = ch.max_op
            stack.extend(ch.dependencies)
        return vec

    def at(self, heads: Optional[Sequence[bytes]]) -> "DeviceDoc":
        """The document as of ``heads``: same log, same element order,
        visibility re-resolved under the clock mask (one kernel run,
        cached per heads set)."""
        base = self._base
        if heads is None:
            return base
        key = tuple(sorted(heads))
        view = base._views.get(key)
        if view is None:
            covered = base.log.covered_mask(base._clock_vec(heads))
            res = merge_columns(
                base.log.padded_columns(covered=covered),
                fetch=self.VIEW_FETCH,
                n_objs=base.log.n_objs,
                n_props=len(base.log.props),
            )
            view = DeviceDoc(base.log, res, covered=covered, base=base)
            base._views[key] = view
        return view

    def _view(self, heads) -> "DeviceDoc":
        return self if heads is None else self.at(heads)

    # -- row selection ------------------------------------------------------

    def _obj_rows(self, obj_key: int) -> np.ndarray:
        lo = np.searchsorted(self._obj_sorted, obj_key, side="left")
        hi = np.searchsorted(self._obj_sorted, obj_key, side="right")
        return self._rows_by_obj[lo:hi]

    def _check_obj(self, obj_key: int) -> ObjType:
        t = self._obj_type.get(obj_key)
        if t is None:
            raise KeyError(f"no such object {self.log.export_id(obj_key)}")
        return t

    def _all_elems(self, obj_key: int) -> List[int]:
        """ALL element rows of a sequence in document order — including
        invisible and mark elements (the host ``SeqObject.elements()``
        walk; order is clock-independent so this lives on the base)."""
        base = self._base
        cached = base._all_elems_cache.get(obj_key)
        if cached is None:
            cached = order_elem_rows(
                base.log, base.elem_index, base._obj_rows(obj_key)
            ).tolist()
            base._all_elems_cache[obj_key] = cached
        return cached

    # -- value rendering ----------------------------------------------------

    def _render(self, row: int):
        a = int(self.log.action[row])
        if is_make_action(a):
            return (
                "obj",
                objtype_for_action(a),
                self.log.export_id(int(self.log.id_key[row])),
            )
        if a == _PUT and int(self.log.value_tag[row]) == TAG_COUNTER:
            return ("counter", int(self.counter_val[row]))
        return ("scalar", self.log.values[row])

    # -- reads (mirror core/document.py) ------------------------------------

    def object_type(self, obj: str) -> ObjType:
        return self._check_obj(self.log.import_id(obj))

    def keys(self, obj: str = "_root", heads=None) -> List[str]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        rows = view._obj_rows(ok)
        props = {
            int(view.log.prop[r])
            for r in rows
            if view.log.prop[r] >= 0 and view.winner[r] >= 0
        }
        return sorted(view.log.props[p] for p in props)

    def map_entries(self, obj: str = "_root", heads=None) -> List[Tuple[str, object, str]]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        best: Dict[int, int] = {}
        for r in view._obj_rows(ok):
            p = int(view.log.prop[r])
            if p >= 0 and view.winner[r] >= 0:
                best[p] = int(view.winner[r])
        out = [
            (
                view.log.props[p],
                view._render(w),
                view.log.export_id(int(view.log.id_key[w])),
            )
            for p, w in best.items()
        ]
        out.sort(key=lambda kv: kv[0])
        return out

    def _seq_elems(self, obj_key: int) -> List[Tuple[int, int]]:
        """Visible elements of a sequence: [(elem_row, winner_row)] in order."""
        return [
            (r, int(self.winner[r]))
            for r in self._all_elems(obj_key)
            if self.winner[r] >= 0
        ]

    def list_items(self, obj: str, heads=None) -> List[Tuple[object, str]]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        return [
            (view._render(w), view.log.export_id(int(view.log.id_key[w])))
            for _, w in view._seq_elems(ok)
        ]

    def text(self, obj: str, heads=None) -> str:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        parts = []
        for _, w in view._seq_elems(ok):
            v = view.log.values[w]
            parts.append(v.value if v.tag == "str" else _OBJ_REPLACEMENT)
        return "".join(parts)

    def length(self, obj: str = "_root", heads=None) -> int:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            return len(view.keys(obj))
        dense = int(np.searchsorted(view.log.obj_table, ok))
        if t == ObjType.TEXT:
            return int(view.res["obj_text_width"][dense])
        return int(view.res["obj_vis_len"][dense])

    def get_all(self, obj: str, prop, heads=None) -> List[Tuple[object, str]]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        rows = view._obj_rows(ok)
        if isinstance(prop, str):
            if t not in (ObjType.MAP, ObjType.TABLE):
                raise ValueError("map lookup requires a map object")
            try:
                p = view.log.props.index(prop)
            except ValueError:
                return []
            vis = [int(r) for r in rows if int(view.log.prop[r]) == p and view.visible[r]]
        else:
            elems = view._seq_elems(ok)
            if prop < 0:
                return []
            if t == ObjType.TEXT:
                # integer index is a character position: accumulate winner
                # widths, matching the host nth's width-aware semantics
                er = None
                at = 0
                for r, w in elems:
                    at += int(view.log.width[w])
                    if prop < at:
                        er = r
                        break
                if er is None:
                    return []
            else:
                if not 0 <= prop < len(elems):
                    return []
                er = elems[prop][0]
            vis = [
                int(r)
                for r in rows
                if view.visible[r]
                and (
                    (view.log.insert[r] and int(r) == er)
                    or (not view.log.insert[r] and int(view.log.elem_ref[r]) == er)
                )
            ]
        vis.sort()  # rows are in Lamport order; winner last
        return [
            (view._render(r), view.log.export_id(int(view.log.id_key[r])))
            for r in vis
        ]

    def get(self, obj: str, prop, heads=None):
        vals = self.get_all(obj, prop, heads)
        return vals[-1] if vals else None

    def map_range(self, obj: str = "_root", start=None, end=None, heads=None):
        """(key, value, id) for map keys in [start, end) (read.rs map_range)."""
        from ..utils.ranges import filter_map_range

        return filter_map_range(self.map_entries(obj, heads=heads), start, end)

    def list_range(self, obj: str, start: int = 0, end=None, heads=None):
        """(index, value, id) for indices in [start, end) (read.rs list_range).
        Renders only the requested rows of the materialized element order."""
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        elems = view._seq_elems(ok)
        stop = len(elems) if end is None else min(end, len(elems))
        return [
            (
                i,
                view._render(elems[i][1]),
                view.log.export_id(int(view.log.id_key[elems[i][1]])),
            )
            for i in range(max(start, 0), stop)
        ]

    def values(self, obj: str = "_root", heads=None):
        """Winner (value, id) pairs (read.rs values)."""
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            return [(val, vid) for _, val, vid in view.map_entries(obj)]
        return view.list_items(obj)

    def parents(self, obj: str, heads=None) -> List[Tuple[str, object]]:
        """Path from ``obj`` up to the root (read.rs parents/parents_at):
        walks the make ops' containing objects through the log columns,
        resolving sequence indices at the given heads."""
        view = self._view(heads)
        log = view.log
        key = log.import_id(obj)
        view._check_obj(key)
        path: List[Tuple[str, object]] = []
        while key != 0:
            row = log.row_of_id(key)
            parent_key = int(log.obj_key[row])
            parent_exid = log.export_id(parent_key)
            p = int(log.prop[row])
            if p >= 0:
                path.append((parent_exid, log.props[p]))
            else:
                # element ordinal among VISIBLE elements (1 each, matching
                # Document._elem_index); None when the element is invisible
                base = view._base
                er = row if log.insert[row] else int(log.elem_ref[row])
                view._check_obj(parent_key)
                idx = 0
                found = None
                for r in base._all_elems(parent_key):
                    visible = int(view.winner[r]) >= 0
                    if r == er:
                        found = idx if visible else None
                        break
                    if visible:
                        idx += 1
                path.append((parent_exid, found))
            key = parent_key
        return path

    # -- cursors (reference: cursor.rs, automerge.rs seek_opid) -------------

    def get_cursor(self, obj: str, position: int, heads=None) -> str:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            raise ValueError("cursors only apply to sequences")
        at = 0
        for r, w in view._seq_elems(ok):
            at += int(view.log.width[w]) if t == ObjType.TEXT else 1
            if position < at:
                return view.log.export_id(int(view.log.id_key[r]))
        raise ValueError(f"cursor position {position} out of bounds")

    def get_cursor_position(self, obj: str, cursor: str, heads=None) -> int:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            raise ValueError("cursors only apply to sequences")
        target = view.log.import_id(cursor)
        index = 0
        for r in view._all_elems(ok):
            if int(view.log.id_key[r]) == target:
                return index
            w = int(view.winner[r])
            if w >= 0:
                index += int(view.log.width[w]) if t == ObjType.TEXT else 1
        raise ValueError(f"cursor {cursor!r} not found in {obj!r}")

    # -- marks (reference: marks.rs MarkStateMachine, automerge.rs:1370) ----

    def marks(self, obj: str, heads=None) -> List[Mark]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            raise ValueError("marks on a non-sequence object")
        log = view.log
        is_text = t == ObjType.TEXT
        open_marks: List[Tuple[int, str, object]] = []  # (begin id_key, name, value)
        index = 0
        spans: Dict[str, List[Mark]] = {}
        for r in view._all_elems(ok):
            if int(log.action[r]) == _MARK:
                # mark begin/end ops are covered-or-absent, never "visible"
                # (core/marks.py visible_or_mark)
                if not view.covered[r]:
                    continue
                mi = int(log.mark_name_idx[r])
                if mi >= 0:  # begin
                    open_marks.append(
                        (int(log.id_key[r]), log.mark_names[mi], log.values[r].to_py())
                    )
                    # packed id order == lamport order (rank = actor byte rank)
                    open_marks.sort()
                else:  # end: pairs with begin id (ctr-1, same actor)
                    begin = int(log.id_key[r]) - (1 << ACTOR_BITS)
                    open_marks = [e for e in open_marks if e[0] != begin]
                continue
            w = int(view.winner[r])
            if w < 0:
                continue
            width = int(log.width[w]) if is_text else 1
            current: Dict[str, object] = {}
            for _, name, value in open_marks:  # lamport-ascending: last wins
                current[name] = value
            for name, value in current.items():
                runs = spans.setdefault(name, [])
                if runs and runs[-1].end == index and runs[-1].value == value:
                    runs[-1].end = index + width
                else:
                    runs.append(Mark(index, index + width, name, value))
            index += width
        out = [
            m
            for runs in spans.values()
            for m in runs
            if m.value is not None  # null-valued spans are unmarks
        ]
        out.sort(key=lambda m: (m.start, m.name))
        return out

    # -- diff / patches -----------------------------------------------------

    def diff(self, before_heads, after_heads=None) -> List[Patch]:
        """Patches turning the state at ``before_heads`` into the state at
        ``after_heads`` (None = current). Same shape and ordering as the
        host differ; computed from two clock-masked kernel resolutions."""
        vb = self.at(before_heads if before_heads is not None else [])
        va = self._view(after_heads)
        patches: List[Patch] = []
        _diff_obj(vb, va, 0, [], patches)
        return patches

    def make_patches(self) -> List[Patch]:
        """Patches materializing the whole current state (applying them to
        an empty dict reproduces ``hydrate()`` — the current_state analogue,
        reference: automerge/current_state.rs)."""
        return self.diff([])

    # -- materialization ----------------------------------------------------

    def hydrate(self, obj: str = "_root", heads=None):
        view = self._view(heads)
        return view._hydrate(view.log.import_id(obj))

    def _hydrate(self, obj_key: int):
        t = self._check_obj(obj_key)
        if t in (ObjType.MAP, ObjType.TABLE):
            return {
                name: self._hydrate_val(val)
                for name, val, _ in self.map_entries(self.log.export_id(obj_key))
            }
        if t == ObjType.TEXT:
            return self.text(self.log.export_id(obj_key))
        return [
            self._hydrate_val(self._render(w)) for _, w in self._seq_elems(obj_key)
        ]

    def _hydrate_val(self, rendered):
        kind = rendered[0]
        if kind == "obj":
            return self._hydrate(self.log.import_id(rendered[2]))
        if kind == "counter":
            return rendered[1]
        return rendered[1].to_py()


# -- the device differ (mirrors patches/diff.py walk) ------------------------


def _patch_value(view: DeviceDoc, row: int):
    """Patch value of a winning op: hydrated subtree / counter / scalar."""
    a = int(view.log.action[row])
    if is_make_action(a):
        return view._hydrate(int(view.log.id_key[row]))
    if a == _PUT and int(view.log.value_tag[row]) == TAG_COUNTER:
        return int(view.counter_val[row])
    return view.log.values[row].to_py()


def _is_counter_row(log: OpLog, row: int) -> bool:
    return int(log.action[row]) == _PUT and int(log.value_tag[row]) == TAG_COUNTER


def _diff_obj(vb, va, obj_key, path, patches):
    t = va._check_obj(obj_key)
    exid = va.log.export_id(obj_key)
    if t in (ObjType.MAP, ObjType.TABLE):
        _diff_map(vb, va, obj_key, exid, path, patches)
    elif t == ObjType.TEXT:
        _diff_text(vb, va, obj_key, exid, path, patches)
    else:
        _diff_list(vb, va, obj_key, exid, path, patches)


def _diff_map(vb, va, obj_key, exid, path, patches):
    log = va.log
    groups: Dict[int, int] = {}  # prop -> representative row
    for r in va._obj_rows(obj_key):
        p = int(log.prop[r])
        if p >= 0 and p not in groups:
            groups[p] = int(r)
    for p in sorted(groups, key=lambda p: log.props[p]):
        rep = groups[p]
        key = log.props[p]
        wb = int(vb.winner[rep])
        wa = int(va.winner[rep])
        if wa < 0:
            if wb >= 0:
                patches.append(Patch(exid, list(path), DeleteMap(key)))
            continue
        conflict = int(va.conflicts[rep]) > 1
        if wb < 0 or wb != wa:
            patches.append(
                Patch(exid, list(path), PutMap(key, _patch_value(va, wa), conflict))
            )
        elif _is_counter_row(log, wa):
            delta = int(va.counter_val[wa]) - int(vb.counter_val[wa])
            if delta:
                patches.append(Patch(exid, list(path), IncrementPatch(key, delta)))
        elif conflict and int(vb.conflicts[rep]) <= 1:
            patches.append(Patch(exid, list(path), FlagConflict(key)))
        if is_make_action(int(log.action[wa])) and wb == wa:
            _diff_obj(
                vb, va, int(log.id_key[wa]), path + [(exid, key)], patches
            )


def _diff_list(vb, va, obj_key, exid, path, patches):
    log = va.log
    idx = 0
    pending_ins = None  # (index, [values])
    for r in va._all_elems(obj_key):
        wb = int(vb.winner[r])
        wa = int(va.winner[r])
        if wa < 0 and wb < 0:
            continue
        if wa >= 0 and wb < 0:
            if pending_ins is None:
                pending_ins = (idx, [])
            pending_ins[1].append(_patch_value(va, wa))
            idx += 1
            continue
        if pending_ins is not None:
            patches.append(Patch(exid, list(path), Insert(*pending_ins)))
            pending_ins = None
        if wa < 0:
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += 1
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx)))
            continue
        conflict = int(va.conflicts[r]) > 1
        if wb != wa:
            patches.append(
                Patch(exid, list(path), PutSeq(idx, _patch_value(va, wa), conflict))
            )
        elif _is_counter_row(log, wa):
            delta = int(va.counter_val[wa]) - int(vb.counter_val[wa])
            if delta:
                patches.append(Patch(exid, list(path), IncrementPatch(idx, delta)))
        elif conflict and int(vb.conflicts[r]) <= 1:
            patches.append(Patch(exid, list(path), FlagConflict(idx)))
        if is_make_action(int(log.action[wa])) and wb == wa:
            _diff_obj(vb, va, int(log.id_key[wa]), path + [(exid, idx)], patches)
        idx += 1
    if pending_ins is not None:
        patches.append(Patch(exid, list(path), Insert(*pending_ins)))


def _diff_text(vb, va, obj_key, exid, path, patches):
    log = va.log
    idx = 0
    pending = None  # [index, str] for inserts
    for r in va._all_elems(obj_key):
        wb = int(vb.winner[r])
        wa = int(va.winner[r])
        if wa < 0 and wb < 0:
            continue
        sa = _char(log, wa) if wa >= 0 else None
        sb = _char(log, wb) if wb >= 0 else None
        if wa >= 0 and wb < 0:
            if pending is None:
                pending = [idx, ""]
            pending[1] += sa
            idx += len(sa)
            continue
        if pending is not None:
            patches.append(Patch(exid, list(path), SpliceText(pending[0], pending[1])))
            pending = None
        if wa < 0:
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += len(sb)
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            continue
        if wb != wa and (sa != sb):
            patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            patches.append(Patch(exid, list(path), SpliceText(idx, sa)))
        idx += len(sa)
    if pending is not None:
        patches.append(Patch(exid, list(path), SpliceText(pending[0], pending[1])))


def _char(log: OpLog, row: int) -> str:
    v = log.values[row]
    return v.value if v.tag == "str" else _OBJ_REPLACEMENT
