"""DeviceDoc: read API over a kernel-resolved op log.

The batched alternative to the host OpStore for N-way merges: build an
OpLog from many replicas' changes, run ops/merge.py once on device, then
answer reads (text/get/keys/length/hydrate) from the resolved columns.
Mirrors the reference ReadDoc surface (reference: rust/automerge/src/
read.rs:32-236) for the current-state case; historical ``*_at`` reads stay
on the host document, which shares the same change history.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..types import ObjType, is_make_action, objtype_for_action
from .merge import merge_columns
from .oplog import OpLog, TAG_COUNTER

_MAKE_OBJ = {0: ObjType.MAP, 2: ObjType.LIST, 4: ObjType.TEXT, 6: ObjType.TABLE}
_OBJ_REPLACEMENT = "￼"
_INCREMENT = 5


class DeviceDoc:
    def __init__(self, log: OpLog, res: Dict[str, np.ndarray]):
        self.log = log
        self.res = res
        n = log.n
        self.visible = res["visible"][:n]
        self.winner = res["winner"][:n]
        self.conflicts = res["conflicts"][:n]
        self.elem_index = res["elem_index"][:n]
        # exact int64 counter totals, host-side (the device kernel keeps the
        # int32 fast path; reference counters are i64, value.rs:369)
        self.counter_val = log.value_int.copy()
        if len(log.pred_src):
            mask = (log.action[log.pred_src] == _INCREMENT) & (log.pred_tgt >= 0)
            np.add.at(
                self.counter_val,
                log.pred_tgt[mask],
                log.value_int[log.pred_src[mask]],
            )
        # object id -> object type, from make ops (+ root)
        self._obj_type: Dict[int, ObjType] = {0: ObjType.MAP}
        for r in np.flatnonzero(np.isin(log.action[:n], (0, 2, 4, 6))):
            self._obj_type[int(log.id_key[r])] = _MAKE_OBJ[int(log.action[r])]
        # row ranges by object
        order = np.argsort(log.obj_key[:n], kind="stable")
        self._rows_by_obj = order.astype(np.int64)
        self._obj_sorted = log.obj_key[:n][order]

    # -- construction -------------------------------------------------------

    @classmethod
    def merge(cls, docs: Sequence) -> "DeviceDoc":
        """N-way fan-in merge of documents (AutoDoc or Document)."""
        return cls.resolve(OpLog.from_documents(docs))

    # the outputs the read API consumes; everything else stays on device
    READ_FETCH = (
        "visible", "winner", "conflicts", "elem_index",
        "obj_vis_len", "obj_text_width",
    )

    @classmethod
    def resolve(cls, log: OpLog) -> "DeviceDoc":
        return cls(
            log,
            merge_columns(
                log.padded_columns(), fetch=cls.READ_FETCH, n_objs=log.n_objs
            ),
        )

    # -- row selection ------------------------------------------------------

    def _obj_rows(self, obj_key: int) -> np.ndarray:
        lo = np.searchsorted(self._obj_sorted, obj_key, side="left")
        hi = np.searchsorted(self._obj_sorted, obj_key, side="right")
        return self._rows_by_obj[lo:hi]

    def _check_obj(self, obj_key: int) -> ObjType:
        t = self._obj_type.get(obj_key)
        if t is None:
            raise KeyError(f"no such object {self.log.export_id(obj_key)}")
        return t

    # -- value rendering ----------------------------------------------------

    def _render(self, row: int):
        a = int(self.log.action[row])
        if is_make_action(a):
            return (
                "obj",
                objtype_for_action(a),
                self.log.export_id(int(self.log.id_key[row])),
            )
        if a == 1 and int(self.log.value_tag[row]) == TAG_COUNTER:
            return ("counter", int(self.counter_val[row]))
        return ("scalar", self.log.values[row])

    # -- reads (mirror core/document.py) ------------------------------------

    def object_type(self, obj: str) -> ObjType:
        return self._check_obj(self.log.import_id(obj))

    def keys(self, obj: str = "_root") -> List[str]:
        ok = self.log.import_id(obj)
        self._check_obj(ok)
        rows = self._obj_rows(ok)
        props = {
            int(self.log.prop[r])
            for r in rows
            if self.log.prop[r] >= 0 and self.winner[r] >= 0
        }
        return sorted(self.log.props[p] for p in props)

    def map_entries(self, obj: str = "_root") -> List[Tuple[str, object, str]]:
        ok = self.log.import_id(obj)
        self._check_obj(ok)
        best: Dict[int, int] = {}
        for r in self._obj_rows(ok):
            p = int(self.log.prop[r])
            if p >= 0 and self.winner[r] >= 0:
                best[p] = int(self.winner[r])
        out = [
            (
                self.log.props[p],
                self._render(w),
                self.log.export_id(int(self.log.id_key[w])),
            )
            for p, w in best.items()
        ]
        out.sort(key=lambda kv: kv[0])
        return out

    def _seq_elems(self, obj_key: int) -> List[Tuple[int, int]]:
        """Visible elements of a sequence: [(elem_row, winner_row)] in order."""
        elems = [
            (int(self.elem_index[r]), int(r), int(self.winner[r]))
            for r in self._obj_rows(obj_key)
            if self.log.insert[r] and self.winner[r] >= 0 and self.elem_index[r] >= 0
        ]
        elems.sort()
        return [(r, w) for _, r, w in elems]

    def list_items(self, obj: str) -> List[Tuple[object, str]]:
        ok = self.log.import_id(obj)
        self._check_obj(ok)
        return [
            (self._render(w), self.log.export_id(int(self.log.id_key[w])))
            for _, w in self._seq_elems(ok)
        ]

    def text(self, obj: str) -> str:
        ok = self.log.import_id(obj)
        self._check_obj(ok)
        parts = []
        for _, w in self._seq_elems(ok):
            v = self.log.values[w]
            parts.append(v.value if v.tag == "str" else _OBJ_REPLACEMENT)
        return "".join(parts)

    def length(self, obj: str = "_root") -> int:
        ok = self.log.import_id(obj)
        t = self._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            return len(self.keys(obj))
        dense = int(np.searchsorted(self.log.obj_table, ok))
        if t == ObjType.TEXT:
            return int(self.res["obj_text_width"][dense])
        return int(self.res["obj_vis_len"][dense])

    def get_all(self, obj: str, prop) -> List[Tuple[object, str]]:
        ok = self.log.import_id(obj)
        t = self._check_obj(ok)
        rows = self._obj_rows(ok)
        if isinstance(prop, str):
            if t not in (ObjType.MAP, ObjType.TABLE):
                raise ValueError("map lookup requires a map object")
            try:
                p = self.log.props.index(prop)
            except ValueError:
                return []
            vis = [int(r) for r in rows if int(self.log.prop[r]) == p and self.visible[r]]
        else:
            elems = self._seq_elems(ok)
            if prop < 0:
                return []
            if t == ObjType.TEXT:
                # integer index is a character position: accumulate winner
                # widths, matching the host nth's width-aware semantics
                er = None
                at = 0
                for r, w in elems:
                    at += int(self.log.width[w])
                    if prop < at:
                        er = r
                        break
                if er is None:
                    return []
            else:
                if not 0 <= prop < len(elems):
                    return []
                er = elems[prop][0]
            vis = [
                int(r)
                for r in rows
                if self.visible[r]
                and (
                    (self.log.insert[r] and int(r) == er)
                    or (not self.log.insert[r] and int(self.log.elem_ref[r]) == er)
                )
            ]
        vis.sort()  # rows are in Lamport order; winner last
        return [
            (self._render(r), self.log.export_id(int(self.log.id_key[r])))
            for r in vis
        ]

    def get(self, obj: str, prop):
        vals = self.get_all(obj, prop)
        return vals[-1] if vals else None

    # -- materialization ----------------------------------------------------

    def hydrate(self, obj: str = "_root"):
        return self._hydrate(self.log.import_id(obj))

    def _hydrate(self, obj_key: int):
        t = self._check_obj(obj_key)
        if t in (ObjType.MAP, ObjType.TABLE):
            return {
                name: self._hydrate_val(val)
                for name, val, _ in self.map_entries(self.log.export_id(obj_key))
            }
        if t == ObjType.TEXT:
            return self.text(self.log.export_id(obj_key))
        return [
            self._hydrate_val(self._render(w)) for _, w in self._seq_elems(obj_key)
        ]

    def _hydrate_val(self, rendered):
        kind = rendered[0]
        if kind == "obj":
            return self._hydrate(self.log.import_id(rendered[2]))
        if kind == "counter":
            return rendered[1]
        return rendered[1].to_py()
