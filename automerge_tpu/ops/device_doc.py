"""DeviceDoc: read API over a kernel-resolved op log.

The batched alternative to the host OpStore for N-way merges: build an
OpLog from many replicas' changes, run ops/merge.py once on device, then
answer reads from the resolved columns. Mirrors the reference ReadDoc
surface (reference: rust/automerge/src/read.rs:32-236) including the
historical ``*_at`` variants: ``at(heads)`` re-resolves visibility under a
clock mask (vectorized ``Clock::covers``, reference: clock.rs:71-77) while
sharing the log and the RGA element order with the current-state view —
element order depends only on the insert forest, never on the clock.

Also a patch source: ``diff(before_heads, after_heads)`` emits the same
path-qualified patches as the host differ (patches/diff.py) straight from
two clock-masked kernel resolutions, so the device merge can feed
materialized views / ``apply_patches`` without a host re-apply
(reference: rust/automerge/src/automerge/diff.rs log_diff).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..obs import prof as _prof
from ..core.marks import Mark
from ..patches.patch import (
    DeleteMap,
    DeleteSeq,
    FlagConflict,
    IncrementPatch,
    Insert,
    Patch,
    PutMap,
    PutSeq,
    SpliceText,
)
from ..types import ObjType, is_make_action, objtype_for_action
from .merge import merge_columns
from .oplog import (
    ELEM_HEAD, ELEM_MISSING, MAKE_ACTIONS, ACTOR_BITS, OpLog, TAG_COUNTER,
)

_MAKE_OBJ = {0: ObjType.MAP, 2: ObjType.LIST, 4: ObjType.TEXT, 6: ObjType.TABLE}

# one jax Mesh per device count, shared across every DeviceDoc with mesh
# residency enabled (see enable_mesh)
_MESH_CACHE: Dict[int, object] = {}


def order_elem_rows(log: "OpLog", elem_index: np.ndarray,
                    obj_rows: np.ndarray) -> np.ndarray:
    """Element rows of one sequence object in DOCUMENT order: the insert
    rows the linearization ranked, sorted by their rank. The single
    definition of the element-order rule shared by DeviceDoc reads and
    the stale-store read path (core/bulk_load.stale_text)."""
    obj_rows = np.asarray(obj_rows, np.int64)
    erows = obj_rows[
        np.asarray(log.insert)[obj_rows] & (elem_index[obj_rows] >= 0)
    ]
    return erows[np.argsort(elem_index[erows], kind="stable")]
_OBJ_REPLACEMENT = "￼"
_PUT = 1
_DELETE = 3
_INCREMENT = 5
_MARK = 7


class DeviceDoc:
    def __init__(
        self,
        log: OpLog,
        res: Dict[str, np.ndarray],
        covered: Optional[np.ndarray] = None,
        base: Optional["DeviceDoc"] = None,
    ):
        # whale-doc mesh residency (opt-in, see enable_mesh); views share
        # the base's mesh state; AUTOMERGE_TPU_MESH_DEVICES is probed
        # LAZILY on the first full re-resolution (never at construction:
        # a many-doc server must not enumerate devices per open)
        self._mesh = None if base is None else base._mesh
        self._mesh_min_rows = 0 if base is None else base._mesh_min_rows
        self._mesh_env_tried = False if base is None else base._mesh_env_tried
        self.log = log
        self.res = res
        n = log.n
        self._base = base if base is not None else self
        self.covered = (
            covered if covered is not None else np.ones(n, np.bool_)
        )
        self.visible = res["visible"][:n]
        self.winner = res["winner"][:n]
        self.conflicts = res["conflicts"][:n]
        if base is None:
            self.elem_index = res["elem_index"][:n]
            self._views: Dict[tuple, "DeviceDoc"] = {}
            self._hash_index = {ch.hash: ch for ch in log.changes}
            self._rank_of = {a.bytes: i for i, a in enumerate(log.actors)}
            self._pending: Dict[bytes, object] = {}
            # object id -> object type, from make ops (+ root)
            self._obj_type: Dict[int, ObjType] = {0: ObjType.MAP}
            for r in np.flatnonzero(np.isin(log.action[:n], MAKE_ACTIONS)):
                self._obj_type[int(log.id_key[r])] = _MAKE_OBJ[int(log.action[r])]
            # row ranges by object
            order = np.argsort(log.obj_key[:n], kind="stable")
            self._rows_by_obj = order.astype(np.int64)
            self._obj_sorted = log.obj_key[:n][order]
            self._all_elems_cache: Dict[int, List[int]] = {}
            self._res_bufs: Dict[str, np.ndarray] = {}
            # successor bookkeeping, maintained incrementally across
            # appends (host mirror of merge.succ_resolution under the
            # base's all-covered clock) — what lets delta resolution
            # recompute visibility without a kernel pass
            self.succ_count = np.zeros(n, np.int32)
            self.inc_count = np.zeros(n, np.int32)
            if len(log.pred_src):
                tgt = np.asarray(log.pred_tgt)
                src = np.asarray(log.pred_src)
                hit = tgt >= 0
                is_inc = np.asarray(log.action)[src] == _INCREMENT
                np.add.at(self.succ_count, tgt[hit & ~is_inc], 1)
                np.add.at(self.inc_count, tgt[hit & is_inc], 1)
        else:
            self.elem_index = base.elem_index
            self._obj_type = base._obj_type
            self._rows_by_obj = base._rows_by_obj
            self._obj_sorted = base._obj_sorted
        self._recompute_counters()

    def _recompute_counters(self) -> None:
        # exact int64 counter totals, host-side, gated by this view's clock
        # (the device kernel keeps the int32 fast path; reference counters
        # are i64, value.rs:369)
        log = self.log
        self.counter_val = np.asarray(log.value_int).copy()
        if len(log.pred_src):
            mask = (
                (log.action[log.pred_src] == _INCREMENT)
                & (log.pred_tgt >= 0)
                & self.covered[log.pred_src]
            )
            np.add.at(
                self.counter_val,
                log.pred_tgt[mask],
                log.value_int[log.pred_src[mask]],
            )

    # -- construction -------------------------------------------------------

    @classmethod
    def merge(cls, docs: Sequence) -> "DeviceDoc":
        """N-way fan-in merge of documents (AutoDoc or Document)."""
        return cls.resolve(OpLog.from_documents(docs))

    # the outputs the read API consumes; everything else stays on device
    READ_FETCH = (
        "visible", "winner", "conflicts", "elem_index",
        "obj_vis_len", "obj_text_width",
    )
    # historical views reuse the base view's element order
    VIEW_FETCH = (
        "visible", "winner", "conflicts", "obj_vis_len", "obj_text_width",
    )

    @classmethod
    def resolve(cls, log: OpLog) -> "DeviceDoc":
        obs.count("device.kernel_launches", labels={"path": "per_doc"})
        _prof.note("launches")
        with _prof.annotate("amtpu.resolve"):
            res = merge_columns(
                log.columns(), fetch=cls.READ_FETCH, n_objs=log.n_objs,
                n_props=len(log.props),
            )
        return cls(log, res)

    # -- incremental updates ------------------------------------------------
    #
    # The persistent-DeviceDoc path: new changes (from sync or local
    # commits) are spliced into the resident OpLog (OpLog.append_changes),
    # and only the objects the delta touches are re-resolved — a subset
    # kernel run over the dirty rows instead of a from-scratch rebuild.
    # When the dirty fraction crosses AUTOMERGE_TPU_DIRTY_FRACTION
    # (default 0.5) the whole log is re-resolved in one pass (still no
    # re-extraction) — the SynchroStore-style cost model: amortize while
    # deltas are small, recompute when they are not.

    def apply_changes(self, changes: Sequence, *, incremental: bool = True) -> int:
        """Integrate new StoredChanges into this resident document.

        Returns the number of changes integrated this call. Changes whose
        dependencies are not yet present are buffered and integrated when
        the gap fills (``pending_changes``). Duplicate (re-delivered)
        changes are no-ops. Only valid on the base (current-state) view.
        """
        if self._base is not self:
            raise ValueError("apply_changes on a historical view; use the base doc")
        # the umbrella span covers the WHOLE host apply — dedup, causal
        # ordering, splice, delta resolution — so a drain-cycle profiler
        # report attributes the staging wall clock without gaps (the
        # stage spans inside are its breakdown)
        with obs.span("device.apply", changes=len(changes)):
            ready = self._take_ready(changes)
            if not ready:
                return 0
            # an empty resident log (a device doc opened before any
            # history existed) has no actor table to splice into: the
            # rebuild path IS the initial build
            if incremental and self.log.n:
                with obs.span("device.stage.splice", changes=len(ready)):
                    info = self.log.append_changes(ready)
            else:
                info = None
            if info is None:
                obs.count("device.apply_rebuild")
                self._rebuild(list(self.log.changes) + ready)
                return len(ready)
            self._apply_append(info, ready)
            if info.n_new and not self._delta_resolve(info):
                self._reresolve(info.dirty_objs)
            self._export_doc_gauges()
        return len(ready)

    def apply_batches(self, batches: Sequence[Sequence]) -> int:
        """Pipelined variant for a stream of delta batches: on accelerator
        backends batch k+1's host-side append and h2d staging overlap
        batch k's in-flight kernel (double-buffered; readback of batch k
        happens only after batch k+1 is dispatched). On the CPU backend
        this degrades to sequential ``apply_changes`` calls."""
        import jax

        if self._base is not self:
            raise ValueError("apply_batches on a historical view; use the base doc")
        if len(batches) > 1:
            # the serving layer's sync coalescing lands here: how many
            # per-message applies each drain amortized is the signal
            obs.count("device.coalesced_batches", n=len(batches))
        if jax.default_backend() == "cpu":
            return sum(self.apply_changes(b) for b in batches)
        total = 0
        inflight = None
        t_buf = 0.0  # host work start while a handle was in flight

        def collect_inflight():
            # host seconds since the loop-top while this handle's kernel
            # was in flight are pipeline overlap — the drain's measurable
            # double-buffering win (prof: drain.overlap_fraction)
            _prof.note("overlap_s", time.perf_counter() - t_buf)
            self._collect_async(inflight)

        for chs in batches:
            if inflight is not None:
                t_buf = time.perf_counter()
            ready = self._take_ready(chs)
            if not ready:
                continue
            if self.log.n:
                with obs.span("device.stage.splice", changes=len(ready)):
                    info = self.log.append_changes(ready)
            else:
                info = None
            if info is None:
                if inflight is not None:
                    collect_inflight()
                    inflight = None
                obs.count("device.apply_rebuild")
                self._rebuild(list(self.log.changes) + ready)
                total += len(ready)
                continue
            if inflight is not None:
                # the in-flight handle's row/object ids move with the splice
                if info.row_map is not None:
                    inflight["rows"] = info.row_map[inflight["rows"]]
                if info.obj_remap is not None:
                    inflight["dirty"] = info.obj_remap[inflight["dirty"]]
            self._apply_append(info, ready)
            if info.n_new:
                handle = self._dispatch_async(info.dirty_objs)
                if handle is not None and handle.get("fallback"):
                    # cost-model fallback resolves synchronously over the
                    # CURRENT log — anything still in flight was computed
                    # from an older snapshot and must land first
                    if inflight is not None:
                        collect_inflight()
                        inflight = None
                    self._reresolve(info.dirty_objs)
                else:
                    if inflight is not None:
                        collect_inflight()
                    inflight = handle
            total += len(ready)
        if inflight is not None:
            self._collect_async(inflight)
        self._export_doc_gauges()
        return total

    def stage_batches(self, batches: Sequence[Sequence]):
        """Host-side half of the cross-document batched apply
        (ops/batched.py): dedup + causal-order + OpLog splice exactly as
        ``apply_batches`` would over the same batches, but the dirty-set
        kernel resolution is NOT dispatched — it is returned as a
        ``BatchStage`` for the caller to pack into one shared multi-doc
        launch.

        Returns ``(applied, stage_or_None)``. ``None`` means resolution
        already completed inside this call: the delta was empty/pure
        bookkeeping, the log had to rebuild (empty/partial resident
        history), or the dirty fraction tripped the per-doc full
        re-resolution cost model — the same per-doc fallbacks
        ``apply_changes`` takes, run eagerly so a returned stage is
        always pack-eligible.
        """
        if self._base is not self:
            raise ValueError("stage_batches on a historical view; use the base doc")
        # same umbrella as apply_changes: the whole host staging half is
        # one contiguous device.apply region for cycle attribution
        with obs.span("device.apply", batches=len(batches)):
            ready = self._take_ready([ch for b in batches for ch in b])
            return self._stage_ready(ready)

    def stage_ready(self, ready: Sequence):
        """``stage_batches`` over an already-deduped/causally-ordered
        ready list — the scalar per-doc fallback (and differential
        oracle) of the cross-doc vectorized staging in
        ops/host_batch.py, which runs ``_take_ready``'s halves itself."""
        if self._base is not self:
            raise ValueError("stage_ready on a historical view; use the base doc")
        with obs.span("device.apply", changes=len(ready)):
            return self._stage_ready(ready)

    def _stage_ready(self, ready):
        from .batched import BatchStage

        if not ready:
            return 0, None
        if self.log.n:
            with obs.span("device.stage.splice", changes=len(ready)):
                info = self.log.append_changes(ready)
        else:
            info = None
        if info is None:
            obs.count("device.apply_rebuild")
            self._rebuild(list(self.log.changes) + ready)
            return len(ready), None
        self._apply_append(info, ready)
        if not info.n_new:
            return len(ready), None
        dirty = np.asarray(info.dirty_objs, np.int64)
        rows = self._subset_rows(dirty)
        if (
            len(rows) / self.log.n > self._dirty_fraction_limit()
            or len(dirty) >= self.log.n_objs
        ):
            self._reresolve(dirty)
            self._export_doc_gauges()
            return len(ready), None
        self._export_doc_gauges()
        return len(ready), BatchStage(self, rows, dirty)

    def pending_changes(self) -> int:
        """Changes buffered awaiting missing dependencies."""
        return len(self._pending)

    def _take_ready(self, changes: Sequence) -> list:
        """Dedup + causal-order the incoming batch against what the log
        already holds; buffer changes with missing deps. The two halves
        are timed separately (``device.stage.dedup`` /
        ``device.stage.causal_order``) — the drain-cycle profiler's host
        stage attribution starts here. The cross-doc host staging path
        (ops/host_batch.py) calls the two span-free halves directly and
        wraps each ONCE for a whole multi-document drain."""
        with obs.span("device.stage.dedup", changes=len(changes)):
            self._dedup_into_pending(changes)
        with obs.span("device.stage.causal_order",
                      pending=len(self._pending)):
            return self._drain_ready_pending()

    def _dedup_into_pending(self, changes: Sequence) -> None:
        have = self._hash_index
        pend = self._pending
        for ch in changes:
            h = ch.hash
            if h is None or h in have or h in pend:
                continue
            pend[h] = ch

    def _drain_ready_pending(self) -> list:
        have = self._hash_index
        pend = self._pending
        ready: list = []
        ready_set: set = set()
        progress = True
        while progress and pend:
            progress = False
            for h in list(pend):
                ch = pend[h]
                if all(d in have or d in ready_set
                       for d in ch.dependencies):
                    ready.append(ch)
                    ready_set.add(h)
                    del pend[h]
                    progress = True
        if pend:
            obs.count("device.apply_deferred", n=len(pend))
        return ready

    def _rebuild(self, changes: list) -> None:
        """Full fallback: re-extract and re-resolve everything in place."""
        pend = self._pending
        mesh_state = (self._mesh, self._mesh_min_rows, self._mesh_env_tried)
        log = OpLog.from_changes(changes)
        obs.count("device.kernel_launches", labels={"path": "per_doc"})
        _prof.note("launches")
        with _prof.annotate("amtpu.rebuild"):
            res = merge_columns(
                log.columns(), fetch=self.READ_FETCH, n_objs=log.n_objs,
                n_props=len(log.props),
            )
        self.__init__(log, res)
        self._pending = pend
        self._mesh, self._mesh_min_rows, self._mesh_env_tried = mesh_state
        self._export_doc_gauges()

    # per-doc accounting label (doc.resident_ops / doc.device_bytes):
    # set by the durable layer when this resident doc serves a named
    # document; None (the default) keeps the export path a no-op
    obs_name = None

    # last resident_nbytes() figure, stamped by the OWNING thread (the
    # apply path under the document lock). Cross-thread readers — the
    # DocStore evict sweeper's admission estimate — read this cache
    # instead of calling resident_nbytes(), because computing it syncs
    # the log's compressed image (a mutation) and must never race an
    # in-flight append. None until first computed.
    _resident_cache = None

    def resident_nbytes(self) -> int:
        """True device-path resident footprint of this document: the
        column image a drain ships/holds (compressed runs where the
        ratio gate admits them — ops/compressed.py; dense-equivalent
        with ``AUTOMERGE_TPU_COMPRESSED=0``) plus the per-row resolution
        readbacks. The number the DocStore admission policy budgets.

        Syncs the compressed image — call only from the thread that
        owns the document (apply paths, gauge export, bench); lock-free
        observers use ``resident_nbytes_estimate``."""
        n = self.log.resident_column_nbytes() + sum(
            a.nbytes for a in self.res.values()
        )
        self._resident_cache = n
        return n

    def resident_nbytes_estimate(self) -> int:
        """Read-only resident estimate for cross-thread observers: the
        owner-stamped cache when available, else the dense arithmetic
        (pure reads — never touches the compressed image)."""
        n = self._resident_cache
        if n is not None:
            return n
        return self.log.dense_column_nbytes() + sum(
            a.nbytes for a in self.res.values()
        )

    def dense_nbytes(self) -> int:
        """What the same residency costs fully decompressed — the
        pre-compression accounting, kept as the ratio denominator."""
        return self.log.dense_column_nbytes() + sum(
            a.nbytes for a in self.res.values()
        )

    def compress_ratio(self) -> float:
        r = self.resident_nbytes()
        return (self.dense_nbytes() / r) if r else 1.0

    def audit_columns(self) -> list:
        """Integrity spot-check of the resident image: sync the
        compressed bundle and verify every encoded column against the
        dense host oracle (``CompressedOpColumns.verify_against``).
        Returns mismatching column names — non-empty means this mirror
        must not serve reads and should be dropped for rebuild. Call
        from the thread that owns the document (the scrubber holds the
        doc lock)."""
        comp = self.log.compressed(sync=True)
        if comp is None:
            return []  # dense mode IS the oracle — nothing encoded to audit
        return comp.verify_against(self.log)

    def _export_doc_gauges(self) -> None:
        if self.obs_name is None:
            return
        labels = {"doc": self.obs_name}
        obs.gauge_set("doc.resident_ops", self.log.n, labels=labels)
        # TRUE resident bytes (the compressed image a drain actually
        # ships), not the dense-equivalent array bytes — the admission
        # policy must see real footprint; the ratio gauge rides along so
        # dashboards can see how hard each doc compresses
        resident = self.resident_nbytes()
        obs.gauge_set("doc.device_bytes", resident, labels=labels)
        obs.gauge_set(
            "doc.compress_ratio",
            round(self.dense_nbytes() / resident, 4) if resident else 1.0,
            labels=labels,
        )

    def _apply_append(self, info, ready: Sequence) -> None:
        """Splice this view's resolution arrays and host caches through an
        AppendInfo (positions move; values of clean objects are reused)."""
        log = self.log
        m = log.n
        n_old, rm = info.n_old, info.row_map
        for ch in ready:
            self._hash_index[ch.hash] = ch
        if info.actors_changed:
            # the log's packed ids were rank-remapped in place; every host
            # cache keyed by a packed id must follow the same monotone map
            new_rank = {a.bytes: i for i, a in enumerate(log.actors)}
            remap = {old: new_rank[b] for b, old in self._rank_of.items()}
            self._obj_type = {
                (
                    k
                    if k == 0
                    else ((k >> ACTOR_BITS) << ACTOR_BITS)
                    | remap[k & ((1 << ACTOR_BITS) - 1)]
                ): v
                for k, v in self._obj_type.items()
            }
            self._rank_of = new_rank
        self._views.clear()
        if info.n_new == 0:
            if info.actors_changed:
                self._all_elems_cache.clear()
            return
        with obs.span("device.materialize", rows=info.n_new):
            nr = np.asarray(info.new_rows, np.int64)
            mk = nr[np.isin(np.asarray(log.action)[nr], MAKE_ACTIONS)]
            for r in mk:
                self._obj_type[int(log.id_key[r])] = _MAKE_OBJ[int(log.action[r])]

            # resolution arrays: old values carried, positions remapped;
            # the new rows' objects are all dirty and re-resolved next.
            # Capacity-bucketed buffers make the tail-append fast path
            # O(delta): only the k new slots are written.
            win_old = self.winner
            if rm is not None:
                safe = max(n_old - 1, 0)
                win_old = np.where(
                    self.winner >= 0,
                    rm[np.clip(self.winner, 0, safe)],
                    -1,
                ).astype(np.int32)
            vis = self._res_splice("visible", np.asarray(self.visible, np.bool_),
                                   m, rm, n_old, False)
            win = self._res_splice("winner", np.asarray(win_old, np.int32),
                                   m, rm, n_old, -1)
            con = self._res_splice("conflicts", np.asarray(self.conflicts, np.int32),
                                   m, rm, n_old, 0)
            ei = self._res_splice("elem_index", np.asarray(self.elem_index, np.int32),
                                  m, rm, n_old, -1)
            orm = info.obj_remap
            n_objs_old = len(orm) if orm is not None else log.n_objs
            ovl = np.zeros(log.n_objs + 2, np.int32)
            otw = np.zeros(log.n_objs + 2, np.int32)
            old_ovl = np.asarray(self.res["obj_vis_len"])
            old_otw = np.asarray(self.res["obj_text_width"])
            take = min(n_objs_old, len(old_ovl))
            if orm is None:
                ovl[:take] = old_ovl[:take]
                otw[:take] = old_otw[:take]
            else:
                ovl[orm[:take]] = old_ovl[:take]
                otw[orm[:take]] = old_otw[:take]
            self.res = {
                "visible": vis, "winner": win, "conflicts": con,
                "elem_index": ei, "obj_vis_len": ovl, "obj_text_width": otw,
            }
            self.visible = vis
            self.winner = win
            self.conflicts = con
            self.elem_index = ei
            self.covered = np.ones(m, np.bool_)

            # successor bookkeeping and exact counter totals ride the same
            # splice, then absorb the delta's edges (kept fresh regardless
            # of which resolution path runs)
            self.succ_count = self._res_splice(
                "succ_count", self.succ_count, m, rm, n_old, 0
            )
            self.inc_count = self._res_splice(
                "inc_count", self.inc_count, m, rm, n_old, 0
            )
            value_int = np.asarray(log.value_int)
            cv = self._res_splice("counter_val", self.counter_val, m, rm, n_old, 0)
            cv[nr] = value_int[nr]
            self.counter_val = cv
            ps = np.asarray(log.pred_src)
            pt = np.asarray(log.pred_tgt)
            eidx = np.concatenate([
                np.arange(info.n_pred_old, len(ps), dtype=np.int64),
                np.asarray(info.rere_pred_edges, np.int64),
            ])
            if len(eidx):
                src = ps[eidx]
                tgt = pt[eidx]
                ok = tgt >= 0
                src, tgt = src[ok], tgt[ok]
                is_inc = np.asarray(log.action)[src] == _INCREMENT
                np.add.at(self.succ_count, tgt[~is_inc], 1)
                np.add.at(self.inc_count, tgt[is_inc], 1)
                np.add.at(self.counter_val, tgt[is_inc], value_int[src[is_inc]])

            # object-sorted row index: merge the (already sorted) old order
            # with the delta's rows — no full argsort
            old_rbo = self._rows_by_obj
            if rm is not None:
                old_rbo = rm[old_rbo]
            obj_key = np.asarray(log.obj_key)
            old_keys = obj_key[old_rbo]
            d_keys = obj_key[nr]
            ordx = np.lexsort((nr, d_keys))
            d_rows = nr[ordx]
            d_keys = d_keys[ordx]
            pos = np.searchsorted(old_keys, d_keys, side="right")
            cnt = np.bincount(pos, minlength=n_old + 1)
            rbo = np.empty(m, np.int64)
            keys = np.empty(m, np.int64)
            old_pos = np.arange(n_old, dtype=np.int64) + np.cumsum(cnt[:n_old])
            rbo[old_pos] = old_rbo
            keys[old_pos] = old_keys
            new_pos = pos + np.arange(len(d_rows), dtype=np.int64)
            rbo[new_pos] = d_rows
            keys[new_pos] = d_keys
            self._rows_by_obj = rbo
            self._obj_sorted = keys

            if info.tail and not info.actors_changed:
                for d in np.asarray(info.dirty_objs):
                    self._all_elems_cache.pop(int(log.obj_table[d]), None)
            else:
                self._all_elems_cache.clear()

    # host delta resolution ---------------------------------------------------
    #
    # The O(delta) path: visibility/winners recomputed ONLY for the key
    # groups the delta touches (from the incrementally-maintained succ/inc
    # counters), and document order spliced by anchor arithmetic — valid
    # because a tail append's ids exceed every resident id, so each new
    # element is its anchor's FIRST child (descending-Lamport sibling
    # order) and a new subtree lands immediately after its anchor. Falls
    # back (returns False) to the object-granularity kernel re-resolution
    # when its assumptions don't hold (non-tail splice, re-resolved refs,
    # unranked anchors).

    def _delta_resolve(self, info) -> bool:
        log = self.log
        m = log.n
        if not info.tail or len(info.rere_elem_rows):
            return False
        nr = np.asarray(info.new_rows, np.int64)
        action = np.asarray(log.action)
        insert = np.asarray(log.insert, np.bool_)
        er = np.asarray(log.elem_ref)
        prop = np.asarray(log.prop)
        od = np.asarray(log.obj_dense)

        ni = nr[insert[nr]]
        anch = er[ni]
        if len(ni) and np.any(anch == ELEM_MISSING):
            return False  # unresolved anchor: cannot place incrementally
        old_anchor = anch[(anch >= 0) & (anch < info.n_old)]
        if len(old_anchor) and np.any(self.elem_index[old_anchor] < 0):
            return False  # anchor itself unranked

        # touched rows: the delta's own + targets of its (re)resolved edges
        ps = np.asarray(log.pred_src)
        pt = np.asarray(log.pred_tgt)
        eidx = np.concatenate([
            np.arange(info.n_pred_old, len(ps), dtype=np.int64),
            np.asarray(info.rere_pred_edges, np.int64),
        ])
        touched = pt[eidx][pt[eidx] >= 0] if len(eidx) else np.empty(0, np.int64)
        cand = np.unique(np.concatenate([nr, touched])).astype(np.int64)
        c_map = prop[cand] >= 0
        c_seq = cand[~c_map]
        if len(c_seq) and np.any(~insert[c_seq] & (er[c_seq] < 0)):
            return False  # sentinel-keyed update groups: let the kernel decide

        with obs.span("device.delta_resolve", rows=len(cand)):
            # group membership (two vectorized passes over the columns)
            heads = np.unique(np.where(insert[c_seq], c_seq, er[c_seq]))
            member = np.zeros(m, np.bool_)
            if len(heads):
                head_mask = np.zeros(m, np.bool_)
                head_mask[heads] = True
                member |= head_mask
                member |= (
                    (~insert) & (er >= 0) & head_mask[np.clip(er, 0, m - 1)]
                )
            n_props = max(len(log.props), 1)
            if np.any(c_map):
                mkeys = np.unique(
                    od[cand[c_map]].astype(np.int64) * n_props
                    + prop[cand[c_map]]
                )
                gid_all = od.astype(np.int64) * n_props + prop
                pos = np.searchsorted(mkeys, gid_all)
                posc = np.clip(pos, 0, len(mkeys) - 1)
                member |= (prop >= 0) & (mkeys[posc] == gid_all)
            rows = np.flatnonzero(member)

            # visibility over the affected rows (merge.visibility mirror;
            # the base clock covers everything)
            vt = np.asarray(log.value_tag)
            act = action[rows]
            never = (act == _DELETE) | (act == _INCREMENT) | (act == _MARK)
            is_counter = (act == _PUT) & (vt[rows] == TAG_COUNTER)
            sc = self.succ_count[rows]
            ic = self.inc_count[rows]
            vis = ~never & np.where(is_counter, sc == 0, (sc + ic) == 0)
            self.visible[rows] = vis

            # winners/conflicts per affected group (rows ascend = Lamport)
            gkey = np.where(
                prop[rows] >= 0,
                np.int64(m) + od[rows].astype(np.int64) * n_props + prop[rows],
                np.where(insert[rows], rows, er[rows].astype(np.int64)),
            )
            order = np.argsort(gkey, kind="stable")
            gs = gkey[order]
            vs = vis[order]
            rr = rows[order]
            newseg = np.concatenate([[True], gs[1:] != gs[:-1]])
            seg = np.cumsum(newseg) - 1
            nseg = int(seg[-1]) + 1 if len(seg) else 0
            win = np.full(nseg, -1, np.int64)
            np.maximum.at(win, seg, np.where(vs, rr, -1))
            cnt = np.zeros(nseg, np.int64)
            np.add.at(cnt, seg, vs.astype(np.int64))

            # per-object stats adjust by the member elements' before/after
            # contributions — winners only ever change inside member groups
            el_rows = rr[insert[rr]]
            w = np.asarray(log.width)
            wold = self.winner[el_rows]
            old_len = wold >= 0
            old_w = np.where(old_len, w[np.clip(wold, 0, m - 1)], 0)

            self.winner[rr] = win[seg]
            self.conflicts[rr] = cnt[seg]

            wnew = self.winner[el_rows]
            new_len = wnew >= 0
            new_w = np.where(new_len, w[np.clip(wnew, 0, m - 1)], 0)
            o = od[el_rows]
            np.add.at(
                self.res["obj_vis_len"], o,
                new_len.astype(np.int32) - old_len.astype(np.int32),
            )
            np.add.at(
                self.res["obj_text_width"], o,
                (new_w - old_w).astype(np.int32),
            )

            # document order: splice the new subtrees in by anchor position
            if len(ni):
                self._splice_elem_order(ni)
        obs.count("device.delta_resolve")
        return True

    def _splice_elem_order(self, ni: np.ndarray) -> None:
        """elem_index update for a tail append's new insert rows: new
        elements form subtrees hanging off old anchors (or object HEADs);
        each subtree's preorder lands immediately after its anchor, and
        older elements shift by the block sizes inserted before them."""
        log = self.log
        er = np.asarray(log.elem_ref)
        od = np.asarray(log.obj_dense)
        insert = np.asarray(log.insert, np.bool_)
        ei = self.elem_index

        ni_l = ni.tolist()
        er_l = er[ni].tolist()
        od_l = od[ni].tolist()
        loc = {r: j for j, r in enumerate(ni_l)}
        kids: Dict[int, list] = {}
        roots: Dict[tuple, list] = {}  # (obj dense, anchor row | -1=HEAD) -> locals
        for j in range(len(ni_l) - 1, -1, -1):  # descending id = sibling order
            a = er_l[j]
            pj = loc.get(a)
            if pj is not None:
                kids.setdefault(pj, []).append(j)
            elif a == ELEM_HEAD or a >= 0:
                roots.setdefault((od_l[j], a if a >= 0 else -1), []).append(j)
        # per-object anchor blocks in subtree preorder
        by_obj: Dict[int, list] = {}  # obj -> [(anchor_pos, [rows...])]
        for (o, a), starts in roots.items():
            block: list = []
            stack = list(reversed(starts))
            while stack:
                j = stack.pop()
                block.append(ni_l[j])
                stack.extend(reversed(kids.get(j, ())))
            p_a = -1 if a < 0 else int(ei[a])
            by_obj.setdefault(o, []).append((p_a, block))
        for o, blocks in by_obj.items():
            blocks.sort()
            pa = np.asarray([p for p, _ in blocks], np.int64)
            sizes = np.asarray([len(b) for _, b in blocks], np.int64)
            cum = np.concatenate([[0], np.cumsum(sizes)])
            # older elements of this object shift by the blocks before them
            obj_key = int(log.obj_table[o])
            orows = self._obj_rows(obj_key)
            # the delta's own rows still carry elem_index -1, so the >= 0
            # filter leaves exactly the resident elements
            old_el = orows[insert[orows] & (ei[orows] >= 0)]
            if len(old_el):
                shift = cum[np.searchsorted(pa, ei[old_el], side="left")]
                ei[old_el] += shift.astype(ei.dtype)
            for bi, (p_a, block) in enumerate(blocks):
                start = p_a + cum[bi] + 1
                ei[np.asarray(block, np.int64)] = (
                    start + np.arange(len(block))
                ).astype(ei.dtype)

    # dirty-set re-resolution ------------------------------------------------

    def _subset_rows(self, dirty: np.ndarray) -> np.ndarray:
        base = self._base
        if len(dirty) == 1 and base is self:
            # one dirty object — the dominant serve-delta shape: its rows
            # are one contiguous slice of the maintained object-sorted
            # index, O(subset) instead of a full-log membership scan.
            # Rows within an object ascend in _rows_by_obj (stable
            # construction + ordered merges); the stable integer sort is
            # a near-free belt-and-braces pass that keeps the ascending
            # (= Lamport) contract the subset kernel relies on.
            key = int(self.log.obj_table[int(dirty[0])])
            lo = np.searchsorted(self._obj_sorted, key, side="left")
            hi = np.searchsorted(self._obj_sorted, key, side="right")
            return np.sort(self._rows_by_obj[lo:hi], kind="stable")
        od = np.asarray(self.log.obj_dense)
        idx = np.searchsorted(dirty, od)
        member = (idx < len(dirty)) & (
            dirty[np.clip(idx, 0, len(dirty) - 1)] == od
        )
        return np.flatnonzero(member)

    def _subset_cols(self, rows: np.ndarray, dirty: np.ndarray):
        """Column dict over the dirty objects' rows only, with references
        renumbered subset-locally (rows stay ascending = Lamport order)."""
        log = self.log
        m = log.n
        S = len(rows)
        full2sub = np.full(m, -1, np.int32)
        full2sub[rows] = np.arange(S, dtype=np.int32)
        er = np.asarray(log.elem_ref)[rows]
        er_sub = np.where(
            er >= 0, full2sub[np.clip(er, 0, m - 1)], er
        ).astype(np.int32)
        # a ref outside the subset would mean a cross-object element ref
        # (malformed); degrade it to MISSING rather than mis-index
        er_sub = np.where((er >= 0) & (er_sub < 0), np.int32(ELEM_MISSING), er_sub)
        ps = np.asarray(log.pred_src)
        pt = np.asarray(log.pred_tgt)
        if len(ps):
            src_sub = full2sub[np.clip(ps, 0, m - 1)]
            emask = src_sub >= 0
            tgt = pt[emask]
            tgt_sub = np.where(
                tgt >= 0, full2sub[np.clip(tgt, 0, m - 1)], -1
            ).astype(np.int32)
            sub_ps = src_sub[emask].astype(np.int32)
        else:
            sub_ps = np.empty(0, np.int32)
            tgt_sub = np.empty(0, np.int32)
        return {
            "action": np.asarray(log.action)[rows],
            "insert": np.asarray(log.insert, np.bool_)[rows],
            "prop": np.asarray(log.prop)[rows],
            "elem_ref": er_sub,
            "obj_dense": np.searchsorted(dirty, np.asarray(log.obj_dense)[rows]).astype(np.int32),
            "value_tag": np.asarray(log.value_tag)[rows],
            "value_i32": np.asarray(log.value_int)[rows].astype(np.int32),
            "width": np.asarray(log.width)[rows],
            "covered": np.ones(S, np.bool_),
            "pred_src": sub_ps,
            "pred_tgt": tgt_sub,
        }

    def _scatter_subset(self, rows, dirty, res_sub) -> None:
        S = len(rows)
        D = len(dirty)
        self.visible[rows] = np.asarray(res_sub["visible"])[:S]
        w = np.asarray(res_sub["winner"])[:S]
        self.winner[rows] = np.where(
            w >= 0, rows[np.clip(w, 0, max(S - 1, 0))], -1
        ).astype(np.int32)
        self.conflicts[rows] = np.asarray(res_sub["conflicts"])[:S]
        self.elem_index[rows] = np.asarray(res_sub["elem_index"])[:S]
        self.res["obj_vis_len"][dirty] = np.asarray(res_sub["obj_vis_len"])[:D]
        self.res["obj_text_width"][dirty] = np.asarray(res_sub["obj_text_width"])[:D]

    def _res_splice(self, name, old, m, rm, n_old, fill):
        """Splice one per-row resolution array through a capacity-bucketed
        backing buffer (tail appends write only the new slots)."""
        from .oplog import _capacity

        buf = self._res_bufs.get(name)
        if rm is None and buf is not None and old.base is buf and len(buf) >= m:
            buf[n_old:m] = fill
            return buf[:m]
        nbuf = np.empty(_capacity(m), old.dtype)
        out = nbuf[:m]
        if rm is None:
            out[:n_old] = old
            out[n_old:] = fill
        else:
            out[:] = fill
            out[rm] = old
        self._res_bufs[name] = nbuf
        return out

    def _dirty_fraction_limit(self) -> float:
        import os

        return float(os.environ.get("AUTOMERGE_TPU_DIRTY_FRACTION", "0.5"))

    def _reresolve(self, dirty) -> None:
        log = self.log
        m = log.n
        dirty = np.asarray(dirty, np.int64)
        if m == 0 or not len(dirty):
            return
        rows = self._subset_rows(dirty)
        frac = len(rows) / m
        if frac > self._dirty_fraction_limit() or len(dirty) >= log.n_objs:
            # cost model says re-resolving everything is cheaper than the
            # bookkeeping win (still NO re-extraction — columns are resident)
            obs.count("device.reresolve_full")
            obs.event("device.reresolve", mode="full", rows=m,
                        dirty_rows=len(rows), frac=round(frac, 4))
            res = self._mesh_resolve()
            if res is None:
                obs.count("device.kernel_launches", labels={"path": "per_doc"})
                _prof.note("launches")
                with _prof.annotate("amtpu.reresolve_full"):
                    res = merge_columns(
                        log.columns(), fetch=self.READ_FETCH,
                        n_objs=log.n_objs, n_props=len(log.props),
                    )
            n = log.n
            vis = np.asarray(res["visible"])[:n]
            win = np.asarray(res["winner"])[:n]
            con = np.asarray(res["conflicts"])[:n]
            ei = np.asarray(res["elem_index"])[:n]
            self.res["visible"][:] = vis
            self.res["winner"][:] = win
            self.res["conflicts"][:] = con
            self.res["elem_index"][:] = ei
            ovl = np.asarray(res["obj_vis_len"])
            otw = np.asarray(res["obj_text_width"])
            take = min(len(ovl), len(self.res["obj_vis_len"]))
            self.res["obj_vis_len"][:take] = ovl[:take]
            self.res["obj_text_width"][:take] = otw[:take]
            return
        obs.count("device.reresolve_subset")
        obs.event("device.reresolve", mode="subset", rows=m,
                    dirty_rows=len(rows), frac=round(frac, 4))
        cols = self._subset_cols(rows, dirty)
        obs.count("device.kernel_launches", labels={"path": "per_doc"})
        _prof.note("launches")
        with _prof.annotate("amtpu.reresolve_subset"):
            res_sub = merge_columns(
                cols, fetch=self.READ_FETCH, n_objs=len(dirty),
                n_props=len(log.props),
            )
        with obs.span("device.scatter", rows=len(rows)):
            self._scatter_subset(rows, dirty, res_sub)

    # staged async subset resolution (apply_batches) --------------------------

    def _dispatch_async(self, dirty):
        """Stage one dirty-set resolution on the accelerator WITHOUT reading
        back: h2d (device_put) and the kernel dispatch are asynchronous, and
        document ordering runs host-side (host_linearize) while the kernel
        is in flight. Returns a handle for _collect_async, None when there
        is nothing to resolve, or ``{"fallback": True}`` when the dirty
        fraction demands a synchronous full re-resolution (which the caller
        runs AFTER draining any in-flight batch)."""
        from .merge import prepare_resolution
        from .oplog import host_linearize, pad_columns

        log = self.log
        dirty = np.asarray(dirty, np.int64)
        if log.n == 0 or not len(dirty):
            return None
        rows = self._subset_rows(dirty)
        if len(rows) / log.n > self._dirty_fraction_limit():
            # the caller must drain any in-flight batch BEFORE resolving
            # synchronously, or its stale results would overwrite ours
            return {"fallback": True}
        D = len(dirty)
        cols_np = pad_columns(self._subset_cols(rows, dirty), D)
        P = len(cols_np["action"])
        # staging: run-native mode hands the kernel the run tables
        # themselves; otherwise device_put moves run tables and the
        # expansion dispatch runs eagerly (merge.stage_cols_device)
        dispatch = prepare_resolution(cols_np, D, len(log.props))
        obs.count("device.kernel_launches", labels={"path": "per_doc"})
        _prof.note("launches")
        with obs.span("device.kernel", rows=P), \
                _prof.annotate("amtpu.dispatch_async"):
            out = dispatch()  # async dispatch
        # element order overlaps the kernel — it needs only the columns
        with obs.span("device.linearize", rows=P):
            ei = host_linearize(cols_np)
        return {"rows": rows, "dirty": dirty, "out": out, "ei": ei}

    def _collect_async(self, handle) -> None:
        if handle is None:
            return
        out = handle["out"]
        S = len(handle["rows"])
        D = len(handle["dirty"])
        with obs.span("device.readback", rows=S):
            res_sub = {
                "visible": np.asarray(out["visible"]),
                "winner": np.asarray(out["winner"]),
                "conflicts": np.asarray(out["conflicts"]),
                "elem_index": handle["ei"],
                "obj_vis_len": np.asarray(out["obj_vis_len"]),
                "obj_text_width": np.asarray(out["obj_text_width"]),
            }
        with obs.span("device.scatter", rows=S):
            self._scatter_subset(handle["rows"], handle["dirty"], res_sub)

    # -- whale-doc mesh residency (parallel/sharding.py) ---------------------
    #
    # Opt-in: full-log re-resolutions of a document too big for one chip
    # route through the sharded merge (every phase split over a
    # jax.sharding.Mesh). The resident columns are handed over PERMUTED
    # into object-id-range-contiguous layout (the incrementally-maintained
    # ``_rows_by_obj`` order), so each device's row slice holds whole
    # object key groups and the per-group winner recompute stays
    # chip-local; the stable sort keeps rows ascending (= Lamport
    # ascending) within every object, preserving the winner rule, and all
    # row references are remapped through the permutation both ways.

    def enable_mesh(
        self, n_devices: Optional[int] = None, min_rows: Optional[int] = None
    ) -> bool:
        """Turn on mesh residency. Returns False — and stays on the
        single-device path — when ``jax.shard_map`` or a multi-device
        mesh is unavailable (the graceful degrade bench.py uses).
        ``min_rows`` (env AUTOMERGE_TPU_MESH_MIN_ROWS, default 4096)
        keeps small re-resolutions on one chip."""
        import os

        import jax

        if self._base is not self:
            raise ValueError("enable_mesh on a historical view; use the base doc")
        if not hasattr(jax, "shard_map"):
            obs.count("device.mesh_unavailable", labels={"reason": "no_shard_map"})
            return False
        try:
            devs = jax.devices()
        except Exception:
            obs.count("device.mesh_unavailable", labels={"reason": "no_backend"})
            return False
        want = n_devices or len(devs)
        if want < 2 or len(devs) < want:
            obs.count("device.mesh_unavailable", labels={"reason": "single_device"})
            return False
        from ..parallel.sharding import default_mesh

        # one Mesh per device count, shared by every DeviceDoc (a Mesh is
        # just a device grid — rebuilding it per document is pure waste)
        mesh = _MESH_CACHE.get(want)
        if mesh is None:
            mesh = _MESH_CACHE[want] = default_mesh(want, devices=devs[:want])
        self._mesh = mesh
        self._mesh_min_rows = int(
            min_rows
            if min_rows is not None
            else os.environ.get("AUTOMERGE_TPU_MESH_MIN_ROWS", "4096")
        )
        return True

    def disable_mesh(self) -> None:
        self._mesh = None

    def _mesh_resolve(self) -> Optional[Dict[str, np.ndarray]]:
        """One sharded full-log resolution over the mesh, or None when
        mesh residency is off / below threshold / degraded."""
        if self._mesh is None:
            if self._mesh_env_tried:
                return None
            self._mesh_env_tried = True
            import os

            nd = os.environ.get("AUTOMERGE_TPU_MESH_DEVICES")
            if not nd:
                return None
            try:
                if not self.enable_mesh(int(nd)):
                    return None
            except Exception:
                return None
        if self.log.n < self._mesh_min_rows:
            return None
        try:
            return self._mesh_resolve_inner()
        except Exception as e:  # noqa: BLE001 — degrade to single device
            obs.count("device.mesh_unavailable", labels={"reason": "error"})
            obs.event("device.mesh_error", error=str(e)[:200])
            return None

    def _mesh_resolve_inner(self) -> Dict[str, np.ndarray]:
        from ..parallel.sharding import sharded_merge_columns
        from .oplog import pad_columns

        log = self.log
        m = log.n
        with obs.span("device.mesh_resolve", rows=m):
            # object-range permutation: new position i holds old row
            # perm[i]; _rows_by_obj is obj-sorted and row-ascending
            # within each object (stable), exactly what we need
            perm = np.asarray(self._rows_by_obj, np.int64)
            inv = np.empty(m, np.int64)
            inv[perm] = np.arange(m, dtype=np.int64)
            cols = log.columns()
            pc = {
                k: np.asarray(cols[k])[perm]
                for k in ("action", "insert", "prop", "obj_dense",
                          "value_tag", "value_i32", "width", "covered")
            }
            er = np.asarray(cols["elem_ref"])[perm]
            pc["elem_ref"] = np.where(
                er >= 0, inv[np.clip(er, 0, m - 1)], er
            ).astype(np.int32)
            ps = np.asarray(cols["pred_src"])
            pt = np.asarray(cols["pred_tgt"])
            pc["pred_src"] = (
                inv[ps].astype(np.int32) if len(ps) else ps
            )
            pc["pred_tgt"] = (
                np.where(pt >= 0, inv[np.clip(pt, 0, m - 1)], pt).astype(np.int32)
                if len(pt)
                else pt
            )
            pc = pad_columns(pc, log.n_objs)
            n_dev = self._mesh.devices.size
            if len(pc["action"]) % n_dev:
                obs.count("device.mesh_unavailable",
                          labels={"reason": "shape"})
                return None
            out = sharded_merge_columns(
                pc, mesh=self._mesh, n_objs=log.n_objs,
                n_props=len(log.props),
            )
            # un-permute the per-row outputs; winner VALUES are permuted
            # row ids and map back through perm itself
            res: Dict[str, np.ndarray] = {}
            for k in ("visible", "conflicts", "elem_index"):
                a = np.asarray(out[k])[:m]
                o = np.empty(m, a.dtype)
                o[perm] = a
                res[k] = o
            w = np.asarray(out["winner"])[:m]
            w_o = np.where(w >= 0, perm[np.clip(w, 0, m - 1)], -1)
            wo = np.empty(m, np.int32)
            wo[perm] = w_o.astype(np.int32)
            res["winner"] = wo
            res["obj_vis_len"] = np.asarray(out["obj_vis_len"])[: log.n_objs + 2]
            res["obj_text_width"] = np.asarray(
                out["obj_text_width"]
            )[: log.n_objs + 2]
            return res

    # -- historical views ---------------------------------------------------

    def current_heads(self) -> List[bytes]:
        """Change hashes no other change in the log depends on."""
        base = self._base
        deps = {d for ch in base.log.changes for d in ch.dependencies}
        return sorted(h for h in base._hash_index if h not in deps)

    def _clock_vec(self, heads: Sequence[bytes]) -> np.ndarray:
        """Dense per-actor-rank max-op vector for the clock at ``heads``
        (the ancestor traversal of change_graph.rs:128-142, host-side)."""
        base = self._base
        vec = np.zeros(len(base.log.actors), np.int64)
        stack = list(heads)
        seen = set()
        while stack:
            h = stack.pop()
            if h in seen:
                continue
            seen.add(h)
            ch = base._hash_index.get(h)
            if ch is None:
                raise KeyError(f"unknown head {h.hex()}")
            rank = base._rank_of[bytes(ch.actor)]
            if ch.max_op > vec[rank]:
                vec[rank] = ch.max_op
            stack.extend(ch.dependencies)
        return vec

    def at(self, heads: Optional[Sequence[bytes]]) -> "DeviceDoc":
        """The document as of ``heads``: same log, same element order,
        visibility re-resolved under the clock mask (one kernel run,
        cached per heads set)."""
        base = self._base
        if heads is None:
            return base
        key = tuple(sorted(heads))
        view = base._views.get(key)
        if view is None:
            covered = base.log.covered_mask(base._clock_vec(heads))
            obs.count("device.kernel_launches", labels={"path": "per_doc"})
            _prof.note("launches")
            with _prof.annotate("amtpu.at_view"):
                res = merge_columns(
                    base.log.padded_columns(covered=covered),
                    fetch=self.VIEW_FETCH,
                    n_objs=base.log.n_objs,
                    n_props=len(base.log.props),
                )
            view = DeviceDoc(base.log, res, covered=covered, base=base)
            base._views[key] = view
        return view

    def _view(self, heads) -> "DeviceDoc":
        return self if heads is None else self.at(heads)

    # -- row selection ------------------------------------------------------

    def _obj_rows(self, obj_key: int) -> np.ndarray:
        lo = np.searchsorted(self._obj_sorted, obj_key, side="left")
        hi = np.searchsorted(self._obj_sorted, obj_key, side="right")
        return self._rows_by_obj[lo:hi]

    def _check_obj(self, obj_key: int) -> ObjType:
        t = self._obj_type.get(obj_key)
        if t is None:
            raise KeyError(f"no such object {self.log.export_id(obj_key)}")
        return t

    def _all_elems(self, obj_key: int) -> List[int]:
        """ALL element rows of a sequence in document order — including
        invisible and mark elements (the host ``SeqObject.elements()``
        walk; order is clock-independent so this lives on the base)."""
        base = self._base
        cached = base._all_elems_cache.get(obj_key)
        if cached is None:
            cached = order_elem_rows(
                base.log, base.elem_index, base._obj_rows(obj_key)
            ).tolist()
            base._all_elems_cache[obj_key] = cached
        return cached

    # -- value rendering ----------------------------------------------------

    def _render(self, row: int):
        a = int(self.log.action[row])
        if is_make_action(a):
            return (
                "obj",
                objtype_for_action(a),
                self.log.export_id(int(self.log.id_key[row])),
            )
        if a == _PUT and int(self.log.value_tag[row]) == TAG_COUNTER:
            return ("counter", int(self.counter_val[row]))
        return ("scalar", self.log.values[row])

    # -- reads (mirror core/document.py) ------------------------------------

    def object_type(self, obj: str) -> ObjType:
        return self._check_obj(self.log.import_id(obj))

    def keys(self, obj: str = "_root", heads=None) -> List[str]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        rows = view._obj_rows(ok)
        props = {
            int(view.log.prop[r])
            for r in rows
            if view.log.prop[r] >= 0 and view.winner[r] >= 0
        }
        return sorted(view.log.props[p] for p in props)

    def map_entries(self, obj: str = "_root", heads=None) -> List[Tuple[str, object, str]]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        best: Dict[int, int] = {}
        for r in view._obj_rows(ok):
            p = int(view.log.prop[r])
            if p >= 0 and view.winner[r] >= 0:
                best[p] = int(view.winner[r])
        out = [
            (
                view.log.props[p],
                view._render(w),
                view.log.export_id(int(view.log.id_key[w])),
            )
            for p, w in best.items()
        ]
        out.sort(key=lambda kv: kv[0])
        return out

    def _seq_elems(self, obj_key: int) -> List[Tuple[int, int]]:
        """Visible elements of a sequence: [(elem_row, winner_row)] in order."""
        return [
            (r, int(self.winner[r]))
            for r in self._all_elems(obj_key)
            if self.winner[r] >= 0
        ]

    def list_items(self, obj: str, heads=None) -> List[Tuple[object, str]]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        return [
            (view._render(w), view.log.export_id(int(view.log.id_key[w])))
            for _, w in view._seq_elems(ok)
        ]

    def text(self, obj: str, heads=None) -> str:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        parts = []
        for _, w in view._seq_elems(ok):
            v = view.log.values[w]
            parts.append(v.value if v.tag == "str" else _OBJ_REPLACEMENT)
        return "".join(parts)

    def length(self, obj: str = "_root", heads=None) -> int:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            return len(view.keys(obj))
        dense = int(np.searchsorted(view.log.obj_table, ok))
        if t == ObjType.TEXT:
            return int(view.res["obj_text_width"][dense])
        return int(view.res["obj_vis_len"][dense])

    def get_all(self, obj: str, prop, heads=None) -> List[Tuple[object, str]]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        rows = view._obj_rows(ok)
        if isinstance(prop, str):
            if t not in (ObjType.MAP, ObjType.TABLE):
                raise ValueError("map lookup requires a map object")
            try:
                p = view.log.props.index(prop)
            except ValueError:
                return []
            vis = [int(r) for r in rows if int(view.log.prop[r]) == p and view.visible[r]]
        else:
            elems = view._seq_elems(ok)
            if prop < 0:
                return []
            if t == ObjType.TEXT:
                # integer index is a character position: accumulate winner
                # widths, matching the host nth's width-aware semantics
                er = None
                at = 0
                for r, w in elems:
                    at += int(view.log.width[w])
                    if prop < at:
                        er = r
                        break
                if er is None:
                    return []
            else:
                if not 0 <= prop < len(elems):
                    return []
                er = elems[prop][0]
            vis = [
                int(r)
                for r in rows
                if view.visible[r]
                and (
                    (view.log.insert[r] and int(r) == er)
                    or (not view.log.insert[r] and int(view.log.elem_ref[r]) == er)
                )
            ]
        vis.sort()  # rows are in Lamport order; winner last
        return [
            (view._render(r), view.log.export_id(int(view.log.id_key[r])))
            for r in vis
        ]

    def get(self, obj: str, prop, heads=None):
        vals = self.get_all(obj, prop, heads)
        return vals[-1] if vals else None

    def map_range(self, obj: str = "_root", start=None, end=None, heads=None):
        """(key, value, id) for map keys in [start, end) (read.rs map_range)."""
        from ..utils.ranges import filter_map_range

        return filter_map_range(self.map_entries(obj, heads=heads), start, end)

    def list_range(self, obj: str, start: int = 0, end=None, heads=None):
        """(index, value, id) for indices in [start, end) (read.rs list_range).
        Renders only the requested rows of the materialized element order."""
        view = self._view(heads)
        ok = view.log.import_id(obj)
        view._check_obj(ok)
        elems = view._seq_elems(ok)
        stop = len(elems) if end is None else min(end, len(elems))
        return [
            (
                i,
                view._render(elems[i][1]),
                view.log.export_id(int(view.log.id_key[elems[i][1]])),
            )
            for i in range(max(start, 0), stop)
        ]

    def values(self, obj: str = "_root", heads=None):
        """Winner (value, id) pairs (read.rs values)."""
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            return [(val, vid) for _, val, vid in view.map_entries(obj)]
        return view.list_items(obj)

    def parents(self, obj: str, heads=None) -> List[Tuple[str, object]]:
        """Path from ``obj`` up to the root (read.rs parents/parents_at):
        walks the make ops' containing objects through the log columns,
        resolving sequence indices at the given heads."""
        view = self._view(heads)
        log = view.log
        key = log.import_id(obj)
        view._check_obj(key)
        path: List[Tuple[str, object]] = []
        while key != 0:
            row = log.row_of_id(key)
            parent_key = int(log.obj_key[row])
            parent_exid = log.export_id(parent_key)
            p = int(log.prop[row])
            if p >= 0:
                path.append((parent_exid, log.props[p]))
            else:
                # element ordinal among VISIBLE elements (1 each, matching
                # Document._elem_index); None when the element is invisible
                base = view._base
                er = row if log.insert[row] else int(log.elem_ref[row])
                view._check_obj(parent_key)
                idx = 0
                found = None
                for r in base._all_elems(parent_key):
                    visible = int(view.winner[r]) >= 0
                    if r == er:
                        found = idx if visible else None
                        break
                    if visible:
                        idx += 1
                path.append((parent_exid, found))
            key = parent_key
        return path

    # -- cursors (reference: cursor.rs, automerge.rs seek_opid) -------------

    def get_cursor(self, obj: str, position: int, heads=None) -> str:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            raise ValueError("cursors only apply to sequences")
        at = 0
        for r, w in view._seq_elems(ok):
            at += int(view.log.width[w]) if t == ObjType.TEXT else 1
            if position < at:
                return view.log.export_id(int(view.log.id_key[r]))
        raise ValueError(f"cursor position {position} out of bounds")

    def get_cursor_position(self, obj: str, cursor: str, heads=None) -> int:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            raise ValueError("cursors only apply to sequences")
        target = view.log.import_id(cursor)
        index = 0
        for r in view._all_elems(ok):
            if int(view.log.id_key[r]) == target:
                return index
            w = int(view.winner[r])
            if w >= 0:
                index += int(view.log.width[w]) if t == ObjType.TEXT else 1
        raise ValueError(f"cursor {cursor!r} not found in {obj!r}")

    # -- marks (reference: marks.rs MarkStateMachine, automerge.rs:1370) ----

    def marks(self, obj: str, heads=None) -> List[Mark]:
        view = self._view(heads)
        ok = view.log.import_id(obj)
        t = view._check_obj(ok)
        if t in (ObjType.MAP, ObjType.TABLE):
            raise ValueError("marks on a non-sequence object")
        log = view.log
        is_text = t == ObjType.TEXT
        open_marks: List[Tuple[int, str, object]] = []  # (begin id_key, name, value)
        index = 0
        spans: Dict[str, List[Mark]] = {}
        for r in view._all_elems(ok):
            if int(log.action[r]) == _MARK:
                # mark begin/end ops are covered-or-absent, never "visible"
                # (core/marks.py visible_or_mark)
                if not view.covered[r]:
                    continue
                mi = int(log.mark_name_idx[r])
                if mi >= 0:  # begin
                    open_marks.append(
                        (int(log.id_key[r]), log.mark_names[mi], log.values[r].to_py())
                    )
                    # packed id order == lamport order (rank = actor byte rank)
                    open_marks.sort()
                else:  # end: pairs with begin id (ctr-1, same actor)
                    begin = int(log.id_key[r]) - (1 << ACTOR_BITS)
                    open_marks = [e for e in open_marks if e[0] != begin]
                continue
            w = int(view.winner[r])
            if w < 0:
                continue
            width = int(log.width[w]) if is_text else 1
            current: Dict[str, object] = {}
            for _, name, value in open_marks:  # lamport-ascending: last wins
                current[name] = value
            for name, value in current.items():
                runs = spans.setdefault(name, [])
                if runs and runs[-1].end == index and runs[-1].value == value:
                    runs[-1].end = index + width
                else:
                    runs.append(Mark(index, index + width, name, value))
            index += width
        out = [
            m
            for runs in spans.values()
            for m in runs
            if m.value is not None  # null-valued spans are unmarks
        ]
        out.sort(key=lambda m: (m.start, m.name))
        return out

    # -- diff / patches -----------------------------------------------------

    def diff(self, before_heads, after_heads=None) -> List[Patch]:
        """Patches turning the state at ``before_heads`` into the state at
        ``after_heads`` (None = current). Same shape and ordering as the
        host differ; computed from two clock-masked kernel resolutions."""
        vb = self.at(before_heads if before_heads is not None else [])
        va = self._view(after_heads)
        patches: List[Patch] = []
        _diff_obj(vb, va, 0, [], patches)
        return patches

    def make_patches(self) -> List[Patch]:
        """Patches materializing the whole current state (applying them to
        an empty dict reproduces ``hydrate()`` — the current_state analogue,
        reference: automerge/current_state.rs)."""
        return self.diff([])

    # -- materialization ----------------------------------------------------

    def hydrate(self, obj: str = "_root", heads=None):
        view = self._view(heads)
        return view._hydrate(view.log.import_id(obj))

    def _hydrate(self, obj_key: int):
        t = self._check_obj(obj_key)
        if t in (ObjType.MAP, ObjType.TABLE):
            return {
                name: self._hydrate_val(val)
                for name, val, _ in self.map_entries(self.log.export_id(obj_key))
            }
        if t == ObjType.TEXT:
            return self.text(self.log.export_id(obj_key))
        return [
            self._hydrate_val(self._render(w)) for _, w in self._seq_elems(obj_key)
        ]

    def _hydrate_val(self, rendered):
        kind = rendered[0]
        if kind == "obj":
            return self._hydrate(self.log.import_id(rendered[2]))
        if kind == "counter":
            return rendered[1]
        return rendered[1].to_py()


# -- the device differ (mirrors patches/diff.py walk) ------------------------


def _patch_value(view: DeviceDoc, row: int):
    """Patch value of a winning op: hydrated subtree / counter / scalar."""
    a = int(view.log.action[row])
    if is_make_action(a):
        return view._hydrate(int(view.log.id_key[row]))
    if a == _PUT and int(view.log.value_tag[row]) == TAG_COUNTER:
        return int(view.counter_val[row])
    return view.log.values[row].to_py()


def _is_counter_row(log: OpLog, row: int) -> bool:
    return int(log.action[row]) == _PUT and int(log.value_tag[row]) == TAG_COUNTER


def _diff_obj(vb, va, obj_key, path, patches):
    t = va._check_obj(obj_key)
    exid = va.log.export_id(obj_key)
    if t in (ObjType.MAP, ObjType.TABLE):
        _diff_map(vb, va, obj_key, exid, path, patches)
    elif t == ObjType.TEXT:
        _diff_text(vb, va, obj_key, exid, path, patches)
    else:
        _diff_list(vb, va, obj_key, exid, path, patches)


def _diff_map(vb, va, obj_key, exid, path, patches):
    log = va.log
    groups: Dict[int, int] = {}  # prop -> representative row
    for r in va._obj_rows(obj_key):
        p = int(log.prop[r])
        if p >= 0 and p not in groups:
            groups[p] = int(r)
    for p in sorted(groups, key=lambda p: log.props[p]):
        rep = groups[p]
        key = log.props[p]
        wb = int(vb.winner[rep])
        wa = int(va.winner[rep])
        if wa < 0:
            if wb >= 0:
                patches.append(Patch(exid, list(path), DeleteMap(key)))
            continue
        conflict = int(va.conflicts[rep]) > 1
        if wb < 0 or wb != wa:
            patches.append(
                Patch(exid, list(path), PutMap(key, _patch_value(va, wa), conflict))
            )
        elif _is_counter_row(log, wa):
            delta = int(va.counter_val[wa]) - int(vb.counter_val[wa])
            if delta:
                patches.append(Patch(exid, list(path), IncrementPatch(key, delta)))
        elif conflict and int(vb.conflicts[rep]) <= 1:
            patches.append(Patch(exid, list(path), FlagConflict(key)))
        if is_make_action(int(log.action[wa])) and wb == wa:
            _diff_obj(
                vb, va, int(log.id_key[wa]), path + [(exid, key)], patches
            )


def _diff_list(vb, va, obj_key, exid, path, patches):
    log = va.log
    idx = 0
    pending_ins = None  # (index, [values])
    for r in va._all_elems(obj_key):
        wb = int(vb.winner[r])
        wa = int(va.winner[r])
        if wa < 0 and wb < 0:
            continue
        if wa >= 0 and wb < 0:
            if pending_ins is None:
                pending_ins = (idx, [])
            pending_ins[1].append(_patch_value(va, wa))
            idx += 1
            continue
        if pending_ins is not None:
            patches.append(Patch(exid, list(path), Insert(*pending_ins)))
            pending_ins = None
        if wa < 0:
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += 1
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx)))
            continue
        conflict = int(va.conflicts[r]) > 1
        if wb != wa:
            patches.append(
                Patch(exid, list(path), PutSeq(idx, _patch_value(va, wa), conflict))
            )
        elif _is_counter_row(log, wa):
            delta = int(va.counter_val[wa]) - int(vb.counter_val[wa])
            if delta:
                patches.append(Patch(exid, list(path), IncrementPatch(idx, delta)))
        elif conflict and int(vb.conflicts[r]) <= 1:
            patches.append(Patch(exid, list(path), FlagConflict(idx)))
        if is_make_action(int(log.action[wa])) and wb == wa:
            _diff_obj(vb, va, int(log.id_key[wa]), path + [(exid, idx)], patches)
        idx += 1
    if pending_ins is not None:
        patches.append(Patch(exid, list(path), Insert(*pending_ins)))


def _diff_text(vb, va, obj_key, exid, path, patches):
    log = va.log
    idx = 0
    pending = None  # [index, str] for inserts
    for r in va._all_elems(obj_key):
        wb = int(vb.winner[r])
        wa = int(va.winner[r])
        if wa < 0 and wb < 0:
            continue
        sa = _char(log, wa) if wa >= 0 else None
        sb = _char(log, wb) if wb >= 0 else None
        if wa >= 0 and wb < 0:
            if pending is None:
                pending = [idx, ""]
            pending[1] += sa
            idx += len(sa)
            continue
        if pending is not None:
            patches.append(Patch(exid, list(path), SpliceText(pending[0], pending[1])))
            pending = None
        if wa < 0:
            last = patches[-1] if patches else None
            if (
                last is not None
                and last.obj == exid
                and isinstance(last.action, DeleteSeq)
                and last.action.index == idx
            ):
                last.action.length += len(sb)
            else:
                patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            continue
        if wb != wa and (sa != sb):
            patches.append(Patch(exid, list(path), DeleteSeq(idx, len(sb))))
            patches.append(Patch(exid, list(path), SpliceText(idx, sa)))
        idx += len(sa)
    if pending is not None:
        patches.append(Patch(exid, list(path), SpliceText(pending[0], pending[1])))


def _char(log: OpLog, row: int) -> str:
    v = log.values[row]
    return v.value if v.tag == "str" else _OBJ_REPLACEMENT
