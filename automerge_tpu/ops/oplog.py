"""Columnar op-log: the device representation of a document's op set.

The reference's *storage* format (rust/automerge/src/storage/document/
doc_op_columns.rs — obj/key/id/insert/action/val/succ columns) is the
blueprint for this layout, not its in-memory B-tree: ops live as a
struct-of-arrays so an entire multi-replica merge is a handful of sorts,
scatters and segmented reductions on device (see ops/merge.py).

Lamport order (reference: types.rs:517-521) compares (counter, actor-bytes).
The host flattens changes, ranks actors by byte order, packs every OpId into
an int64 ``counter << ACTOR_BITS | actor_rank`` key, and **sorts the whole
log by that key once** — after which the row index itself is a dense int32
Lamport rank. All cross-op references (pred targets, RGA reference elements,
containing objects) are resolved to row indices host-side with vectorized
searchsorted, so the device kernel is pure int32: no 64-bit emulation on
TPU, no device-side joins, comparisons are plain row-index comparisons.
"""

from __future__ import annotations

import os
import warnings
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from ..storage.change import StoredChange
from ..types import ActorId, ScalarValue, str_width

# Up to 2^20 distinct actors per merged log; counters up to 2^43
# (single authority: types.ACTOR_BITS).
from ..types import ACTOR_BITS  # noqa: E402
ACTOR_MASK = (1 << ACTOR_BITS) - 1
PAD_ACTION = 15
# the make actions (object-creating ops; reference: types.rs action
# indices 0/2/4/6) — single authority for the columnar layers
MAKE_ACTIONS = (0, 2, 4, 6)

# elem_ref sentinels (column is an int32 row index otherwise)
ELEM_HEAD = -1  # insert at list HEAD
ELEM_MAP = -2  # a map op (no element reference)
ELEM_MISSING = -3  # reference element not in this log

# value_tag codes (aligned with storage value-metadata type codes where
# they exist; reference: value.rs ValueType)
TAG_NULL = 0
TAG_FALSE = 1
TAG_TRUE = 2
TAG_UINT = 3
TAG_INT = 4
TAG_F64 = 5
TAG_STR = 6
TAG_BYTES = 7
TAG_COUNTER = 8
TAG_TIMESTAMP = 9
TAG_UNKNOWN = 10

_TAG_FOR = {
    "null": TAG_NULL,
    "uint": TAG_UINT,
    "int": TAG_INT,
    "f64": TAG_F64,
    "str": TAG_STR,
    "bytes": TAG_BYTES,
    "counter": TAG_COUNTER,
    "timestamp": TAG_TIMESTAMP,
    "unknown": TAG_UNKNOWN,
}


def pack_id(ctr: int, rank: int) -> int:
    return (int(ctr) << ACTOR_BITS) | int(rank)


def unpack_id(key: int) -> Tuple[int, int]:
    return int(key) >> ACTOR_BITS, int(key) & ACTOR_MASK


class OpLog:
    """A merged, deduplicated change set flattened into Lamport-ordered
    op columns.

    Host-side (int64/object) state: ``id_key`` packed op ids, ``obj_key``
    packed object ids, the ``values`` heap, actor/prop tables. Device-facing
    int32 columns: action/insert/prop/value_tag/value_i32/width plus
    resolved references ``elem_ref``, ``obj_dense``, ``pred_src``/
    ``pred_tgt`` (see padded_columns).
    """

    __slots__ = (
        "actors",
        "props",
        "values",
        "changes",
        "mark_names",
        "n",
        "n_objs",
        "id_key",
        "obj_key",
        "obj_table",
        "obj_dense",
        "prop",
        "elem_ref",
        "action",
        "insert",
        "value_tag",
        "value_int",
        "width",
        "pred_src",
        "pred_tgt",
        "expand",
        "mark_name_idx",
        "_actor_order",
    )

    def __init__(self):
        self.actors: List[ActorId] = []
        self.props: List[str] = []
        self.values: List[ScalarValue] = []
        self.changes: List[StoredChange] = []
        self.mark_names: List[str] = []
        self.n = 0
        self.n_objs = 1
        self._actor_order = None

    # -- construction --------------------------------------------------

    @classmethod
    def from_changes(
        cls, changes: Iterable[StoredChange], fast: bool = None
    ) -> "OpLog":
        """Flatten changes (deduped by hash) into Lamport-ordered columns.

        Order-independent: visibility and RGA order depend only on op ids
        and pred links, never on application order — which is what makes the
        N-way fan-in merge a single batched kernel instead of the
        reference's per-op seek/insert loop (automerge.rs:1258-1280).

        ``fast`` selects the vectorized column extraction (native codecs,
        ops/extract.py); default: use it when available and every change
        retains its column bytes. Falls back to the per-op python path.
        """
        log = cls()
        seen = set()
        deduped: List[StoredChange] = []
        actor_bytes = set()
        for ch in changes:
            if ch.hash in seen:
                continue
            seen.add(ch.hash)
            deduped.append(ch)
            for a in ch.actors:
                actor_bytes.add(bytes(a))
        log.changes = deduped
        ranked = sorted(actor_bytes)
        rank_of = {a: i for i, a in enumerate(ranked)}
        log.actors = [ActorId(a) for a in ranked]
        if len(ranked) >= (1 << ACTOR_BITS):
            raise ValueError("too many actors for packed id encoding")

        if fast is None:
            from .. import native

            fast = native.available() and all(
                ch.op_col_data is not None or ch.cached_cols is not None
                for ch in deduped
            )
        if fast:
            from .. import native
            from .assemble import AssembleError, assemble_log
            from .extract import ExtractError

            try:
                return assemble_log(log, deduped, rank_of)
            except (
                AssembleError, ExtractError, native.NativeUnavailable,
                ValueError,
            ) as e:
                if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                    raise
                warnings.warn(
                    f"native log assembly failed ({e!r}); "
                    "falling back to the batch extraction path",
                    RuntimeWarning,
                    stacklevel=2,
                )
            try:
                return cls._collect_fast(log, deduped, rank_of)
            except (ExtractError, native.NativeUnavailable, ValueError) as e:
                if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                    raise
                warnings.warn(
                    f"vectorized op extraction failed ({e!r}); "
                    "falling back to the per-op path",
                    RuntimeWarning,
                    stacklevel=2,
                )
        return cls._collect_slow(log, deduped, rank_of)

    @classmethod
    def _collect_slow(cls, log, deduped, rank_of) -> "OpLog":
        prop_of: Dict[str, int] = {}
        mark_of: Dict[str, int] = {}
        id_key, obj, prop, elem = [], [], [], []
        action, insert, vtag, vint, width = [], [], [], [], []
        pred_src, pred_key = [], []
        expand, mark_idx = [], []
        values: List[ScalarValue] = []

        for ch in deduped:
            ranks = [rank_of[bytes(a)] for a in ch.actors]
            author = ranks[0]
            for i, cop in enumerate(ch.ops):
                row = len(id_key)
                id_key.append(pack_id(ch.start_op + i, author))
                if cop.obj[0] == 0:
                    obj.append(0)
                else:
                    obj.append(pack_id(cop.obj[0], ranks[cop.obj[1]]))
                if cop.key.prop is not None:
                    prop.append(prop_of.setdefault(cop.key.prop, len(prop_of)))
                    elem.append(-1)
                else:
                    e = cop.key.elem
                    prop.append(-1)
                    elem.append(0 if e[0] == 0 else pack_id(e[0], ranks[e[1]]))
                action.append(int(cop.action))
                insert.append(bool(cop.insert))
                v = cop.value
                vtag.append(_value_tag(v))
                vint.append(_int_payload(v))
                values.append(v)
                width.append(str_width(v.value) if v.tag == "str" else 1)
                for pc, pa in cop.pred:
                    pred_src.append(row)
                    pred_key.append(pack_id(pc, ranks[pa]))
                expand.append(bool(cop.expand))
                if cop.mark_name is not None:
                    mark_idx.append(mark_of.setdefault(cop.mark_name, len(mark_of)))
                else:
                    mark_idx.append(-1)

        log.props = [p for p, _ in sorted(prop_of.items(), key=lambda kv: kv[1])]
        log.mark_names = [m for m, _ in sorted(mark_of.items(), key=lambda kv: kv[1])]
        return cls._finalize(
            log,
            np.asarray(id_key, np.int64),
            np.asarray(obj, np.int64),
            np.asarray(prop, np.int32),
            np.asarray(elem, np.int64),
            np.asarray(action, np.int32),
            np.asarray(insert, np.bool_),
            np.asarray(vtag, np.int32),
            np.asarray(vint, np.int64),
            np.asarray(width, np.int32),
            np.asarray(expand, np.bool_),
            np.asarray(mark_idx, np.int32),
            np.asarray(pred_src, np.int64),
            np.asarray(pred_key, np.int64),
            values,
        )

    @classmethod
    def _collect_fast(cls, log, deduped, rank_of) -> "OpLog":
        """Batch-vectorized extraction: change column bytes -> numpy arrays.

        The native core decodes every change's op columns in one pass per
        column kind (native/extract_batch.cpp) — including string interning
        for map keys / mark names — then actor indices are rank-translated
        with a single table gather (extract.ranked_batch, shared with the
        host bulk rebuild) before the shared Lamport sort. No per-change
        Python or FFI work at all.
        """
        from .extract import ranked_batch

        r = ranked_batch(deduped, rank_of)
        a = r["a"]
        N = a["n"]
        mark_idx = (
            a["mark_ids"] if a["mark_ids"] is not None else np.full(N, -1, np.int32)
        )
        log.props = list(a["key_table"])
        log.mark_names = list(a["mark_table"])
        return cls._finalize(
            log,
            r["id_key"],
            r["obj"],
            r["prop_ids"].astype(np.int32),
            r["elem"],
            a["action"],
            a["insert"],
            np.minimum(a["vcode"], TAG_UNKNOWN).astype(np.int32),
            a["value_int"],
            a["width"],
            a["expand"],
            mark_idx.astype(np.int32),
            r["pred_src"],
            r["pred_key"],
            (a["vcode"], a["voff"], a["vlen"], a["vraw"]),
        )

    @classmethod
    def _finalize(
        cls,
        log,
        id_key,
        obj,
        prop,
        elem,
        action,
        insert,
        vtag,
        vint,
        width,
        expand,
        mark_idx,
        pred_src,
        pred_key,
        values,
    ) -> "OpLog":
        """Sort everything into Lamport order and resolve references."""
        n = len(id_key)
        log.n = n

        # one argsort makes row index == dense Lamport rank
        order = np.argsort(id_key, kind="stable")
        log.id_key = id_key[order]
        obj = np.asarray(obj, np.int64)[order]
        log.obj_key = obj
        log.prop = np.asarray(prop, np.int32)[order]
        elem = np.asarray(elem, np.int64)[order]
        log.action = np.asarray(action, np.int32)[order]
        log.insert = np.asarray(insert, np.bool_)[order]
        log.value_tag = np.asarray(vtag, np.int32)[order]
        log.value_int = np.asarray(vint, np.int64)[order]
        log.width = np.asarray(width, np.int32)[order]
        log.expand = np.asarray(expand, np.bool_)[order]
        log.mark_name_idx = np.asarray(mark_idx, np.int32)[order]
        if isinstance(values, tuple):  # lazy heap: (code, off, len, raw)
            from .extract import LazyValues

            code, off, ln, raw = values
            log.values = LazyValues(code[order], off[order], ln[order], raw)
        else:
            log.values = [values[i] for i in order]

        # resolve cross-op references to row indices (vectorized joins)
        inv = np.empty(n, np.int32)  # old row -> new row
        inv[order] = np.arange(n, dtype=np.int32)

        from .. import native

        if native.available():
            def rows_of(keys: np.ndarray, missing: int) -> np.ndarray:
                return native.join_rows(log.id_key, keys, missing)
        else:
            def rows_of(keys: np.ndarray, missing: int) -> np.ndarray:
                pos = np.searchsorted(log.id_key, keys)
                posc = np.clip(pos, 0, max(n - 1, 0)).astype(np.int32)
                hit = (log.id_key[posc] == keys) if n else np.zeros(len(keys), bool)
                return np.where(hit, posc, np.int32(missing)).astype(np.int32)

        # element references: HEAD=-1, map op=-2, missing=-3
        log.elem_ref = np.where(
            log.prop >= 0,
            np.int32(ELEM_MAP),
            np.where(elem == 0, np.int32(ELEM_HEAD), rows_of(elem, ELEM_MISSING)),
        ).astype(np.int32)

        # dense object ids: 0 = root, then by packed object id order.
        # Candidate ids come from the make ops (every object IS a make
        # op's id) — O(#objects log #objects) instead of np.unique's full
        # O(n log n) sort; a log whose ops reference objects with no make
        # op in it (partial histories) falls back to the exact unique.
        make_rows = np.flatnonzero(np.isin(log.action, MAKE_ACTIONS))
        cand = np.unique(np.concatenate([[0], log.id_key[make_rows]]))
        pos = np.searchsorted(cand, obj)
        posc = np.clip(pos, 0, len(cand) - 1)
        if np.all(cand[posc] == obj):
            log.obj_table = cand
            log.obj_dense = posc.astype(np.int32)
        else:
            # partial history: some referenced object has no make op here.
            # The table still UNIONS the make candidates so childless
            # objects resolve identically on both paths (consumers
            # searchsorted into obj_table without a membership check).
            log.obj_table = np.unique(np.concatenate([cand, obj]))
            log.obj_dense = np.searchsorted(log.obj_table, obj).astype(np.int32)
        log.n_objs = len(log.obj_table)

        # pred references -> (src row, tgt row) pairs
        pred_src = np.asarray(pred_src, np.int64)
        pred_key = np.asarray(pred_key, np.int64)
        log.pred_src = inv[pred_src] if len(pred_src) else np.empty(0, np.int32)
        tgt = rows_of(pred_key, -1) if len(pred_key) else np.empty(0, np.int32)
        log.pred_tgt = tgt.astype(np.int32)
        return log

    @classmethod
    def from_documents(cls, docs: Sequence) -> "OpLog":
        """Union of several documents' histories (the N-way fan-in input).

        AutoDocs are committed first — the device log is built from change
        history, so pending transaction ops would otherwise be silently
        absent (the reference's AutoCommit likewise commits at every
        save/merge/sync boundary, autocommit.rs:582)."""
        from ..types import using_text_encoding

        changes: List[StoredChange] = []
        encoding = None
        for d in docs:
            commit = getattr(d, "commit", None)
            if commit is not None:
                commit()
            doc = getattr(d, "doc", d)  # AutoDoc or Document
            if getattr(doc, "open_transactions", None):
                raise ValueError(
                    "document has an open manual transaction; commit or "
                    "roll it back before building a device log"
                )
            # None means "follow the process default" — resolve it before
            # comparing, else a default-encoding doc mixed with an
            # explicit-encoding doc slips past the check
            from ..types import get_text_encoding

            d_enc = getattr(doc, "text_encoding", None) or get_text_encoding()
            if encoding is None:
                encoding = d_enc
            elif d_enc != encoding:
                raise ValueError(
                    f"documents carry conflicting text encodings "
                    f"({encoding!r} vs {d_enc!r}); width columns would "
                    "silently disagree — re-encode one side first"
                )
            changes.extend(a.stored for a in doc.history)
        # width columns follow the documents' (verified-uniform) text
        # encoding; in the reference the unit is fixed per build
        with using_text_encoding(encoding):
            return cls.from_changes(changes)

    # -- device prep -----------------------------------------------------

    def columns(self, covered: np.ndarray = None, include_aorder: bool = False):
        """The device-facing column dict WITHOUT capacity padding — the
        host merge engine consumes it as-is (merge_columns pads lazily
        when it routes to the jit kernel, whose shapes must bucket).

        ``include_aorder`` attaches the compacted actor-order layout the
        condensed all-device kernel reads (bench/tests opt in; the default
        paths skip the extra device upload).
        """
        if covered is None:
            covered = np.ones(self.n, np.bool_)
        return {
            "action": self.action,
            "insert": np.asarray(self.insert, np.bool_),
            "prop": self.prop,
            "elem_ref": self.elem_ref,
            "obj_dense": self.obj_dense,
            "value_tag": self.value_tag,
            "value_i32": self.value_int.astype(np.int32),
            "width": self.width,
            "covered": np.asarray(covered, np.bool_),
            "pred_src": self.pred_src,
            "pred_tgt": self.pred_tgt,
            **({"aorder": self.actor_order()} if include_aorder else {}),
        }

    def padded_columns(self, min_capacity: int = 16, covered: np.ndarray = None,
                       include_aorder: bool = False):
        """Pad to power-of-two capacities for shape-stable jit.

        Everything is int32/bool — deliberately: int64 is emulated on TPU.
        Counter payloads are truncated to int32 on device (exact int64
        totals are recovered host-side from ``value_int`` when needed).

        ``covered`` is the per-row clock mask for historical reads
        (default: every op covered — the current-state resolution).
        """
        return pad_columns(
            self.columns(covered=covered, include_aorder=include_aorder),
            self.n_objs, min_capacity,
        )

    def actor_order(self) -> np.ndarray:
        """INSERT rows in ACTOR-CONCATENATED order: each actor's element
        ops consecutive, counters ascending. In this order a typing chain
        is a contiguous stretch (the per-op RGA references point at the
        author's previous op), which is what lets the condensed device
        linearization find chains with scans instead of pointer-chasing
        (ops/merge.device_linearize_condensed)."""
        ao = self._actor_order
        if ao is None:
            rank = (self.id_key & ACTOR_MASK).astype(np.int64)
            perm = np.argsort(rank, kind="stable").astype(np.int32)
            ao = perm[np.asarray(self.insert, bool)[perm]]
            self._actor_order = ao
        return ao

    def condensed_run_count(self) -> int:
        """Exact chain-run count of device_linearize_condensed, computed
        host-side with vector passes — picks the kernel's rcap bucket."""
        n = self.n
        if n == 0:
            return 1
        ins = np.asarray(self.insert, bool)
        er = self.elem_ref
        rows = np.arange(n, dtype=np.int64)
        # first_child[p] = LAST insert row referencing p (ascending
        # prepend: later rows shadow earlier, fancy assignment keeps the
        # last write)
        fc = np.full(n, -1, np.int64)
        em = ins & (er >= 0)
        fc[er[em]] = rows[em]
        erc = np.clip(er, 0, n - 1)
        is_cont = em & (fc[erc] == rows)
        vs = self.actor_order()
        prev = np.concatenate([[-9], vs[:-1]])
        cont = is_cont[vs] & (er[vs] == prev)
        return max(int((~cont).sum()), 1)

    def covered_mask(self, clock_max_op: np.ndarray) -> np.ndarray:
        """Vectorized ``Clock::covers`` (reference: clock.rs:71-77): row i is
        covered iff its counter <= clock_max_op[actor rank]. ``clock_max_op``
        is the dense per-rank max-op vector (0 = actor not in clock)."""
        ctr = self.id_key >> ACTOR_BITS
        rank = (self.id_key & ACTOR_MASK).astype(np.int64)
        return ctr <= np.asarray(clock_max_op, np.int64)[rank]

    # -- host-side id helpers ---------------------------------------------

    def export_id(self, key: int) -> str:
        if key == 0:
            return "_root"
        ctr, rank = unpack_id(key)
        return f"{ctr}@{self.actors[rank].to_hex()}"

    def import_id(self, exid: str) -> int:
        if exid == "_root":
            return 0
        ctr_s, actor_hex = exid.split("@", 1)
        target = bytes.fromhex(actor_hex)
        for rank, a in enumerate(self.actors):
            if a.bytes == target:
                return pack_id(int(ctr_s), rank)
        raise KeyError(f"unknown actor in id {exid!r}")

    def row_of_id(self, key: int) -> int:
        pos = int(np.searchsorted(self.id_key, key))
        if pos < self.n and self.id_key[pos] == key:
            return pos
        raise KeyError(f"no op with id {self.export_id(key)}")


def _value_tag(v: ScalarValue) -> int:
    if v.tag == "bool":
        return TAG_TRUE if v.value else TAG_FALSE
    return _TAG_FOR.get(v.tag, TAG_UNKNOWN)


def _int_payload(v: ScalarValue) -> int:
    if v.tag in ("int", "uint", "counter", "timestamp"):
        return int(v.value)
    if v.tag == "bool":
        return int(v.value)
    return 0


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _capacity(n: int, minimum: int = 16) -> int:
    """Jit-bucket capacity: powers of two up to 8k, then multiples of 8k —
    snug enough that padded work stays within ~12% of the real row count."""
    n = max(n, minimum)
    if n <= 8192:
        return _next_pow2(n)
    return ((n + 8191) // 8192) * 8192


def _pad(a: np.ndarray, size: int, fill) -> np.ndarray:
    if len(a) == size:
        return a
    out = np.full(size, fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


def pad_columns(cols, n_objs: int, min_capacity: int = 16):
    """Pad a columns() dict to jit-bucket capacities (idempotent: already
    bucket-sized arrays pass through untouched)."""
    p = _capacity(len(cols["action"]), min_capacity)
    q = _capacity(len(cols["pred_src"]), min_capacity)
    fills = {
        "action": PAD_ACTION,
        "insert": False,
        "prop": -1,
        "elem_ref": ELEM_MAP,
        "obj_dense": np.int32(n_objs),
        "value_tag": TAG_NULL,
        "value_i32": 0,
        "width": 0,
        "covered": False,
        "pred_src": 0,
        "pred_tgt": -1,
        # compacted element order: pad slots carry the out-of-range
        # sentinel p (the kernel tests "slot < P" for validity)
        "aorder": p,
    }
    return {
        k: _pad(
            np.asarray(v),
            q if k.startswith("pred_") else p,
            fills.get(k, 0),
        )
        for k, v in cols.items()
    }


def host_forest(cols_np):
    """Sibling forest (is_elem, parent_row, first_child, next_sib) from
    numpy columns — the host mirror of ops/merge.py forest(). Children
    order is descending row (= descending Lamport, query/insert.rs),
    built with one lexsort."""
    action = np.asarray(cols_np["action"])
    P = len(action)
    insert = np.asarray(cols_np["insert"]).astype(bool) & (action != PAD_ACTION)
    elem_ref = np.asarray(cols_np["elem_ref"])
    obj_dense = np.asarray(cols_np["obj_dense"])
    N = 2 * P + 3
    S = N - 1
    parent_row = np.where(
        insert,
        np.where(
            elem_ref == ELEM_HEAD,
            P + obj_dense,
            np.where(elem_ref >= 0, elem_ref, S),
        ),
        S,
    ).astype(np.int32)
    er = np.flatnonzero(insert).astype(np.int32)
    order = np.lexsort((-er, parent_row[er]))
    sp = parent_row[er][order]
    sr = er[order]
    first_child = np.full(N, -1, np.int32)
    next_sib = np.full(N, -1, np.int32)
    if len(sr):
        first = np.concatenate([[True], sp[1:] != sp[:-1]])
        first_child[sp[first]] = sr[first]
        same = np.concatenate([sp[1:] == sp[:-1], [False]])
        nxt = np.concatenate([sr[1:], np.array([-1], np.int32)])
        next_sib[sr] = np.where(same, nxt, -1)
    return insert, parent_row, first_child, next_sib


def host_linearize(cols_np) -> np.ndarray:
    """Document-order element indices computed host-side from the numpy
    columns, overlapping the device kernel.

    Element order depends ONLY on the insert forest (elem_ref / insert /
    obj_dense) — never on visibility (historical views of one log share
    one element order) — so the host can rank it from the same arrays it
    just uploaded, with zero extra device traffic: a lexsort builds the
    sibling lists and the native preorder walk ranks them.
    """
    from .. import native

    insert, parent_row, first_child, next_sib = host_forest(cols_np)
    P = len(insert)
    elem_index = native.preorder_index(first_child, next_sib, parent_row, P)
    return np.where(insert, elem_index, np.int32(-1))
