"""Columnar op-log: the device representation of a document's op set.

The reference's *storage* format (rust/automerge/src/storage/document/
doc_op_columns.rs — obj/key/id/insert/action/val/succ columns) is the
blueprint for this layout, not its in-memory B-tree: ops live as a
struct-of-arrays so an entire multi-replica merge is a handful of sorts,
scatters and segmented reductions on device (see ops/merge.py).

Lamport order (reference: types.rs:517-521) compares (counter, actor-bytes).
The host flattens changes, ranks actors by byte order, packs every OpId into
an int64 ``counter << ACTOR_BITS | actor_rank`` key, and **sorts the whole
log by that key once** — after which the row index itself is a dense int32
Lamport rank. All cross-op references (pred targets, RGA reference elements,
containing objects) are resolved to row indices host-side with vectorized
searchsorted, so the device kernel is pure int32: no 64-bit emulation on
TPU, no device-side joins, comparisons are plain row-index comparisons.
"""

from __future__ import annotations

import os
import warnings
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from ..storage.change import StoredChange
from ..types import ActorId, ScalarValue, str_width

# Up to 2^20 distinct actors per merged log; counters up to 2^43
# (single authority: types.ACTOR_BITS).
from ..types import ACTOR_BITS  # noqa: E402
ACTOR_MASK = (1 << ACTOR_BITS) - 1
PAD_ACTION = 15
# the make actions (object-creating ops; reference: types.rs action
# indices 0/2/4/6) — single authority for the columnar layers
MAKE_ACTIONS = (0, 2, 4, 6)

# elem_ref sentinels (column is an int32 row index otherwise)
ELEM_HEAD = -1  # insert at list HEAD
ELEM_MAP = -2  # a map op (no element reference)
ELEM_MISSING = -3  # reference element not in this log

# value_tag codes (aligned with storage value-metadata type codes where
# they exist; reference: value.rs ValueType)
TAG_NULL = 0
TAG_FALSE = 1
TAG_TRUE = 2
TAG_UINT = 3
TAG_INT = 4
TAG_F64 = 5
TAG_STR = 6
TAG_BYTES = 7
TAG_COUNTER = 8
TAG_TIMESTAMP = 9
TAG_UNKNOWN = 10

_TAG_FOR = {
    "null": TAG_NULL,
    "uint": TAG_UINT,
    "int": TAG_INT,
    "f64": TAG_F64,
    "str": TAG_STR,
    "bytes": TAG_BYTES,
    "counter": TAG_COUNTER,
    "timestamp": TAG_TIMESTAMP,
    "unknown": TAG_UNKNOWN,
}


def join_rows(sorted_keys: np.ndarray, keys, missing: int) -> np.ndarray:
    """Row indices of ``keys`` in the sorted packed-id column
    ``sorted_keys`` (``missing`` for absent keys) — the one vectorized
    id->row join shared by log finalize, the incremental append path and
    the cross-doc host staging (ops/host_batch.py)."""
    from .. import native

    keys = np.asarray(keys, np.int64)
    if native.available():
        return native.join_rows(sorted_keys, keys, missing)
    n = len(sorted_keys)
    pos = np.searchsorted(sorted_keys, keys)
    posc = np.clip(pos, 0, max(n - 1, 0)).astype(np.int32)
    hit = (sorted_keys[posc] == keys) if n else np.zeros(len(keys), bool)
    return np.where(hit, posc, np.int32(missing)).astype(np.int32)


def pack_id(ctr: int, rank: int) -> int:
    return (int(ctr) << ACTOR_BITS) | int(rank)


def unpack_id(key: int) -> Tuple[int, int]:
    return int(key) >> ACTOR_BITS, int(key) & ACTOR_MASK


class OpLog:
    """A merged, deduplicated change set flattened into Lamport-ordered
    op columns.

    Host-side (int64/object) state: ``id_key`` packed op ids, ``obj_key``
    packed object ids, the ``values`` heap, actor/prop tables. Device-facing
    int32 columns: action/insert/prop/value_tag/value_i32/width plus
    resolved references ``elem_ref``, ``obj_dense``, ``pred_src``/
    ``pred_tgt`` (see padded_columns).
    """

    __slots__ = (
        "actors",
        "props",
        "values",
        "changes",
        "mark_names",
        "n",
        "n_objs",
        "id_key",
        "obj_key",
        "obj_table",
        "obj_dense",
        "prop",
        "elem_ref",
        "action",
        "insert",
        "value_tag",
        "value_int",
        "width",
        "pred_src",
        "pred_tgt",
        "expand",
        "mark_name_idx",
        "elem_key",
        "pred_key",
        "n_miss_elem",
        "n_miss_pred",
        "_actor_order",
        "_hash_set",
        "_bufs",
        "_comp",
    )

    def __init__(self):
        self.actors: List[ActorId] = []
        self.props: List[str] = []
        self.values: List[ScalarValue] = []
        self.changes: List[StoredChange] = []
        self.mark_names: List[str] = []
        self.n = 0
        self.n_objs = 1
        self.elem_key = None
        self.pred_key = None
        # unresolved-reference counts (elem_ref == ELEM_MISSING rows /
        # pred_tgt < 0 edges), maintained across appends: the cross-doc
        # host staging fast path is only sound when there is nothing to
        # re-resolve, and a full-column scan per drain to find that out
        # would cost O(resident) per document
        self.n_miss_elem = 0
        self.n_miss_pred = 0
        self._actor_order = None
        self._hash_set = None
        self._bufs = None
        # the incrementally-maintained compressed column image
        # (ops/compressed.py); None = stale/absent, rebuilt lazily
        self._comp = None

    # -- construction --------------------------------------------------

    @classmethod
    def from_changes(
        cls, changes: Iterable[StoredChange], fast: bool = None
    ) -> "OpLog":
        """Flatten changes (deduped by hash) into Lamport-ordered columns.

        Order-independent: visibility and RGA order depend only on op ids
        and pred links, never on application order — which is what makes the
        N-way fan-in merge a single batched kernel instead of the
        reference's per-op seek/insert loop (automerge.rs:1258-1280).

        ``fast`` selects the vectorized column extraction (native codecs,
        ops/extract.py); default: use it when available and every change
        retains its column bytes. Falls back to the per-op python path.
        """
        log = cls()
        seen = set()
        deduped: List[StoredChange] = []
        actor_bytes = set()
        for ch in changes:
            if ch.hash in seen:
                continue
            seen.add(ch.hash)
            deduped.append(ch)
            for a in ch.actors:
                actor_bytes.add(bytes(a))
        log.changes = deduped
        ranked = sorted(actor_bytes)
        rank_of = {a: i for i, a in enumerate(ranked)}
        log.actors = [ActorId(a) for a in ranked]
        if len(ranked) >= (1 << ACTOR_BITS):
            raise ValueError("too many actors for packed id encoding")

        if fast is None:
            from .. import native

            fast = native.available() and all(
                ch.op_col_data is not None or ch.cached_cols is not None
                for ch in deduped
            )
        from .. import obs

        if fast:
            from .. import native
            from .assemble import AssembleError, assemble_log
            from .extract import ExtractError

            try:
                with obs.span("device.extract", changes=len(deduped)):
                    return assemble_log(log, deduped, rank_of)
            except (
                AssembleError, ExtractError, native.NativeUnavailable,
                ValueError,
            ) as e:
                if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                    raise
                warnings.warn(
                    f"native log assembly failed ({e!r}); "
                    "falling back to the batch extraction path",
                    RuntimeWarning,
                    stacklevel=2,
                )
            try:
                with obs.span("device.extract", changes=len(deduped)):
                    return cls._collect_fast(log, deduped, rank_of)
            except (ExtractError, native.NativeUnavailable, ValueError) as e:
                if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                    raise
                warnings.warn(
                    f"vectorized op extraction failed ({e!r}); "
                    "falling back to the per-op path",
                    RuntimeWarning,
                    stacklevel=2,
                )
        with obs.span("device.extract", changes=len(deduped)):
            return cls._collect_slow(log, deduped, rank_of)

    @classmethod
    def _collect_slow(cls, log, deduped, rank_of) -> "OpLog":
        prop_of: Dict[str, int] = {}
        mark_of: Dict[str, int] = {}
        id_key, obj, prop, elem = [], [], [], []
        action, insert, vtag, vint, width = [], [], [], [], []
        pred_src, pred_key = [], []
        expand, mark_idx = [], []
        values: List[ScalarValue] = []

        for ch in deduped:
            ranks = [rank_of[bytes(a)] for a in ch.actors]
            author = ranks[0]
            for i, cop in enumerate(ch.ops):
                row = len(id_key)
                id_key.append(pack_id(ch.start_op + i, author))
                if cop.obj[0] == 0:
                    obj.append(0)
                else:
                    obj.append(pack_id(cop.obj[0], ranks[cop.obj[1]]))
                if cop.key.prop is not None:
                    prop.append(prop_of.setdefault(cop.key.prop, len(prop_of)))
                    elem.append(-1)
                else:
                    e = cop.key.elem
                    prop.append(-1)
                    elem.append(0 if e[0] == 0 else pack_id(e[0], ranks[e[1]]))
                action.append(int(cop.action))
                insert.append(bool(cop.insert))
                v = cop.value
                vtag.append(_value_tag(v))
                vint.append(_int_payload(v))
                values.append(v)
                width.append(str_width(v.value) if v.tag == "str" else 1)
                for pc, pa in cop.pred:
                    pred_src.append(row)
                    pred_key.append(pack_id(pc, ranks[pa]))
                expand.append(bool(cop.expand))
                if cop.mark_name is not None:
                    mark_idx.append(mark_of.setdefault(cop.mark_name, len(mark_of)))
                else:
                    mark_idx.append(-1)

        log.props = [p for p, _ in sorted(prop_of.items(), key=lambda kv: kv[1])]
        log.mark_names = [m for m, _ in sorted(mark_of.items(), key=lambda kv: kv[1])]
        return cls._finalize(
            log,
            np.asarray(id_key, np.int64),
            np.asarray(obj, np.int64),
            np.asarray(prop, np.int32),
            np.asarray(elem, np.int64),
            np.asarray(action, np.int32),
            np.asarray(insert, np.bool_),
            np.asarray(vtag, np.int32),
            np.asarray(vint, np.int64),
            np.asarray(width, np.int32),
            np.asarray(expand, np.bool_),
            np.asarray(mark_idx, np.int32),
            np.asarray(pred_src, np.int64),
            np.asarray(pred_key, np.int64),
            values,
        )

    @classmethod
    def _collect_fast(cls, log, deduped, rank_of) -> "OpLog":
        """Batch-vectorized extraction: change column bytes -> numpy arrays.

        The native core decodes every change's op columns in one pass per
        column kind (native/extract_batch.cpp) — including string interning
        for map keys / mark names — then actor indices are rank-translated
        with a single table gather (extract.ranked_batch, shared with the
        host bulk rebuild) before the shared Lamport sort. No per-change
        Python or FFI work at all.
        """
        from .extract import ranked_batch

        r = ranked_batch(deduped, rank_of)
        a = r["a"]
        N = a["n"]
        mark_idx = (
            a["mark_ids"] if a["mark_ids"] is not None else np.full(N, -1, np.int32)
        )
        log.props = list(a["key_table"])
        log.mark_names = list(a["mark_table"])
        return cls._finalize(
            log,
            r["id_key"],
            r["obj"],
            r["prop_ids"].astype(np.int32),
            r["elem"],
            a["action"],
            a["insert"],
            np.minimum(a["vcode"], TAG_UNKNOWN).astype(np.int32),
            a["value_int"],
            a["width"],
            a["expand"],
            mark_idx.astype(np.int32),
            r["pred_src"],
            r["pred_key"],
            (a["vcode"], a["voff"], a["vlen"], a["vraw"]),
        )

    @classmethod
    def _finalize(
        cls,
        log,
        id_key,
        obj,
        prop,
        elem,
        action,
        insert,
        vtag,
        vint,
        width,
        expand,
        mark_idx,
        pred_src,
        pred_key,
        values,
    ) -> "OpLog":
        """Sort everything into Lamport order and resolve references."""
        n = len(id_key)
        log.n = n

        # one argsort makes row index == dense Lamport rank
        order = np.argsort(id_key, kind="stable")
        log.id_key = id_key[order]
        obj = np.asarray(obj, np.int64)[order]
        log.obj_key = obj
        log.prop = np.asarray(prop, np.int32)[order]
        elem = np.asarray(elem, np.int64)[order]
        log.action = np.asarray(action, np.int32)[order]
        log.insert = np.asarray(insert, np.bool_)[order]
        log.value_tag = np.asarray(vtag, np.int32)[order]
        log.value_int = np.asarray(vint, np.int64)[order]
        log.width = np.asarray(width, np.int32)[order]
        log.expand = np.asarray(expand, np.bool_)[order]
        log.mark_name_idx = np.asarray(mark_idx, np.int32)[order]
        if isinstance(values, tuple):  # lazy heap: (code, off, len, raw)
            from .extract import LazyValues

            code, off, ln, raw = values
            log.values = LazyValues(code[order], off[order], ln[order], raw)
        else:
            log.values = [values[i] for i in order]

        # resolve cross-op references to row indices (vectorized joins)
        inv = np.empty(n, np.int32)  # old row -> new row
        inv[order] = np.arange(n, dtype=np.int32)

        def rows_of(keys: np.ndarray, missing: int) -> np.ndarray:
            return join_rows(log.id_key, keys, missing)

        # element references: HEAD=-1, map op=-2, missing=-3
        log.elem_ref = np.where(
            log.prop >= 0,
            np.int32(ELEM_MAP),
            np.where(elem == 0, np.int32(ELEM_HEAD), rows_of(elem, ELEM_MISSING)),
        ).astype(np.int32)

        # dense object ids: 0 = root, then by packed object id order.
        # Candidate ids come from the make ops (every object IS a make
        # op's id) — O(#objects log #objects) instead of np.unique's full
        # O(n log n) sort; a log whose ops reference objects with no make
        # op in it (partial histories) falls back to the exact unique.
        make_rows = np.flatnonzero(np.isin(log.action, MAKE_ACTIONS))
        cand = np.unique(np.concatenate([[0], log.id_key[make_rows]]))
        pos = np.searchsorted(cand, obj)
        posc = np.clip(pos, 0, len(cand) - 1)
        if np.all(cand[posc] == obj):
            log.obj_table = cand
            log.obj_dense = posc.astype(np.int32)
        else:
            # partial history: some referenced object has no make op here.
            # The table still UNIONS the make candidates so childless
            # objects resolve identically on both paths (consumers
            # searchsorted into obj_table without a membership check).
            log.obj_table = np.unique(np.concatenate([cand, obj]))
            log.obj_dense = np.searchsorted(log.obj_table, obj).astype(np.int32)
        log.n_objs = len(log.obj_table)

        # pred references -> (src row, tgt row) pairs
        pred_src = np.asarray(pred_src, np.int64)
        pred_key = np.asarray(pred_key, np.int64)
        log.pred_src = inv[pred_src] if len(pred_src) else np.empty(0, np.int32)
        tgt = rows_of(pred_key, -1) if len(pred_key) else np.empty(0, np.int32)
        log.pred_tgt = tgt.astype(np.int32)
        # packed reference keys retained for the incremental append path
        # (re-resolving MISSING refs when the referenced op arrives later)
        log.elem_key = elem
        log.pred_key = pred_key
        log.n_miss_elem = int(np.count_nonzero(log.elem_ref == ELEM_MISSING))
        log.n_miss_pred = int(np.count_nonzero(log.pred_tgt < 0))
        return log

    @classmethod
    def from_documents(cls, docs: Sequence) -> "OpLog":
        """Union of several documents' histories (the N-way fan-in input).

        AutoDocs are committed first — the device log is built from change
        history, so pending transaction ops would otherwise be silently
        absent (the reference's AutoCommit likewise commits at every
        save/merge/sync boundary, autocommit.rs:582)."""
        from ..types import using_text_encoding

        changes: List[StoredChange] = []
        encoding = None
        for d in docs:
            commit = getattr(d, "commit", None)
            if commit is not None:
                commit()
            doc = getattr(d, "doc", d)  # AutoDoc or Document
            if getattr(doc, "open_transactions", None):
                raise ValueError(
                    "document has an open manual transaction; commit or "
                    "roll it back before building a device log"
                )
            # None means "follow the process default" — resolve it before
            # comparing, else a default-encoding doc mixed with an
            # explicit-encoding doc slips past the check
            from ..types import get_text_encoding

            d_enc = getattr(doc, "text_encoding", None) or get_text_encoding()
            if encoding is None:
                encoding = d_enc
            elif d_enc != encoding:
                raise ValueError(
                    f"documents carry conflicting text encodings "
                    f"({encoding!r} vs {d_enc!r}); width columns would "
                    "silently disagree — re-encode one side first"
                )
            changes.extend(a.stored for a in doc.history)
        # width columns follow the documents' (verified-uniform) text
        # encoding; in the reference the unit is fixed per build
        with using_text_encoding(encoding):
            return cls.from_changes(changes)

    # -- device prep -----------------------------------------------------

    def columns(self, covered: np.ndarray = None, include_aorder: bool = False):
        """The device-facing column dict WITHOUT capacity padding — the
        host merge engine consumes it as-is (merge_columns pads lazily
        when it routes to the jit kernel, whose shapes must bucket).

        ``include_aorder`` attaches the compacted actor-order layout the
        condensed all-device kernel reads (bench/tests opt in; the default
        paths skip the extra device upload).
        """
        if covered is None:
            covered = np.ones(self.n, np.bool_)
        return {
            "action": self.action,
            "insert": np.asarray(self.insert, np.bool_),
            "prop": self.prop,
            "elem_ref": self.elem_ref,
            "obj_dense": self.obj_dense,
            "value_tag": self.value_tag,
            "value_i32": self.value_int.astype(np.int32),
            "width": self.width,
            "covered": np.asarray(covered, np.bool_),
            "pred_src": self.pred_src,
            "pred_tgt": self.pred_tgt,
            **({"aorder": self.actor_order()} if include_aorder else {}),
        }

    def padded_columns(self, min_capacity: int = 16, covered: np.ndarray = None,
                       include_aorder: bool = False):
        """Pad to power-of-two capacities for shape-stable jit.

        Everything is int32/bool — deliberately: int64 is emulated on TPU.
        Counter payloads are truncated to int32 on device (exact int64
        totals are recovered host-side from ``value_int`` when needed).

        ``covered`` is the per-row clock mask for historical reads
        (default: every op covered — the current-state resolution).
        """
        return pad_columns(
            self.columns(covered=covered, include_aorder=include_aorder),
            self.n_objs, min_capacity,
        )

    def actor_order(self) -> np.ndarray:
        """INSERT rows in ACTOR-CONCATENATED order: each actor's element
        ops consecutive, counters ascending. In this order a typing chain
        is a contiguous stretch (the per-op RGA references point at the
        author's previous op), which is what lets the condensed device
        linearization find chains with scans instead of pointer-chasing
        (ops/merge.device_linearize_condensed)."""
        ao = self._actor_order
        if ao is None:
            rank = (self.id_key & ACTOR_MASK).astype(np.int64)
            perm = np.argsort(rank, kind="stable").astype(np.int32)
            ao = perm[np.asarray(self.insert, bool)[perm]]
            self._actor_order = ao
        return ao

    def condensed_run_count(self) -> int:
        """Exact chain-run count of device_linearize_condensed, computed
        host-side with vector passes — picks the kernel's rcap bucket."""
        n = self.n
        if n == 0:
            return 1
        ins = np.asarray(self.insert, bool)
        er = self.elem_ref
        rows = np.arange(n, dtype=np.int64)
        # first_child[p] = LAST insert row referencing p (ascending
        # prepend: later rows shadow earlier, fancy assignment keeps the
        # last write)
        fc = np.full(n, -1, np.int64)
        em = ins & (er >= 0)
        fc[er[em]] = rows[em]
        erc = np.clip(er, 0, n - 1)
        is_cont = em & (fc[erc] == rows)
        vs = self.actor_order()
        prev = np.concatenate([[-9], vs[:-1]])
        cont = is_cont[vs] & (er[vs] == prev)
        return max(int((~cont).sum()), 1)

    def covered_mask(self, clock_max_op: np.ndarray) -> np.ndarray:
        """Vectorized ``Clock::covers`` (reference: clock.rs:71-77): row i is
        covered iff its counter <= clock_max_op[actor rank]. ``clock_max_op``
        is the dense per-rank max-op vector (0 = actor not in clock)."""
        ctr = self.id_key >> ACTOR_BITS
        rank = (self.id_key & ACTOR_MASK).astype(np.int64)
        return ctr <= np.asarray(clock_max_op, np.int64)[rank]

    # -- compressed residency (ops/compressed.py) ---------------------------

    def compressed(self, sync: bool = True):
        """The compressed image of the resident columns, or None when
        ``AUTOMERGE_TPU_COMPRESSED=0``. Maintained incrementally: tail
        appends extend the last runs; prefix rewrites invalidate and the
        next call re-encodes lazily."""
        from . import compressed as C

        if not C.enabled():
            return None
        if self._comp is None:
            self._comp = C.CompressedOpColumns()
        if sync:
            self._comp.sync(self)
        return self._comp

    def dense_column_nbytes(self) -> int:
        """Dense-equivalent footprint of the resident column set (what
        the pre-compression representation held per doc). Columns not
        materialized yet (``elem_key``/``pred_key`` on assembler-built
        logs) count zero on BOTH sides of the ratio — phantom bytes in
        the numerator would inflate ``compress_ratio`` and overcharge
        the dense-mode admission estimate."""
        from . import compressed as C

        q = len(self.pred_src)
        return sum(
            self.n * item
            for name, _, item in C.ROW_SPEC
            if getattr(self, name) is not None
        ) + sum(
            q * item
            for name, _, item in C.EDGE_SPEC
            if getattr(self, name) is not None
        )

    def resident_column_nbytes(self) -> int:
        """True resident bytes of the column set under the active mode
        (compressed runs where the ratio gate admits them, dense
        otherwise)."""
        comp = self.compressed()
        if comp is None:
            return self.dense_column_nbytes()
        return comp.nbytes(self)

    def compress_ratio(self) -> float:
        comp = self.compressed()
        if comp is None:
            return 1.0
        return comp.ratio(self)

    # -- host-side id helpers ---------------------------------------------

    def export_id(self, key: int) -> str:
        if key == 0:
            return "_root"
        ctr, rank = unpack_id(key)
        return f"{ctr}@{self.actors[rank].to_hex()}"

    def import_id(self, exid: str) -> int:
        if exid == "_root":
            return 0
        ctr_s, actor_hex = exid.split("@", 1)
        target = bytes.fromhex(actor_hex)
        for rank, a in enumerate(self.actors):
            if a.bytes == target:
                return pack_id(int(ctr_s), rank)
        raise KeyError(f"unknown actor in id {exid!r}")

    def row_of_id(self, key: int) -> int:
        pos = int(np.searchsorted(self.id_key, key))
        if pos < self.n and self.id_key[pos] == key:
            return pos
        raise KeyError(f"no op with id {self.export_id(key)}")

    # -- incremental append -------------------------------------------------

    def hashes(self) -> set:
        hs = self._hash_set
        if hs is None:
            hs = self._hash_set = {ch.hash for ch in self.changes}
        return hs

    def _ensure_ref_keys(self) -> bool:
        """Materialize the packed reference-key columns (``elem_key`` per
        row, ``pred_key`` per edge) the append path splices and re-resolves.
        Logs built by ``_finalize`` carry them; assembler-built logs
        reconstruct them from the resolved row refs — impossible only when
        a ref is MISSING (partial history), in which case the caller falls
        back to a full rebuild."""
        if self.elem_key is None:
            er = self.elem_ref
            if self.n and np.any(er == ELEM_MISSING):
                return False
            safe = np.clip(er, 0, max(self.n - 1, 0))
            self.elem_key = np.where(
                er == ELEM_MAP,
                np.int64(-1),
                np.where(er == ELEM_HEAD, np.int64(0), self.id_key[safe]),
            ).astype(np.int64)
        if self.pred_key is None:
            if len(self.pred_tgt) and np.any(self.pred_tgt < 0):
                return False
            self.pred_key = (
                self.id_key[self.pred_tgt].astype(np.int64)
                if len(self.pred_tgt)
                else np.empty(0, np.int64)
            )
        return True

    def _splice_col(self, name, old, new_vals, row_map, new_rows, tail, m):
        """One column's splice into a capacity-bucketed backing buffer.

        Tail appends into a still-roomy buffer write only the k new slots;
        everything else allocates at the bucket capacity and scatters both
        sides through the position maps (one vectorized pass per column)."""
        old = np.asarray(old)
        new_vals = np.asarray(new_vals).astype(old.dtype, copy=False)
        n = len(old)
        buf = self._bufs.get(name)
        if tail and buf is not None and old.base is buf and len(buf) >= m:
            buf[n:m] = new_vals
            return buf[:m]
        nbuf = np.empty(_capacity(m), old.dtype)
        out = nbuf[:m]
        if tail:
            out[:n] = old
            out[n:] = new_vals
        else:
            out[row_map] = old
            out[new_rows] = new_vals
        self._bufs[name] = nbuf
        return out

    def append_changes(self, changes: Iterable[StoredChange]):
        """Splice new changes into the existing columns WITHOUT re-collecting
        prior replicas: extract only the fresh changes (vectorized, through
        the per-change-hash column cache), merge their rows into the
        Lamport order with searchsorted position arithmetic, re-resolve
        references that touch the delta, and report the dirty object set.

        Returns an ``AppendInfo`` on success, or ``None`` when the log
        cannot be updated in place (no retained column bytes, partial
        history with unreconstructable refs, packed-id collisions) — the
        caller then rebuilds via ``from_changes``. New actors are handled
        in place: actor ranks are byte-ordered, so inserting actors remaps
        every packed key through a MONOTONE rank map, which preserves the
        existing sort order.

        Caller contract: the active text encoding must match the one the
        resident columns were built under (as in ``from_documents``).
        """
        from .. import obs

        known = self.hashes()
        fresh: List[StoredChange] = []
        batch_seen = set()
        for ch in changes:
            if ch.hash is None or ch.hash in known or ch.hash in batch_seen:
                continue
            batch_seen.add(ch.hash)
            fresh.append(ch)
        if not fresh:
            return AppendInfo(self.n, 0, np.empty(0, np.int64), None, True,
                              np.empty(0, np.int64), None, False, 0)
        if any(
            ch.op_col_data is None and ch.cached_cols is None for ch in fresh
        ):
            obs.count("oplog.append_fallback", labels={"reason": "no_columns"})
            return None
        if not self._ensure_ref_keys():
            obs.count("oplog.append_fallback", labels={"reason": "missing_refs"})
            return None

        # -- actor universe (monotone rank remap keeps old order sorted) --
        old_bytes = [a.bytes for a in self.actors]
        delta_bytes = {bytes(a) for ch in fresh for a in ch.actors}
        actors_changed = not delta_bytes.issubset(old_bytes_set := set(old_bytes))
        if actors_changed:
            all_bytes = sorted(old_bytes_set | delta_bytes)
            if len(all_bytes) >= (1 << ACTOR_BITS):
                obs.count("oplog.append_fallback", labels={"reason": "too_many_actors"})
                return None
        else:
            all_bytes = old_bytes
        rank_of = {b: i for i, b in enumerate(all_bytes)}
        if actors_changed:
            rank_map = np.fromiter(
                (rank_of[b] for b in old_bytes), np.int64, count=len(old_bytes)
            )

            def remap_packed(key):
                key = np.asarray(key, np.int64)
                idx = np.where(key > 0, key, 0) & ACTOR_MASK
                return np.where(
                    key > 0,
                    ((key >> ACTOR_BITS) << ACTOR_BITS) | rank_map[idx],
                    key,
                )
        else:
            def remap_packed(key):
                return np.asarray(key, np.int64)

        # -- extract ONLY the fresh changes -------------------------------
        with obs.span("device.extract", changes=len(fresh)):
            r = self._extract_delta(fresh, rank_of)
        if r is None:
            return None
        a = r["a"]
        k = int(a["n"])

        n = self.n
        old_id = remap_packed(self.id_key) if n else np.empty(0, np.int64)

        if k == 0:
            # dependency-only changes: commit bookkeeping, no rows
            self._commit_actors(all_bytes, actors_changed, remap_packed, old_id)
            self.changes.extend(fresh)
            known.update(batch_seen)
            return AppendInfo(n, 0, np.empty(0, np.int64), None, True,
                              np.empty(0, np.int64), None, actors_changed,
                              len(fresh))

        order = np.argsort(r["id_key"], kind="stable")
        d_id = r["id_key"][order]
        if np.any(d_id[1:] == d_id[:-1]):
            obs.count("oplog.append_fallback", labels={"reason": "dup_op_id"})
            return None
        pos = np.searchsorted(old_id, d_id)
        if n:
            posc = np.clip(pos, 0, n - 1)
            if np.any(old_id[posc] == d_id):
                obs.count("oplog.append_fallback", labels={"reason": "id_collision"})
                return None
        tail = n == 0 or pos[0] == n
        m = n + k
        # offset-value-coded id join: the compressed id_key runs (delta+
        # RLE over the packed (counter, actor) composites), extended
        # eagerly with the delta, answer every reference join below over
        # R run heads + stride arithmetic instead of a searchsorted over
        # all N resident keys (ops/compressed.py StrideRuns.join)
        idruns = None
        if tail and not actors_changed and n:
            from . import compressed as C

            if C.enabled():
                comp = self._comp
                if comp is None:
                    comp = self._comp = C.CompressedOpColumns()
                comp._sync_col("id_key", "delta", self.id_key, n)
                idruns = comp.extend_id(d_id)
        new_rows = pos + np.arange(k, dtype=np.int64)
        if tail:
            row_map = None
        else:
            cnt = np.bincount(pos, minlength=n + 1)
            row_map = np.arange(n, dtype=np.int64) + np.cumsum(cnt[:n])
        if self._bufs is None:
            self._bufs = {}

        # -- string tables (old ids stable; new names appended) ------------
        props, d_prop = _merge_table(self.props, a["key_table"],
                                     r["prop_ids"], order)
        mark_ids = a.get("mark_ids")
        if mark_ids is None:
            mark_names = list(self.mark_names)
            d_mark = np.full(k, -1, np.int32)
        else:
            mark_names, d_mark = _merge_table(self.mark_names,
                                              a["mark_table"], mark_ids, order)

        # -- splice the plain per-row columns ------------------------------
        sp = lambda name, old, new: self._splice_col(  # noqa: E731
            name, old, new, row_map, new_rows, tail, m
        )
        id_new = sp("id_key", old_id, d_id)
        obj_new = sp("obj_key", remap_packed(self.obj_key), r["obj"][order])
        ek_new = sp("elem_key", remap_packed(self.elem_key), r["elem"][order])
        action_new = sp("action", self.action, a["action"][order])
        prop_new = sp("prop", self.prop, d_prop)
        insert_new = sp("insert", np.asarray(self.insert, np.bool_),
                        np.asarray(a["insert"], np.bool_)[order])
        vtag_new = sp("value_tag", self.value_tag,
                      np.minimum(a["vcode"], TAG_UNKNOWN)[order])
        vint_new = sp("value_int", self.value_int, a["value_int"][order])
        width_new = sp("width", self.width, a["width"][order])
        expand_new = sp("expand", np.asarray(self.expand, np.bool_),
                        np.asarray(a["expand"], np.bool_)[order])
        mark_new = sp("mark_name_idx", self.mark_name_idx, d_mark)

        def rows_of(keys):
            if idruns is not None:
                obs.count("oplog.ovc_join", n=len(keys))
                return idruns.join(keys, ELEM_MISSING)
            return join_rows(id_new, keys, ELEM_MISSING)

        # -- element references --------------------------------------------
        old_er = self.elem_ref
        if not tail:
            old_er = np.where(
                old_er >= 0, row_map[np.clip(old_er, 0, max(n - 1, 0))], old_er
            )
        d_ek = r["elem"][order]
        d_er = np.where(
            d_ek == -1,
            np.int32(ELEM_MAP),
            np.where(d_ek == 0, np.int32(ELEM_HEAD), rows_of(d_ek)),
        ).astype(np.int32)
        er_new = sp("elem_ref", old_er.astype(np.int32, copy=False), d_er)
        # previously-MISSING refs may now resolve (their target arrived)
        rere_rows = np.empty(0, np.int64)
        n_miss_elem = 0
        miss = np.flatnonzero(er_new == ELEM_MISSING)
        if len(miss):
            res = rows_of(ek_new[miss])
            got = res != ELEM_MISSING
            n_miss_elem = int(len(miss) - np.count_nonzero(got))
            if np.any(got):
                er_new[miss[got]] = res[got]
                rere_rows = miss[got]

        # -- pred edges (appended at the end; order is irrelevant) ---------
        q = len(self.pred_src)
        old_ps = self.pred_src
        old_pt = self.pred_tgt
        if not tail:
            safe_n = max(n - 1, 0)
            old_ps = row_map[np.clip(old_ps, 0, safe_n)].astype(np.int32) \
                if q else old_ps
            old_pt = np.where(
                old_pt >= 0, row_map[np.clip(old_pt, 0, safe_n)], old_pt
            ).astype(np.int32) if q else old_pt
        inv = np.empty(k, np.int64)
        inv[order] = np.arange(k)
        d_ps = new_rows[inv[r["pred_src"]]].astype(np.int32) \
            if len(r["pred_src"]) else np.empty(0, np.int32)
        d_pk = r["pred_key"]
        d_pt = rows_of(d_pk).astype(np.int32) if len(d_pk) \
            else np.empty(0, np.int32)
        d_pt = np.where(d_pt == ELEM_MISSING, np.int32(-1), d_pt)
        qm = q + len(d_ps)
        cat = lambda name, old, new: self._splice_col(  # noqa: E731
            name, np.asarray(old), new, None, None, True, qm
        )
        ps_new = cat("pred_src", old_ps, d_ps)
        pt_new = cat("pred_tgt", old_pt, d_pt)
        pk_new = cat("pred_key", remap_packed(self.pred_key), d_pk)
        # previously-unresolved pred targets may now resolve
        rere_pred = np.empty(0, np.int64)
        n_miss_pred = 0
        pmiss = np.flatnonzero(pt_new == -1)
        if len(pmiss):
            res = rows_of(pk_new[pmiss])
            got = res != ELEM_MISSING
            n_miss_pred = int(len(pmiss) - np.count_nonzero(got))
            if np.any(got):
                pt_new[pmiss[got]] = res[got]
                rere_pred = pmiss[got]

        # -- object table / dense ids --------------------------------------
        old_table = remap_packed(self.obj_table)
        make_new = d_id[np.isin(a["action"][order], MAKE_ACTIONS)]
        add = np.concatenate([make_new, r["obj"][order]])
        new_table = np.union1d(old_table, add)
        if len(new_table) == len(old_table):
            obj_remap = None
            od_old = self.obj_dense
            self.obj_table = new_table
        else:
            obj_remap = np.searchsorted(new_table, old_table).astype(np.int32)
            od_old = obj_remap[self.obj_dense]
            self.obj_table = new_table
        od_new = np.searchsorted(new_table, r["obj"][order]).astype(np.int32)
        od_all = sp("obj_dense", od_old.astype(np.int32, copy=False), od_new)

        # -- values heap ----------------------------------------------------
        self._splice_values(a, order, row_map, new_rows, tail, m)

        # -- dirty objects (NEW dense numbering) ---------------------------
        parts = [od_new, np.searchsorted(new_table, make_new)]
        if len(rere_rows):
            parts.append(od_all[rere_rows])
        if len(rere_pred):
            src = ps_new[rere_pred]
            tgt = pt_new[rere_pred]
            parts.append(od_all[src])
            parts.append(od_all[np.clip(tgt, 0, m - 1)])
        if len(d_pt):
            hit = d_pt >= 0
            if np.any(hit):
                parts.append(od_all[d_pt[hit]])
        dirty = np.unique(np.concatenate(parts)).astype(np.int64)

        # -- commit ---------------------------------------------------------
        self.id_key = id_new
        self.obj_key = obj_new
        self.elem_key = ek_new
        self.action = action_new
        self.prop = prop_new
        self.insert = insert_new
        self.value_tag = vtag_new
        self.value_int = vint_new
        self.width = width_new
        self.expand = expand_new
        self.mark_name_idx = mark_new
        self.elem_ref = er_new
        self.obj_dense = od_all
        self.pred_src = ps_new
        self.pred_tgt = pt_new
        self.pred_key = pk_new
        self.props = props
        self.mark_names = mark_names
        self.n = m
        self.n_objs = len(new_table)
        self.n_miss_elem = n_miss_elem
        self.n_miss_pred = n_miss_pred
        self.actors = [ActorId(b) for b in all_bytes]
        self._actor_order = None
        # the compressed image survives only the pure tail append: actor
        # remaps rewrite every packed key, non-tail splices move the
        # prefix, and re-resolved MISSING references mutate elem_ref /
        # pred_tgt in place — all invalidate; the next consumer
        # re-encodes lazily
        if not tail or actors_changed or len(rere_rows) or len(rere_pred):
            self._comp = None
        self.changes.extend(fresh)
        known.update(batch_seen)
        obs.count("oplog.append_rows", n=k)
        obs.event(
            "oplog.append", rows=k, total=m, tail=int(tail),
            dirty_objs=len(dirty), actors_changed=int(actors_changed),
        )
        return AppendInfo(n, k, new_rows, row_map, tail, dirty, obj_remap,
                          actors_changed, len(fresh), n_pred_old=q,
                          rere_elem_rows=rere_rows, rere_pred_edges=rere_pred)

    def _commit_actors(self, all_bytes, actors_changed, remap_packed, old_id):
        if not actors_changed:
            return
        self.id_key = old_id
        self.obj_key = remap_packed(self.obj_key)
        self.elem_key = remap_packed(self.elem_key)
        self.pred_key = remap_packed(self.pred_key)
        self.obj_table = remap_packed(self.obj_table)
        self.actors = [ActorId(b) for b in all_bytes]
        self._actor_order = None
        self._comp = None  # every packed key was rank-remapped
        # remapped arrays no longer alias the backing buffers
        self._bufs = {}

    def _extract_delta(self, fresh, rank_of):
        """ranked_batch-shaped columns for the fresh changes only, through
        whichever vectorized path is available (cached-cols assembler
        input first, then raw batch extraction)."""
        from .. import native

        try:
            from .assemble import AssembleError, ranked_from_caches

            return ranked_from_caches(list(fresh), rank_of)
        except (AssembleError, native.NativeUnavailable, ValueError):
            pass
        except Exception:
            if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                raise
        try:
            from .extract import ExtractError, ranked_batch

            return ranked_batch(list(fresh), rank_of)
        except (ExtractError, native.NativeUnavailable, ValueError):
            from .. import obs

            obs.count("oplog.append_fallback", labels={"reason": "extract_failed"})
            return None

    def _splice_values(self, a, order, row_map, new_rows, tail, m):
        from .extract import LazyValues

        vals = self.values
        d_code = a["vcode"][order].astype(np.int32)
        d_off = a["voff"][order].astype(np.int64)
        d_ln = a["vlen"][order].astype(np.int64)
        d_raw = a["vraw"]
        if isinstance(vals, LazyValues):
            base = len(vals.raw)
            code = self._splice_col("vcode", vals.code, d_code,
                                    row_map, new_rows, tail, m)
            off = self._splice_col("voff", vals.off, d_off + base,
                                   row_map, new_rows, tail, m)
            ln = self._splice_col("vlen", vals.ln, d_ln,
                                  row_map, new_rows, tail, m)
            # append-only raw heap: a bytearray grows geometrically, so a
            # delta stream costs O(delta) amortized instead of re-copying
            # the resident bytes each append (offsets of old rows never
            # move, so sharing the buffer with prior LazyValues is safe)
            raw = vals.raw
            if not isinstance(raw, bytearray):
                raw = bytearray(raw)
            raw += d_raw
            nv = LazyValues(code, off, ln, raw, cap=vals.cap)
            nv.hits, nv.misses = vals.hits, vals.misses
            self.values = nv
            return
        # eager python list (slow collection path): object-array splice
        dv = LazyValues(d_code, d_off, d_ln, d_raw)
        new_list = [dv[i] for i in range(len(d_code))]
        arr = np.empty(m, object)
        if tail:
            arr[: len(vals)] = vals
            arr[len(vals):] = new_list
        else:
            arr[row_map] = vals
            arr[new_rows] = new_list
        self.values = arr.tolist()


class AppendInfo:
    """What an in-place ``OpLog.append_changes`` did — everything a resident
    consumer (DeviceDoc) needs to splice its own row-indexed state.

    ``row_map`` maps old row index -> new row index (None = identity, the
    tail-append fast path); ``new_rows`` are the spliced rows' positions;
    ``dirty_objs`` are the dense object ids (NEW numbering) whose resolution
    is stale; ``obj_remap`` maps old dense ids -> new (None = identity)."""

    __slots__ = (
        "n_old", "n_new", "new_rows", "row_map", "tail", "dirty_objs",
        "obj_remap", "actors_changed", "n_changes", "n_pred_old",
        "rere_elem_rows", "rere_pred_edges",
    )

    def __init__(self, n_old, n_new, new_rows, row_map, tail, dirty_objs,
                 obj_remap, actors_changed, n_changes, n_pred_old=0,
                 rere_elem_rows=None, rere_pred_edges=None):
        self.n_old = n_old
        self.n_new = n_new
        self.new_rows = new_rows
        self.row_map = row_map
        self.tail = tail
        self.dirty_objs = dirty_objs
        self.obj_remap = obj_remap
        self.actors_changed = actors_changed
        self.n_changes = n_changes
        # edge bookkeeping for host-side delta resolution: edges before
        # index n_pred_old are carried; rere_* name previously-MISSING
        # references that resolved when their target arrived in this append
        self.n_pred_old = n_pred_old
        self.rere_elem_rows = (
            rere_elem_rows if rere_elem_rows is not None
            else np.empty(0, np.int64)
        )
        self.rere_pred_edges = (
            rere_pred_edges if rere_pred_edges is not None
            else np.empty(0, np.int64)
        )


def _merge_table(old: List[str], delta_table, ids, order) -> Tuple[List[str], np.ndarray]:
    """Union a delta's string table into the resident one (old ids stable,
    new names appended) and translate the delta's per-row ids."""
    merged = list(old)
    k = len(order)
    if not delta_table:
        return merged, np.full(k, -1, np.int32)
    pos_of = {s: i for i, s in enumerate(merged)}
    remap = np.empty(len(delta_table), np.int32)
    for j, s in enumerate(delta_table):
        gi = pos_of.get(s)
        if gi is None:
            gi = len(merged)
            merged.append(s)
            pos_of[s] = gi
        remap[j] = gi
    ids = np.asarray(ids)
    out = np.where(
        ids >= 0, remap[np.clip(ids, 0, len(delta_table) - 1)], np.int32(-1)
    ).astype(np.int32)
    return merged, out[order]


def _value_tag(v: ScalarValue) -> int:
    if v.tag == "bool":
        return TAG_TRUE if v.value else TAG_FALSE
    return _TAG_FOR.get(v.tag, TAG_UNKNOWN)


def _int_payload(v: ScalarValue) -> int:
    if v.tag in ("int", "uint", "counter", "timestamp"):
        return int(v.value)
    if v.tag == "bool":
        return int(v.value)
    return 0


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _capacity(n: int, minimum: int = 16) -> int:
    """Jit-bucket capacity: powers of two up to 8k, then multiples of 8k —
    snug enough that padded work stays within ~12% of the real row count."""
    n = max(n, minimum)
    if n <= 8192:
        return _next_pow2(n)
    return ((n + 8191) // 8192) * 8192


def _pad(a: np.ndarray, size: int, fill) -> np.ndarray:
    if len(a) == size:
        return a
    out = np.full(size, fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


def pad_columns(cols, n_objs: int, min_capacity: int = 16):
    """Pad a columns() dict to jit-bucket capacities (idempotent: already
    bucket-sized arrays pass through untouched)."""
    p = _capacity(len(cols["action"]), min_capacity)
    q = _capacity(len(cols["pred_src"]), min_capacity)
    fills = {
        "action": PAD_ACTION,
        "insert": False,
        "prop": -1,
        "elem_ref": ELEM_MAP,
        "obj_dense": np.int32(n_objs),
        "value_tag": TAG_NULL,
        "value_i32": 0,
        "width": 0,
        "covered": False,
        "pred_src": 0,
        "pred_tgt": -1,
        # compacted element order: pad slots carry the out-of-range
        # sentinel p (the kernel tests "slot < P" for validity)
        "aorder": p,
    }
    return {
        k: _pad(
            np.asarray(v),
            q if k.startswith("pred_") else p,
            fills.get(k, 0),
        )
        for k, v in cols.items()
    }


def host_forest(cols_np):
    """Sibling forest (is_elem, parent_row, first_child, next_sib) from
    numpy columns — the host mirror of ops/merge.py forest(). Children
    order is descending row (= descending Lamport, query/insert.rs),
    built with one lexsort."""
    action = np.asarray(cols_np["action"])
    P = len(action)
    insert = np.asarray(cols_np["insert"]).astype(bool) & (action != PAD_ACTION)
    elem_ref = np.asarray(cols_np["elem_ref"])
    obj_dense = np.asarray(cols_np["obj_dense"])
    N = 2 * P + 3
    S = N - 1
    parent_row = np.where(
        insert,
        np.where(
            elem_ref == ELEM_HEAD,
            P + obj_dense,
            np.where(elem_ref >= 0, elem_ref, S),
        ),
        S,
    ).astype(np.int32)
    er = np.flatnonzero(insert).astype(np.int32)
    order = np.lexsort((-er, parent_row[er]))
    sp = parent_row[er][order]
    sr = er[order]
    first_child = np.full(N, -1, np.int32)
    next_sib = np.full(N, -1, np.int32)
    if len(sr):
        first = np.concatenate([[True], sp[1:] != sp[:-1]])
        first_child[sp[first]] = sr[first]
        same = np.concatenate([sp[1:] == sp[:-1], [False]])
        nxt = np.concatenate([sr[1:], np.array([-1], np.int32)])
        next_sib[sr] = np.where(same, nxt, -1)
    return insert, parent_row, first_child, next_sib


def host_linearize(cols_np) -> np.ndarray:
    """Document-order element indices computed host-side from the numpy
    columns, overlapping the device kernel.

    Element order depends ONLY on the insert forest (elem_ref / insert /
    obj_dense) — never on visibility (historical views of one log share
    one element order) — so the host can rank it from the same arrays it
    just uploaded, with zero extra device traffic: a lexsort builds the
    sibling lists and the native preorder walk ranks them.
    """
    from .. import native

    insert, parent_row, first_child, next_sib = host_forest(cols_np)
    P = len(insert)
    elem_index = native.preorder_index(first_child, next_sib, parent_row, P)
    return np.where(insert, elem_index, np.int32(-1))
