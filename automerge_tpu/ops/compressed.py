"""Compute-on-compressed resident columns: RLE / delta+RLE codecs.

Device-resident OpLog columns were stored fully decompressed, so per-doc
residency, H2D staging bytes, and the tiered store's warm->hot promotion
cost all scaled linearly with history size. This module keeps the
resident representation encoded end to end, following LSM-OPD's
compute-on-compressed argument (arXiv:2508.11862) and the reference's
own RLE/delta columnar storage format:

* **run-length** for the low-cardinality columns (``action``,
  ``value_tag``, ``insert``, ``width``, ``expand``, ``mark_name_idx``,
  ``prop``, ``obj_dense``): runs of one repeated value.
* **delta+RLE** for the monotone / striding columns (the packed-key
  columns ``id_key`` / ``obj_key`` / ``elem_key``, plus ``elem_ref`` /
  ``value_int`` whose typing-chain shapes are stride runs): each run is
  an arithmetic sequence ``(start, stride, length)``. The per-run table
  of a sorted key column doubles as an offset-value coding
  (arXiv:2209.08420) of the ``(counter, actor)`` composite: a
  Lamport-order membership probe is a searchsorted over ``R`` run heads
  plus O(1) stride arithmetic, instead of a searchsorted over all ``N``
  packed keys (``StrideRuns.join``).
* **dense passthrough** for everything that doesn't compress: a column
  whose run count crosses the ratio gate demotes to dense (accounted at
  its dense size, counted via ``oplog.compress_fallback{column,reason}``)
  so degenerate histories never pay encode+decode for nothing.

The resident bundle (``CompressedOpColumns``) is maintained
*incrementally*: tail appends — the dominant shape, every
``OpLog.append_changes`` / ``ops/host_batch._tail_write`` splice —
extend the last run in place instead of re-encoding
(``StrideRuns.extend_tail``); anything that rewrites the resident prefix
(non-tail splices, actor-rank remaps, re-resolved MISSING references)
invalidates the bundle and the next consumer re-encodes lazily.

``AUTOMERGE_TPU_COMPRESSED=0`` restores the dense path everywhere (the
A/B and differential-oracle knob — read per call, so one process can
compare both modes).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def enabled() -> bool:
    """Whether compressed residency is active (default on)."""
    return os.environ.get("AUTOMERGE_TPU_COMPRESSED", "1") != "0"


def gate_ratio() -> float:
    """Run-count demotion gate: a column with more than ``gate * rows``
    runs stores nothing and accounts dense
    (``AUTOMERGE_TPU_COMPRESS_GATE``, default 0.5)."""
    try:
        return float(os.environ.get("AUTOMERGE_TPU_COMPRESS_GATE", "0.5"))
    except ValueError:
        return 0.5


def run_gate(n_runs: int, n_rows: int) -> bool:
    """True when a run table is degenerate enough that a column must ship
    (and compute) dense — the same two-axis demotion rule the resident
    bundle applies: the run count crosses ``gate_ratio()`` of the rows,
    or the run table wouldn't even undercut the dense int32 image (one
    (w, cum) int32 pair per run vs one int32 per row)."""
    return n_runs > gate_ratio() * n_rows or 2 * n_runs >= n_rows


class StrideRuns:
    """One column as arithmetic-sequence runs.

    ``starts`` are row offsets (ascending, ``starts[0] == 0``),
    ``vals`` the per-run start values, ``strides`` the per-run step
    (all int64; a pure-RLE encode pins every stride to 0). ``n`` is the
    decoded length. ``is_sorted`` marks a strictly-increasing column —
    the precondition for ``join``.
    """

    __slots__ = ("starts", "vals", "strides", "n", "dtype", "is_sorted",
                 "stride_mode")

    def __init__(self, starts, vals, strides, n, dtype, is_sorted,
                 stride_mode=True):
        self.starts = starts
        self.vals = vals
        self.strides = strides
        self.n = int(n)
        self.dtype = np.dtype(dtype)
        self.is_sorted = bool(is_sorted)
        self.stride_mode = bool(stride_mode)

    # -- construction --------------------------------------------------------

    @classmethod
    def encode(cls, arr, stride: bool = True) -> "StrideRuns":
        """Encode one column. ``stride=False`` produces pure RLE (every
        run a repeated value) — the low-cardinality column mode."""
        arr = np.asarray(arr)
        dtype = arr.dtype
        x = arr.astype(np.int64, copy=False)
        n = len(x)
        if n == 0:
            z = np.empty(0, np.int64)
            return cls(z, z, z, 0, dtype, True, stride)
        if n == 1:
            z = np.zeros(1, np.int64)
            return cls(z, x.copy(), np.zeros(1, np.int64), 1, dtype, True,
                       stride)
        d = np.diff(x)
        if stride:
            # row p >= 2 starts a new run when the step into it differs
            # from the step before it; row 1 always rides run 0
            b = np.flatnonzero(d[1:] != d[:-1]) + 2
        else:
            b = np.flatnonzero(d != 0) + 1
        starts = np.concatenate([[0], b]).astype(np.int64)
        lengths = np.diff(np.concatenate([starts, [n]]))
        vals = x[starts]
        if stride:
            safe = np.minimum(starts, n - 2)
            strides = np.where(lengths > 1, d[safe], 0).astype(np.int64)
        else:
            strides = np.zeros(len(starts), np.int64)
        return cls(starts, vals, strides, n, dtype, bool(np.all(d > 0)),
                   stride)

    # -- primitives ----------------------------------------------------------

    @property
    def run_count(self) -> int:
        return len(self.starts)

    @property
    def nbytes(self) -> int:
        """Actual resident footprint of the encoded form."""
        return self.starts.nbytes + self.vals.nbytes + self.strides.nbytes

    def lengths(self) -> np.ndarray:
        return np.diff(np.concatenate([self.starts, [self.n]]))

    def decode(self) -> np.ndarray:
        if self.n == 0:
            return np.empty(0, self.dtype)
        ln = self.lengths()
        off = np.arange(self.n, dtype=np.int64) - np.repeat(self.starts, ln)
        out = np.repeat(self.vals, ln) + np.repeat(self.strides, ln) * off
        return out.astype(self.dtype, copy=False)

    def last_value(self) -> int:
        ln = self.n - 1 - int(self.starts[-1])
        return int(self.vals[-1] + self.strides[-1] * ln)

    def slice(self, lo: int, hi: int) -> "StrideRuns":
        """The encoded form of ``decode()[lo:hi]`` without decoding the
        whole column (run-walking: clip the overlapping runs)."""
        lo = max(int(lo), 0)
        hi = min(int(hi), self.n)
        if hi <= lo:
            z = np.empty(0, np.int64)
            return StrideRuns(z, z, z, 0, self.dtype, True, self.stride_mode)
        j0 = int(np.searchsorted(self.starts, lo, side="right")) - 1
        j1 = int(np.searchsorted(self.starts, hi, side="left"))
        starts = self.starts[j0:j1].copy()
        vals = self.vals[j0:j1].copy()
        strides = self.strides[j0:j1].copy()
        vals[0] += strides[0] * (lo - starts[0])
        starts[0] = lo
        starts -= lo
        return StrideRuns(starts, vals, strides, hi - lo, self.dtype,
                          self.is_sorted, self.stride_mode)

    def extend_tail(self, tail) -> None:
        """Append ``tail`` in place: the boundary run extends instead of
        re-encoding the resident prefix (the tail-append fast path).
        O(len(tail) + new runs)."""
        tail = np.asarray(tail).astype(np.int64, copy=False)
        k = len(tail)
        if k == 0:
            return
        if self.n == 0:
            e = StrideRuns.encode(tail.astype(self.dtype, copy=False),
                                  stride=self.stride_mode)
            self.starts, self.vals, self.strides = e.starts, e.vals, e.strides
            self.n, self.is_sorted = e.n, e.is_sorted
            return
        pure_rle = not self.stride_mode
        e = StrideRuns.encode(tail, stride=not pure_rle)
        last = self.last_value()
        d0 = int(tail[0]) - last
        if d0 <= 0:
            self.is_sorted = False
        if not e.is_sorted:
            self.is_sorted = False
        n0 = self.n
        L = n0 - int(self.starts[-1])  # length of the resident last run
        st = int(self.strides[-1])
        l0 = int(e.lengths()[0])
        st0 = int(e.strides[0])
        merge = False
        new_stride = st
        if pure_rle:
            merge = d0 == 0 and st0 == 0
            new_stride = 0
        elif L >= 2:
            merge = d0 == st and (l0 == 1 or st0 == st)
        else:  # singleton resident run adopts whatever stride continues it
            merge = l0 == 1 or st0 == d0
            new_stride = d0
        drop = 1 if merge else 0
        if merge:
            self.strides[-1] = new_stride
        self.starts = np.concatenate([self.starts, e.starts[drop:] + n0])
        self.vals = np.concatenate([self.vals, e.vals[drop:]])
        self.strides = np.concatenate([self.strides, e.strides[drop:]])
        self.n = n0 + k

    def splice(self, pos: int, values) -> "StrideRuns":
        """Encoded form after inserting ``values`` at row ``pos``. The
        ``pos == n`` tail case extends runs in place (and returns self);
        interior splices re-encode — the generic, rare path."""
        if pos == self.n:
            self.extend_tail(values)
            return self
        x = self.decode()
        out = np.concatenate([
            x[:pos],
            np.asarray(values).astype(self.dtype, copy=False),
            x[pos:],
        ])
        return StrideRuns.encode(out, stride=self.stride_mode)

    # -- the offset-value-coded membership join ------------------------------

    def join(self, keys, missing: int) -> np.ndarray:
        """Row indices of ``keys`` in this (strictly sorted) column —
        ``join_rows`` over the run table: searchsorted over R run heads
        + stride arithmetic, instead of over all N rows. Requires
        ``is_sorted``."""
        if not self.is_sorted:
            raise ValueError("join requires a strictly sorted column")
        keys = np.asarray(keys, np.int64)
        if self.run_count == 0 or len(keys) == 0:
            return np.full(len(keys), missing, np.int32)
        j = np.searchsorted(self.vals, keys, side="right") - 1
        inside = j >= 0
        jc = np.clip(j, 0, self.run_count - 1)
        rel = keys - self.vals[jc]
        st = self.strides[jc]
        ln = self.lengths()[jc]
        st_safe = np.where(st > 0, st, 1)
        q = rel // st_safe
        hit = (
            inside
            & (rel >= 0)
            & (rel % st_safe == 0)
            & (q < ln)
            & ((st > 0) | (rel == 0))
        )
        row = self.starts[jc] + q
        return np.where(hit, row, np.int64(missing)).astype(np.int32)


# -- the resident bundle ------------------------------------------------------

# (column attr, codec mode, dense itemsize). Mode "rle" = repeated-value
# runs, "delta" = stride runs. Row columns index by log.n; the pred_*
# edge columns (by len(pred_src)) ride the same machinery below.
ROW_SPEC = (
    ("action", "rle", 4),
    ("insert", "rle", 1),
    ("prop", "rle", 4),
    ("value_tag", "rle", 4),
    ("width", "rle", 4),
    ("expand", "rle", 1),
    ("mark_name_idx", "rle", 4),
    ("obj_dense", "rle", 4),
    ("id_key", "delta", 8),
    ("obj_key", "delta", 8),
    ("elem_key", "delta", 8),
    ("elem_ref", "delta", 4),
    ("value_int", "delta", 8),
)
EDGE_SPEC = (
    ("pred_src", "delta", 4),
    ("pred_tgt", "delta", 4),
    ("pred_key", "delta", 8),
)

_DENSE = "dense"  # per-column demotion marker


class CompressedOpColumns:
    """The incrementally-maintained compressed image of one OpLog's
    resident columns: per-column ``StrideRuns`` (or the dense-demotion
    marker), each with its own covered-row cursor so a lazy consumer
    only ever encodes the un-covered tail. The authority for true
    resident bytes (``nbytes``), the dense equivalent
    (``dense_nbytes``), and the offset-value-coded id join."""

    __slots__ = ("entries", "covered", "demoted")

    def __init__(self):
        self.entries: Dict[str, object] = {}
        self.covered: Dict[str, int] = {}
        self.demoted: Dict[str, str] = {}

    # -- introspection -------------------------------------------------------

    def all_dense(self, names) -> bool:
        """True when every tracked column in ``names`` is dense-demoted —
        the snapshot writer's short-circuit signal: there are no run
        tables to serialize, so the compressed-encode walk can be skipped
        entirely (storage/runsnap.py counts ``compact.dense_shortcut``).
        Zero-row run entries (e.g. empty pred columns) count as dense-
        compatible: they hold no runs either way."""
        seen_dense = False
        for nm in names:
            e = self.entries.get(nm)
            if e is _DENSE:
                seen_dense = True
            elif e is None or e.n:
                return False
        return seen_dense

    def runs_for(self, name: str, rows: int):
        """The live StrideRuns for ``name`` iff it covers exactly
        ``rows`` rows; None for dense-demoted / stale / untracked
        columns (callers then serialize the dense array verbatim)."""
        ent = self.entries.get(name)
        if ent is None or ent is _DENSE or ent.n != rows:
            return None
        return ent

    # -- maintenance ---------------------------------------------------------

    def _sync_col(self, name: str, mode: str, arr, total: int,
                  itemsize: int = 8) -> None:
        from .. import obs

        cov = self.covered.get(name, 0)
        ent = self.entries.get(name)
        if cov > total or (ent is not None and ent is not _DENSE
                           and ent.n != cov):
            # the resident prefix moved under us (or the cursor is
            # ahead of the column): rebuild from scratch
            ent = None
            cov = 0
        if ent is _DENSE:
            self.covered[name] = total
            return
        arr = np.asarray(arr)
        if ent is None:
            ent = StrideRuns.encode(arr[:total], stride=(mode == "delta"))
        elif cov < total:
            ent.extend_tail(arr[cov:total].astype(np.int64, copy=False)
                            if arr.dtype != np.int64 else arr[cov:total])
        # demotion gate, both axes: run-structure degeneracy (run count
        # past the ratio gate) and plain bytes (an encoded column must
        # never cost more than its dense self — 24 B/run vs itemsize/row)
        if total and (
            ent.run_count > gate_ratio() * total
            or ent.nbytes >= total * itemsize
        ):
            obs.count("oplog.compress_fallback",
                      labels={"column": name, "reason": "ratio"})
            self.entries[name] = _DENSE
            self.demoted[name] = "ratio"
        else:
            self.entries[name] = ent
        self.covered[name] = total

    def sync(self, log) -> "CompressedOpColumns":
        """Bring every tracked column's encoding up to the log's current
        row/edge counts (tail-encode only what is new)."""
        n = log.n
        q = len(log.pred_src)
        for name, mode, item in ROW_SPEC:
            arr = getattr(log, name)
            if arr is None:  # assembler-built logs defer elem_key
                self.entries.pop(name, None)
                self.covered[name] = 0
                continue
            if name in ("insert", "expand"):
                arr = np.asarray(arr, np.bool_).view(np.int8)
            self._sync_col(name, mode, arr, n, item)
        for name, mode, item in EDGE_SPEC:
            arr = getattr(log, name)
            if arr is None:
                self.entries.pop(name, None)
                self.covered[name] = 0
                continue
            self._sync_col(name, mode, arr, q, item)
        return self

    def extend_id(self, d_id) -> Optional[StrideRuns]:
        """Extend ONLY the id_key runs with a tail delta (the append
        path's eager extension, so the offset-value join can run against
        the post-splice column before the rest of the bundle syncs).
        Returns the extended runs, or None when id_key is demoted."""
        ent = self.entries.get("id_key")
        if ent is None or ent is _DENSE:
            return None
        ent.extend_tail(d_id)
        self.covered["id_key"] = ent.n
        return ent if ent.is_sorted else None

    def id_runs(self) -> Optional[StrideRuns]:
        ent = self.entries.get("id_key")
        if ent is None or ent is _DENSE or not ent.is_sorted:
            return None
        return ent

    # -- accounting ----------------------------------------------------------

    def nbytes(self, log) -> int:
        """True resident bytes of the column set under this encoding
        (demoted columns count dense)."""
        total = 0
        for name, _, item in ROW_SPEC + EDGE_SPEC:
            ent = self.entries.get(name)
            rows = self.covered.get(name, 0)
            if ent is None or ent is _DENSE:
                total += rows * item
            else:
                total += ent.nbytes
        return total

    def dense_nbytes(self, log) -> int:
        return sum(
            self.covered.get(name, 0) * item
            for name, _, item in ROW_SPEC + EDGE_SPEC
        )

    def ratio(self, log) -> float:
        c = self.nbytes(log)
        return (self.dense_nbytes(log) / c) if c else 1.0

    def run_counts(self) -> Dict[str, int]:
        return {
            name: (-1 if ent is _DENSE else ent.run_count)
            for name, ent in self.entries.items()
        }

    # -- integrity (integrity.py device-mirror audit) ------------------------

    def verify_against(self, log) -> list:
        """Differential oracle check: decode every encoded column and
        compare it to the dense host array it claims to represent.
        Returns the names of mismatching columns (empty = faithful).
        Read-only — verification must observe the bundle as consumers
        would, not repair it."""
        bad = []
        n = log.n
        q = len(log.pred_src)
        for name, _mode, _item in ROW_SPEC + EDGE_SPEC:
            ent = self.entries.get(name)
            if ent is None or ent is _DENSE:
                continue
            arr = getattr(log, name)
            if arr is None:
                bad.append(name)  # encoded rows for a column the log lost
                continue
            rows = q if name in ("pred_src", "pred_tgt", "pred_key") else n
            cov = self.covered.get(name, 0)
            if cov > rows or ent.n != cov:
                bad.append(name)
                continue
            if name in ("insert", "expand"):
                arr = np.asarray(arr, np.bool_).view(np.int8)
            want = np.asarray(arr[:cov]).astype(np.int64, copy=False)
            if not np.array_equal(ent.decode().astype(np.int64, copy=False),
                                  want):
                bad.append(name)
        return bad
