"""Cross-document batched device merge: one kernel launch per drain cycle.

A server draining N hot documents used to pay N separate kernel
dispatches — one dirty-set re-resolution per ``DeviceDoc`` — even though
the serve layer already hands the drain over as multi-document work
(serve/shards.py) and each dispatch is launch-overhead-bound at serve
sizes. This module multiplies those dispatches away: the coalesced
deltas of many small documents are packed into ONE ragged super-batch
(per-doc subset columns concatenated with row/object-id offsets, padded
to a shared capacity bucket so jit caches stay warm) and succ
resolution, visibility, winner recompute and dirty-set re-resolution run
as a single kernel launch, results scattered back per document.

Soundness: every group id in the resolution kernel (sequence runs keyed
by run-head row, map groups keyed by (object, prop)) is derived from row
and object ids, so offsetting each document's subset rows and dense
object ids into disjoint ranges keeps all key groups disjoint across
documents — the packed kernel resolves each document exactly as its own
subset launch would, bit for bit (asserted by tests/test_batched_merge).
Rows stay ascending within each document, preserving the "max row = max
Lamport" winner rule.

Two entry points:

* ``apply_cross_doc(work)`` — synchronous: stage every document's
  drained batches (``DeviceDoc.stage_batches``), resolve them in shared
  launches. The bench / CI driver.
* ``CrossDocBatcher`` — the serving-layer collector: workers draining
  different documents submit concurrently; the first submitter of a
  generation becomes the flush leader, waits a tiny window
  (``AUTOMERGE_TPU_BATCH_WINDOW_MS``) for co-arriving documents, then
  packs and launches once for everyone (the group-commit pattern the
  journal fsync combiner already uses). Submitters hold their document
  lock while waiting, so per-doc single-writer discipline is preserved:
  nothing else can touch a document between its host-side stage and the
  scatter of its kernel results.

Fallback: a document whose subset rows exceed
``AUTOMERGE_TPU_BATCH_FALLBACK_RATIO`` (default 0.5, strict) of the
combined batch is peeled off and resolved through the existing per-doc
path — padding 99 small documents up to a whale's capacity bucket (and
making them wait out its kernel) costs more than the launch it saves.
Documents whose dirty fraction trips the per-doc full-re-resolution
cost model never reach the packer (``stage_batches`` resolves them
per-doc immediately, same as ``apply_changes`` would).

Every packed launch counts ``device.kernel_launches{path=batched}``;
the per-doc and sharded dispatch sites carry the same counter with
their own ``path`` label, so "launches per drain cycle" is directly
observable (and asserted by the ``serve_batched`` bench config).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from ..obs import prof as _prof

# the READ_FETCH surface a DeviceDoc subset scatter consumes
_FETCH = (
    "visible", "winner", "conflicts", "elem_index",
    "obj_vis_len", "obj_text_width",
)
_PACK_COLS = (
    "action", "insert", "prop", "elem_ref", "obj_dense", "value_tag",
    "value_i32", "width", "covered", "pred_src", "pred_tgt",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class BatchStage:
    """One document's staged host append awaiting kernel resolution:
    the dirty-object subset (``rows`` are log row indices, ``dirty`` the
    dense dirty-object ids) plus the document itself for the scatter."""

    __slots__ = ("doc", "rows", "dirty", "error", "trace")

    def __init__(self, doc, rows: np.ndarray, dirty: np.ndarray):
        self.doc = doc
        self.rows = rows
        self.dirty = dirty
        self.error: Optional[BaseException] = None
        # the submitting request's trace context (trace_id, span_id), so
        # the shared launch span can link back to every request it served
        self.trace = None

    @property
    def n_rows(self) -> int:
        return len(self.rows)


def plan_stages(
    stages: Sequence[BatchStage], fallback_ratio: Optional[float] = None
) -> Tuple[List[BatchStage], List[BatchStage]]:
    """Split staged documents into (packed batch, per-doc fallbacks).

    A document is peeled (largest first, totals recomputed after each
    peel) while its subset rows STRICTLY exceed ``fallback_ratio`` of
    the remaining batch total — the whale rule. Ratio >= 1 never peels
    (a doc cannot exceed the total it is part of); ratio 0 peels
    everything down to the smallest document.
    """
    if fallback_ratio is None:
        fallback_ratio = _env_float("AUTOMERGE_TPU_BATCH_FALLBACK_RATIO", 0.5)
    batch = sorted(stages, key=lambda s: s.n_rows)
    whales: List[BatchStage] = []
    total = sum(s.n_rows for s in batch)
    while len(batch) > 1 and batch[-1].n_rows > fallback_ratio * total:
        w = batch.pop()
        total -= w.n_rows
        whales.append(w)
    return batch, whales


def _pack(stages: Sequence[BatchStage]):
    """Concatenate per-doc subset columns into one super-batch.

    Row references (``elem_ref``/``pred_src``/``pred_tgt``) shift by the
    document's row offset, dense object ids by its object offset;
    negative sentinels (HEAD / map / missing) pass through untouched.
    Returns (cols, metas, n_rows, n_objs) with metas =
    [(stage, row_off, n_rows, obj_off, n_objs)].
    """
    parts = {k: [] for k in _PACK_COLS}
    metas = []
    row_off = 0
    obj_off = 0
    for st in stages:
        sub = st.doc._subset_cols(st.rows, st.dirty)
        er = sub["elem_ref"]
        sub["elem_ref"] = np.where(er >= 0, er + row_off, er).astype(np.int32)
        sub["obj_dense"] = (sub["obj_dense"] + obj_off).astype(np.int32)
        sub["pred_src"] = (sub["pred_src"] + row_off).astype(np.int32)
        pt = sub["pred_tgt"]
        sub["pred_tgt"] = np.where(pt >= 0, pt + row_off, pt).astype(np.int32)
        for k in parts:
            parts[k].append(np.asarray(sub[k]))
        S, D = len(st.rows), len(st.dirty)
        metas.append((st, row_off, S, obj_off, D))
        row_off += S
        obj_off += D
    cols = {k: np.concatenate(v) for k, v in parts.items()}
    return cols, metas, row_off, obj_off


def _dispatch_packed(cols, n_objs: int, n_props: int):
    """The host + dispatch half of a packed launch: pad, stage
    (run-native run tables or the eager-expand staging), dispatch the
    kernel WITHOUT reading back, and rank element order host-side while
    it flies — exactly like the per-doc dispatch
    (DeviceDoc._dispatch_async). Returns an in-flight handle for
    ``_collect_packed``."""
    from .merge import prepare_resolution
    from .oplog import host_linearize, pad_columns

    useful = len(cols["action"])
    with obs.span("device.pack", rows=useful):
        cols = pad_columns(cols, n_objs)
    P = len(cols["action"])
    # occupancy at the pack site: padded-vs-useful rows were invisible
    # before, and the ratio is the first input the super-batch tuner
    # needs (a batch padded 10x past its useful rows is burning its win)
    obs.count("device.batch_rows", n=useful)
    obs.count("device.batch_padding_rows", n=P - useful)
    _prof.note("useful_rows", useful)
    _prof.note("padded_rows", P - useful)
    _prof.note("launches")
    obs.count("device.kernel_launches", labels={"path": "batched"})
    # the super-batch ships compressed: runs are packed under the same
    # _capacity buckets as the rows, so jit caches stay warm and
    # device_put moves run tables, not dense rows; with run-native
    # kernels the tables are the kernel's input itself
    dispatch = prepare_resolution(cols, n_objs, n_props)
    with obs.span("device.kernel", rows=P), \
            _prof.annotate("amtpu.batched_launch"):
        out = dispatch()  # async dispatch
    with obs.span("device.linearize", rows=P):
        ei = host_linearize(cols)
    return {"out": out, "ei": ei, "P": P}


def _collect_packed(handle):
    """The blocking half of a packed launch: read the resolution back."""
    with obs.span("device.readback", rows=handle["P"]):
        res = {
            k: np.asarray(handle["out"][k])
            for k in ("visible", "winner", "conflicts",
                      "obj_vis_len", "obj_text_width")
        }
    res["elem_index"] = handle["ei"]
    return res


def _launch_packed(cols, n_objs: int, n_props: int):
    """One kernel launch over the padded super-batch; element order is
    ranked host-side overlapped with the kernel."""
    return _collect_packed(_dispatch_packed(cols, n_objs, n_props))


def _scatter(metas, res) -> None:
    """Slice the packed results back per document and scatter them into
    each DeviceDoc's resolution arrays (winner values return to
    subset-local numbering — the contract of ``_scatter_subset``)."""
    for st, r0, S, o0, D in metas:
        w = res["winner"][r0 : r0 + S]
        res_sub = {
            "visible": res["visible"][r0 : r0 + S],
            "winner": np.where(w >= 0, w - r0, -1).astype(np.int32),
            "conflicts": res["conflicts"][r0 : r0 + S],
            "elem_index": res["elem_index"][r0 : r0 + S],
            "obj_vis_len": res["obj_vis_len"][o0 : o0 + D],
            "obj_text_width": res["obj_text_width"][o0 : o0 + D],
        }
        st.doc._scatter_subset(st.rows, st.dirty, res_sub)


def dispatch_stages(
    stages: Sequence[BatchStage], fallback_ratio: Optional[float] = None
) -> dict:
    """The dispatch half of ``resolve_stages``: whales resolve per-doc
    immediately (they never pipeline), the rest pack into ONE kernel
    launch that is dispatched but NOT collected. The returned handle
    feeds ``collect_stages`` — possibly after the caller has staged more
    host work under the in-flight launch (the drain pipeline)."""
    batch, whales = plan_stages(stages, fallback_ratio)
    for w in whales:
        obs.count("device.batched_fallback")
        w.doc._reresolve(w.dirty)
    handle = None
    metas = None
    if batch:
        links = [st.trace for st in batch if st.trace is not None]
        with obs.span("device.batched", links=links, docs=len(batch)):
            obs.observe("device.batch_docs", len(batch))
            with obs.span("device.pack", docs=len(batch)):
                cols, metas, n_rows, n_objs = _pack(batch)
            n_props = max(
                (len(st.doc.log.props) for st in batch), default=1
            )
            handle = _dispatch_packed(cols, n_objs, max(n_props, 1))
    return {
        "batched": len(batch),
        "fallback": len(whales),
        "handle": handle,
        "metas": metas,
    }


def collect_stages(disp: dict) -> dict:
    """The blocking half of ``resolve_stages``: read the packed launch
    back and scatter the results into each document."""
    if disp["handle"] is not None:
        with obs.span("device.batched", docs=disp["batched"]):
            res = _collect_packed(disp["handle"])
            with obs.span("device.scatter", docs=disp["batched"]):
                _scatter(disp["metas"], res)
    return {"batched": disp["batched"], "fallback": disp["fallback"]}


def resolve_stages(
    stages: Sequence[BatchStage], fallback_ratio: Optional[float] = None
) -> dict:
    """Resolve staged documents: whales per-doc, the rest in ONE packed
    launch. Returns {"batched": n_docs, "fallback": n_docs}."""
    return collect_stages(dispatch_stages(stages, fallback_ratio))


def pipeline_enabled() -> bool:
    """Whether the drain double-buffers: chunk N's packed kernel flies
    while chunk N+1 runs its host pack/sort/splice. Host seconds spent
    under an in-flight launch are noted as ``overlap_s`` and surface as
    ``drain.overlap_fraction``."""
    return os.environ.get("AUTOMERGE_TPU_DRAIN_PIPELINE", "1") != "0"


def apply_cross_doc(
    work,
    *,
    fallback_ratio: Optional[float] = None,
    max_docs_per_launch: Optional[int] = None,
    pipeline: Optional[bool] = None,
) -> dict:
    """Synchronous multi-document apply: ``work`` is an iterable of
    ``(device_doc, batches)`` pairs (``batches`` = a sequence of change
    batches, as ``apply_batches`` takes). Stages every document
    host-side, then resolves the stages in shared packed launches of at
    most ``max_docs_per_launch`` documents (None = all in one).

    Returns {"applied": total changes, "batched": docs resolved in
    packed launches, "fallback": docs resolved per-doc}.

    When ``max_docs_per_launch`` splits the drain into several launches
    and the pipeline is enabled (``pipeline`` kwarg, defaulting to
    ``AUTOMERGE_TPU_DRAIN_PIPELINE`` which is on), the chunks
    double-buffer: chunk N's packed kernel stays in
    flight while chunk N+1 runs its host staging (dedup / causal-order /
    pack / Lamport-sort / splice), and only then is chunk N collected.
    Host seconds spent under an in-flight launch are noted as
    ``overlap_s`` → ``drain.overlap_fraction``.
    """
    # the same DeviceDoc may appear several times in ``work``; its
    # batches must merge into ONE staging — a later append splices the
    # log and would silently invalidate an earlier stage's row/object
    # indices (apply_batches remaps its in-flight handle for exactly
    # this; the stage path merges up front instead)
    merged: dict = {}
    order: List[int] = []
    for dev, batches in work:
        k = id(dev)
        if k in merged:
            merged[k][1].extend(batches)
        else:
            merged[k] = (dev, list(batches))
            order.append(k)

    from . import host_batch

    def _stage_chunk(keys, idx0):
        """Stage one chunk of documents host-side; returns
        (stages, applied). Self-contained per call — host_batch.stage_docs
        dedups within the call and the chunks are disjoint documents."""
        applied = 0
        stages: List[BatchStage] = []
        if host_batch.enabled():
            # the vectorized cross-doc staging: dedup/causal-order/
            # extract/Lamport-sort/splice run as shared columnar passes
            # with per-doc offset ranges; ineligible documents stage
            # through the scalar path inside (host_batch.stage_docs
            # merges duplicates itself, but the merge above also backs
            # the scalar branch below)
            stages, results = host_batch.stage_docs(
                [merged[k] for k in keys]
            )
            for r in results.values():
                if r.error is not None:
                    raise r.error
                applied += r.applied
        else:
            for i, k in enumerate(keys):
                dev, batches = merged[k]
                t0 = time.perf_counter()
                n, st = dev.stage_batches(batches)
                _prof.note_doc(
                    getattr(dev, "obs_name", None) or f"doc{idx0 + i}",
                    time.perf_counter() - t0,
                )
                applied += n
                if st is not None:
                    stages.append(st)
        return stages, applied

    out = {"applied": 0, "batched": 0, "fallback": 0}

    def _account(r):
        out["batched"] += r["batched"]
        out["fallback"] += r["fallback"]

    if pipeline is None:
        pipeline = pipeline_enabled()
    step = max_docs_per_launch or len(order) or 1
    if pipeline and len(order) > step:
        # double-buffered drain: chunk the WORK (not the stages) so each
        # chunk's host staging runs while the previous chunk's packed
        # kernel is in flight
        pending = None
        try:
            for lo in range(0, len(order), step):
                t0 = time.perf_counter()
                stages, n = _stage_chunk(order[lo : lo + step], lo)
                out["applied"] += n
                d = dispatch_stages(stages, fallback_ratio)
                if pending is not None:
                    # everything since the loop top ran under pending's
                    # in-flight launch — the pipeline's measurable win
                    _prof.note("overlap_s", time.perf_counter() - t0)
                    _account(collect_stages(pending))
                pending = d
        except BaseException:
            if pending is not None:
                p, pending = pending, None
                collect_stages(p)
            raise
        if pending is not None:
            _account(collect_stages(pending))
    else:
        stages, n = _stage_chunk(order, 0)
        out["applied"] += n
        sstep = max_docs_per_launch or len(stages) or 1
        for lo in range(0, len(stages), sstep):
            _account(resolve_stages(stages[lo : lo + sstep], fallback_ratio))
    _prof.note("docs", len(order))
    _prof.note("changes", out["applied"])
    return out


# -- the serving-layer collector ---------------------------------------------


class _Submission:
    """One document's raw drained batches awaiting the leader-staged
    vectorized flush (host_batch mode): the submitter keeps holding its
    document lock while the flush leader stages every co-arriving
    document in one columnar pass."""

    __slots__ = ("dev", "batches", "trace", "applied", "error")

    def __init__(self, dev, batches, trace):
        self.dev = dev
        self.batches = batches
        self.trace = trace
        self.applied = 0
        self.error: Optional[BaseException] = None


class _Generation:
    __slots__ = ("stages", "subs", "done")

    def __init__(self):
        self.stages: List[BatchStage] = []  # scalar (submitter-staged)
        self.subs: List[_Submission] = []  # vectorized (leader-staged)
        self.done = threading.Event()


class CrossDocBatcher:
    """Group-commit collector for concurrent per-document workers.

    ``apply(dev, batches)`` stages the document's drained device feed
    (the caller MUST hold that document's execution lock) and blocks
    until a shared launch has resolved it. The first stager of a
    generation is the leader: it waits up to ``window_ms`` for
    co-arriving documents (waking early at ``max_docs``), closes the
    generation, and runs ``resolve_stages`` for everyone.

    ``mode``: "1" always batches, "0" never (callers fall back to
    ``apply_batches``), "auto" batches only on accelerator backends —
    on CPU the per-doc host delta-resolution path is faster than any
    kernel, packed or not.
    """

    def __init__(
        self,
        *,
        window_ms: Optional[float] = None,
        max_docs: Optional[int] = None,
        fallback_ratio: Optional[float] = None,
        mode: Optional[str] = None,
    ):
        self.window = (
            window_ms
            if window_ms is not None
            else _env_float("AUTOMERGE_TPU_BATCH_WINDOW_MS", 2.0)
        ) / 1000.0
        self.max_docs = int(
            max_docs
            if max_docs is not None
            else _env_float("AUTOMERGE_TPU_BATCH_DOCS", 32)
        )
        self.fallback_ratio = fallback_ratio
        self.mode = (
            mode
            if mode is not None
            else os.environ.get("AUTOMERGE_TPU_SERVE_BATCHED", "auto")
        )
        # generations at least this many docs wide flush as TWO
        # half-launches so the second half's pack/linearize runs under
        # the first half's in-flight kernel (the drain pipeline); small
        # generations keep the single launch — splitting them would
        # trade kernel occupancy for overlap that can't cover the cost
        self.pipeline_min_docs = int(
            _env_float("AUTOMERGE_TPU_PIPELINE_MIN_DOCS", 16)
        )
        self._cv = threading.Condition(threading.Lock())
        self._gen = _Generation()
        self._active: Optional[bool] = None

    def active(self) -> bool:
        """Whether device feeds should route through this batcher."""
        if self._active is None:
            if self.mode == "0":
                self._active = False
            elif self.mode == "auto":
                plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
                if plat:
                    self._active = plat != "cpu"
                else:
                    import jax

                    self._active = jax.default_backend() != "cpu"
            else:
                self._active = True
        return self._active

    def apply(self, dev, batches) -> int:
        """Stage ``dev``'s drained batches and resolve them in the next
        shared launch; blocks until resolved. Returns changes applied.

        With the vectorized host staging active (the default,
        ``AUTOMERGE_TPU_HOST_BATCH``), the submitter hands its RAW
        batches over and the generation's flush leader stages every
        co-arriving document in one shared columnar pass
        (host_batch.stage_docs) before the shared kernel launch — the
        submitter keeps holding its document lock while it waits, so the
        single-writer discipline is unchanged. With the knob off, each
        submitter stages its own document (the scalar per-doc path) and
        only the launch is shared, exactly as before."""
        if not self.active():
            return dev.apply_batches(batches)
        from . import host_batch

        if host_batch.enabled():
            return self._apply_leader_staged(dev, batches)
        t0 = time.perf_counter()
        applied, stage = dev.stage_batches(batches)
        _prof.note("docs")
        _prof.note("changes", applied)
        _prof.note_doc(
            getattr(dev, "obs_name", None), time.perf_counter() - t0
        )
        if stage is None:
            return applied
        # attribute the (possibly other-thread) shared launch back to
        # this submitter's propagated trace, if one is active
        stage.trace = obs.current_trace_context()
        with self._cv:
            gen = self._gen
            gen.stages.append(stage)
            # leadership is elected over BOTH submission kinds: a
            # mid-generation AUTOMERGE_TPU_HOST_BATCH flip can mix
            # leader-staged subs and submitter-staged stages in one
            # generation, and exactly ONE leader must flush it
            leader = len(gen.stages) + len(gen.subs) == 1
            if not leader and len(gen.stages) + len(gen.subs) >= self.max_docs:
                self._cv.notify_all()  # wake the leader early
        if leader:
            self._lead(gen)
        else:
            gen.done.wait()
        if stage.error is not None:
            raise stage.error
        return applied

    def _apply_leader_staged(self, dev, batches) -> int:
        sub = _Submission(dev, list(batches), obs.current_trace_context())
        with self._cv:
            gen = self._gen
            gen.subs.append(sub)
            leader = len(gen.stages) + len(gen.subs) == 1
            if not leader and len(gen.stages) + len(gen.subs) >= self.max_docs:
                self._cv.notify_all()  # wake the leader early
        if leader:
            self._lead(gen)
        else:
            gen.done.wait()
        if sub.error is not None:
            raise sub.error
        return sub.applied

    def _lead(self, gen: _Generation) -> None:
        """The (single) flush leader: wait out the batch window for
        co-arriving documents, close the generation, flush it."""
        deadline = time.monotonic() + self.window
        with self._cv:
            while len(gen.stages) + len(gen.subs) < self.max_docs:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(left)
            if self._gen is gen:  # close the generation we lead
                self._gen = _Generation()
        self._flush(gen)

    def _flush(self, gen: _Generation) -> None:
        """Close one generation: stage any leader-staged submissions in
        one vectorized pass (host_batch.stage_docs), merge them with any
        submitter-staged stages (the scalar-knob mode — an env-knob flip
        mid-generation can mix the two; both drain here), launch once,
        and release every waiter. On failure everything degrades per
        doc."""
        from . import host_batch

        stages: List[BatchStage] = list(gen.stages)
        subs_staged = False
        try:
            if gen.subs:
                more, results = host_batch.stage_docs(
                    [(s.dev, s.batches) for s in gen.subs]
                )
                subs_staged = True
                trace_of = {}
                n_changes = 0
                for s in gen.subs:
                    r = results.get(id(s.dev))
                    if r is not None:
                        s.applied = r.applied
                        s.error = r.error
                        n_changes += r.applied
                    if s.trace is not None:
                        trace_of.setdefault(id(s.dev), s.trace)
                for st in more:
                    st.trace = trace_of.get(id(st.doc))
                _prof.note("docs", len(gen.subs))
                _prof.note("changes", n_changes)
                stages.extend(more)
            if (
                pipeline_enabled()
                and len(stages) >= self.pipeline_min_docs
            ):
                # wide generation: flush as two half-launches so the
                # second half's pack/linearize runs under the first
                # half's in-flight kernel (drain.overlap_fraction)
                mid = len(stages) // 2
                d1 = dispatch_stages(stages[:mid], self.fallback_ratio)
                try:
                    t0 = time.perf_counter()
                    d2 = dispatch_stages(
                        stages[mid:], self.fallback_ratio
                    )
                    _prof.note("overlap_s", time.perf_counter() - t0)
                except BaseException:
                    collect_stages(d1)
                    raise
                collect_stages(d1)
                collect_stages(d2)
            else:
                resolve_stages(stages, self.fallback_ratio)
        except BaseException as e:  # noqa: BLE001 — degrade per doc
            obs.count("device.batched_error")
            recovered = True
            for st in stages:
                try:
                    st.doc._reresolve(st.dirty)
                except BaseException as e2:  # noqa: BLE001
                    st.error = e2
                    recovered = False
            if not subs_staged:
                # staging itself failed before any submission's state
                # moved: every leader-staged submitter must see it
                for s in gen.subs:
                    if s.error is None:
                        s.error = e
            for st in stages:
                if st.error is not None:
                    for s in gen.subs:
                        if s.dev is st.doc and s.error is None:
                            s.error = st.error
            if recovered and stages:
                obs.event("device.batched_recovered", error=str(e)[:200])
        finally:
            gen.done.set()
