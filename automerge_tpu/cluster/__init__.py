"""Cluster tier: multi-node sharded serving with journal-shipping
replication and leader failover.

* hashring.py     — consistent-hash placement of documents onto groups
* replication.py  — leader-side hub shipping acked journal records,
                    follower catch-up (snapshot + journal tail), the
                    quorum ack gate
* node.py         — a backend node: socket server + leader/follower
                    role + the cluster RPC surface
* router.py       — the client-facing proxy: placement, handle
                    virtualization, heartbeat failover, live migration
"""

from .hashring import HashRing
from .node import ClusterNode, ClusterRpcServer, REPL_SHARD_KEY
from .replication import (
    ReplicationError,
    ReplicationHub,
    ReplicationTimeout,
    decode_batch,
    decode_cursor,
    encode_batch,
    encode_cursor,
)
from .router import ClusterRouter

__all__ = [
    "ClusterNode",
    "ClusterRouter",
    "ClusterRpcServer",
    "HashRing",
    "REPL_SHARD_KEY",
    "ReplicationError",
    "ReplicationHub",
    "ReplicationTimeout",
    "decode_batch",
    "decode_cursor",
    "encode_batch",
    "encode_cursor",
]
