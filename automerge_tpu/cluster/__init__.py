"""Cluster tier: multi-node sharded serving with journal-shipping
replication and leader failover.

* hashring.py     — consistent-hash placement of documents onto groups
* replication.py  — leader-side hub shipping acked journal records,
                    follower catch-up (snapshot + journal tail), the
                    quorum ack gate
* node.py         — a backend node: socket server + leader/follower
                    role + the cluster RPC surface
* router.py       — the client-facing proxy: placement, handle
                    virtualization, heartbeat failover, live migration
* chaos.py        — the chaos fabric: a seeded TCP fault interposer
                    (drop/delay/throttle/partition/sever) + scripted
                    fault schedules for the soak
"""

from .chaos import ChaosProxy, ChaosSchedule, LinkPolicy
from .hashring import HashRing
from .node import ClusterNode, ClusterRpcServer, REPL_SHARD_KEY
from .replication import (
    ReplicationError,
    ReplicationHub,
    ReplicationTimeout,
    decode_batch,
    decode_cursor,
    encode_batch,
    encode_cursor,
)
from .router import ClusterRouter

__all__ = [
    "ChaosProxy",
    "ChaosSchedule",
    "ClusterNode",
    "ClusterRouter",
    "ClusterRpcServer",
    "HashRing",
    "LinkPolicy",
    "REPL_SHARD_KEY",
    "ReplicationError",
    "ReplicationHub",
    "ReplicationTimeout",
    "decode_batch",
    "decode_cursor",
    "encode_batch",
    "encode_cursor",
]
