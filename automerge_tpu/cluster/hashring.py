"""Consistent hashing of document ids onto backend shard groups.

The router tier (cluster/router.py) places every durable document name
on one shard group (a leader plus its followers). Placement must be
stable across router restarts and minimally disruptive when groups join
or leave — the classic consistent-hash ring: each group projects
``vnodes`` points onto a 64-bit circle (sha256 of ``group:replica``),
a document maps to the first point clockwise of its own hash, and
adding or removing one group only moves the keys that landed on its
arcs (~1/N of the keyspace).

The ring is pure placement: migration overrides (a doc moved off its
hash-home by a live shard migration) live in the router, not here.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Hashable, List, Tuple


def _point(key: str) -> int:
    return int.from_bytes(
        hashlib.sha256(key.encode("utf-8")).digest()[:8], "big"
    )


class HashRing:
    """Stable key -> member placement with virtual nodes."""

    def __init__(self, members: List[Hashable] = (), vnodes: int = 64):
        if vnodes <= 0:
            raise ValueError("vnodes must be positive")
        self.vnodes = vnodes
        self._points: List[Tuple[int, Hashable]] = []
        self._members: Dict[Hashable, None] = {}
        for m in members:
            self.add(m)

    @property
    def members(self) -> List[Hashable]:
        return list(self._members)

    def add(self, member: Hashable) -> None:
        if member in self._members:
            return
        self._members[member] = None
        for i in range(self.vnodes):
            self._points.append((_point(f"{member}:{i}"), member))
        self._points.sort()

    def remove(self, member: Hashable) -> None:
        if member not in self._members:
            return
        del self._members[member]
        self._points = [(h, m) for h, m in self._points if m != member]

    def member_for(self, key: str) -> Hashable:
        """The member owning ``key``; raises when the ring is empty."""
        if not self._points:
            raise ValueError("hash ring has no members")
        h = _point(key)
        i = bisect.bisect_right(self._points, (h, ""))
        if i >= len(self._points):
            i = 0
        return self._points[i][1]

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: Hashable) -> bool:
        return member in self._members
