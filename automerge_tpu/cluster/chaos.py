"""Chaos fabric: a seeded, scriptable TCP fault interposer.

``ChaosProxy`` generalizes the in-process ``FaultyChannel``
(sync/faults.py) to real sockets: it sits between router↔node and
leader↔follower links as a transparent byte pump that can, per
direction,

* **drop** a read chunk (seeded probability — on a line-framed protocol
  this garbles at most the frames the chunk covered; both the router and
  the server tolerate garbled lines, and the retry layer owns the rest),
* **corrupt** a read chunk (seeded probability): one byte of the chunk
  is bit-flipped before forwarding — a payload that still parses as a
  frame carries silently-wrong bytes, the wire analogue of disk bit rot
  (a garbled frame is rejected at the framing layer; a *valid* frame
  with corrupt content is what the integrity digests exist to catch),
* **delay** every chunk by a fixed latency,
* **throttle** to a byte rate,
* **reorder** a chunk behind its successor (seeded probability),
* **black-hole** one direction entirely — the *asymmetric partition*:
  requests still arrive, responses never return (or vice versa), the
  deadlock-shaped failure a symmetric kill can never produce,
* **sever** every live connection (and refuse new ones) until
  ``heal()``.

Everything stochastic draws from one seeded RNG per proxy, so a fault
sequence is reproducible from its seed. Every injected fault counts
``chaos.injected{kind=...}`` — a soak asserts its faults actually fired
instead of vacuously passing.

``ChaosSchedule`` runs a scripted timeline of fault actions on a
background thread::

    p = ChaosProxy(target="127.0.0.1:7001", seed=3); p.start()
    sched = ChaosSchedule()
    sched.at(2.0, "partition", lambda: p.partition("s2c"))
    sched.at(6.0, "heal", lambda: p.heal())
    sched.start(); ...; sched.join()

The schedule itself is plain data (sorted ``(at, label)`` steps), so two
schedules built from the same seed compare equal — the determinism the
``CHAOS_SEED`` replay workflow relies on.
"""

from __future__ import annotations

import contextlib
import random
import socket
import threading
import time
from typing import Callable, List, Optional, Tuple

from .. import obs

_CHUNK = 64 << 10

DIRECTIONS = ("c2s", "s2c")


def _count(kind: str) -> None:
    obs.count("chaos.injected", labels={"kind": kind})


class LinkPolicy:
    """Per-direction fault dials for one proxy. Mutable at runtime (the
    schedule flips them live); reads are lock-free snapshots of floats
    and bools, which Python assigns atomically."""

    __slots__ = ("drop", "reorder", "delay_s", "throttle_bps", "blackhole",
                 "corrupt")

    def __init__(self, drop: float = 0.0, reorder: float = 0.0,
                 delay_s: float = 0.0, throttle_bps: float = 0.0,
                 blackhole: bool = False, corrupt: float = 0.0):
        self.drop = drop
        self.reorder = reorder
        self.delay_s = delay_s
        self.throttle_bps = throttle_bps
        self.blackhole = blackhole
        self.corrupt = corrupt


class _Pipe:
    """One direction of one proxied connection."""

    def __init__(self, proxy: "ChaosProxy", direction: str,
                 src: socket.socket, dst: socket.socket, rng: random.Random):
        self.proxy = proxy
        self.direction = direction
        self.src = src
        self.dst = dst
        self.rng = rng
        self._held: Optional[bytes] = None  # chunk waiting to be overtaken

    def run(self) -> None:
        try:
            while True:
                try:
                    chunk = self.src.recv(_CHUNK)
                except OSError:
                    break
                if not chunk:
                    break
                if not self._forward(chunk):
                    break
        finally:
            # flush a held (reordered) chunk rather than silently eat it:
            # reorder means late, not lost
            held, self._held = self._held, None
            if held is not None:
                with contextlib.suppress(OSError):
                    self.dst.sendall(held)
            # half-close so the peer sees EOF on this direction only
            with contextlib.suppress(OSError):
                self.dst.shutdown(socket.SHUT_WR)
            with contextlib.suppress(OSError):
                self.src.shutdown(socket.SHUT_RD)

    def _forward(self, chunk: bytes) -> bool:
        pol = self.proxy.policy(self.direction)
        if pol.blackhole:
            # asymmetric partition: swallow, keep reading (the socket
            # stays up — the far side sees silence, not a reset)
            _count(f"blackhole_{self.direction}")
            return True
        if pol.drop and self.rng.random() < pol.drop:
            _count("drop")
            return True
        if pol.corrupt and self.rng.random() < pol.corrupt:
            _count("corrupt")
            i = self.rng.randrange(len(chunk))
            chunk = chunk[:i] + bytes([chunk[i] ^ 0x40]) + chunk[i + 1:]
        if pol.delay_s:
            _count("delay")
            time.sleep(pol.delay_s)
        if pol.throttle_bps:
            _count("throttle")
            time.sleep(len(chunk) / pol.throttle_bps)
        out = chunk
        if self._held is not None:
            out = chunk + self._held  # the held chunk arrives LATE
            self._held = None
        elif pol.reorder and self.rng.random() < pol.reorder:
            _count("reorder")
            self._held = chunk
            return True
        try:
            self.dst.sendall(out)
        except OSError:
            return False
        return True


class ChaosProxy:
    """A TCP interposer between one upstream and its clients.

    ``target`` is ``"host:port"``; the proxy listens on its own
    ``address`` and pumps bytes both ways through the fault policies.
    All control methods are safe to call from any thread at any time.
    """

    def __init__(self, target: str, *, host: str = "127.0.0.1",
                 port: int = 0, seed: int = 0, name: Optional[str] = None):
        thost, _, tport = target.rpartition(":")
        self.target = (thost or "127.0.0.1", int(tport))
        self.name = name or f"chaos->{target}"
        self._host = host
        self._port = port
        self._rng = random.Random(seed)
        self._policies = {d: LinkPolicy() for d in DIRECTIONS}
        self._severed = False
        self._listener: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self._conns: List[Tuple[socket.socket, socket.socket]] = []
        self._stop = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> str:
        assert self._listener is not None, "proxy not started"
        return "%s:%d" % self._listener.getsockname()[:2]

    def start(self) -> "ChaosProxy":
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self._port))
        ls.listen(64)
        self._listener = ls
        threading.Thread(target=self._accept_loop,
                         name=f"{self.name}-accept", daemon=True).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            with contextlib.suppress(OSError):
                self._listener.close()
        self._close_conns()

    def live_connections(self) -> int:
        """Open proxied connection pairs — the fd-leak assertion surface
        (0 after ``stop()`` means no pump stranded its sockets)."""
        with self._lock:
            return len(self._conns)

    # -- fault controls ------------------------------------------------------

    def policy(self, direction: str) -> LinkPolicy:
        return self._policies[direction]

    def set_policy(self, direction: str, **dials) -> None:
        pol = self._policies[direction]
        for k, v in dials.items():
            if k not in LinkPolicy.__slots__:
                raise ValueError(f"unknown policy dial {k!r}")
            setattr(pol, k, v)

    def partition(self, direction: str = "both") -> None:
        """Black-hole one direction (``"c2s"`` / ``"s2c"``) or both.
        Existing connections stay up; bytes in the partitioned direction
        vanish — the asymmetric partition a FIN can never express."""
        dirs = DIRECTIONS if direction == "both" else (direction,)
        for d in dirs:
            if d not in DIRECTIONS:
                raise ValueError(f"unknown direction {d!r}")
            self._policies[d].blackhole = True
        _count(f"partition_{direction}")
        obs.event("chaos.partition", proxy=self.name, direction=direction)

    def sever(self) -> None:
        """Cut every live connection and refuse new ones until heal() —
        the crashed-switch failure (peers see resets, not silence)."""
        self._severed = True
        _count("sever")
        obs.event("chaos.sever", proxy=self.name)
        self._close_conns()

    def heal(self) -> None:
        """Clear partition + sever: new connections flow clean. (Dial
        faults — drop/delay/throttle/reorder — are policy state and stay
        as set.)"""
        for d in DIRECTIONS:
            self._policies[d].blackhole = False
        self._severed = False
        _count("heal")
        obs.event("chaos.heal", proxy=self.name)

    # -- the pump ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                csock, _ = self._listener.accept()
            except OSError:
                return
            if self._severed:
                with contextlib.suppress(OSError):
                    csock.close()
                continue
            threading.Thread(target=self._serve_conn, args=(csock,),
                             name=f"{self.name}-conn", daemon=True).start()

    def _serve_conn(self, csock: socket.socket) -> None:
        try:
            ssock = socket.create_connection(self.target, timeout=10)
        except OSError:
            with contextlib.suppress(OSError):
                csock.close()
            return
        for s in (csock, ssock):
            with contextlib.suppress(OSError):
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        pair = (csock, ssock)
        with self._lock:
            self._conns.append(pair)
        obs.count("chaos.proxied_connections")
        # deterministic per-connection RNG streams drawn from the proxy
        # seed: thread interleaving cannot reorder WHICH faults fire on a
        # given connection's byte stream
        seeds = (self._rng.randrange(1 << 30), self._rng.randrange(1 << 30))
        pipes = [
            _Pipe(self, "c2s", csock, ssock, random.Random(seeds[0])),
            _Pipe(self, "s2c", ssock, csock, random.Random(seeds[1])),
        ]
        threads = [
            threading.Thread(target=p.run, name=f"{self.name}-{p.direction}",
                             daemon=True)
            for p in pipes
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for s in pair:
            with contextlib.suppress(OSError):
                s.close()
        with self._lock:
            if pair in self._conns:
                self._conns.remove(pair)

    def _close_conns(self) -> None:
        with self._lock:
            conns = list(self._conns)
        for pair in conns:
            for s in pair:
                with contextlib.suppress(OSError):
                    s.shutdown(socket.SHUT_RDWR)
                with contextlib.suppress(OSError):
                    s.close()


class ChaosSchedule:
    """A scripted fault timeline: ordered ``(at_seconds, label, action)``
    steps executed on a background thread. The step list (times + labels)
    is plain data — print it, compare it, rebuild it from the same seed
    and it is identical; only ``run`` touches the wall clock."""

    def __init__(self):
        self.steps: List[Tuple[float, str, Callable[[], None]]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.executed: List[Tuple[float, str]] = []  # (at, label) as run
        self.errors: List[Tuple[str, str]] = []  # (label, error) of failures

    def at(self, at_s: float, label: str, action: Callable[[], None]
           ) -> "ChaosSchedule":
        self.steps.append((float(at_s), label, action))
        self.steps.sort(key=lambda s: s[0])
        return self

    def plan(self) -> List[Tuple[float, str]]:
        """The timeline as data (the determinism/replay surface)."""
        return [(at, label) for at, label, _ in self.steps]

    def start(self) -> "ChaosSchedule":
        self._thread = threading.Thread(
            target=self._run, name="chaos-schedule", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        t0 = time.monotonic()
        for at, label, action in self.steps:
            delay = at - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            obs.event("chaos.step", at=round(at, 3), step=label)
            try:
                action()
            except Exception as e:  # noqa: BLE001 — a failed step is data
                obs.count("chaos.step_error", step=label, error=str(e)[:200])
                self.errors.append((label, f"{type(e).__name__}: {e}"))
            self.executed.append((at, label))

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for the timeline to finish; True when it did."""
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def cancel(self) -> None:
        self._stop.set()
