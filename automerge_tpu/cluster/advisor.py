"""Report-only placement advisor: ranked, explained recommendations.

ROADMAP item 1 (read scale-out and elastic placement) needs an
actuation loop; this module is the half that can be built — and
trusted — first: a **pure function** from one telemetry snapshot (the
per-group heat tables of obs/heat.py, the seconds-based staleness of
the replication hub, per-doc store tiers) to an ordered list of
migration / replication / attention recommendations, each with a
human-readable reason. It never moves anything: the router's
``clusterAdvise`` RPC serves its output, the ``cluster-top`` CLI
renders it live, and actuation stays a small follow-up that consumes
the same list.

Being a pure function of its input dict is the whole design: the unit
tests feed synthetic skew and assert exact output; determinism comes
from sorted iteration and explicit tie-breaks (score desc, then kind,
then doc name) — no clocks, no randomness, no I/O.

Rule set (each rule names itself in the reason string):

* **imbalance** — when one group's total request heat exceeds
  ``imbalance_ratio``× the coolest group's, recommend moving the
  hottest group's *coldest* documents (cold ballast moves cheap and
  frees capacity without relocating the hotspot) to the coolest group;
* **hot-doc** — when a single document carries more than ``hot_frac``
  of its group's heat, migration would only move the hotspot, so
  recommend adding a read replica for that document instead;
* **staleness** — a follower whose staleness exceeds
  ``staleness_threshold`` seconds gets an attention recommendation
  (replication is the bottleneck there, not placement);
* **tier** — a document ranked in its group's top few by heat but
  resident warm/cold is paying hydration latency on a hot path:
  recommend promotion.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _group_load(heat: dict) -> float:
    return sum(
        float(e.get("rank", 0.0))
        for e in (heat.get("entries") or ())
    )


def _fmt(v: float) -> str:
    return f"{v:.2f}"


def advise(
    snapshot: dict,
    *,
    max_recommendations: int = 8,
    imbalance_ratio: float = 2.0,
    hot_frac: float = 0.7,
    staleness_threshold: float = 1.0,
    migrate_docs: int = 3,
) -> dict:
    """Snapshot in, ranked explained recommendations out. See the
    module docstring for the shape contract: ``snapshot["groups"]`` is
    a list of ``{"group": idx, "leader": addr, "heat": <heatStatus>,
    "staleness": <hub staleness_report>, "tiers": {doc: tier}}`` (all
    parts optional — missing telemetry shrinks the rule set, it never
    raises)."""
    groups = sorted(
        (g for g in (snapshot.get("groups") or ()) if isinstance(g, dict)),
        key=lambda g: g.get("group", 0),
    )
    recs: List[dict] = []
    loads: Dict[int, float] = {}
    for g in groups:
        loads[g.get("group", 0)] = _group_load(g.get("heat") or {})

    # -- imbalance / hot-doc (needs at least two groups) ---------------------
    if len(groups) >= 2:
        by_load = sorted(groups, key=lambda g: (loads[g.get("group", 0)],
                                                g.get("group", 0)))
        coolest, hottest = by_load[0], by_load[-1]
        lo = loads[coolest.get("group", 0)]
        hi = loads[hottest.get("group", 0)]
        if hi > 0.0 and hi > imbalance_ratio * max(lo, 1e-9):
            entries = sorted(
                ((hottest.get("heat") or {}).get("entries") or ()),
                key=lambda e: (-float(e.get("rank", 0.0)),
                               str(e.get("doc", ""))),
            )
            top = entries[0] if entries else None
            if top is not None and float(top.get("rank", 0.0)) > hot_frac * hi:
                recs.append({
                    "kind": "replicate",
                    "doc": str(top.get("doc", "")),
                    "group": hottest.get("group", 0),
                    "score": round(float(top.get("rank", 0.0)), 4),
                    "reason": (
                        f"doc {top.get('doc')!r} carries "
                        f"{_fmt(100.0 * float(top.get('rank', 0.0)) / hi)}% "
                        f"of group {hottest.get('group', 0)}'s heat "
                        f"({_fmt(hi)} vs coolest group "
                        f"{coolest.get('group', 0)} at {_fmt(lo)}); "
                        "migrating it would only move the hotspot — add a "
                        "read replica and route reads there instead"
                    ),
                })
            else:
                # cold ballast: cheapest-to-move docs first, never the
                # hottest (moving the top doc moves the problem)
                ballast = sorted(
                    entries[1:] if len(entries) > 1 else entries,
                    key=lambda e: (float(e.get("rank", 0.0)),
                                   str(e.get("doc", ""))),
                )
                gap = hi - lo
                for e in ballast[:migrate_docs]:
                    recs.append({
                        "kind": "migrate",
                        "doc": str(e.get("doc", "")),
                        "group": hottest.get("group", 0),
                        "to": coolest.get("group", 0),
                        "score": round(gap, 4),
                        "reason": (
                            f"group {hottest.get('group', 0)} carries "
                            f"{_fmt(hi)} heat vs group "
                            f"{coolest.get('group', 0)}'s {_fmt(lo)} "
                            f"(> {imbalance_ratio:g}x); "
                            f"doc {e.get('doc')!r} is cold ballast there "
                            f"(rank {_fmt(float(e.get('rank', 0.0)))}) — "
                            "moving it rebalances without relocating the "
                            "hot set"
                        ),
                    })

    # -- staleness attention --------------------------------------------------
    for g in groups:
        stale = g.get("staleness") or {}
        for follower in sorted(stale):
            per = (stale.get(follower) or {}).get("computed") or {}
            if not per:
                continue
            worst_doc = max(sorted(per), key=lambda d: per[d])
            worst = float(per[worst_doc])
            if worst > staleness_threshold:
                recs.append({
                    "kind": "staleness",
                    "doc": str(worst_doc),
                    "group": g.get("group", 0),
                    "node": str(follower),
                    "score": round(worst, 4),
                    "reason": (
                        f"follower {follower} is {_fmt(worst)}s stale on "
                        f"doc {worst_doc!r} (threshold "
                        f"{staleness_threshold:g}s): replication, not "
                        "placement, is the bottleneck — check link health "
                        "before routing reads there"
                    ),
                })

    # -- tier mismatch --------------------------------------------------------
    for g in groups:
        tiers = g.get("tiers") or {}
        entries = sorted(
            ((g.get("heat") or {}).get("entries") or ()),
            key=lambda e: (-float(e.get("rank", 0.0)), str(e.get("doc", ""))),
        )
        for e in entries[:3]:
            doc = str(e.get("doc", ""))
            tier = tiers.get(doc)
            rank = float(e.get("rank", 0.0))
            if tier in ("warm", "cold") and rank > 0.0:
                recs.append({
                    "kind": "promote",
                    "doc": doc,
                    "group": g.get("group", 0),
                    "score": round(rank, 4),
                    "reason": (
                        f"doc {doc!r} ranks top-3 by heat in group "
                        f"{g.get('group', 0)} (rank {_fmt(rank)}) but is "
                        f"resident {tier}: every access pays hydration — "
                        "promote it to the hot tier"
                    ),
                })

    recs.sort(key=lambda r: (-r["score"], r["kind"], r.get("doc", "")))
    return {
        "recommendations": recs[:max_recommendations],
        "groupLoads": {str(k): round(v, 4) for k, v in sorted(loads.items())},
        "groups": [
            {
                "group": g.get("group", 0),
                "leader": g.get("leader"),
                "load": round(loads[g.get("group", 0)], 4),
                "docs": len((g.get("heat") or {}).get("entries") or ()),
            }
            for g in groups
        ],
    }


def render_text(advice: dict, top: Optional[int] = None) -> str:
    """The ``cluster-top`` / ``clusterAdvise`` human rendering."""
    lines = []
    groups = advice.get("groups") or []
    if groups:
        lines.append(f"  {'group':<7} {'leader':<24} {'load':>10} {'docs':>6}")
        for g in groups:
            lines.append(
                f"  {g.get('group', 0):<7} {str(g.get('leader', '')):<24} "
                f"{g.get('load', 0.0):>10.2f} {g.get('docs', 0):>6}"
            )
    recs = advice.get("recommendations") or []
    if not recs:
        lines.append("no recommendations: load is balanced and "
                     "replication is fresh")
    else:
        lines.append("recommendations (report-only; nothing was moved):")
        for i, r in enumerate(recs[: top or len(recs)], start=1):
            lines.append(f"  {i}. [{r.get('kind')}] "
                         f"score {r.get('score', 0.0):g}")
            lines.append(f"     {r.get('reason', '')}")
    return "\n".join(lines) + "\n"
