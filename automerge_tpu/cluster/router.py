"""The router tier: consistent-hash placement, proxying, failover.

``ClusterRouter`` speaks the exact line-delimited JSON-RPC framing on
both sides: clients connect to it as if it were a single server, and it
fans their requests out to backend shard groups (a leader plus its
followers, cluster/node.py). Placement is by durable document name on a
consistent-hash ring (cluster/hashring.py) plus a migration override
table; handle ids are virtualized so a client never sees (or depends
on) which node owns its documents.

Ordering: requests from one client connection against one document flow
through one router thread onto one pooled node connection, and the
node's per-document shard queue serializes them — same-doc requests
keep arrival order end to end.

Failover: a heartbeat monitor polls each group leader's
``clusterStatus``; consecutive misses (or a connection death observed
by the data path) trigger failover — the group freezes, every reachable
follower reports its durable replication cursor, the **longest durable
acked prefix** wins promotion (follower states are strict prefixes of
the leader's ship order, so "longest" is well-defined), surviving
followers are rewired onto the new leader, and the group unfreezes.
Virtual handles re-resolve lazily: a durable doc re-opens by name on
the new leader, an attached sync session re-attaches by (doc, peer) —
the epoch bump makes the client's surviving session renegotiate via the
epoch/reset handshake instead of a full resync. Requests in flight on
the dead node answer ``Unavailable`` (retriable); requests arriving
during the freeze wait it out.

Live shard migration (``clusterMigrate``): snapshot while serving, then
pause the doc, ship the journal tail, flip the override, release the
source — the compaction dance, across two nodes.

Run: ``python -m automerge_tpu cluster-router --listen HOST:PORT
--group addr,addr,... [--group ...]``.
"""

from __future__ import annotations

import contextlib
import json
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..serve.admission import priority_class
from .hashring import HashRing
from .replication import _env_float

_CREATES = {
    # method -> result field that carries a fresh handle, and its kind
    "create": ("doc", "doc"),
    "load": ("doc", "doc"),
    "fork": ("doc", "doc"),
    "openDurable": ("doc", "doc"),
    "syncStateNew": ("sync", "sync"),
    "syncStateDecode": ("sync", "sync"),
    "syncSessionNew": ("session", "session"),
    "syncSessionRestore": ("session", "session"),
    "syncSessionAttach": ("session", "session"),
}

_FREES = {"free": "doc", "syncStateFree": "sync", "syncSessionFree": "session"}

# params fields that carry handles, by name
_HANDLE_PARAMS = ("doc", "other", "sync", "session")

_ROUTER_METHODS = frozenset({
    "metrics", "clusterMetrics", "clusterInfo", "clusterMigrate",
    "clusterJoin", "clusterAdvise", "shutdown"})


class _VHandle:
    """One virtualized client handle."""

    __slots__ = ("kind", "group", "real", "gen", "name", "doc_vid", "peer")

    def __init__(self, kind, group, real, gen, *, name=None, doc_vid=None,
                 peer=None):
        self.kind = kind
        self.group = group
        self.real = real  # node-side integer handle
        self.gen = gen  # group generation the handle was minted under
        self.name = name  # durable doc name (re-resolvable)
        self.doc_vid = doc_vid  # sessions: their document's vid
        self.peer = peer  # attached sessions: peer name (re-attachable)


class _Group:
    """One shard group: an ordered list of node addresses + leadership."""

    def __init__(self, idx: int, addrs: List[str]):
        self.idx = idx
        self.addrs = list(addrs)
        self.leader = addrs[0]
        self.gen = 0  # bumps on every failover; stale handles re-resolve
        self.stream: Optional[str] = None  # leader's replication stream id
        self.up = threading.Event()
        self.up.set()
        self.failing = False  # a failover for this group is in flight
        # overload advertisement from the leader's heartbeat: lowest
        # priority rank it is shedding (5 = nothing), its backoff hint,
        # and when the advertisement was read (stale ones are ignored)
        self.shed_class = 5
        self.shed_retry_ms = 0
        self.shed_ts = 0.0


class _DataConn:
    """One pooled router->node connection: pipelined, id-rewritten."""

    def __init__(self, addr: str):
        host, _, port = addr.rpartition(":")
        self.addr = addr
        self.sock = socket.create_connection((host, int(port)), timeout=10)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.f = self.sock.makefile("r")
        self.wlock = threading.Lock()
        self.plock = threading.Lock()
        self.pending: Dict[int, Tuple] = {}  # nid -> (conn, rid, ctx)
        self.nid = 0
        self.dead = False

    def send(self, req: dict, conn, rid, ctx) -> None:
        with self.plock:
            if self.dead:
                raise OSError("node connection is dead")
            self.nid += 1
            nid = self.nid
            # the method rides along so the death sweep can label its
            # Unavailable answers (cluster.unavailable{method})
            self.pending[nid] = (conn, rid, ctx, req.get("method"))
        req["id"] = nid
        data = (json.dumps(req) + "\n").encode("utf-8")
        try:
            with self.wlock:
                self.sock.sendall(data)
        except Exception as e:
            with self.plock:
                swept = self.pending.pop(nid, None) is None
            if swept:
                # the reader observed the death first and already
                # answered this request from the pending sweep — a
                # second reply would desynchronize the client
                raise _AlreadyAnswered() from e
            raise

    def close(self) -> None:
        self.dead = True
        with contextlib.suppress(OSError):
            self.sock.shutdown(socket.SHUT_RDWR)
        with contextlib.suppress(OSError):
            self.sock.close()


class ClusterRouter:
    """See module docstring."""

    def __init__(
        self,
        groups: List[List[str]],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        conns_per_node: int = 2,
        heartbeat: Optional[float] = None,
        miss_limit: int = 3,
        vnodes: int = 64,
    ):
        if not groups or any(not g for g in groups):
            raise ValueError("router needs at least one non-empty group")
        self._groups = [_Group(i, g) for i, g in enumerate(groups)]
        self._ring = HashRing(list(range(len(groups))), vnodes=vnodes)
        self._overrides: Dict[str, int] = {}  # migrated doc name -> group
        self._migrating: Dict[str, threading.Event] = {}
        self._host = host
        self._port = port
        self._conns_per_node = max(1, conns_per_node)
        self.heartbeat = (
            heartbeat if heartbeat is not None
            else _env_float("AUTOMERGE_TPU_CLUSTER_HEARTBEAT", 1.0)
        )
        self.miss_limit = max(1, miss_limit)
        self.unavailable_timeout = _env_float(
            "AUTOMERGE_TPU_CLUSTER_ACK_TIMEOUT", 30.0)
        self._lock = threading.RLock()
        self._vh: Dict[int, _VHandle] = {}
        self._durable_vids: Dict[str, int] = {}  # doc name -> vid
        self._next_vid = 1
        self._links: Dict[str, List[_DataConn]] = {}  # addr -> pool
        self._listener: Optional[socket.socket] = None
        self._shutdown = threading.Event()
        self._failover_wanted: Dict[int, bool] = {}
        self._monitor_wake = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        assert self._listener is not None, "router not started"
        return self._listener.getsockname()[:2]

    def start(self) -> None:
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self._port))
        ls.listen(128)
        self._listener = ls
        for name, target in (
            ("router-accept", self._accept_loop),
            ("router-monitor", self._monitor_loop),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def serve_forever(self) -> None:
        if self._listener is None:
            self.start()
        self._shutdown.wait()
        self.stop()

    def stop(self) -> None:
        self._shutdown.set()
        self._monitor_wake.set()
        if self._listener is not None:
            with contextlib.suppress(OSError):
                self._listener.close()
            self._listener = None
        with self._lock:
            pools = [c for pool in self._links.values()
                     for c in pool if c is not None]
            self._links.clear()
        for c in pools:
            c.close()

    # -- placement -----------------------------------------------------------

    def group_for_name(self, name: str) -> _Group:
        with self._lock:
            idx = self._overrides.get(name)
        if idx is None:
            idx = self._ring.member_for(name)
        return self._groups[idx]

    def _anchor_group(self, cid: int) -> _Group:
        # connection-scoped state (plain docs, bare sync states) pins to
        # one group so cross-handle methods (merge, generateSyncMessage)
        # land on a single node
        return self._groups[
            self._ring.member_for(f"__conn__{cid}")
        ]

    # -- node connections ----------------------------------------------------

    def _data_conn(self, addr: str, affinity: int) -> _DataConn:
        with self._lock:
            pool = self._links.get(addr)
            if pool is None:
                pool = self._links[addr] = []
            slot = affinity % self._conns_per_node
            while len(pool) <= slot:
                pool.append(None)
            conn = pool[slot]
            if conn is not None and not conn.dead:
                return conn
        conn = _DataConn(addr)
        t = threading.Thread(
            target=self._node_reader, args=(conn,),
            name=f"router-node-{addr}", daemon=True,
        )
        with self._lock:
            pool = self._links.setdefault(addr, [])
            while len(pool) <= slot:
                pool.append(None)
            if pool[slot] is not None and not pool[slot].dead:
                conn.close()
                return pool[slot]
            pool[slot] = conn
        t.start()
        return conn

    def _admin(self, addr: str, method: str, params: dict,
               timeout: float = 10.0) -> dict:
        """One synchronous request on a fresh short-lived connection —
        the control path (status polls, promotion, re-resolution,
        migration) must not share fate with pipelined data traffic."""
        host, _, port = addr.rpartition(":")
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            line = json.dumps(
                {"id": 1, "method": method, "params": params}) + "\n"
            sock.sendall(line.encode("utf-8"))
            f = sock.makefile("r")
            raw = f.readline()
        if not raw:
            raise OSError(f"{addr}: connection closed during {method}")
        resp = json.loads(raw)
        if "error" in resp:
            err = resp["error"]
            raise RuntimeError(f"{err.get('type')}: {err.get('message')}")
        return resp.get("result") or {}

    # -- client side ---------------------------------------------------------

    def _accept_loop(self) -> None:
        cid = 0
        while not self._shutdown.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            with contextlib.suppress(OSError):
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            cid += 1
            obs.count("router.accepted")
            threading.Thread(
                target=self._client_loop, args=(cid, sock),
                name=f"router-client-{cid}", daemon=True,
            ).start()

    def _client_loop(self, cid: int, sock: socket.socket) -> None:
        wlock = threading.Lock()

        def reply(payload: dict) -> None:
            data = (json.dumps(payload) + "\n").encode("utf-8")
            try:
                with wlock:
                    sock.sendall(data)
            except OSError:
                pass

        conn = (sock, wlock, reply)
        f = sock.makefile("rb")
        try:
            while not self._shutdown.is_set():
                raw = f.readline(32 << 20)
                if not raw:
                    return
                line = raw.decode("utf-8", errors="replace").strip()
                if not line:
                    continue
                try:
                    req = json.loads(line)
                    if not isinstance(req, dict):
                        raise ValueError("request must be a JSON object")
                except Exception as e:
                    reply({"id": None, "error": {
                        "type": "ParseError", "message": str(e),
                        "retriable": False}})
                    continue
                # deadline propagation: note when the budget-carrying
                # request entered the router, so the forwarded deadline
                # can be rewritten net of router queueing/waits
                if "deadlineMs" in req:
                    req["_arrival"] = obs.now()
                try:
                    self._route(cid, conn, req)
                except _RouteError as e:
                    obs.count("router.errors", labels={"type": e.type})
                    if e.type == "Unavailable":
                        # failover-window error volume, by method — the
                        # measurable cost of an outage to clients
                        obs.count("cluster.unavailable", labels={
                            "method": str(req.get("method"))[:40]})
                    # every router-originated error states its retry
                    # semantics: Unavailable (outage windows) is
                    # retriable, handle/placement errors are not
                    err = {"type": e.type, "message": str(e),
                           "retriable": e.type == "Unavailable"}
                    reply({"id": req.get("id"), "error": err})
                except Exception as e:  # noqa: BLE001 — isolate clients
                    obs.count("router.errors",
                              labels={"type": type(e).__name__})
                    # non-_RouteError escapes are router-side infra
                    # mishaps (an admin call racing a failover, a dead
                    # pooled conn) — transient by nature, so retriable
                    reply({"id": req.get("id"), "error": {
                        "type": "RouterError", "message": str(e),
                        "retriable": True}})
        finally:
            with contextlib.suppress(Exception):
                f.close()
            with contextlib.suppress(OSError):
                sock.close()

    # -- routing -------------------------------------------------------------

    def _route(self, cid: int, conn, req: dict) -> None:
        method = req.get("method")
        reply = conn[2]
        if method == "shutdown":
            # the ack must leave before stop() sweeps the sockets closed
            reply({"id": req.get("id"), "result": None})
            self._shutdown.set()
            self._monitor_wake.set()
            return
        if method in _ROUTER_METHODS:
            reply(self._local(method, req))
            return
        # trace propagation: a client-supplied {"trace": {"t", "s"}}
        # parents the router's span into the client's chain and is
        # rewritten to name the ROUTER span as the node's parent — so
        # the proxied hop appears between client and leader in the
        # merged flight timeline. No trace field, no work.
        tr = req.get("trace")
        if isinstance(tr, dict):
            with obs.trace_scope(tr.get("t"), tr.get("s")):
                with obs.span("router.request",
                              labels={"method": str(method)[:40]}) as sp:
                    fwd = None
                    tid = obs.current_trace.get()
                    if tid is not None:
                        fwd = {"t": tid, "s": sp.span_id}
                    self._route_remote(cid, conn, req, trace=fwd)
            return
        with obs.span("router.request", labels={"method": str(method)[:40]}):
            self._route_remote(cid, conn, req)

    def _route_remote(self, cid: int, conn, req: dict, trace=None) -> None:
        method = req.get("method")
        rid = req.get("id")
        params = dict(req.get("params") or {})

        # 1. placement: which group must serve this request. A doc
        # mid-migration holds its traffic until the flip, and the group
        # is (re)computed AFTER the wait — the whole point of waiting is
        # that the answer may change
        name = None
        if method == "openDurable" or (
            method == "docDigest" and isinstance(params.get("name"), str)
        ):
            name = params.get("name")
            if not isinstance(name, str):
                raise _RouteError("ValueError", "openDurable requires name")
            self._await_migration(name)
            group = self.group_for_name(name)
            vh = None
        else:
            vh, group = self._params_group(cid, params)
            if vh is not None and vh.name is not None:
                self._await_migration(vh.name)
                group = self.group_for_name(vh.name)

        # 2. group availability (failover may be in flight)
        if not group.up.wait(timeout=self.unavailable_timeout):
            raise _RouteError(
                "Unavailable", f"group {group.idx} has no leader")

        # 2b. shed-mode: the leader's heartbeat advertised it is
        # refusing this priority class — answer Overloaded here instead
        # of burning a round trip on a guaranteed refusal. Stale
        # advertisements (no heartbeat for ~3 periods) are ignored.
        if group.shed_class < 5 and (
            obs.now() - group.shed_ts <= max(self.heartbeat * 3, 3.0)
        ):
            rank, cls = priority_class(method if isinstance(method, str)
                                       else "")
            if rank >= group.shed_class:
                obs.count("router.shed", labels={"class": cls})
                err = {
                    "type": "Overloaded",
                    "message": f"leader {group.leader} is shedding "
                               f"{cls} work",
                    "retriable": True,
                }
                if group.shed_retry_ms > 0:
                    err["retryAfterMs"] = group.shed_retry_ms
                conn[2]({"id": rid, "error": err})
                return

        # 3. re-resolve stale virtual handles (post-failover lazily)
        self._refresh_handles(params)

        # 4. rewrite handle params to node-side reals
        affinity = 0
        for fld in _HANDLE_PARAMS:
            v = params.get(fld)
            if isinstance(v, int):
                h = self._vh.get(v)
                if h is None:
                    raise _RouteError(
                        "InvalidHandle", f"unknown handle {v} in {fld!r}")
                params[fld] = h.real
                if fld in ("doc", "session"):
                    affinity = v

        # 5. response context: creation methods mint a virtual handle
        ctx = None
        if method in _CREATES:
            field, kind = _CREATES[method]
            doc_vid = req.get("params", {}).get("doc")
            peer = params.get("peer") if method == "syncSessionAttach" else None
            ctx = ("create", field, kind, group.idx, group.gen, name,
                   doc_vid, peer)
        elif method in _FREES:
            fld = {"free": "doc", "syncStateFree": "sync",
                   "syncSessionFree": "session"}[method]
            ctx = ("free", (req.get("params") or {}).get(fld))

        # 5b. deadline rewrite: forward the budget net of the time this
        # request spent inside the router (parse, migration/availability
        # waits). A budget that burned away entirely answers
        # DeadlineExceeded here — shipping it would only make the node
        # refuse it after a queue slot and a round trip.
        fwd_deadline = None
        dl = req.get("deadlineMs")
        if (isinstance(dl, (int, float)) and not isinstance(dl, bool)
                and dl > 0):
            arrival = req.get("_arrival")
            elapsed_ms = (
                (obs.now() - arrival) * 1000.0
                if isinstance(arrival, (int, float)) else 0.0
            )
            remaining = float(dl) - elapsed_ms
            if remaining <= 0:
                obs.count("router.deadline_expired")
                conn[2]({"id": rid, "error": {
                    "type": "DeadlineExceeded",
                    "message": "client deadline expired in the router",
                    "retriable": True,
                }})
                return
            fwd_deadline = max(1, int(remaining))

        # 6. ship on the leader's pooled connection
        try:
            out = {"method": method, "params": params}
            if fwd_deadline is not None:
                out["deadlineMs"] = fwd_deadline
            if trace is not None:
                out["trace"] = trace
            dconn = self._data_conn(group.leader, affinity)
            dconn.send(out, conn, rid, ctx)
        except _AlreadyAnswered:
            self._note_node_trouble(group, group.leader)
        except Exception as e:
            self._note_node_trouble(group, group.leader)
            raise _RouteError(
                "Unavailable", f"leader {group.leader}: {e}") from e

    def _params_group(self, cid: int, params: dict):
        """(vhandle, group) for a handle-bearing request — every handle
        must live in one group; bare requests pin to the anchor."""
        found = None
        for fld in _HANDLE_PARAMS:
            v = params.get(fld)
            if isinstance(v, int):
                h = self._vh.get(v)
                if h is None:
                    raise _RouteError(
                        "InvalidHandle", f"unknown handle {v} in {fld!r}")
                if found is not None and h.group != found.group:
                    raise _RouteError(
                        "CrossNode",
                        "handles live on different shard groups; co-locate "
                        "them (same durable-name hash) to combine them",
                    )
                found = h
        if found is not None:
            return found, self._groups[found.group]
        return None, self._anchor_group(cid)

    def _refresh_handles(self, params: dict) -> None:
        """After a failover bumped ``group.gen``, node-side handles died
        with the old leader: re-materialize them by name (docs) or by
        (doc, peer) attachment (sessions) on the new leader."""
        for fld in _HANDLE_PARAMS:
            v = params.get(fld)
            if not isinstance(v, int):
                continue
            h = self._vh.get(v)
            if h is None or h.gen == self._groups[h.group].gen:
                continue
            g = self._groups[h.group]
            if h.kind == "doc" and h.name is not None:
                res = self._admin(g.leader, "openDurable", {"name": h.name})
                h.real, h.gen = res["doc"], g.gen
            elif h.kind == "session" and h.peer is not None:
                doc_h = self._vh.get(h.doc_vid)
                if doc_h is None or doc_h.name is None:
                    raise _RouteError(
                        "Gone", "session's document did not survive failover")
                if doc_h.gen != g.gen:
                    res = self._admin(
                        g.leader, "openDurable", {"name": doc_h.name})
                    doc_h.real, doc_h.gen = res["doc"], g.gen
                res = self._admin(g.leader, "syncSessionAttach",
                                  {"doc": doc_h.real, "peer": h.peer})
                h.real, h.gen = res["session"], g.gen
            else:
                raise _RouteError(
                    "Gone",
                    f"{h.kind} handle {v} was lost with the failed node "
                    "(only named durable docs and attached sessions survive "
                    "failover)",
                )

    # -- node side -----------------------------------------------------------

    def _node_reader(self, dconn: _DataConn) -> None:
        try:
            while True:
                raw = dconn.f.readline()
                if not raw:
                    break
                # per-line fault isolation: one response that trips the
                # bookkeeping must not take down the whole pooled conn
                # (and every pending request on it) as collateral
                try:
                    resp = json.loads(raw)
                    with dconn.plock:
                        entry = dconn.pending.pop(resp.get("id"), None)
                    if entry is None:
                        continue
                    conn, rid, ctx, _method = entry
                    resp["id"] = rid
                    if ctx is not None and "error" not in resp:
                        self._apply_ctx(ctx, resp)
                    conn[2](resp)
                except Exception as e:  # noqa: BLE001 — isolate the line
                    obs.count("router.garbled_node_frames",
                              error=str(e)[:200])
        except Exception:
            pass
        finally:
            dconn.dead = True
            with dconn.plock:
                pending = list(dconn.pending.values())
                dconn.pending.clear()
            for conn, rid, _ctx, method in pending:
                obs.count("cluster.unavailable",
                          labels={"method": str(method)[:40]})
                conn[2]({"id": rid, "error": {
                    "type": "Unavailable",
                    "message": f"node {dconn.addr} went away mid-request",
                    "retriable": True,
                }})
            self._on_conn_death(dconn.addr)

    def _apply_ctx(self, ctx, resp: dict) -> None:
        if ctx[0] == "create":
            _, field, kind, gidx, gen, name, doc_vid, peer = ctx
            result = resp.get("result")
            if not isinstance(result, dict) or field not in result:
                return
            real = result[field]
            with self._lock:
                if name is not None and name in self._durable_vids:
                    # reopening an already-virtualized durable doc: keep
                    # the same vid (and refresh its real handle)
                    vid = self._durable_vids[name]
                    h = self._vh[vid]
                    h.real, h.gen = real, gen
                else:
                    vid = self._next_vid
                    self._next_vid += 1
                    self._vh[vid] = _VHandle(
                        kind, gidx, real, gen,
                        name=name, doc_vid=doc_vid, peer=peer)
                    if name is not None:
                        self._durable_vids[name] = vid
            result[field] = vid
        elif ctx[0] == "free":
            vid = ctx[1]
            with self._lock:
                h = self._vh.pop(vid, None)
                if h is not None and h.name is not None:
                    self._durable_vids.pop(h.name, None)

    def _on_conn_death(self, addr: str) -> None:
        for g in self._groups:
            if g.leader == addr and not self._shutdown.is_set():
                self._note_node_trouble(g, addr)

    def _note_node_trouble(self, group: _Group, addr: str) -> None:
        if group.leader == addr:
            self._failover_wanted[group.idx] = True
            self._monitor_wake.set()

    # -- failover ------------------------------------------------------------

    def _monitor_loop(self) -> None:
        misses = {g.idx: 0 for g in self._groups}
        while not self._shutdown.is_set():
            self._monitor_wake.wait(timeout=self.heartbeat)
            self._monitor_wake.clear()
            if self._shutdown.is_set():
                return
            for g in self._groups:
                if g.failing:
                    continue
                # a data-path death report only shortcuts the miss
                # accumulation — the liveness probe ALWAYS runs, so a
                # stale report about an already-replaced leader (its old
                # connections die during the freeze) can never trigger a
                # second failover against the healthy new one
                wanted = self._failover_wanted.pop(g.idx, False)
                try:
                    # the timeout floor matters: a leader mid-fsync-storm
                    # can stall longer than a tight heartbeat, and a
                    # spurious promotion (while survivable — quorum acks
                    # keep it lossless) churns the group
                    t0 = obs.now()
                    st = self._admin(
                        g.leader, "clusterStatus", {},
                        timeout=max(self.heartbeat * 2, 1.0))
                    t1 = obs.now()
                    # the liveness poll doubles as a clock-sync probe
                    # (RTT midpoint), so flight-merge can chain router ->
                    # leader -> follower onto one timeline
                    peer_now = st.get("now")
                    if isinstance(peer_now, (int, float)):
                        obs.flight.note_clock_sync(
                            st.get("nodeId") or g.leader, t0, t1, peer_now)
                    g.stream = st.get("stream") or g.stream
                    # shed-mode advertisement: stop routing sheddable
                    # classes at a leader that would only refuse them
                    adm = st.get("admission")
                    if isinstance(adm, dict):
                        try:
                            g.shed_class = int(adm.get("shedClass", 5))
                            g.shed_retry_ms = int(adm.get("retryAfterMs", 0))
                        except (TypeError, ValueError):
                            g.shed_class, g.shed_retry_ms = 5, 0
                    else:
                        g.shed_class, g.shed_retry_ms = 5, 0
                    g.shed_ts = t1
                    misses[g.idx] = 0
                    continue
                except Exception:
                    misses[g.idx] += 1
                    if not wanted and misses[g.idx] < self.miss_limit:
                        continue
                misses[g.idx] = 0
                self._failover(g)

    def _failover(self, group: _Group) -> None:
        """Promote the longest durable acked prefix; rewire; unfreeze."""
        t0 = time.monotonic()
        group.failing = True
        group.up.clear()
        dead = group.leader
        obs.count("cluster.leader_deaths")
        candidates = []
        try:
            statuses = {}
            for addr in group.addrs:
                if addr == dead:
                    continue
                try:
                    st = self._admin(addr, "clusterStatus", {}, timeout=5.0)
                except Exception:
                    continue
                statuses[addr] = st
                total = 0
                for info in (st.get("docs") or {}).values():
                    cur = info.get("cursor")
                    if cur and (group.stream is None
                                or cur.get("stream") == group.stream):
                        total += int(cur.get("lsn", 0))
                candidates.append((total, addr))
            if not candidates:
                return  # stays frozen; the finally below schedules a retry
            candidates.sort()
            _best_lsn, winner = candidates[-1]
            res = self._admin(winner, "clusterPromote", {}, timeout=30.0)
            group.leader = winner
            group.stream = res.get("stream")
            group.gen += 1
            # per-doc streams ship independently, so cursor SUMS can be
            # incomparable — a follower behind on one doc can out-sum
            # the only holder of another doc's acked writes. Union every
            # other reachable follower's state into the winner (changes
            # deduplicate by hash — a CRDT merge is always safe): any
            # follower that confirmed a quorum ack either is reachable
            # here or was a second simultaneous failure.
            self._reconcile(winner, statuses)
            for addr in group.addrs:
                if addr in (dead, winner):
                    continue
                with contextlib.suppress(Exception):
                    self._admin(winner, "clusterReplicateTo",
                                {"addr": addr}, timeout=10.0)
            # the dead leader leaves the membership (no point probing a
            # corpse on later failovers); a restarted incarnation
            # re-enters through clusterJoin
            if dead in group.addrs:
                group.addrs.remove(dead)
            # drop stale pooled conns to the dead node
            with self._lock:
                pool = self._links.pop(dead, [])
            for c in pool:
                if c is not None:
                    c.close()
            group.up.set()
            # trouble reports that accumulated about the OLD leader while
            # we were failing over are resolved by this promotion
            self._failover_wanted.pop(group.idx, None)
            dt = time.monotonic() - t0
            obs.observe("cluster.failover_latency", dt)
            obs.count("cluster.failovers")
            obs.event("cluster.failover", group=group.idx, dead=dead,
                      promoted=winner, seconds=round(dt, 3))
            # a failover IS a postmortem moment: snapshot the flight
            # rings now (no-op unless a flight dir is installed)
            obs.flight.dump(reason="failover")
        finally:
            group.failing = False
            if not group.up.is_set():
                # promotion did not complete (nobody reachable, or the
                # promote call itself failed): stay frozen; the wanted
                # flag makes the next heartbeat tick retry (no wake —
                # an immediate retry against dead nodes would spin)
                self._failover_wanted[group.idx] = True

    def _reconcile(self, winner: str, statuses: Dict[str, dict]) -> None:
        """Union other followers' documents into the promoted winner
        wherever their durable cursor is not clearly dominated (ahead on
        LSN, or on a different stream — incomparable). Harvested saves
        merge through ``migrateIn``: already-known changes deduplicate,
        missing acked writes land, and the winner's own replication then
        fans the union back out."""
        wdocs = (statuses.get(winner) or {}).get("docs") or {}
        for addr, st in statuses.items():
            if addr == winner:
                continue
            for name, info in (st.get("docs") or {}).items():
                cur = info.get("cursor") or {}
                wcur = (wdocs.get(name) or {}).get("cursor") or {}
                dominated = (
                    name in wdocs
                    and cur.get("stream") == wcur.get("stream")
                    and int(cur.get("lsn", 0)) <= int(wcur.get("lsn", 0))
                )
                if dominated:
                    continue
                try:
                    harvest = self._admin(addr, "replHarvest",
                                          {"name": name}, timeout=30.0)
                    self._admin(winner, "migrateIn", {
                        "name": name, "snapshot": harvest["snapshot"],
                    }, timeout=60.0)
                    obs.count("cluster.reconcile_harvests")
                except Exception as e:  # noqa: BLE001 — best effort past
                    # the quorum guarantee; count loudly, keep promoting
                    obs.count("cluster.reconcile_error",
                              error=str(e)[:200])

    # -- router-local methods ------------------------------------------------

    def _local(self, method: str, req: dict) -> dict:
        rid = req.get("id")
        p = req.get("params") or {}
        try:
            if method == "metrics":
                if p.get("format") == "json":
                    return {"id": rid, "result": {
                        "format": "json", "metrics": obs.snapshot()}}
                return {"id": rid, "result": {
                    "format": "prometheus",
                    "body": obs.render_prometheus()}}
            if method == "clusterMetrics":
                return {"id": rid, "result": self._cluster_metrics()}
            if method == "clusterInfo":
                return {"id": rid, "result": {
                    "groups": [
                        {"idx": g.idx, "addrs": g.addrs,
                         "leader": g.leader, "gen": g.gen,
                         "up": g.up.is_set()}
                        for g in self._groups
                    ],
                    "overrides": dict(self._overrides),
                    "handles": len(self._vh),
                }}
            if method == "clusterMigrate":
                return {"id": rid, "result": self._migrate(
                    p["name"], int(p["to"]))}
            if method == "clusterJoin":
                return {"id": rid, "result": self._join(
                    int(p["group"]), p["addr"])}
            if method == "clusterAdvise":
                return {"id": rid, "result": self._cluster_advise(p)}
            raise ValueError(f"unknown router method {method}")
        except Exception as e:  # noqa: BLE001 — answer, never die
            return {"id": rid, "error": {
                "type": type(e).__name__, "message": str(e)}}

    def _cluster_metrics(self) -> dict:
        """Fan the ``metrics`` RPC out to every node (leaders AND
        followers) and merge the expositions into one family set with a
        ``node`` label per sample — the single scrape endpoint for the
        whole cluster. The router's own registry joins as
        ``node="router"``; unreachable nodes are reported, not fatal."""
        from ..obs.metrics import merge_prometheus

        bodies = {"router": obs.render_prometheus()}
        unreachable = []
        out_lock = threading.Lock()

        def scrape(addr: str) -> None:
            try:
                res = self._admin(addr, "metrics", {}, timeout=5.0)
                with out_lock:
                    bodies[addr] = res.get("body") or ""
            except Exception as e:  # noqa: BLE001 — scrape what's up
                with out_lock:
                    unreachable.append(
                        {"node": addr, "error": str(e)[:200]})

        # scrape nodes concurrently: one hung node costs the whole
        # scrape its OWN timeout, not timeout x cluster size
        threads = [
            threading.Thread(target=scrape, args=(addr,), daemon=True)
            for g in self._groups for addr in g.addrs
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        # snapshot under the lock: a straggler thread past the deadline
        # must not mutate what merge_prometheus is iterating, and it
        # reports as unreachable rather than vanishing
        with out_lock:
            bodies_snap = dict(bodies)
            unreachable_snap = list(unreachable)
        answered = set(bodies_snap) | {u["node"] for u in unreachable_snap}
        for g in self._groups:
            for addr in g.addrs:
                if addr not in answered:
                    unreachable_snap.append(
                        {"node": addr, "error": "scrape deadline exceeded"})
        return {
            "format": "prometheus",
            "body": merge_prometheus(bodies_snap),
            "nodes": sorted(bodies_snap),
            "unreachable": unreachable_snap,
        }

    def _cluster_advise(self, p: dict) -> dict:
        """Gather each group leader's heat table, staleness report and
        per-doc store tiers, then run the pure placement advisor
        (cluster/advisor.py) over the combined snapshot. Report-only:
        the answer ranks and explains, actuation is the caller's call.
        Unreachable or partial telemetry shrinks the rule set instead
        of failing the request."""
        from . import advisor

        groups_out = []
        for g in self._groups:
            entry: dict = {"group": g.idx, "leader": g.leader}
            try:
                entry["heat"] = self._admin(
                    g.leader, "heatStatus", {}, timeout=5.0)
            except Exception as e:  # noqa: BLE001 — advise on what's up
                entry["error"] = str(e)[:200]
            try:
                st = self._admin(
                    g.leader, "clusterStatus", {}, timeout=5.0)
                entry["staleness"] = st.get("staleness") or {}
            except Exception:  # noqa: BLE001
                pass
            try:
                ss = self._admin(
                    g.leader, "storeStatus", {"docs": True}, timeout=5.0)
                entry["tiers"] = {
                    name: info.get("tier")
                    for name, info in (ss.get("docs") or {}).items()
                    if isinstance(info, dict)
                }
            except Exception:  # noqa: BLE001 — not every node runs a store
                pass
            groups_out.append(entry)
        kwargs = {}
        for key, snake, cast in (
            ("maxRecommendations", "max_recommendations", int),
            ("imbalanceRatio", "imbalance_ratio", float),
            ("hotFrac", "hot_frac", float),
            ("stalenessThreshold", "staleness_threshold", float),
        ):
            v = p.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                kwargs[snake] = cast(v)
        advice = advisor.advise({"groups": groups_out}, **kwargs)
        if p.get("snapshot"):
            advice["snapshot"] = {"groups": groups_out}
        if p.get("format") == "text":
            advice["text"] = advisor.render_text(advice)
        return advice

    def _join(self, gidx: int, addr: str) -> dict:
        """Admit a (re)joined node into a group as a follower: future
        failovers consider it, and the current leader starts shipping to
        it immediately."""
        if not (0 <= gidx < len(self._groups)):
            raise ValueError(f"no group {gidx}")
        g = self._groups[gidx]
        if addr not in g.addrs:
            g.addrs.append(addr)
        self._admin(g.leader, "clusterReplicateTo", {"addr": addr},
                    timeout=10.0)
        obs.count("cluster.joins")
        return {"group": gidx, "addrs": list(g.addrs)}

    # -- live shard migration ------------------------------------------------

    def _await_migration(self, name: str) -> None:
        ev = self._migrating.get(name)
        if ev is not None and not ev.wait(timeout=self.unavailable_timeout):
            raise _RouteError(
                "Unavailable", f"migration of {name!r} did not finish")

    def _fence_doc(self, group: _Group, name: str) -> None:
        """Flush the in-flight pipeline for one document: a cheap
        affinity-matched request down the same pooled connection; its
        response proves every earlier frame for the doc was read and
        executed by the node's per-doc shard queue."""
        with self._lock:
            vid = self._durable_vids.get(name)
            h = self._vh.get(vid) if vid is not None else None
        if h is None:
            return  # never routed through us: nothing can be in flight
        done = threading.Event()
        sentinel = (None, None, lambda _resp: done.set())
        try:
            dconn = self._data_conn(group.leader, vid)
            # docFence, not heads: the fence must not HYDRATE a cold
            # document — keeping it cold is what makes it the cheap
            # migration source this fence is clearing the way for
            dconn.send({"method": "docFence", "params": {"doc": h.real}},
                       sentinel, 0, None)
        except Exception:
            return  # conn is dead: nothing pipelined survives on it
        if not done.wait(timeout=self.unavailable_timeout):
            raise _RouteError(
                "Unavailable", f"fence for {name!r} never drained")

    def _migrate(self, name: str, to: int) -> dict:
        if not (0 <= to < len(self._groups)):
            raise ValueError(f"no group {to}")
        src = self.group_for_name(name)
        dst = self._groups[to]
        if src.idx == dst.idx:
            return {"migrated": False, "group": to}
        t0 = time.monotonic()
        # phase 1: snapshot while the doc keeps serving on the source
        out = self._admin(src.leader, "migrateOut", {"name": name},
                          timeout=60.0)
        # phase 2: pause the doc, ship the tail since the snapshot
        ev = threading.Event()
        self._migrating[name] = ev
        try:
            # fence the data path: new requests are paused above, but
            # frames already pipelined toward the source may not have
            # been read yet — a write acked after the tail is read would
            # be lost. A sentinel request through the SAME pooled conn
            # (and, via doc affinity, the same node-side shard queue)
            # proves everything ahead of it has fully executed;
            # migrateTail then queues strictly after the fence.
            self._fence_doc(src, name)
            if out.get("cold"):
                # cold source: the phase-1 bytes could have gone stale if
                # an access hydrated the doc in between — re-read under
                # the pause (cheap: file reads, no residency rebuild).
                # Still cold => snapshot+tail came back whole in `data`
                # and there is no live stream to tail.
                out = self._admin(src.leader, "migrateOut", {"name": name},
                                  timeout=60.0)
            if out.get("cold"):
                tail = {"data": out.get("data") or "", "lsn": out["lsn"],
                        "dataCodec": out.get("dataCodec")}
            else:
                try:
                    tail = self._admin(
                        src.leader, "migrateTail",
                        {"name": name, "since": out["lsn"]}, timeout=60.0)
                except Exception:
                    # tail trimmed (or the doc demoted mid-pause):
                    # re-snapshot under the pause (now final)
                    out = self._admin(src.leader, "migrateOut",
                                      {"name": name}, timeout=60.0)
                    tail = {"data": out.get("data") or "",
                            "lsn": out["lsn"],
                            "dataCodec": out.get("dataCodec")}
            # payload codec fields ride along verbatim: the source node
            # decides whether each blob shipped compressed (_wire_blob)
            # and the target's migrateIn decodes by codec tag
            self._admin(dst.leader, "migrateIn", {
                "name": name, "snapshot": out["snapshot"],
                "snapshotCodec": out.get("snapshotCodec"),
                "data": tail.get("data") or "",
                "dataCodec": tail.get("dataCodec"),
                "meta": out.get("meta") or {},
            }, timeout=60.0)
            with self._lock:
                self._overrides[name] = to
                vid = self._durable_vids.get(name)
                if vid is not None:
                    h = self._vh[vid]
                    h.group = dst.idx
                    h.gen = dst.gen - 1  # force re-resolution on next use
                    # sessions attached to the migrated doc move with it:
                    # left behind they would route to the source node,
                    # whose copy migrateRelease is about to close. The
                    # stale gen makes the next use re-attach by
                    # (doc, peer) on the destination leader — the carried
                    # sync/<peer> meta resumes them via the epoch
                    # handshake.
                    for sh in self._vh.values():
                        if sh.kind == "session" and sh.doc_vid == vid:
                            sh.group = dst.idx
                            sh.gen = dst.gen - 1
            self._admin(src.leader, "migrateRelease", {"name": name},
                        timeout=30.0)
        finally:
            ev.set()
            self._migrating.pop(name, None)
        dt = time.monotonic() - t0
        obs.observe("cluster.migration_latency", dt)
        obs.count("cluster.migrations")
        return {"migrated": True, "group": to, "seconds": round(dt, 4)}


class _AlreadyAnswered(Exception):
    """The node reader's death sweep already answered this request."""


class _RouteError(Exception):
    def __init__(self, type_: str, message: str):
        super().__init__(message)
        self.type = type_


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="automerge_tpu cluster-router",
        description="consistent-hash router + failover monitor over "
                    "cluster node groups",
    )
    ap.add_argument("--listen", metavar="HOST:PORT", default="127.0.0.1:0")
    ap.add_argument(
        "--group", action="append", required=True, metavar="ADDR,ADDR,...",
        help="one shard group: comma-separated node addresses, leader "
             "first (repeatable)",
    )
    ap.add_argument("--heartbeat", type=float, default=None,
                    help="leader liveness poll interval in seconds "
                         "(default AUTOMERGE_TPU_CLUSTER_HEARTBEAT or 1.0)")
    ap.add_argument("--miss-limit", type=int, default=3,
                    help="consecutive missed heartbeats before failover")
    ap.add_argument("--flight-dir", metavar="DIR", default=None,
                    help="dump the flight recorder to DIR on "
                         "exit/failover (default AUTOMERGE_TPU_FLIGHT_DIR)")
    args = ap.parse_args(argv)
    import os

    flight_dir = args.flight_dir or os.environ.get("AUTOMERGE_TPU_FLIGHT_DIR")
    if flight_dir:
        obs.flight.install(flight_dir, node_id=f"router-{os.getpid()}")
    host, _, port = args.listen.rpartition(":")
    groups = [[a.strip() for a in g.split(",") if a.strip()]
              for g in args.group]
    router = ClusterRouter(
        groups, host=host or "127.0.0.1", port=int(port),
        heartbeat=args.heartbeat, miss_limit=args.miss_limit,
    )
    router.start()
    print(f"routing on {router.address}", file=sys.stderr, flush=True)
    router.serve_forever()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
