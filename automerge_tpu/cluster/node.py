"""A cluster backend node: the concurrent socket server plus a role.

``ClusterNode`` wraps ``SocketRpcServer`` with a replication role:

* **leader** — serves the full client method surface, runs a
  ``ReplicationHub`` that ships every acked journal append to its
  followers, and (with ``ack_replicas``) withholds client acks until
  enough followers hold the write durably;
* **follower** — rejects client mutations with a ``NotLeader`` error
  (carrying the leader address as a hint), applies the replication
  stream serially through one shard key (so its state is always a
  prefix of the leader's log), and can be promoted in place.

The RPC surface grows cluster methods (``clusterStatus``,
``clusterPromote``, ``clusterReplicateTo``, ``replApply``,
``replSnapshot``, ``replPing``, ``migrateOut`` / ``migrateTail`` /
``migrateIn`` / ``migrateRelease``) — same line framing, same error
envelope, dispatched through the same allowlist discipline as every
other method.

Promotion (``clusterPromote``): flip role, mint a fresh
``ReplicationHub`` (new stream id, so surviving followers notice the
incarnation change and snapshot-resync), warm-open every durable
directory, and count ``cluster.promotions``. Client sync sessions resume
through ``syncSessionAttach`` — the replicated ``sync/<peer>`` journal
meta restores each session with a bumped epoch, so the PR 1 epoch/reset
handshake renegotiates in one round instead of a full resync.
"""

from __future__ import annotations

import base64
import contextlib
import os
import threading
import time
import zlib
from typing import Optional, Sequence

from .. import obs
from ..rpc import RpcServer
from ..serve.server import SocketRpcServer
from .replication import (
    ReplicationHub,
    decode_batch,
    decode_cursor,
    encode_batch,
)

# the whole replication stream serializes through ONE shard key: each
# follower's durable state stays a strict prefix of the leader's log,
# which keeps follower states totally ordered for promotion
REPL_SHARD_KEY = "__replication__"

_REPL_METHODS = frozenset(
    {"replApply", "replSnapshot", "replReset", "migrateIn"}
)

# what a follower will answer; everything else is NotLeader. The
# durable-recovery and chaos-injection surfaces are follower-ok: a
# degraded FOLLOWER doc (live disk fault on the replica) is repaired in
# place by compact/reopen, and the chaos soak deals its faults to
# followers directly.
_FOLLOWER_OK = frozenset({
    "clusterStatus", "clusterPromote", "clusterReplicateTo",
    "replApply", "replSnapshot", "replPing", "replHarvest",
    "metrics", "configure",
    "durableInfo", "durableCompact", "durableReopen", "openDurable",
    "chaosDisk",
    # residency is node-local: a follower's store demotes and hydrates
    # its replica copies independently of the leader's tiers
    "storeStatus", "storeDemote",
    # integrity surface: the leader's anti-entropy scrub probes follower
    # digests, resets diverged replicas, and CI forces follower rounds
    "docDigest", "replReset", "scrubNow",
    # read-only telemetry: a follower's heat table and history rings
    # are its own (the advisor reads every node's view)
    "heatStatus", "historyStatus",
})


class NotLeader(Exception):
    pass


def _wire_blob(data: bytes):
    """Encode one migration payload for the wire: ``(b64, codec)``.

    With compressed residency on (``AUTOMERGE_TPU_COMPRESSED``), blobs
    past a floor ship zlib-compressed (level 1 — migration is
    latency-sensitive; the snapshot format is already columnar-packed,
    so the cheap level captures most of the win) so cold migration and
    live handoffs move compressed bytes, not raw journal rows. Byte
    counters (``cluster.migrate_raw_bytes`` / ``_wire_bytes``) make the
    saving observable. Returns ``codec=None`` (field omitted by
    callers) when compression is off or doesn't pay."""
    from ..ops import compressed as _C

    obs.count("cluster.migrate_raw_bytes", n=len(data))
    if _C.enabled() and len(data) >= 512:
        z = zlib.compress(data, 1)
        if len(z) < len(data):
            obs.count("cluster.migrate_wire_bytes", n=len(z))
            return base64.b64encode(z).decode("ascii"), "zlib"
    obs.count("cluster.migrate_wire_bytes", n=len(data))
    return base64.b64encode(data).decode("ascii"), None


def _unwire_blob(b64s, codec) -> bytes:
    """Inverse of ``_wire_blob``; raw base64 when ``codec`` is absent
    (every pre-codec sender, e.g. a replHarvest snapshot)."""
    raw = base64.b64decode(b64s or "")
    return zlib.decompress(raw) if codec == "zlib" else raw


class ClusterRpcServer(RpcServer):
    """RpcServer + the cluster method surface and follower gating."""

    METHODS = RpcServer.METHODS | frozenset({
        "clusterStatus", "clusterPromote", "clusterReplicateTo",
        "replApply", "replSnapshot", "replPing", "replHarvest",
        "replReset",
        "migrateOut", "migrateTail", "migrateIn", "migrateRelease",
    })

    def __init__(self, *a, node_id: str = "node", **kw):
        super().__init__(*a, **kw)
        self.node_id = node_id
        self.cluster_role = "leader"
        self.leader_hint: Optional[str] = None  # follower's known leader
        self.hub: Optional[ReplicationHub] = None
        self.last_leader_contact = 0.0
        self._role_lock = threading.RLock()
        # set by the node's batched follower drain for the duration of a
        # coalesced replApply run (the repl shard is single-threaded):
        # apply_replicated hands each doc's applied changes here instead
        # of leaving the device mirror untouched
        self._repl_device_feed = None
        # follower staleness self-estimate, kept in the LEADER's
        # monotonic frame: the last leader clock sample (leader now,
        # local now at receipt — every replApply/replPing carries one),
        # the per-doc applied LSN, and per doc the leader-frame instant
        # at which this follower last held everything the leader had
        self._stale_lock = threading.Lock()
        self._leader_clock = None  # (leader_now, local_now)
        self._applied_lsn: dict = {}
        self._fresh_at: dict = {}

    # -- gating --------------------------------------------------------------

    def handle(self, req: dict) -> dict:
        method = req.get("method", "")
        if (
            self.cluster_role == "follower"
            and isinstance(method, str)
            and method in self.METHODS
            and method not in _FOLLOWER_OK
        ):
            obs.count("rpc.errors",
                      labels={"method": method, "type": "NotLeader"})
            return {"id": req.get("id"), "error": {
                "type": "NotLeader",
                "message": f"node {self.node_id} is a follower"
                + (f" of {self.leader_hint}" if self.leader_hint else ""),
                "leader": self.leader_hint,
                # retriable: mid-failover the router can briefly route at
                # a node that has not been promoted yet; retry re-resolves
                "retriable": True,
            }}
        return super().handle(req)

    # -- replicated document access ------------------------------------------

    def _repl_doc(self, name):
        """Open-or-get the named durable doc for the replication /
        migration paths (bypasses the follower gate by construction:
        these handlers are already past it). A cold-demoted replica
        hydrates here — applying a shipped batch needs the live doc."""
        h = self.openDurable({"name": name})["doc"]
        doc = self._ensure_resident(h)
        return doc if doc is not None else self._docs[h]

    # -- follower staleness self-estimate ------------------------------------

    def _note_leader_clock(self, leader_now) -> None:
        if isinstance(leader_now, (int, float)):
            with self._stale_lock:
                self._leader_clock = (float(leader_now), obs.now())

    def _est_leader_now(self):
        """The leader's monotonic clock, extrapolated from the last
        sample it shipped us (one-way, so off by up to one transit —
        within the RTT bound the agreement assertion allows)."""
        with self._stale_lock:
            lc = self._leader_clock
        if lc is None:
            return None
        return lc[0] + (obs.now() - lc[1])

    def _note_applied(self, name, lsn, leader_now, leader_lsn) -> None:
        """Record one applied batch/snapshot: our durable LSN for the
        doc, and — when the batch brought us level with the leader's
        latest — the leader-frame instant we became fresh at."""
        self._note_leader_clock(leader_now)
        with self._stale_lock:
            self._applied_lsn[name] = int(lsn)
            if (
                isinstance(leader_now, (int, float))
                and isinstance(leader_lsn, int)
                and int(lsn) >= leader_lsn
            ):
                self._fresh_at[name] = float(leader_now)

    def follower_staleness(self) -> dict:
        """{doc: seconds} — this follower's own staleness estimate:
        extrapolated leader-now minus the last instant we were level.
        Empty until the first leader clock sample arrives."""
        est_now = self._est_leader_now()
        if est_now is None:
            return {}
        with self._stale_lock:
            return {
                name: max(0.0, est_now - t)
                for name, t in self._fresh_at.items()
            }

    # -- cluster status ------------------------------------------------------

    def clusterStatus(self, p):
        docs = {}
        with self._lock:
            named = dict(self._durable_names)
        for name, h in sorted(named.items()):
            doc = self._docs.get(h)
            if doc is None or not hasattr(doc, "journal"):
                continue
            acked, appended = doc.acked_prefix()
            cur = doc.replication_cursor
            info = {
                "acked": acked,
                "appended": appended,
                "cursor": None,
            }
            if cur is not None:
                stream, lsn = decode_cursor(cur)
                info["cursor"] = {"stream": stream, "lsn": lsn}
            if self.hub is not None:
                info["lsn"] = self.hub.lsn(name)
            try:
                dg = doc.doc_digest()
                info["digest"] = dg["digest"]
                info["digestChanges"] = dg["changes"]
            except Exception:  # noqa: BLE001 — racing close/demote
                pass
            docs[name] = info
        out = {
            "nodeId": self.node_id,
            "role": self.cluster_role,
            "docs": docs,
            # clock-sync sample for the router's heartbeat poll (same
            # contract as replPing's "now")
            "now": obs.now(),
        }
        if self.hub is not None:
            out["stream"] = self.hub.stream_id
            out["followers"] = self.hub.followers()
            # seconds-based lag, both leader-computed and
            # follower-reported, refreshed (gauges included) on every
            # status poll so whoever is looking sees current numbers
            self.hub.publish_staleness()
            out["staleness"] = self.hub.staleness_report()
        else:
            stale = self.follower_staleness()
            if stale:
                out["stalenessSeconds"] = stale
                for name, s in stale.items():
                    if name in docs:
                        docs[name]["stalenessSeconds"] = s
        if self.leader_hint:
            out["leader"] = self.leader_hint
        # overload advertisement: the serving layer's admission
        # controller (installed by SocketRpcServer) rides the heartbeat
        # so the router stops routing sheddable classes at this node
        adm = getattr(self, "admission", None)
        if adm is not None:
            out["admission"] = adm.advertisement()
        return out

    # -- replication receive path (follower) ---------------------------------

    def replApply(self, p):
        """Apply one shipped record batch. Cursor arithmetic guards
        contiguity: our persisted cursor must name the same stream at
        exactly ``prev`` or the leader falls back to a snapshot."""
        name = p["name"]
        doc = self._repl_doc(name)
        cur = doc.replication_cursor
        have_stream, have_lsn = (None, 0) if cur is None else decode_cursor(cur)
        if have_stream != p["stream"] or have_lsn != int(p["prev"]):
            raise ReplCursorMismatch(
                f"{name}: have {have_stream}@{have_lsn}, "
                f"leader sent prev={p['prev']} on {p['stream']}"
            )
        records = decode_batch(base64.b64decode(p["data"]))
        # the shipped batch covers many leader-side requests: link this
        # follower's apply (and the journal fsync it nests) to each of
        # their traces so flight-merge connects client -> leader ->
        # follower on one timeline
        with obs.span("repl.apply",
                      links=obs.decode_wire_traces(p.get("traces")),
                      records=len(records)):
            applied = doc.apply_replicated(
                records, base64.b64decode(p["cursor"]),
                device_feed=self._repl_device_feed)
        obs.count("cluster.records_applied", n=len(records))
        self._note_applied(name, int(p["lsn"]),
                           p.get("now"), p.get("leaderLsn"))
        return {"lsn": int(p["lsn"]), "applied": applied}

    def replSnapshot(self, p):
        """Catch-up: full leader save + pinned cursor, applied through
        the listener path (known changes deduplicate on the history
        index, so converging snapshots never conflict)."""
        name = p["name"]
        doc = self._repl_doc(name)
        doc.apply_replicated_snapshot(
            base64.b64decode(p["snapshot"]), base64.b64decode(p["cursor"]))
        obs.count("cluster.snapshots_applied")
        self._note_applied(name, int(p["lsn"]),
                           p.get("now"), p.get("leaderLsn"))
        return {"lsn": int(p["lsn"])}

    def replPing(self, p):
        self.last_leader_contact = time.monotonic()
        # the ping's request half carries the leader's clock and per-doc
        # latest LSNs: any doc we already hold in full is fresh as of
        # the leader instant the ping left — that keeps an IDLE doc's
        # staleness pinned near zero instead of growing since its last
        # write. The response half reports our estimate back.
        now_l = p.get("now")
        docs = p.get("docs")
        if isinstance(now_l, (int, float)):
            self._note_leader_clock(now_l)
            if isinstance(docs, dict):
                with self._stale_lock:
                    for name, llsn in docs.items():
                        if (
                            isinstance(llsn, int)
                            and self._applied_lsn.get(name, -1) >= llsn
                        ):
                            self._fresh_at[name] = float(now_l)
        out = {"nodeId": self.node_id, "role": self.cluster_role,
               "now": obs.now()}
        stale = self.follower_staleness()
        if stale:
            out["staleness"] = stale
            obs.gauge_set("cluster.staleness_seconds",
                          max(stale.values()),
                          labels={"node": self.node_id})
        # "now" (this process's monotonic obs clock) turns every ping
        # into a clock-sync sample: the pinger records the RTT midpoint
        # and flight-merge aligns the two processes' span timelines
        return out

    def replHarvest(self, p):
        """Hand out this node's full state for one document — the
        post-promotion reconciliation path: the router unions every
        reachable follower's state into the promoted leader (changes
        deduplicate by hash, so a CRDT merge is always safe), which
        keeps promotion lossless even when per-doc cursors diverge
        across followers and the longest-sum choice alone would not."""
        doc = self._repl_doc(p["name"])
        with doc.lock:
            data = doc._core.save()
        return {"snapshot": base64.b64encode(data).decode("ascii")}

    # -- integrity surface (anti-entropy scrub, integrity.py) ----------------

    def docDigest(self, p):
        """Base digest plus replication coordinates, so the leader's
        anti-entropy exchange can compare digests only when both sides
        sit at the same ``(stream, lsn)`` — never against a lagging or
        mid-apply replica."""
        out = super().docDigest(p)
        name = p.get("name")
        if name is None:
            return out
        if self.hub is not None:
            out["stream"] = self.hub.stream_id
            out["lsn"] = self.hub.lsn(name)
            return out
        # follower: digest and cursor must describe one instant — a
        # shipped batch landing between the two reads would pair a fresh
        # digest with a stale LSN and false-positive the leader's scrub
        with self._lock:
            h = self._durable_names.get(name)
            doc = self._docs.get(h) if h is not None else None
        if (
            doc is not None
            and hasattr(doc, "journal")
            and not getattr(doc, "_closed", False)
        ):
            with doc.lock:
                out.update(doc.doc_digest())
                cur = doc.replication_cursor
            if cur is not None:
                stream, lsn = decode_cursor(cur)
                out["stream"] = stream
                out["lsn"] = lsn
        return out

    def replReset(self, p):
        """Wipe and rebuild one replica document from a leader snapshot
        — the anti-entropy repair for a diverged copy. A catch-up
        snapshot alone cannot heal a replica holding EXTRA changes (CRDT
        merge is a union, it only ever adds), so the on-disk state is
        deleted and the doc re-opened empty before the leader's save is
        applied with its pinned cursor. The handle survives (same
        aliasing as ``durableReopen``); the leader's ship loop recovers
        from the cursor jump via its normal snapshot-resync fallback."""
        name = p["name"]
        res = self.durableReopen({"name": name, "wipe": True})
        h = res["doc"]
        doc = self._ensure_resident(h)
        if doc is None:
            doc = self._docs[h]
        doc.apply_replicated_snapshot(
            base64.b64decode(p["snapshot"]), base64.b64decode(p["cursor"]))
        obs.count("cluster.repl_resets")
        out = {"reset": True, "lsn": int(p.get("lsn", 0))}
        try:
            out["digest"] = doc.doc_digest()["digest"]
        except Exception:  # noqa: BLE001 — digest echo is best-effort
            pass
        return out

    # -- role transitions ----------------------------------------------------

    def _become_leader(self, ack_replicas: int) -> int:
        """Flip to leader: fresh hub incarnation + warm-open. Returns
        the number of durable directories opened."""
        self.cluster_role = "leader"
        self.leader_hint = None
        with self._stale_lock:
            # follower-frame staleness state is meaningless once leading
            self._leader_clock = None
            self._fresh_at.clear()
            self._applied_lsn.clear()
        self.hub = ReplicationHub(self.node_id, ack_replicas=ack_replicas)
        self.on_durable_open = self._on_durable_open
        n = self._warm_open()
        # docs opened before the hub existed (or by a prior role) must
        # attach too — attach() is idempotent per name. Cold docs have
        # no live journal to hook; they attach lazily when an access
        # hydrates them (on_durable_open fires on the hydration path)
        with self._lock:
            named = list(self._durable_names.items())
        for name, h in named:
            doc = self._docs.get(h)
            if (
                doc is not None
                and hasattr(doc, "journal")
                and not getattr(doc, "_closed", False)
            ):
                self.hub.attach(name, doc)
        return n

    def clusterPromote(self, p):
        """Follower -> leader: mint a fresh hub incarnation, warm-open
        every durable directory, start serving client mutations. The
        caller (the router's failover monitor) picked this node as the
        longest durable acked prefix."""
        with self._role_lock:
            if self.cluster_role == "leader" and self.hub is not None:
                return {"promoted": False, "role": "leader",
                        "stream": self.hub.stream_id}
            n = self._become_leader(
                int(p.get("ackReplicas", self.cluster_ack_replicas)))
        obs.count("cluster.promotions")
        return {"promoted": True, "role": "leader",
                "stream": self.hub.stream_id, "docs": n}

    def clusterReplicateTo(self, p):
        """Leader: add a follower link (the post-promotion rewire the
        failover monitor drives, and the startup ``--replicate-to``)."""
        with self._role_lock:
            if self.hub is None:
                raise NotLeader("cannot replicate from a follower")
            self.hub.add_follower(p["addr"])
        return {"followers": sorted(self.hub.followers())}

    cluster_ack_replicas = 0  # default; ClusterNode sets from config

    def _on_durable_open(self, name, dd):
        if self.hub is not None:
            self.hub.attach(name, dd)

    def _warm_open(self) -> int:
        """Open (and attach) every durable directory under the serving
        dir — promotion and leader start must replicate docs that exist
        on disk but have no live client handle yet."""
        n = 0
        if not self.durable_dir or not os.path.isdir(self.durable_dir):
            return n
        for entry in sorted(os.listdir(self.durable_dir)):
            path = os.path.join(self.durable_dir, entry)
            if not os.path.isdir(path):
                continue
            try:
                self.openDurable({"name": entry})
                n += 1
            except Exception as e:  # noqa: BLE001 — one bad dir, not all
                obs.count("cluster.warm_open_error", error=str(e)[:200])
        return n

    # -- live shard migration ------------------------------------------------

    def migrateOut(self, p):
        """Phase 1 of the handoff: a full snapshot pinned to an LSN,
        taken while the document keeps serving. The journal meta rides
        along (minus replication bookkeeping) so attached sync sessions
        resume on the target instead of renegotiating from nothing.

        A COLD document short-circuits all of that: its entire state IS
        the fsynced on-disk snapshot + journal tail, so the response
        ships those bytes verbatim (``cold: true``, tail records in
        ``data``) with no hydration and no residency rebuild — the cheap
        live-migration source rebalancing wants. The router re-runs this
        under the routing pause, making the cold bytes authoritative."""
        if self.hub is None:
            raise NotLeader("migration source must be a leader")
        name = p["name"]
        if self.store is not None and self.store.tier(name) == "cold":
            return self._migrate_out_cold(name)
        doc = self._repl_doc(name)  # ensure open + attached
        data, lsn = self.hub.snapshot(name)
        from ..storage.durable import REPL_META_PREFIX

        meta = {
            k: base64.b64encode(v).decode("ascii")
            for k, v in doc.meta.items()
            if not k.startswith(REPL_META_PREFIX)
        }
        snap_b64, codec = _wire_blob(data)
        return {
            "snapshot": snap_b64,
            **({"snapshotCodec": codec} if codec else {}),
            "lsn": lsn,
            "stream": self.hub.stream_id,
            "meta": meta,
        }

    def _migrate_out_cold(self, name: str):
        """Read a cold document's on-disk bytes for migration: snapshot
        file verbatim, journal change-records as the shipped tail, meta
        records latest-wins (minus replication bookkeeping). Read-only —
        the flock is free (the journal is closed) and the doc stays
        cold on this node throughout."""
        from ..storage.durable import (
            JOURNAL_NAME,
            REPL_META_PREFIX,
            SNAPSHOT_NAME,
        )
        from ..storage.journal import (
            REC_CHANGE,
            REC_META,
            decode_meta,
            scan_records,
        )

        path = self._durable_path(name)
        snap = b""
        sp = os.path.join(path, SNAPSHOT_NAME)
        if os.path.exists(sp):
            with open(sp, "rb") as f:
                snap = f.read()
        records = []
        meta = {}
        jp = os.path.join(path, JOURNAL_NAME)
        if os.path.exists(jp):
            with open(jp, "rb") as f:
                raw = f.read()
            recs, _tail = scan_records(raw)  # read-only torn-tail scan
            for r in recs:
                if r.rec_type == REC_CHANGE:
                    records.append((r.rec_type, r.payload))
                elif r.rec_type == REC_META:
                    mname, blob = decode_meta(r.payload)
                    if not mname.startswith(REPL_META_PREFIX):
                        meta[mname] = base64.b64encode(blob).decode("ascii")
        obs.count("cluster.migrate_cold_source")
        snap_b64, s_codec = _wire_blob(snap)
        data_b64, d_codec = _wire_blob(encode_batch(records))
        return {
            "snapshot": snap_b64,
            **({"snapshotCodec": s_codec} if s_codec else {}),
            "data": data_b64,
            **({"dataCodec": d_codec} if d_codec else {}),
            "lsn": -1,  # no live stream to pin; the router skips the tail
            "cold": True,
            "meta": meta,
        }

    def migrateTail(self, p):
        """Phase 2 (routing paused): the journal tail since the
        snapshot's LSN. Raises when the tail was trimmed — the router
        then repeats migrateOut under the pause."""
        if self.hub is None:
            raise NotLeader("migration source must be a leader")
        records, last, _traces = self.hub.tail_after(p["name"], int(p["since"]))
        data_b64, codec = _wire_blob(encode_batch(records))
        return {
            "data": data_b64,
            **({"dataCodec": codec} if codec else {}),
            "lsn": last,
        }

    def migrateIn(self, p):
        """Target side: snapshot + tail through the replicated-apply
        path (plus carried journal meta), then own the document as a
        normal leader doc (no cursor — it follows nobody). Also the
        post-promotion union sink: a replHarvest snapshot fed here
        merges any state the promoted leader was missing."""
        name = p["name"]
        doc = self._repl_doc(name)
        snap = _unwire_blob(p["snapshot"], p.get("snapshotCodec"))
        if snap:  # a cold source that never compacted ships no snapshot
            doc.apply_replicated_snapshot(snap, None)
        records = decode_batch(_unwire_blob(p.get("data"), p.get("dataCodec")))
        if records:
            doc.apply_replicated(records, None)
        meta = p.get("meta") or {}
        if meta:
            with doc.lock, doc.ack_scope():
                for k, blob in meta.items():
                    doc.set_meta(k, base64.b64decode(blob))
        obs.count("cluster.migrations_in")
        return {"heads": [base64.b64encode(h).decode("ascii")
                          for h in doc.get_heads()]}

    def migrateRelease(self, p):
        """Source side: drop the migrated document (close the journal,
        release the flock) after the router flipped routing."""
        name = p["name"]
        with self._lock:
            h = self._durable_names.get(name)
        if h is None:
            return {"released": False}
        if self.hub is not None:
            self.hub.detach(name)
        self.free({"doc": h})
        obs.count("cluster.migrations_out")
        return {"released": True}


class ReplCursorMismatch(Exception):
    """Follower journal cursor does not extend the shipped batch."""


class ClusterNode(SocketRpcServer):
    """A backend node process: socket server + role + replication."""

    def __init__(
        self,
        *,
        node_id: str,
        host: Optional[str] = None,
        port: int = 0,
        unix_path: Optional[str] = None,
        durable_dir: str,
        role: str = "leader",
        leader_addr: Optional[str] = None,
        replicate_to: Sequence[str] = (),
        ack_replicas: Optional[int] = None,
        workers: Optional[int] = None,
    ):
        if role not in ("leader", "follower"):
            raise ValueError(f"unknown cluster role {role!r}")
        rpc = ClusterRpcServer(durable_dir=durable_dir, node_id=node_id)
        super().__init__(
            rpc, host=host, port=port, unix_path=unix_path, workers=workers,
            durable_dir=durable_dir,
        )
        if ack_replicas is None:
            try:
                ack_replicas = int(os.environ.get(
                    "AUTOMERGE_TPU_CLUSTER_ACK_REPLICAS", "0"))
            except ValueError:
                ack_replicas = 0
        rpc.cluster_ack_replicas = ack_replicas
        rpc.cluster_role = role
        rpc.leader_hint = leader_addr
        if role == "leader":
            # starting as leader is not a promotion — no counter
            rpc._become_leader(ack_replicas)
            for addr in replicate_to:
                rpc.clusterReplicateTo({"addr": addr})
        else:
            rpc._warm_open()

    # replication ingest serializes through one shard key (prefix-ordered
    # follower state); migration source methods take the migrated doc's
    # OWN shard key, so they execute after every write this node already
    # read for it — the tail a migrateTail ships really is the tail
    def _affinity(self, req: dict):
        method = req.get("method")
        if method in _REPL_METHODS:
            return REPL_SHARD_KEY
        if method in ("migrateOut", "migrateTail", "migrateRelease"):
            name = (req.get("params") or {}).get("name")
            if isinstance(name, str):
                with self.rpc._lock:
                    h = self.rpc._durable_names.get(name)
                if h is not None:
                    return h
        return super()._affinity(req)

    # -- batched follower apply ----------------------------------------------
    #
    # A drained grab of the replication shard's queue holds replApply
    # requests for MANY documents (the leader ships per doc, the pool
    # batches up to max_batch per grab). The old path replayed them
    # per-request and serially; now adjacent replApply frames coalesce
    # into one run: same-doc sub-runs share one ack scope (one fsync per
    # doc per drain instead of one per shipped batch), and every touched
    # device mirror's feed drains through ONE vectorized cross-doc
    # staging pass + shared launch (ops/host_batch.py) — the follower
    # applies at the same super-batch discipline as the serve drain, so
    # replication lag stops being the ceiling for follower reads.
    # ``AUTOMERGE_TPU_REPL_BATCH=0`` forces the old serial path (the
    # bench / soak A/B knob).

    @staticmethod
    def _repl_batch_enabled() -> bool:
        return os.environ.get("AUTOMERGE_TPU_REPL_BATCH", "1") != "0"

    def _coalesce_key(self, req):
        if req.get("method") == "replApply" and self._repl_batch_enabled():
            # every adjacent replApply frame coalesces regardless of its
            # target doc — the batched drain groups per doc itself
            return ("replApply",)
        return super()._coalesce_key(req)

    def _coalesce_single(self, method) -> bool:
        if method == "replApply":
            return True
        return super()._coalesce_single(method)

    def _run_coalesced(self, run, out) -> None:
        if run[0][1].get("method") == "replApply":
            self._run_repl_apply(run, out)
            return
        super()._run_coalesced(run, out)

    def _run_repl_apply(self, run, out) -> None:
        rpc = self.rpc
        obs.observe("cluster.repl_apply_batch_size", len(run))
        if len(run) > 1:
            obs.count("rpc.coalesced", n=len(run),
                      labels={"method": "replApply"})
        feeds: list = []

        def defer_feed(doc, dev, changes):
            feeds.append((doc, dev, [changes]))

        i = 0
        while i < len(run):
            name = (run[i][1].get("params") or {}).get("name")
            j = i
            while (
                j + 1 < len(run)
                and (run[j + 1][1].get("params") or {}).get("name") == name
            ):
                j += 1
            group = run[i : j + 1]
            scope = None
            if len(group) > 1 and isinstance(name, str):
                # same-doc sub-run: one shared ack scope — the nested
                # apply_replicated scopes defer their fsync to this exit
                try:
                    doc = rpc._repl_doc(name)
                    scope = getattr(doc, "ack_scope", None)
                except Exception:  # noqa: BLE001 — handle() reports it
                    scope = None
            first = len(out)
            rpc._repl_device_feed = defer_feed
            try:
                with scope() if scope is not None else (
                    contextlib.nullcontext()
                ):
                    for conn2, req2 in group:
                        out.append((conn2, rpc.handle(req2)))
            except Exception as e:  # the shared group fsync failed
                # an un-fsynced ack is no ack: convert the sub-run
                obs.count("rpc.errors", labels={
                    "method": "replApply", "type": type(e).__name__})
                err = {"type": type(e).__name__,
                       "message": f"replicated group commit failed: {e}"}
                retriable = getattr(e, "retriable", None)
                if retriable is None and isinstance(e, OSError):
                    retriable = True
                if retriable is not None:
                    err["retriable"] = bool(retriable)
                out[first:] = [
                    (c, r if "error" in r else {
                        "id": r.get("id"), "error": dict(err)})
                    for c, r in out[first:]
                ]
            finally:
                rpc._repl_device_feed = None
            i = j + 1
        if feeds:
            self._feed_repl_mirrors(feeds)

    def _feed_repl_mirrors(self, feeds) -> None:
        """One vectorized cross-doc staging pass + shared launch for
        every device mirror the drained replApply run touched — the
        follower-side analogue of the serve drain's batcher feed.
        Mirror failures are isolated (the journaled host apply already
        acked; it is authoritative): a mirror whose feed errored is
        dropped and rebuilt on its next use instead of serving stale
        reads."""
        from ..ops import host_batch
        from ..ops.batched import resolve_stages

        try:
            docs = {}
            for doc, _dev, _b in feeds:
                docs.setdefault(id(doc), doc)
            with contextlib.ExitStack() as st:
                # deterministic multi-lock order; single-lock takers
                # (background compaction) cannot form a cycle with it
                for doc in sorted(
                    docs.values(),
                    key=lambda d: str(getattr(d, "path", "")),
                ):
                    st.enter_context(doc.lock)
                stages, results = host_batch.stage_docs(
                    [(dev, b) for _doc, dev, b in feeds]
                )
                bad = {
                    key for key, r in results.items()
                    if r.error is not None
                }
                if stages:
                    resolve_stages(
                        [s for s in stages if id(s.doc) not in bad]
                    )
                # one error/drop per DOCUMENT: feeds holds one entry per
                # coalesced frame, and a 10-frame doc must not count 10
                # errors or drop its mirror 10 times
                dropped = set()
                for doc, dev, _b in feeds:
                    if id(dev) in bad and id(dev) not in dropped:
                        dropped.add(id(dev))
                        obs.count("cluster.repl_device_feed_error")
                        obs.event("cluster.repl_device_feed_error",
                                  doc=str(getattr(doc, "obs_name", "")),
                                  error=str(results[id(dev)].error)[:200])
                        doc.drop_device_mirror()
        except Exception as e:  # noqa: BLE001 — never fail the acked path
            obs.count("cluster.repl_device_feed_error")
            obs.event("cluster.repl_device_feed_error", error=str(e)[:200])
            # a failed staging/launch leaves mirrors part-updated: drop
            # them all; build_device_mirror recovers from history on the
            # next use (never serve a possibly-corrupt resolution)
            for doc, _dev, _b in feeds:
                with contextlib.suppress(Exception):
                    doc.drop_device_mirror()

    def _stop_inner(self) -> None:
        hub = self.rpc.hub
        if hub is not None:
            hub.close()
        super()._stop_inner()
