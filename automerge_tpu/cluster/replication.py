"""Journal-shipping replication: leader streams acked journal records.

The PR 3 change journal is already a checksummed, truncation-safe,
torn-tail-recoverable change log — this module treats it as what it is:
a replication stream. The leader's ``ReplicationHub`` hooks every
durable document's journal (``on_record`` / ``on_synced``) and ships the
locally-durable record prefix to followers **verbatim** — the bytes on
the wire are ``journal.encode_record`` output, parsed on the far side by
the same CRC scan that recovers a journal file (``scan_record_seq``).
There is no second serialization format.

Topology and flow (leader dials follower, both speak the RPC line
framing of serve/server.py):

* every attached document gets a per-hub **LSN** sequence (one per
  appended record) and a bounded in-memory retention buffer of already
  synced records;
* a ``_FollowerLink`` per follower ships, over one pooled connection,
  ``replApply`` requests carrying contiguous record batches (prev/lsn
  cursor arithmetic, so a gap is detected by the follower, answered with
  ``ReplCursorMismatch``, and repaired by a snapshot);
* a new or lagging follower (cursor from another leader incarnation, or
  behind the retention buffer) catches up exactly the way compaction
  recovers: a full **snapshot** (``core.save()``) pinned to an LSN, then
  the journal tail from there;
* the follower applies through the durable listener path
  (``DurableDocument.apply_replicated``), so every replicated change is
  journaled on the follower's own disk before the ack returns, and the
  **replication cursor** rides the same fsync as journal meta;
* ``replPing`` heartbeats flow on idle links so followers can observe
  leader liveness, and the router's failover monitor uses
  ``clusterStatus`` cursors to promote from the longest durable acked
  prefix.

Durability gate: with ``ack_replicas >= 1`` the hub installs a
``replication_gate`` on each attached document — the outermost
``ack_scope`` exit (the moment a batch would ack to clients) blocks
until at least that many followers have *durably* applied the covering
LSN. A client-visible ack therefore implies the write is on
``1 + ack_replicas`` disks, which is what makes kill -9 of the leader
lose zero acked writes.

Observability: ``cluster.replication_lag{follower,doc}`` gauges,
``cluster.records_shipped`` / ``cluster.snapshots_shipped`` counters,
``cluster.follower_up{follower}`` gauges, ``cluster.ship_batch`` span.
"""

from __future__ import annotations

import base64
import json
import os
import socket
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import obs
from ..storage.journal import encode_record, scan_record_seq
from ..utils.leb128 import decode_uleb, encode_uleb


class ReplicationError(Exception):
    pass


class ReplicationTimeout(ReplicationError):
    """The ack gate could not confirm enough follower copies in time —
    the covering batch must surface as errors, never as acks. Retriable:
    the write is journaled locally but unconfirmed; a retry after the
    partition heals (or after failover) deduplicates by change hash."""

    retriable = True


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


# -- wire codecs (journal record encoding, verbatim) --------------------------


def encode_batch(records: List[Tuple[int, bytes]]) -> bytes:
    """Concatenated journal records — byte-identical to what the leader's
    journal file holds for the same appends."""
    out = bytearray()
    for rec_type, payload in records:
        out += encode_record(rec_type, payload)
    return bytes(out)


def decode_batch(data: bytes) -> List[Tuple[int, bytes]]:
    """Inverse of ``encode_batch`` via the journal's own CRC scan."""
    return [(r.rec_type, r.payload) for r in scan_record_seq(data)]


def encode_cursor(stream: str, lsn: int) -> bytes:
    """Follower cursor blob: ULEB(lsn) | stream id (utf-8). ``stream``
    names one leader incarnation — a cursor from another stream forces
    snapshot catch-up instead of silently splicing two histories."""
    out = bytearray()
    encode_uleb(lsn, out)
    out += stream.encode("utf-8")
    return bytes(out)


def decode_cursor(blob: bytes) -> Tuple[str, int]:
    lsn, pos = decode_uleb(blob, 0)
    return bytes(blob[pos:]).decode("utf-8"), lsn


# -- leader side --------------------------------------------------------------


class _DocStream:
    """Per-document replication state on the leader."""

    __slots__ = (
        "name", "dd", "lsn", "synced_lsn", "pending", "buffer",
        "buffer_bytes", "base_lsn", "stamps",
    )

    def __init__(self, name: str, dd, stamp_cap: int = 4096):
        self.name = name
        self.dd = dd
        self.lsn = 0  # appended records (this hub incarnation)
        self.synced_lsn = 0  # locally durable prefix — what may ship
        # appended but not yet covered by an fsync: (lsn, append_seq,
        # rec_type, payload)
        self.pending: deque = deque()
        # locally durable, retained for follower tail-shipping:
        # (lsn, rec_type, payload)
        self.buffer: deque = deque()
        self.buffer_bytes = 0
        self.base_lsn = 0  # everything <= this has been trimmed
        # (lsn, leader monotonic append time) — the seconds-based
        # staleness base: a follower behind LSN f is stale by "now minus
        # the append time of the first record it has not applied". The
        # maxlen bound means a VERY deep backlog under-reports (oldest
        # stamp wins), which only ever understates — never invents — lag.
        self.stamps: deque = deque(maxlen=stamp_cap)


class ReplicationHub:
    """Leader-side replication state machine. One per leader node."""

    def __init__(
        self,
        node_id: str,
        *,
        ack_replicas: int = 0,
        heartbeat: Optional[float] = None,
        retain_bytes: int = 16 << 20,
        ack_timeout: Optional[float] = None,
        batch_bytes: int = 4 << 20,
    ):
        self.node_id = node_id
        # one leader INCARNATION: a restarted or newly promoted leader
        # must not be mistaken for the stream a stale cursor names
        self.stream_id = f"{node_id}/{uuid.uuid4().hex[:12]}"
        self.ack_replicas = int(ack_replicas)
        self.heartbeat = (
            heartbeat if heartbeat is not None
            else _env_float("AUTOMERGE_TPU_CLUSTER_HEARTBEAT", 1.0)
        )
        self.ack_timeout = (
            ack_timeout if ack_timeout is not None
            else _env_float("AUTOMERGE_TPU_CLUSTER_ACK_TIMEOUT", 30.0)
        )
        # the bounded tail-retention buffer: a follower whose cursor
        # falls off it catches up via snapshot+tail (the chaos soak
        # shrinks this to force that path constantly)
        self.retain_bytes = int(_env_float(
            "AUTOMERGE_TPU_REPL_RETAIN_BYTES", retain_bytes))
        self.batch_bytes = batch_bytes
        # per-request I/O timeout on follower links: a STALLED follower
        # (black-holed response path) must fail the request and recycle
        # the link rather than freeze the ship loop forever
        self.io_timeout = _env_float("AUTOMERGE_TPU_REPL_IO_TIMEOUT", 10.0)
        # per-doc LSN->append-time stamp ring (staleness accounting)
        self.stamp_cap = max(16, int(_env_float(
            "AUTOMERGE_TPU_REPL_STAMPS", 4096)))
        self._lock = threading.Lock()
        self._acked = threading.Condition(self._lock)
        self._streams: Dict[str, _DocStream] = {}
        self._links: Dict[str, _FollowerLink] = {}
        self._closed = False
        # circuit breaker on the ack gate: repeated ReplicationTimeouts
        # (a partitioned/stalled follower set) trip the gate OPEN —
        # writes ack on leader durability alone (follower-degraded
        # quorum, loudly counted) instead of every ack stalling out the
        # full timeout. After a cooldown one half-open probe waits for
        # real acks again; success re-closes the breaker.
        self.breaker_enabled = (
            os.environ.get("AUTOMERGE_TPU_REPL_BREAKER", "1") != "0")
        self.breaker_threshold = max(1, int(_env_float(
            "AUTOMERGE_TPU_REPL_BREAKER_THRESHOLD", 3)))
        self.breaker_cooldown = _env_float(
            "AUTOMERGE_TPU_REPL_BREAKER_COOLDOWN", 5.0)
        self._breaker_lock = threading.Lock()
        self._breaker_state = "closed"
        self._breaker_failures = 0
        self._breaker_opened_at = 0.0
        self._breaker_gauges()

    # -- document attachment -------------------------------------------------

    def attach(self, name: str, dd) -> None:
        """Start replicating ``dd``'s journal under ``name``. Installs
        the journal hooks and (with ``ack_replicas``) the ack gate.

        Re-attaching the same name with a DIFFERENT document (a
        ``durableReopen`` after a disk fault replaced the wrapper and
        its journal) swaps the stream onto the new incarnation in place:
        the LSN sequence continues (follower cursors stay meaningful —
        everything they hold is still a prefix of what ships next), and
        pending never-fsynced records from the dead journal are dropped
        (they were never acked; the reopened document no longer holds
        them either)."""
        reattached = False
        with self._lock:
            if self._closed:
                return
            st = self._streams.get(name)
            if st is not None:
                if st.dd is dd:
                    return
                old = st.dd
                old.journal.on_record = None
                old.journal.on_synced = None
                old.replication_gate = None
                st.dd = dd
                st.pending.clear()
                reattached = True
                links = list(self._links.values())
            else:
                st = _DocStream(name, dd, stamp_cap=self.stamp_cap)
                self._streams[name] = st
        if reattached:
            # the reopened document's recovered history may contain
            # records the old journal wrote but never confirmed (a
            # poisoned fsync leaves the tail's durability unknowable) —
            # records the LSN bookkeeping can no longer replay from the
            # buffer. One forced snapshot per follower squares every
            # cursor with the recovered state; known changes deduplicate.
            for link in links:
                link.force_snapshot(name)
            obs.count("cluster.catchup_snapshots",
                      labels={"reason": "reattach"})
        j = dd.journal
        j.on_record = lambda rt, pl, seq, _n=name: self._on_record(
            _n, rt, pl, seq)
        j.on_synced = lambda seq, _n=name: self._on_synced(_n, seq)
        if self.ack_replicas > 0:
            dd.replication_gate = lambda _n=name: self.wait_acked(_n)
        with self._lock:
            for link in self._links.values():
                link.note_doc(name)

    def detach(self, name: str) -> None:
        with self._lock:
            st = self._streams.pop(name, None)
        if st is not None:
            st.dd.journal.on_record = None
            st.dd.journal.on_synced = None
            st.dd.replication_gate = None

    def doc_names(self) -> List[str]:
        with self._lock:
            return list(self._streams)

    def lsn(self, name: str) -> int:
        with self._lock:
            st = self._streams.get(name)
            return st.lsn if st is not None else 0

    def doc_lsns(self) -> Dict[str, int]:
        with self._lock:
            return {name: st.lsn for name, st in self._streams.items()}

    # -- seconds-based staleness ---------------------------------------------

    @staticmethod
    def _stamp_after(st: _DocStream, lsn: int) -> Optional[float]:
        """Append time of the first retained record with LSN > ``lsn``
        (the oldest write a follower at ``lsn`` has not applied);
        falls back to the oldest stamp when the ring trimmed past it."""
        for rec_lsn, t in st.stamps:
            if rec_lsn > lsn:
                return t
        return st.stamps[0][1] if st.stamps else None

    def staleness(self, now: Optional[float] = None) -> Dict[str, Dict[str, float]]:
        """Leader-computed ``{follower_addr: {doc: seconds}}``: zero for
        a caught-up follower, else how long ago the first record it is
        missing was appended here (leader monotonic clock). A follower
        with no cursor for a doc yet (mid-handshake) reports nothing for
        it rather than a fake number."""
        if now is None:
            now = obs.now()
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for addr, link in self._links.items():
                per: Dict[str, float] = {}
                for name, st in self._streams.items():
                    f = link.durable_lsn.get(name)
                    if f is None:
                        continue
                    if f >= st.lsn:
                        per[name] = 0.0
                        continue
                    t = self._stamp_after(st, f)
                    per[name] = max(0.0, now - t) if t is not None else 0.0
                out[addr] = per
        return out

    def staleness_report(self, now: Optional[float] = None) -> dict:
        """Both sides of the staleness picture per follower: what this
        leader computes from its stamps, and what the follower last
        self-reported over the ping exchange (its own estimate against
        the RTT-aligned leader clock) — the agreement CI asserts on."""
        computed = self.staleness(now=now)
        out: Dict[str, dict] = {}
        with self._lock:
            links = dict(self._links)
        for addr, per in computed.items():
            link = links.get(addr)
            out[addr] = {
                "computed": per,
                "reported": dict(link.reported_staleness) if link else {},
            }
        return out

    def publish_staleness(self, now: Optional[float] = None) -> Dict[str, Dict[str, float]]:
        """Export the computed view: one ``cluster.staleness_seconds
        {node}`` gauge per follower (worst doc) plus one histogram
        observation per (follower, doc). Called from each link's idle
        ping cycle and from ``clusterStatus``, so the gauges are fresh
        whenever anything looks."""
        stale = self.staleness(now=now)
        for addr, per in stale.items():
            obs.gauge_set("cluster.staleness_seconds",
                          max(per.values(), default=0.0),
                          labels={"node": addr})
            for s in per.values():
                obs.observe("cluster.staleness_seconds", s)
        return stale

    # -- journal hooks (leader write path) -----------------------------------

    def _on_record(self, name: str, rec_type: int, payload: bytes,
                   seq: int) -> None:
        # capture the appending request's trace context (None outside a
        # propagated trace): the ship span and the follower's apply span
        # link back to every request a shipped batch covers
        ctx = obs.current_trace_context()
        with self._lock:
            st = self._streams.get(name)
            if st is None:
                return
            st.lsn += 1
            st.pending.append((st.lsn, seq, rec_type, payload, ctx))
            st.stamps.append((st.lsn, obs.now()))

    def _drain_pending_locked(self, st: _DocStream) -> bool:
        """Promote pending records covered by the journal's durable
        prefix into the ship buffer (hub lock held). Reading
        ``acked_seq`` directly makes the promotion self-synchronizing:
        the group-commit combiner fires ``on_synced`` OUTSIDE the
        journal condition, so a combined-fsync waiter can reach the ack
        gate before the hook ran — draining against the journal's own
        counter closes that window."""
        covering = st.dd.journal.acked_seq
        moved = False
        while st.pending and st.pending[0][1] <= covering:
            lsn, _seq, rec_type, payload, ctx = st.pending.popleft()
            st.buffer.append((lsn, rec_type, payload, ctx))
            st.buffer_bytes += len(payload) + 16
            st.synced_lsn = lsn
            moved = True
        while st.buffer and st.buffer_bytes > self.retain_bytes:
            lsn, _rt, pl, _ctx = st.buffer.popleft()
            st.buffer_bytes -= len(pl) + 16
            st.base_lsn = lsn
        return moved

    def _on_synced(self, name: str, covering: int) -> None:
        """Records up to journal append seq ``covering`` are durable on
        the leader: promote them into the ship buffer and wake links."""
        with self._lock:
            st = self._streams.get(name)
            if st is None:
                return
            if not self._drain_pending_locked(st):
                return
            links = list(self._links.values())
        for link in links:
            link.wake()

    # -- the ack gate --------------------------------------------------------

    def _breaker_gauges(self) -> None:
        for s in ("closed", "open", "half_open"):
            obs.gauge_set("repl.breaker",
                          1.0 if s == self._breaker_state else 0.0,
                          labels={"state": s})

    def _breaker_transition_locked(self, to: str) -> None:
        frm, self._breaker_state = self._breaker_state, to
        self._breaker_gauges()
        obs.count("repl.breaker_transitions", labels={"to": to})
        if to == "open":
            obs.count("repl.breaker_trips")
        obs.event("repl.breaker", frm=frm, to=to,
                  failures=self._breaker_failures)

    def breaker_state(self) -> str:
        with self._breaker_lock:
            return self._breaker_state

    def wait_acked(self, name: str) -> None:
        """The ack gate, behind the circuit breaker: closed -> wait for
        real follower acks; open -> ack on leader durability alone until
        the cooldown elapses (every bypass counted as
        ``repl.breaker_bypass``); half-open -> one probe waits for real
        acks while concurrent callers keep bypassing."""
        probe = False
        if self.breaker_enabled:
            with self._breaker_lock:
                if self._breaker_state == "open":
                    if (time.monotonic() - self._breaker_opened_at
                            < self.breaker_cooldown):
                        obs.count("repl.breaker_bypass")
                        return
                    self._breaker_transition_locked("half_open")
                    probe = True
                elif self._breaker_state == "half_open":
                    # a probe is already in flight; stacking more callers
                    # onto full ack timeouts is the stall being prevented
                    obs.count("repl.breaker_bypass")
                    return
        try:
            self._wait_acked(name)
        except ReplicationTimeout:
            if self.breaker_enabled:
                with self._breaker_lock:
                    self._breaker_failures += 1
                    if (self._breaker_state != "open"
                            and (probe or self._breaker_failures
                                 >= self.breaker_threshold)):
                        self._breaker_opened_at = time.monotonic()
                        self._breaker_transition_locked("open")
            raise
        else:
            if self.breaker_enabled:
                with self._breaker_lock:
                    self._breaker_failures = 0
                    if self._breaker_state != "closed":
                        self._breaker_transition_locked("closed")

    def _wait_acked(self, name: str) -> None:
        """Block until >= ack_replicas followers hold this document's
        current locally-durable LSN on their own disks. Raises
        ``ReplicationTimeout`` after ``ack_timeout`` — an un-replicated
        ack is no ack."""
        deadline = time.monotonic() + self.ack_timeout
        with self._acked:
            st = self._streams.get(name)
            if st is None:
                return
            # the caller's records are journal-durable by now, but the
            # combiner's on_synced hook may not have run yet: drain
            # against the journal's acked counter so the target covers
            # THIS caller's writes, never a stale prefix
            moved = self._drain_pending_locked(st)
            target = st.synced_lsn
            links = list(self._links.values()) if moved else []
        for link in links:
            link.wake()
        with self._acked:
            if target == 0:
                return  # nothing durable to replicate yet
            while True:
                n = sum(
                    1 for link in self._links.values()
                    if not link.quarantined
                    and link.durable_lsn.get(name, 0) >= target
                )
                if n >= self.ack_replicas:
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    raise ReplicationTimeout(
                        f"only {n}/{self.ack_replicas} followers confirmed "
                        f"{name}@{target} within {self.ack_timeout}s"
                    )
                self._acked.wait(timeout=min(remaining, 0.5))

    def _note_follower_ack(self, name: str, lsn: int) -> None:
        with self._acked:
            self._acked.notify_all()
        st = self._streams.get(name)
        if st is not None:
            obs.gauge_set(
                "cluster.replication_lag", max(0, st.synced_lsn - lsn),
                labels={"doc": name},
            )

    # -- snapshots (catch-up) ------------------------------------------------

    def snapshot(self, name: str) -> Tuple[bytes, int]:
        """A full save pinned to an LSN, taken under the document lock so
        save bytes and LSN describe the same instant. Mirrors the
        compaction dance: snapshot first, tail records after."""
        with self._lock:
            st = self._streams.get(name)
        if st is None:
            raise ReplicationError(f"no replicated document {name!r}")
        # timed acquire: the ack gate can hold this lock on the stdio
        # path while waiting for us — back off and let the caller requeue
        if not st.dd.lock.acquire(timeout=self.ack_timeout):
            raise ReplicationError(f"snapshot of {name!r}: doc lock busy")
        try:
            # the on-disk codec verbatim (run-coded when enabled): the
            # follower hydrates the same bytes the leader's disk holds,
            # no encode here / no re-encode there
            data = st.dd.snapshot_bytes()
            with self._lock:
                lsn = st.lsn
        finally:
            st.dd.lock.release()
        obs.count("cluster.snapshots_shipped")
        return data, lsn

    def tail_after(
        self, name: str, lsn: int
    ) -> Tuple[List[Tuple[int, bytes]], int, List[tuple]]:
        """Retained records with LSN > ``lsn`` (bounded by batch_bytes)
        plus the distinct trace contexts of the covered records, or raise
        when the tail has been trimmed past that point."""
        with self._lock:
            st = self._streams.get(name)
            if st is None:
                raise ReplicationError(f"no replicated document {name!r}")
            if lsn < st.base_lsn:
                raise ReplicationError(
                    f"{name!r}: records after {lsn} trimmed "
                    f"(base is {st.base_lsn}); snapshot required"
                )
            out, total, last = [], 0, lsn
            traces: List[tuple] = []
            for rec_lsn, rec_type, payload, ctx in st.buffer:
                if rec_lsn <= lsn:
                    continue
                if out and total + len(payload) > self.batch_bytes:
                    break
                out.append((rec_type, payload))
                total += len(payload)
                last = rec_lsn
                if ctx is not None and ctx not in traces and len(traces) < 8:
                    traces.append(ctx)
            return out, last, traces

    # -- follower management -------------------------------------------------

    def add_follower(self, addr: str) -> None:
        with self._lock:
            if self._closed or addr in self._links:
                return
            link = _FollowerLink(self, addr)
            self._links[addr] = link
        link.start()

    def remove_follower(self, addr: str) -> None:
        with self._lock:
            link = self._links.pop(addr, None)
        if link is not None:
            link.stop()
        with self._acked:
            self._acked.notify_all()

    def followers(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                addr: dict(link.durable_lsn)
                for addr, link in self._links.items()
            }

    def follower_addrs(self) -> List[str]:
        """Addresses of followers currently trusted for quorum (the
        anti-entropy scrub probes exactly this set)."""
        with self._lock:
            return [
                addr for addr, link in self._links.items()
                if not link.quarantined
            ]

    def quarantined_addrs(self) -> List[str]:
        with self._lock:
            return [
                addr for addr, link in self._links.items()
                if link.quarantined
            ]

    def quarantine(self, addr: str) -> bool:
        """Drop a follower from the ack-gate quorum without detaching it.

        The scrub loop calls this when a replica diverges AGAIN after a
        repair — a disk or host that corrupts twice cannot be trusted to
        hold acked writes, so its confirmations stop counting toward
        ``ack_replicas``. Shipping continues (the replica may still
        recover and serve reads); only its vote is revoked. Returns False
        for unknown addresses."""
        with self._lock:
            link = self._links.get(addr)
            if link is None:
                return False
            already = link.quarantined
            link.quarantined = True
            n = sum(1 for l in self._links.values() if l.quarantined)
        if not already:
            obs.count("cluster.quarantine", labels={"follower": addr})
            obs.event("cluster.quarantine", follower=addr)
        obs.gauge_set("cluster.quarantined", n)
        # the quorum just shrank: wake ack waiters so they re-count
        # against the reduced set instead of sleeping out their deadline
        with self._acked:
            self._acked.notify_all()
        return True

    def close(self) -> None:
        with self._lock:
            self._closed = True
            links = list(self._links.values())
            self._links.clear()
            streams = list(self._streams.values())
            self._streams.clear()
        for st in streams:
            st.dd.journal.on_record = None
            st.dd.journal.on_synced = None
            st.dd.replication_gate = None
        for link in links:
            link.stop()
        with self._acked:
            self._acked.notify_all()


class _FollowerLink:
    """One follower: a dialing connection plus a ship worker thread.

    The worker ships every attached document's durable tail in LSN order
    over a single connection — the follower applies the stream serially
    (one replication shard key), so each follower's state is always a
    prefix of the leader's replication log and follower states are
    mutually comparable (what promotion-by-longest-prefix relies on)."""

    def __init__(self, hub: ReplicationHub, addr: str):
        self.hub = hub
        self.addr = addr
        self.durable_lsn: Dict[str, int] = {}  # follower's durable cursor
        self.quarantined = False  # vote revoked (integrity divergence)
        # follower's last self-reported per-doc staleness estimate
        # (seconds, from the ping exchange)
        self.reported_staleness: Dict[str, float] = {}
        self._sent_lsn: Dict[str, int] = {}
        self._needs_snapshot: Dict[str, bool] = {}
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._sock: Optional[socket.socket] = None
        self._rid = 0
        self._thread = threading.Thread(
            target=self._run, name=f"repl:{addr}", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def wake(self) -> None:
        self._wake.set()

    def note_doc(self, name: str) -> None:
        self._wake.set()

    def force_snapshot(self, name: str) -> None:
        """Next ship for ``name`` starts from a fresh snapshot (the
        reattach/resync path)."""
        self._needs_snapshot[name] = True
        self._wake.set()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=10)

    # -- request plumbing (line framing, serial request/response) ------------

    def _connect(self):
        host, _, port = self.addr.rpartition(":")
        sock = socket.create_connection(
            (host, int(port)), timeout=self.hub.io_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # the connect timeout stays as the per-op socket timeout: a
        # stalled follower (response path black-holed) times the request
        # out instead of freezing the ship loop — the link recycles and
        # the ack gate sees an honest zero instead of a hang
        self._sock = sock
        return sock.makefile("r")

    def _request(self, f, method: str, params: dict, trace=None) -> dict:
        self._rid += 1
        req = {"id": self._rid, "method": method, "params": params}
        if trace is not None:
            # parent the follower's request handling into the (first)
            # covered client trace; the full covered set rides in
            # params["traces"] as span links
            req["trace"] = {"t": trace[0], "s": trace[1]}
        line = json.dumps(req) + "\n"
        self._sock.sendall(line.encode("utf-8"))
        raw = f.readline()
        if not raw:
            raise ReplicationError("follower connection closed")
        resp = json.loads(raw)
        if "error" in resp:
            err = resp["error"]
            raise ReplicationError(
                f"{err.get('type')}: {err.get('message')}"
            )
        return resp.get("result") or {}

    # -- the ship loop -------------------------------------------------------

    def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set():
            try:
                f = self._connect()
                obs.gauge_set("cluster.follower_up", 1,
                              labels={"follower": self.addr})
                self._handshake(f)
                backoff = 0.05
                self._ship_loop(f)
            except Exception as e:  # noqa: BLE001 — links must self-heal
                if self._stop.is_set():
                    return
                obs.count("cluster.link_error", error=str(e)[:200])
                obs.gauge_set("cluster.follower_up", 0,
                              labels={"follower": self.addr})
                sock, self._sock = self._sock, None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                # a dead follower must not freeze the gate accounting at
                # its last acked values — it no longer counts
                self.durable_lsn.clear()
                self._sent_lsn.clear()
                self.reported_staleness.clear()
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 2.0)

    def _handshake(self, f) -> None:
        """Learn the follower's persisted cursors; decide tail vs
        snapshot per document."""
        status = self._request(f, "clusterStatus", {})
        cursors = {
            name: info.get("cursor")
            for name, info in (status.get("docs") or {}).items()
        }
        for name in self.hub.doc_names():
            cur = cursors.get(name)
            if (
                cur
                and cur.get("stream") == self.hub.stream_id
            ):
                self._sent_lsn[name] = int(cur["lsn"])
                self.durable_lsn[name] = int(cur["lsn"])
                self._needs_snapshot[name] = False
            else:
                self._needs_snapshot[name] = True
        self.hub._note_follower_ack("", 0)

    def _ship_loop(self, f) -> None:
        last_sent = time.monotonic()
        while not self._stop.is_set():
            progressed = False
            for name in self.hub.doc_names():
                if self._needs_snapshot.get(name, True):
                    self._ship_snapshot(f, name)
                    progressed = True
                while self._ship_tail(f, name):
                    progressed = True
            if progressed:
                last_sent = time.monotonic()
                continue
            if not self._wake.wait(timeout=self.hub.heartbeat):
                if time.monotonic() - last_sent >= self.hub.heartbeat:
                    # the idle heartbeat doubles as a clock-sync probe:
                    # the RTT midpoint around the follower's reported
                    # monotonic "now" is what flight-merge uses to put
                    # both processes' spans on one timeline
                    t0 = obs.now()
                    # the ping carries the leader clock and per-doc
                    # latest LSNs out; the response carries the
                    # follower's own staleness estimate back — the two
                    # halves of the PR 8 RTT exchange the agreement
                    # assertion in run_cluster compares
                    res = self._request(f, "replPing", {
                        "stream": self.hub.stream_id,
                        "now": t0,
                        "docs": self.hub.doc_lsns(),
                    })
                    t1 = obs.now()
                    peer_now = res.get("now")
                    if isinstance(peer_now, (int, float)):
                        obs.flight.note_clock_sync(
                            res.get("nodeId") or self.addr, t0, t1, peer_now)
                    rep = res.get("staleness")
                    if isinstance(rep, dict):
                        self.reported_staleness = {
                            str(k): float(v) for k, v in rep.items()
                            if isinstance(v, (int, float))
                        }
                    self.hub.publish_staleness(now=t1)
                    last_sent = time.monotonic()
            self._wake.clear()

    def _ship_snapshot(self, f, name: str) -> None:
        data, lsn = self.hub.snapshot(name)
        cursor = encode_cursor(self.hub.stream_id, lsn)
        self._request(f, "replSnapshot", {
            "name": name,
            "stream": self.hub.stream_id,
            "lsn": lsn,
            "snapshot": base64.b64encode(data).decode("ascii"),
            "cursor": base64.b64encode(cursor).decode("ascii"),
            # staleness base: a snapshot pinned to the leader's latest
            # LSN makes the follower fresh as of this leader instant
            "now": obs.now(),
            "leaderLsn": lsn,
        })
        self._needs_snapshot[name] = False
        self._sent_lsn[name] = lsn
        self.durable_lsn[name] = lsn
        self.hub._note_follower_ack(name, lsn)

    def _ship_tail(self, f, name: str) -> bool:
        """Ship one contiguous batch after the follower's cursor; True
        when records went out (call again — there may be more)."""
        since = self._sent_lsn.get(name, 0)
        try:
            records, last, traces = self.hub.tail_after(name, since)
        except ReplicationError:
            # the follower's cursor fell off the bounded retention
            # buffer (it stalled, or died and came back late): forced
            # snapshot catch-up instead of a stall — and counted, so
            # the soak can assert the path actually exercised
            obs.count("cluster.catchup_snapshots",
                      labels={"reason": "retention"})
            self._needs_snapshot[name] = True
            self._ship_snapshot(f, name)
            return True
        if not records:
            return False
        cursor = encode_cursor(self.hub.stream_id, last)
        with obs.span("cluster.ship_batch", links=traces,
                      records=len(records)):
            try:
                params = {
                    "name": name,
                    "stream": self.hub.stream_id,
                    "prev": since,
                    "lsn": last,
                    "data": base64.b64encode(
                        encode_batch(records)).decode("ascii"),
                    "cursor": base64.b64encode(cursor).decode("ascii"),
                    # leader ship-time clock + latest LSN: the follower
                    # marks itself fresh-as-of "now" when this batch
                    # brings it level with leaderLsn
                    "now": obs.now(),
                    "leaderLsn": self.hub.lsn(name),
                }
                if traces:
                    params["traces"] = [[t, s] for t, s in traces]
                self._request(f, "replApply", params,
                              trace=traces[0] if traces else None)
            except ReplicationError as e:
                if "ReplCursorMismatch" in str(e):
                    # the follower's journal disagrees with our
                    # bookkeeping (its restart raced an ack): resync
                    # through a snapshot instead of guessing
                    obs.count("cluster.catchup_snapshots",
                              labels={"reason": "cursor_mismatch"})
                    self._needs_snapshot[name] = True
                    self._ship_snapshot(f, name)
                    return True
                raise
        obs.count("cluster.records_shipped", n=len(records))
        self._sent_lsn[name] = last
        self.durable_lsn[name] = last
        self.hub._note_follower_ack(name, last)
        return True
