"""AutoDoc: the implicit-transaction document API.

Mirrors the reference's AutoCommit (reference:
rust/automerge/src/autocommit.rs): every mutating call opens a transaction if
none is open; reads and history operations commit it first. This is the
primary user-facing API of the framework (the analogue of the reference's
wasm/JS surface is built on top of it).

    doc = AutoDoc()
    text = doc.put_object("_root", "content", ObjType.TEXT)
    doc.splice_text(text, 0, 0, "hello")
    data = doc.save()
    doc2 = AutoDoc.load(data)
    doc2.merge(doc)
"""

from __future__ import annotations

from typing import List, Optional

from .core.document import AutomergeError, Document, ROOT
from .core.transaction import Transaction
from .patches.patch_log import PatchCallback, PatchLog
from .types import ActorId, ObjType


class AutoDoc:
    def __init__(
        self,
        actor: Optional[ActorId] = None,
        document: Optional[Document] = None,
        text_encoding: Optional[str] = None,
    ):
        self.doc = document or Document(actor, text_encoding=text_encoding)
        self._tx: Optional[Transaction] = None
        self._manual: Optional[Transaction] = None
        self._isolation: Optional[List[bytes]] = None
        # (obj, tx, closure) memo for the per-edit splice hot path; valid
        # only while the same autocommit transaction is live
        self._splice_cache = None
        # same shape for the per-op map-put hot path; _put_block pins the
        # transaction whose values proved session-ineligible
        self._put_cache = None
        self._put_block = None
        self._diff_cursor: List[bytes] = []
        # persistent observer log (reference: autocommit.rs owns a PatchLog);
        # inactive until an observer is attached so the hot path pays nothing
        self.patch_log = PatchLog(active=False)
        self._patch_callback: Optional[PatchCallback] = None

    # -- observers ----------------------------------------------------------

    def set_patch_callback(
        self, callback: Optional[PatchCallback], from_scratch: bool = False
    ) -> None:
        """Attach a live observer: ``callback(patches)`` fires after every
        commit / apply / merge / sync-receive / incremental load.

        ``from_scratch=True`` leaves the log's cursor unset so the first
        notification materializes the whole current state (reference:
        automerge/current_state.rs — load with an active patch log).
        Otherwise only changes made after attachment are reported.
        """
        self._patch_callback = callback
        if callback is None:
            self.patch_log.set_active(False)
            return
        self.patch_log.set_active(True)
        if not from_scratch:
            self.patch_log.reset(self.doc)
        self._notify_patches()

    def make_patches(self):
        """Drain the patch log: patches covering everything since the last
        drain (reference: Automerge::make_patches / autocommit diff cursor)."""
        return self.patch_log.make_patches(self.doc)

    def _notify_patches(self) -> None:
        if self._patch_callback is None or not self.patch_log.is_active():
            return
        patches = self.patch_log.make_patches(self.doc)
        if patches:
            self._patch_callback(patches)

    # -- transaction management --------------------------------------------

    def _check_manual(self) -> None:
        if self._manual is not None:
            if not self._manual._done:
                raise AutomergeError(
                    "a manual transaction is open; commit or roll it back "
                    "before mutating through the document"
                )
            self._manual = None

    def _ensure_tx(self) -> Transaction:
        self._check_manual()
        if self._tx is None:
            if self._isolation is not None:
                self._tx = self.doc.transaction_at(self._isolation)
            else:
                self._tx = Transaction(self.doc)
                # autocommit transactions may route text splices through
                # the native edit session (core/transaction.py)
                self._tx.enable_sessions = True
        return self._tx

    def _sync_reads(self) -> None:
        # pending native-session ops drain into the store before any read
        if self._tx is not None:
            self._tx._drain_all()

    def commit(self, message: Optional[str] = None, timestamp: Optional[int] = None) -> Optional[bytes]:
        tx = self._tx
        self._tx = None
        self._splice_cache = None  # the closures retain the whole tx
        self._put_cache = None
        self._put_block = None
        if tx is None:
            return None
        if message is not None:
            tx.message = message
        if timestamp is not None:
            tx.timestamp = timestamp
        h = tx.commit()
        if h is not None and self._isolation is not None:
            # isolated edits build on each other: advance the isolation
            # point to the committed change (reference: autocommit isolate)
            self._isolation = [h]
        if h is not None:
            self._notify_patches()
        return h

    def rollback(self) -> int:
        tx = self._tx
        self._tx = None
        self._splice_cache = None
        self._put_cache = None
        self._put_block = None
        return tx.rollback() if tx is not None else 0

    def pending_ops(self) -> int:
        return self._tx.pending_ops() if self._tx else 0

    def transaction(self, message=None, timestamp=None) -> Transaction:
        """Open a manual transaction (commit/rollback is the caller's job).

        While it is open, autocommit mutations on this document raise —
        two live transactions would mint duplicate opids.
        """
        self._check_manual()
        self.commit()
        self._manual = Transaction(self.doc, message=message, timestamp=timestamp)
        return self._manual

    def isolate(self, heads: List[bytes]) -> None:
        """Scope subsequent edits to ``heads`` (reference: autocommit isolate)."""
        self.commit()
        self._isolation = list(heads)

    def integrate(self) -> None:
        self.commit()
        self._isolation = None

    # -- identity ----------------------------------------------------------

    def get_actor(self) -> ActorId:
        return self.doc.actor

    def set_actor(self, actor: ActorId) -> "AutoDoc":
        self.commit()
        self.doc.set_actor(actor)
        return self

    # -- mutation (delegates through the open transaction) ------------------

    def put(self, obj: str, prop, value) -> None:
        c = self._put_cache
        if c is not None and c[0] == obj and c[1] is self._tx:
            r = c[2](prop, value)
            if r > 0:
                return
            self._put_cache = None
            if r < 0:
                # key/value not session-eligible: stop rebuilding for this
                # (transaction, object) or every such put would pay an
                # O(keys) preload
                self._put_block = (self._tx, obj)
        tx = self._ensure_tx()
        if self._put_block != (tx, obj):
            # build the session BEFORE the first generic put: a pure-session
            # transaction commits straight from arrays (no prefix rows)
            fn = tx.fast_put_fn(obj)
            if fn is None:
                # ineligible object (conflicted key, wide ranks, no native):
                # memoize or every put repeats the O(keys) eligibility scan
                self._put_block = (tx, obj)
            else:
                r = fn(prop, value)
                if r > 0:
                    self._put_cache = (obj, tx, fn)
                    return
                if r < 0:
                    self._put_block = (tx, obj)
        tx.put(obj, prop, value)

    def put_object(self, obj: str, prop, obj_type: ObjType) -> str:
        return self._ensure_tx().put_object(obj, prop, obj_type)

    def insert(self, obj: str, index: int, value) -> None:
        self._ensure_tx().insert(obj, index, value)

    def insert_object(self, obj: str, index: int, obj_type: ObjType) -> str:
        return self._ensure_tx().insert_object(obj, index, obj_type)

    def delete(self, obj: str, prop) -> None:
        self._ensure_tx().delete(obj, prop)

    def increment(self, obj: str, prop, by: int) -> None:
        self._ensure_tx().increment(obj, prop, by)

    def splice_text(self, obj: str, pos: int, delete: int, text: str) -> None:
        c = self._splice_cache
        if c is not None and c[0] == obj and c[1] is self._tx:
            if c[2](pos, delete, text):
                return
            self._splice_cache = None  # session gone; rebuild below
        tx = self._ensure_tx()
        tx.splice_text(obj, pos, delete, text)
        fn = tx.fast_splice_fn(obj)
        self._splice_cache = (obj, tx, fn) if fn is not None else None

    def splice_text_many(self, obj: str, edits, clamp: bool = True) -> int:
        """Bulk text ingest: (pos, delete, text) edits in one native pass."""
        return self._ensure_tx().splice_text_many(obj, edits, clamp=clamp)

    def splice(self, obj: str, pos: int, delete: int, values) -> None:
        self._ensure_tx().splice(obj, pos, delete, values)

    def mark(self, obj: str, start: int, end: int, name: str, value, expand="after") -> None:
        self._ensure_tx().mark(obj, start, end, name, value, expand)

    def unmark(self, obj: str, start: int, end: int, name: str, expand="none") -> None:
        self._ensure_tx().unmark(obj, start, end, name, expand)

    # -- reads -------------------------------------------------------------
    # Reads see the open transaction's ops in place (the store is updated as
    # ops are created). Under isolation they read at the isolation clock so
    # reads and mutations agree on what is visible.

    def _read_clock(self, heads):
        if heads is not None:
            return self.doc.clock_at(heads)
        if self._isolation is not None:
            if self._tx is not None and self._tx.scope is not None:
                return self._tx.scope
            return self.doc.clock_at(self._isolation)
        return None

    def get(self, obj: str, prop, heads=None):
        self._sync_reads()
        return self.doc.get(obj, prop, clock=self._read_clock(heads))

    def get_all(self, obj: str, prop, heads=None):
        self._sync_reads()
        return self.doc.get_all(obj, prop, clock=self._read_clock(heads))

    def keys(self, obj: str = ROOT, heads=None):
        self._sync_reads()
        return self.doc.keys(obj, clock=self._read_clock(heads))

    def length(self, obj: str = ROOT, heads=None) -> int:
        if heads is None and self._tx is not None and self._tx._sessions:
            n = self._tx.session_length(self.doc.import_id(obj))
            if n is not None:
                return n
        self._sync_reads()
        return self.doc.length(obj, clock=self._read_clock(heads))

    def text(self, obj: str, heads=None) -> str:
        self._sync_reads()
        return self.doc.text(obj, clock=self._read_clock(heads))

    def list_items(self, obj: str, heads=None):
        self._sync_reads()
        return self.doc.list_items(obj, clock=self._read_clock(heads))

    def map_entries(self, obj: str = ROOT, heads=None):
        self._sync_reads()
        return self.doc.map_entries(obj, clock=self._read_clock(heads))

    def hydrate(self, obj: str = ROOT, heads=None):
        self._sync_reads()
        return self.doc.hydrate(obj, clock=self._read_clock(heads))

    def get_cursor(self, obj: str, position: int, heads=None) -> str:
        self._sync_reads()
        return self.doc.get_cursor(obj, position, clock=self._read_clock(heads))

    def get_cursor_position(self, obj: str, cursor: str, heads=None) -> int:
        self._sync_reads()
        return self.doc.get_cursor_position(obj, cursor, clock=self._read_clock(heads))

    def marks(self, obj: str, heads=None):
        self._sync_reads()
        return self.doc.marks(obj, clock=self._read_clock(heads))

    def object_type(self, obj: str) -> ObjType:
        self._sync_reads()
        return self.doc.object_type(obj)

    def map_range(self, obj: str = ROOT, start=None, end=None, heads=None):
        self._sync_reads()
        return self.doc.map_range(obj, start, end, clock=self._read_clock(heads))

    def list_range(self, obj: str, start: int = 0, end=None, heads=None):
        self._sync_reads()
        return self.doc.list_range(obj, start, end, clock=self._read_clock(heads))

    def values(self, obj: str = ROOT, heads=None):
        self._sync_reads()
        return self.doc.values(obj, clock=self._read_clock(heads))

    def parents(self, obj: str, heads=None):
        self._sync_reads()
        return self.doc.parents(obj, clock=self._read_clock(heads))

    # -- history -----------------------------------------------------------

    def get_heads(self) -> List[bytes]:
        self.commit()
        return self.doc.get_heads()

    def merge(self, other: "AutoDoc") -> List[bytes]:
        self.commit()
        other.commit()
        heads = self.doc.merge(other.doc)
        self._notify_patches()
        return heads

    def fork(self, actor: Optional[ActorId] = None) -> "AutoDoc":
        self.commit()
        return AutoDoc(document=self.doc.fork(actor))

    def fork_at(self, heads: List[bytes], actor: Optional[ActorId] = None) -> "AutoDoc":
        self.commit()
        return AutoDoc(document=self.doc.fork_at(heads, actor))

    def apply_changes(self, changes) -> None:
        self.commit()
        self.doc.apply_changes(changes)
        self._notify_patches()

    def get_changes(self, have_deps: List[bytes]):
        self.commit()
        return self.doc.get_changes(have_deps)

    def get_missing_deps(self, heads: List[bytes] = ()) -> List[bytes]:
        """Hashes named as deps (or in ``heads``) but absent from history
        (reference: automerge.rs get_missing_deps)."""
        self.commit()
        return self.doc.get_missing_deps(list(heads))

    def get_last_local_change(self):
        self.commit()
        idxs = self.doc.states.get(self.doc.actors.lookup(self.doc.actor), [])
        return self.doc.history[idxs[-1]].stored if idxs else None

    # -- diff / patches ------------------------------------------------------

    def diff(self, before_heads, after_heads):
        self.commit()
        return self.doc.diff(before_heads, after_heads)

    def diff_incremental(self, commit: bool = True):
        """Patches since the last diff_incremental / update_diff_cursor call
        (reference: autocommit.rs diff cursor).

        ``commit=False`` diffs only up to the last COMMITTED state — the
        open transaction is left intact (its message/timestamp survive a
        later explicit commit) and its patches surface on the pop after
        that commit."""
        if commit:
            self.commit()
        before = self._diff_cursor
        after = self.doc.get_heads()
        self._diff_cursor = after
        return self.doc.diff(before, after)

    def update_diff_cursor(self, commit: bool = True) -> None:
        if commit:
            self.commit()
        self._diff_cursor = self.doc.get_heads()

    def reset_diff_cursor(self) -> None:
        self._diff_cursor = []

    # -- sync ---------------------------------------------------------------

    def generate_sync_message(self, state):
        """Next sync message for the peer tracked by ``state`` (or None).

        Commits any open transaction first (reference: autocommit.rs sync
        adapter).
        """
        from .sync import generate_sync_message

        self.commit()
        return generate_sync_message(self.doc, state)

    def receive_sync_message(self, state, message) -> None:
        from .sync import receive_sync_message

        self.commit()
        receive_sync_message(self.doc, state, message)
        self._notify_patches()

    # -- save / load -------------------------------------------------------

    def save(self, deflate: bool = True, retain_orphans: bool = True) -> bytes:
        self.commit()
        return self.doc.save(deflate, retain_orphans=retain_orphans)

    def save_and_verify(self, deflate: bool = True) -> bytes:
        self.commit()
        return self.doc.save_and_verify(deflate)

    def save_incremental_after(self, heads: List[bytes]) -> bytes:
        self.commit()
        return self.doc.save_incremental_after(heads)

    @classmethod
    def open(cls, path, **kw):
        """Open (or create) a crash-safe durable document at ``path``
        (storage/durable.py): commits and sync-absorbed changes are
        journaled before acking, the journal compacts into atomic
        snapshots, and reopening replays snapshot + journal with
        torn-tail recovery. Returns a ``DurableDocument`` that delegates
        the whole AutoDoc surface."""
        from .storage.durable import DurableDocument

        return DurableDocument.open(path, doc_factory=cls, **kw)

    @classmethod
    def load(
        cls,
        data: bytes,
        actor: Optional[ActorId] = None,
        verify: bool = True,
        on_partial: str = "error",
        string_migration: str = "none",
        text_encoding: Optional[str] = None,
        on_error: Optional[str] = None,
    ) -> "AutoDoc":
        return cls(
            document=Document.load(
                data, actor, verify,
                on_partial=on_partial, string_migration=string_migration,
                text_encoding=text_encoding, on_error=on_error,
            )
        )

    def load_incremental(
        self,
        data: bytes,
        verify: bool = True,
        on_partial: str = "ignore",
        on_error: Optional[str] = None,
    ) -> int:
        self.commit()
        applied = self.doc.load_incremental(
            data, verify, on_partial=on_partial, on_error=on_error
        )
        self._notify_patches()
        return applied

    @property
    def salvage_report(self):
        """The report left by the last ``on_error="salvage"`` load, or None."""
        return self.doc.salvage_report
