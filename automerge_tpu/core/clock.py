"""Vector clocks over interned actor indices.

Semantics mirror the reference (reference: rust/automerge/src/clock.rs):
``covers`` is THE historical-visibility primitive, the partial order includes
concurrency, and ``isolate`` pins an actor to u64::MAX so an isolated
transaction's own ops stay visible to itself.

The dense-array form of a clock (``as_dense``) is what the device kernel
consumes: historical reads become a vectorized ``counter <= clock[actor]``
mask over op columns.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

_MAX = (1 << 64) - 1


class ClockData(NamedTuple):
    max_op: int
    seq: int


class Clock:
    __slots__ = ("data",)

    def __init__(self, data: Dict[int, ClockData] | None = None):
        self.data: Dict[int, ClockData] = dict(data) if data else {}

    def include(self, actor_idx: int, data: ClockData) -> None:
        """Merge knowledge of ``actor_idx`` up to ``data`` (keep the max)."""
        cur = self.data.get(actor_idx)
        if cur is None or data.max_op > cur.max_op:
            self.data[actor_idx] = data

    def covers(self, opid) -> bool:
        """True iff an op with id (counter, actor_idx) is in this clock's past."""
        ctr, actor = opid
        cur = self.data.get(actor)
        return cur is not None and cur.max_op >= ctr

    def isolate(self, actor_idx: int) -> None:
        """Pin ``actor_idx`` so the isolated actor always sees its own ops."""
        self.data[actor_idx] = ClockData(_MAX, _MAX)

    def merge(self, other: "Clock") -> None:
        for a, d in other.data.items():
            self.include(a, d)

    def copy(self) -> "Clock":
        return Clock(self.data)

    def seq_of(self, actor_idx: int) -> int:
        cur = self.data.get(actor_idx)
        return cur.seq if cur else 0

    def max_op_of(self, actor_idx: int) -> int:
        cur = self.data.get(actor_idx)
        return cur.max_op if cur else 0

    def as_dense(self, n_actors: int) -> list:
        """Dense per-actor max_op vector for device-side visibility masks."""
        return [self.max_op_of(a) for a in range(n_actors)]

    # Partial order: returns "eq" | "lt" | "gt" | "concurrent"
    def compare(self, other: "Clock") -> str:
        le = all(other.max_op_of(a) >= d.max_op for a, d in self.data.items())
        ge = all(self.max_op_of(a) >= d.max_op for a, d in other.data.items())
        if le and ge:
            return "eq"
        if le:
            return "lt"
        if ge:
            return "gt"
        return "concurrent"

    def __eq__(self, other):
        if not isinstance(other, Clock):
            return NotImplemented
        return self.compare(other) == "eq"

    def __repr__(self):
        return f"Clock({self.data})"
