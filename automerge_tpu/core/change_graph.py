"""The change DAG: hash-indexed adjacency lists over applied changes.

Semantics mirror the reference (reference:
rust/automerge/src/change_graph.rs): index-based adjacency for cache-friendly
traversal, ``clock_for_heads`` derives a vector clock by ancestor traversal,
``remove_ancestors`` filters a change set down to those not already implied
by a peer's heads (used by the sync protocol).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set

from .clock import Clock, ClockData


from ..errors import AutomergeError


class ChangeGraphError(AutomergeError):
    pass


class _Node:
    __slots__ = ("actor_idx", "seq", "max_op", "parents")

    def __init__(self, actor_idx: int, seq: int, max_op: int, parents: List[int]):
        self.actor_idx = actor_idx
        self.seq = seq
        self.max_op = max_op
        self.parents = parents


class ChangeGraph:
    def __init__(self):
        self._nodes: List[_Node] = []
        self._hashes: List[bytes] = []
        self._index: Dict[bytes, int] = {}
        self._clock_cache: Dict[frozenset, Clock] = {}

    def __len__(self) -> int:
        return len(self._nodes)

    def has(self, h: bytes) -> bool:
        return h in self._index

    def add_change(
        self, h: bytes, actor_idx: int, seq: int, max_op: int, deps: Iterable[bytes]
    ) -> None:
        if h in self._index:
            return
        parents = []
        for dep in deps:
            idx = self._index.get(dep)
            if idx is None:
                raise ChangeGraphError(f"missing dependency {dep.hex()}")
            parents.append(idx)
        self._index[h] = len(self._nodes)
        self._hashes.append(h)
        self._nodes.append(_Node(actor_idx, seq, max_op, parents))
        self._clock_cache.clear()

    def clock_for_heads(self, heads: Iterable[bytes]) -> Clock:
        key = frozenset(heads)
        cached = self._clock_cache.get(key)
        if cached is not None:
            return cached.copy()
        clock = Clock()
        stack = []
        for h in key:
            idx = self._index.get(h)
            if idx is None:
                raise ChangeGraphError(f"unknown head {h.hex()}")
            stack.append(idx)
        seen: Set[int] = set()
        while stack:
            i = stack.pop()
            if i in seen:
                continue
            seen.add(i)
            node = self._nodes[i]
            clock.include(node.actor_idx, ClockData(node.max_op, node.seq))
            stack.extend(node.parents)
        if len(self._clock_cache) > 64:
            self._clock_cache.clear()
        self._clock_cache[key] = clock
        return clock.copy()

    def remove_ancestors(self, changes: Set[bytes], heads: Iterable[bytes]) -> None:
        """Remove from ``changes`` every change that is an ancestor of ``heads``."""
        stack = [self._index[h] for h in heads if h in self._index]
        seen: Set[int] = set()
        while stack:
            i = stack.pop()
            if i in seen:
                continue
            seen.add(i)
            changes.discard(self._hashes[i])
            stack.extend(self._nodes[i].parents)

    def ancestor_hashes(self, heads: Iterable[bytes]) -> Set[bytes]:
        """All change hashes reachable from ``heads`` (inclusive)."""
        out: Set[bytes] = set()
        stack = [self._index[h] for h in heads if h in self._index]
        seen: Set[int] = set()
        while stack:
            i = stack.pop()
            if i in seen:
                continue
            seen.add(i)
            out.add(self._hashes[i])
            stack.extend(self._nodes[i].parents)
        return out
