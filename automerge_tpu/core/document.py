"""The document/history layer: apply, merge, fork, save, load, reads.

Semantics mirror the reference's Automerge struct (reference:
rust/automerge/src/automerge.rs): a causally-ordered change history with a
queue for not-yet-ready changes, a change DAG for clock derivation, an op
store for current state, and a uniform read API with ``*_at(heads)``
historical variants driven by vector clocks.

Public object ids use the Automerge convention: "_root" or "<ctr>@<actorhex>".
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..storage.change import (
    ChangeOp,
    HEAD_STORED,
    ROOT_STORED,
    StoredChange,
    build_change,
    chunk_local_ops,
    parse_change,
)
from ..storage.chunk import (
    CHUNK_CHANGE,
    CHUNK_DOCUMENT,
    MAGIC_BYTES,
    parse_chunk,
)
from ..storage.document import (
    DocChangeMeta,
    DocOp,
    ParsedDocument,
    build_document,
    parse_document,
)
from ..types import (
    Action,
    ActorId,
    HEAD,
    Key,
    ObjType,
    OpId,
    ScalarValue,
    is_make_action,
    objtype_for_action,
)
from ..utils.indexed_cache import IndexedCache
from .change_graph import ChangeGraph
from .clock import Clock
from .op_store import (
    LIST_ENC,
    TEXT_ENC,
    MapObject,
    Op,
    OpStore,
    ROOT_OBJ,
    SeqObject,
)

ROOT = "_root"


class FastSaveUnavailable(ValueError):
    """Expected fast-save fallback (not a bug): the array-native encoder
    cannot serve this document; the per-op python path takes over."""

# the typed hierarchy lives in automerge_tpu.errors (error.rs analogue);
# re-exported here because this module historically defined it
from ..errors import AutomergeError, DuplicateSeqNumber  # noqa: E402,F401


class AppliedChange:
    """A change in the history with its actor translation table."""

    __slots__ = ("stored", "actor_idx", "actor_map")

    def __init__(self, stored: StoredChange, actor_idx: int, actor_map: List[int]):
        self.stored = stored
        self.actor_idx = actor_idx
        self.actor_map = actor_map  # chunk-local actor index -> global index

    @property
    def hash(self) -> bytes:
        return self.stored.hash


class Document:
    """A CRDT document: nested maps/lists/text/counters with full history."""

    def __init__(
        self,
        actor: Optional[ActorId] = None,
        text_encoding: Optional[str] = None,
    ):
        from ..types import TEXT_ENCODINGS

        if text_encoding is not None and text_encoding not in TEXT_ENCODINGS:
            raise ValueError(f"unknown text encoding {text_encoding!r}")
        # the text index unit of THIS document (reference: a per-build
        # property, text_value.rs:5-15); None = follow the process default.
        # Activated via a context stack around every width-sensitive entry
        # point (see _width_ctx below), so documents with different
        # encodings coexist in one process.
        self.text_encoding = text_encoding
        self.actor = actor or ActorId()
        self.actors: IndexedCache[ActorId] = IndexedCache()
        self.props: IndexedCache[str] = IndexedCache()
        self._ops = OpStore(self.actors)
        self._ops_stale = False
        self.history: List[AppliedChange] = []
        self.history_index: Dict[bytes, int] = {}
        self.states: Dict[int, List[int]] = {}
        self.queue: List[StoredChange] = []
        self.deps: Set[bytes] = set()
        self.change_graph = ChangeGraph()
        self.max_op = 0
        # set by the last salvage load (on_error="salvage"), else None
        self.salvage_report = None
        # exid-string -> OpId memo: actor interning is append-only, so a
        # resolved id never changes (misses are NOT cached)
        self._exid_cache: Dict[str, OpId] = {}
        # ((history length, obj), text) memo for stale-store text reads;
        # history is append-only so the length keys the doc state
        self._stale_text_memo = None
        # live manual transactions (registered by Transaction); a device
        # merge or save while one is open would silently miss its ops.
        # Weak refs: an abandoned (unreachable, never committed) transaction
        # must not block the document forever.
        import weakref

        self.open_transactions = weakref.WeakSet()
        # called with each StoredChange as it enters history — the durable
        # write path (storage/durable.py) journals through this hook so a
        # commit/merge/sync-receive only acks once the change is on disk.
        # An exception here propagates: the caller must not ack.
        self.change_listeners = []

    def _live_transaction(self):
        """The live (un-done) manual transaction, if any."""
        for live in self.open_transactions:
            if not getattr(live, "_done", True):
                return live
        return None

    def _check_no_pending_tx(self, what: str) -> None:
        """Exports built from history (save / incremental save / change
        export) silently miss a live transaction's eagerly-applied ops —
        refuse rather than emit bytes that diverge from local reads."""
        live = self._live_transaction()
        if live is not None and live.pending_ops():
            raise AutomergeError(
                f"cannot {what} while a transaction with pending ops is "
                "open; commit or roll it back first"
            )

    # -- op store (lazily materialized) ------------------------------------
    #
    # The change history is the document's source of truth; the op store is
    # a materialized view of it. Bulk applies (merge / sync catch-up / fork)
    # only mark the view stale — the first read or local edit rebuilds it
    # once, so K consecutive bulk applies pay ONE rebuild, not K. This is
    # the host-side mirror of the device design (op columns are derived
    # from changes on demand); the reference has no analogue because its
    # reads and writes share the eagerly-maintained B-tree (op_set.rs:28).

    @property
    def ops(self) -> OpStore:
        if self._ops_stale:
            self._materialize_ops()
        return self._ops

    @ops.setter
    def ops(self, store: OpStore) -> None:
        self._ops = store
        self._ops_stale = False

    def _materialize_ops(self) -> None:
        from .bulk_load import rebuild_op_store

        self._ops_stale = False  # cleared first: rebuild reads doc state
        try:
            rebuild_op_store(self)
        except Exception:
            try:
                self._rebuild_slow()
            except Exception:
                # a half-built store must never serve reads: keep the view
                # stale so EVERY read raises, not just the first
                self._ops_stale = True
                raise

    # -- identity ----------------------------------------------------------

    def set_actor(self, actor: ActorId) -> None:
        self.actor = actor

    def get_actor(self) -> ActorId:
        return self.actor

    # -- object id conversion ----------------------------------------------

    def export_id(self, obj: OpId) -> str:
        if obj[0] == 0:
            return ROOT
        return f"{obj[0]}@{self.actors.get(obj[1]).to_hex()}"

    def import_id(self, exid: str) -> OpId:
        if exid == ROOT:
            return ROOT_OBJ
        hit = self._exid_cache.get(exid)
        if hit is not None:
            return hit
        try:
            ctr_s, actor_hex = exid.split("@", 1)
            ctr = int(ctr_s)
            idx = self.actors.lookup(ActorId.from_hex(actor_hex))
        except (ValueError, AttributeError) as e:
            raise AutomergeError(f"invalid object id {exid!r}") from e
        if idx is None:
            raise AutomergeError(f"object id {exid!r} references unknown actor")
        opid = (ctr, idx)
        self._exid_cache[exid] = opid
        return opid

    def import_obj(self, exid: str) -> OpId:
        obj = self.import_id(exid)
        if not self.ops.has_obj(obj):
            raise AutomergeError(f"no such object {exid!r}")
        return obj

    # -- heads / clocks ----------------------------------------------------

    def get_heads(self) -> List[bytes]:
        return sorted(self.deps)

    def clock_at(self, heads: Optional[Iterable[bytes]]) -> Optional[Clock]:
        if heads is None:
            return None
        return self.change_graph.clock_for_heads(heads)

    # -- change application ------------------------------------------------

    # Large batches skip per-op python apply: the native integrate rebuilds
    # the op store in bulk (core/bulk_load.py). Threshold balances the
    # linear rebuild of the whole history against the per-op cost of the
    # incremental path.
    BULK_MIN_OPS = 8_000

    def apply_changes(self, changes: Iterable[StoredChange]) -> None:
        changes = list(changes)
        from .. import obs

        if obs.enabled():
            obs.event(
                "apply_changes", changes=len(changes),
                ops=sum(len(c.ops) for c in changes),
            )
        if self._bulk_eligible(changes):
            try:
                self._apply_changes_bulk(changes)
                return
            except ValueError:
                # malformed batch for the native path: the incremental
                # apply below reports the precise failure
                pass
        for change in changes:
            if change.hash in self.history_index:
                continue
            if self._is_duplicate_seq(change):
                raise DuplicateSeqNumber(change.seq, change.actor.hex())
            if self._is_causally_ready(change):
                self._apply_change(change)
            else:
                self.queue.append(change)
        self._drain_queue()
        # Changes still in the queue wait for their dependencies; the
        # reference likewise holds not-yet-ready changes without erroring.

    def _bulk_eligible(self, changes: List[StoredChange]) -> bool:
        from .. import native

        new_ops = sum(
            len(c.ops) for c in changes if c.hash not in self.history_index
        )
        if new_ops < self.BULK_MIN_OPS:
            return False
        existing = sum(len(a.stored.ops) for a in self.history)
        if new_ops * 8 < existing:
            return False  # small increment on a big doc: incremental wins
        return native.available()

    def _apply_changes_bulk(self, changes: List[StoredChange]) -> None:
        """History bookkeeping per change, one native op-store rebuild.

        Same causal-queue / dup-seq semantics as the incremental path; the
        op store is marked stale and rebuilt from the full history on the
        next read (core/bulk_load.py), so per-op python apply never runs.

        Structural validation of op payloads is deferred with the rebuild:
        a malformed change accepted here raises on every subsequent read
        (fail-loud; the store is never partially served), where the per-op
        path would have raised at apply time.
        """
        ready: List[StoredChange] = []
        pending: List[StoredChange] = []
        seen_hashes = set()
        seen_seqs = set()
        for change in changes:
            if change.hash in self.history_index or change.hash in seen_hashes:
                continue
            if self._is_duplicate_seq(change) or (change.actor, change.seq) in seen_seqs:
                raise DuplicateSeqNumber(change.seq, change.actor.hex())
            seen_hashes.add(change.hash)
            seen_seqs.add((change.actor, change.seq))
            pending.append(change)
        known = set(self.history_index)
        progress = True
        while progress:
            progress = False
            still = []
            for change in pending:
                if all(d in known for d in change.dependencies):
                    ready.append(change)
                    known.add(change.hash)
                    progress = True
                else:
                    still.append(change)
            pending = still
        # also pull anything already queued whose deps are now satisfied
        queued_ready = True
        while queued_ready:
            queued_ready = False
            remaining = []
            for change in self.queue:
                if change.hash in known:
                    continue
                if all(d in known for d in change.dependencies):
                    ready.append(change)
                    known.add(change.hash)
                    queued_ready = True
                else:
                    remaining.append(change)
            self.queue = remaining
        self.queue.extend(pending)
        if not ready:
            return
        for change in ready:
            actor_map = [self.actors.cache(ActorId(a)) for a in change.actors]
            self._update_history(AppliedChange(change, actor_map[0], actor_map))
        # defer the op-store rebuild to the first read/edit (see `ops`)
        self._ops_stale = True

    def _rebuild_slow(self) -> None:
        """Correctness fallback: replay the whole history through the
        per-op apply path into a fresh store — installed only on success,
        so a mid-replay failure never leaves a partial store behind."""
        from .op_store import OpStore

        store = OpStore(self.actors)
        for applied in self.history:
            actor_map = applied.actor_map
            for obj_id, op in self._import_ops(applied.stored, actor_map):
                store.insert_op(obj_id, op)
        self.ops = store

    def _drain_queue(self) -> None:
        applied = True
        while applied:
            applied = False
            remaining = []
            for change in self.queue:
                if change.hash in self.history_index:
                    applied = True
                    continue
                if self._is_causally_ready(change):
                    self._apply_change(change)
                    applied = True
                else:
                    remaining.append(change)
            self.queue = remaining

    def _is_causally_ready(self, change: StoredChange) -> bool:
        return all(d in self.history_index for d in change.dependencies)

    def _is_duplicate_seq(self, change: StoredChange) -> bool:
        actor_idx = self.actors.lookup(ActorId(change.actor))
        if actor_idx is None:
            return False
        for hist_idx in self.states.get(actor_idx, []):
            if self.history[hist_idx].stored.seq == change.seq:
                return True
        return False

    def get_missing_deps(self, heads: Iterable[bytes]) -> List[bytes]:
        """Dependencies required before queued changes (and ``heads``) apply."""
        in_queue = {c.hash for c in self.queue}
        missing = set()
        for change in self.queue:
            for dep in change.dependencies:
                if dep not in self.history_index and dep not in in_queue:
                    missing.add(dep)
        for h in heads:
            if h not in self.history_index and h not in in_queue:
                missing.add(h)
        return sorted(missing)

    def _apply_change(self, change: StoredChange) -> None:
        actor_map = [self.actors.cache(ActorId(a)) for a in change.actors]
        applied = AppliedChange(change, actor_map[0], actor_map)
        if self._ops_stale:
            # the store is already due a full rebuild from history — fold
            # this change into it instead of materializing mid-apply
            self._update_history(applied)
            return
        ops = self._import_ops(change, actor_map)
        self._update_history(applied)
        for obj_id, op in ops:
            self.ops.insert_op(obj_id, op)

    def _import_ops(
        self, change: StoredChange, actor_map: List[int]
    ) -> List[Tuple[OpId, Op]]:
        """Translate chunk-local ChangeOps to store ops with global indices.

        Mirrors reference import_ops (automerge.rs:860-914).
        """
        out = []
        author = actor_map[0]
        for i, cop in enumerate(change.ops):
            opid = (change.start_op + i, author)
            obj = self._import_objid(cop.obj, actor_map)
            key = None
            elem = None
            if cop.key.prop is not None:
                key = self.props.cache(cop.key.prop)
            else:
                e = cop.key.elem
                elem = HEAD if e[0] == 0 else (e[0], actor_map[e[1]])
            pred = self.ops.sort_opids(
                [(p[0], actor_map[p[1]]) for p in cop.pred]
            )
            op = Op(
                id=opid,
                action=cop.action,
                value=cop.value,
                key=key,
                elem=elem,
                insert=cop.insert,
                pred=pred,
                mark_name=cop.mark_name,
                expand=cop.expand,
            )
            out.append((obj, op))
        return out

    @staticmethod
    def _import_objid(obj: OpId, actor_map: List[int]) -> OpId:
        if obj[0] == 0:
            return ROOT_OBJ
        return (obj[0], actor_map[obj[1]])

    def _update_history(self, applied: AppliedChange) -> None:
        idx = len(self.history)
        self.history.append(applied)
        self.history_index[applied.hash] = idx
        self.states.setdefault(applied.actor_idx, []).append(idx)
        self.change_graph.add_change(
            applied.hash,
            applied.actor_idx,
            applied.stored.seq,
            applied.stored.max_op,
            applied.stored.dependencies,
        )
        for dep in applied.stored.dependencies:
            self.deps.discard(dep)
        self.deps.add(applied.hash)
        self.max_op = max(self.max_op, applied.stored.max_op)
        if self.change_listeners:
            try:
                for cb in self.change_listeners:
                    cb(applied.stored)
            except Exception:
                # the change is in history but the caller's op-store
                # bookkeeping for it will never complete (the exception
                # unwinds through it): force a rebuild from history so
                # reads stay consistent with the heads we now advertise
                self._ops_stale = True
                raise

    # -- transactions ------------------------------------------------------

    def transaction(self, message=None, timestamp=None):
        """Open a manual transaction at the current heads
        (reference: automerge.rs transaction())."""
        from .transaction import Transaction

        return Transaction(self, message=message, timestamp=timestamp)

    def isolate_actor(self, heads: List[bytes]):
        """(scope clock, actor) for an isolated transaction at ``heads``.

        Walks concurrency-suffix levels until it finds an actor whose
        existing ops are all covered by the clock — pinning that actor in
        the scope then cannot leak ops from a previous isolation session
        at the same heads (reference: automerge.rs isolate_actor
        1072-1092, get_isolated_actor_index)."""
        scope = self.clock_at(heads)
        actor = self.actor
        level = 1
        while True:
            idx = self.actors.cache(actor)
            idxs = self.states.get(idx)
            max_op = self.history[idxs[-1]].stored.max_op if idxs else 0
            if max_op == 0 or scope.covers((max_op, idx)):
                return scope, actor
            actor = self.actor.with_concurrency_suffix(level)
            level += 1

    def transaction_at(self, heads: List[bytes], message=None, timestamp=None):
        """Open a manual transaction scoped to the state at ``heads``:
        reads and position resolution see only ops the heads' clock covers,
        and the transaction's actor gets a concurrency suffix so its opids
        cannot collide with edits made since (reference:
        automerge.rs:295-298 transaction_at, isolate_actor 1072-1092)."""
        from .transaction import Transaction

        scope, actor = self.isolate_actor(heads)
        tx = Transaction(
            self, message=message, timestamp=timestamp, scope=scope, actor=actor
        )
        tx.deps = list(heads)
        return tx

    # -- merge / fork ------------------------------------------------------

    def get_changes(self, have_deps: List[bytes]) -> List[StoredChange]:
        """Changes not reachable from ``have_deps``, in causal order."""
        self._check_no_pending_tx("export changes")
        known = self.change_graph.ancestor_hashes(have_deps)
        return [c.stored for c in self.history if c.hash not in known]

    def get_change_by_hash(self, h: bytes) -> Optional[StoredChange]:
        idx = self.history_index.get(h)
        return self.history[idx].stored if idx is not None else None

    def get_changes_added(self, other: "Document") -> List[StoredChange]:
        """Changes in ``other`` that this document lacks (reference:
        automerge.rs get_changes_added — DFS from other's heads)."""
        return [
            c.stored for c in other.history if c.hash not in self.history_index
        ]

    def merge(self, other: "Document") -> List[bytes]:
        other._check_no_pending_tx("merge from")  # exports other's history
        changes = self.get_changes_added(other)
        self.apply_changes(changes)
        return self.get_heads()

    def fork(self, actor: Optional[ActorId] = None) -> "Document":
        self._check_no_pending_tx("fork")
        doc = Document(actor or ActorId(), text_encoding=self.text_encoding)
        doc.apply_changes(c.stored for c in self.history)
        return doc

    def fork_at(self, heads: List[bytes], actor: Optional[ActorId] = None) -> "Document":
        self._check_no_pending_tx("fork_at")
        keep = self.change_graph.ancestor_hashes(heads)
        missing = [h for h in heads if h not in self.history_index]
        if missing:
            raise AutomergeError(f"fork_at: unknown heads {missing}")
        doc = Document(actor or ActorId(), text_encoding=self.text_encoding)
        doc.apply_changes(c.stored for c in self.history if c.hash in keep)
        return doc

    # -- reads -------------------------------------------------------------

    def object_type(self, obj: str) -> ObjType:
        return self.ops.obj_type(self.import_obj(obj))

    def _render_op(self, op: Op, clock) -> object:
        """The public value of a visible op: obj / counter / scalar tuple."""
        if is_make_action(op.action):
            return ("obj", objtype_for_action(op.action), self.export_id(op.id))
        if op.is_counter:
            return ("counter", op.counter_value_at(clock))
        return ("scalar", op.value)

    def _resolve_clock(self, heads, clock):
        return clock if clock is not None else self.clock_at(heads)

    def get(self, obj: str, prop, heads=None, clock=None):
        """Winner value at ``prop`` (a key or an index): (value, id) or None."""
        vals = self.get_all(obj, prop, heads, clock)
        return vals[-1] if vals else None

    def get_all(self, obj: str, prop, heads=None, clock=None) -> List[Tuple[object, str]]:
        """All conflicting values at ``prop``, winner last."""
        obj_id = self.import_obj(obj)
        clock = self._resolve_clock(heads, clock)
        info = self.ops.get_obj(obj_id)
        if isinstance(info.data, MapObject):
            if not isinstance(prop, str):
                raise AutomergeError("map lookup requires a string key")
            key = self.props.lookup(prop)
            if key is None:
                return []
            vis = self.ops.visible_map_ops(obj_id, key, clock)
        else:
            if not isinstance(prop, int):
                raise AutomergeError("sequence lookup requires an integer index")
            # index by the object's own encoding: character position for
            # TEXT (reference get_all_for passes obj.encoding,
            # automerge.rs:1544-1556)
            enc = TEXT_ENC if info.data.obj_type == ObjType.TEXT else LIST_ENC
            el = self.ops.nth(obj_id, prop, enc, clock)
            if el is None:
                return []
            vis = el.visible_ops(clock)
        return [(self._render_op(op, clock), self.export_id(op.id)) for op in vis]

    def keys(self, obj: str, heads=None, clock=None) -> List[str]:
        obj_id = self.import_obj(obj)
        clock = self._resolve_clock(heads, clock)
        idxs = self.ops.map_keys(obj_id, clock)
        return sorted(self.props.get(i) for i in idxs)

    def length(self, obj: str, heads=None, clock=None) -> int:
        obj_id = self.import_obj(obj)
        info = self.ops.get_obj(obj_id)
        clock = self._resolve_clock(heads, clock)
        if isinstance(info.data, MapObject):
            return len(self.ops.map_keys(obj_id, clock))
        enc = TEXT_ENC if info.data.obj_type == ObjType.TEXT else LIST_ENC
        return self.ops.seq_length(obj_id, enc, clock)

    def text(self, obj: str, heads=None, clock=None) -> str:
        clock = self._resolve_clock(heads, clock)
        if clock is None and self._ops_stale:
            # read-only consumer after a bulk apply (the sync catch-up
            # pattern): answer from history arrays without materializing
            # the op store (bulk_load.stale_text)
            t = self._stale_text(obj)
            if t is not None:
                return t
        obj_id = self.import_obj(obj)
        return self.ops.text(obj_id, clock)

    def _stale_text(self, obj: str):
        import os

        from .bulk_load import stale_text

        from .bulk_load import stale_read_state

        memo = self._stale_text_memo
        if memo is None or memo[0] != len(self.history):
            # state=False: not computed yet; None: path unavailable
            memo = self._stale_text_memo = [len(self.history), False, {}]
        cache = memo[2]
        if obj in cache:
            return cache[obj]
        if memo[1] is False:
            try:
                memo[1] = stale_read_state(self)
            except Exception:
                if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                    raise
                memo[1] = None
        t = None
        if memo[1] is not None:
            try:
                t = stale_text(self, obj, memo[1])
            except Exception:
                if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                    raise
        cache[obj] = t  # None memoized too: don't re-try per failed read
        return t

    def list_items(self, obj: str, heads=None, clock=None) -> List[Tuple[object, str]]:
        obj_id = self.import_obj(obj)
        clock = self._resolve_clock(heads, clock)
        return [
            (self._render_op(w, clock), self.export_id(w.id))
            for _, w in self.ops.visible_elements(obj_id, clock)
        ]

    def map_entries(self, obj: str, heads=None, clock=None) -> List[Tuple[str, object, str]]:
        obj_id = self.import_obj(obj)
        clock = self._resolve_clock(heads, clock)
        out = []
        for key_idx in self.ops.map_keys(obj_id, clock):
            run = self.ops.visible_map_ops(obj_id, key_idx, clock)
            if run:
                w = run[-1]
                out.append(
                    (self.props.get(key_idx), self._render_op(w, clock), self.export_id(w.id))
                )
        out.sort(key=lambda kv: kv[0])
        return out

    def map_range(
        self, obj: str, start: Optional[str] = None, end: Optional[str] = None,
        heads=None, clock=None,
    ) -> List[Tuple[str, object, str]]:
        """(key, winner value, value id) for map keys in [start, end)
        (reference: read.rs map_range/map_range_at)."""
        from ..utils.ranges import filter_map_range

        return filter_map_range(self.map_entries(obj, heads=heads, clock=clock), start, end)

    def list_range(
        self, obj: str, start: int = 0, end: Optional[int] = None,
        heads=None, clock=None,
    ) -> List[Tuple[int, object, str]]:
        """(index, winner value, value id) for indices in [start, end)
        (reference: read.rs list_range/list_range_at). Walks only the
        requested span — O(end-start + index seek), not O(list length)."""
        obj_id = self.import_obj(obj)
        clock = self._resolve_clock(heads, clock)
        out: List[Tuple[int, object, str]] = []
        idx = max(start, 0)
        for _, w in self.ops.visible_elements_range(obj_id, start, end, clock):
            out.append((idx, self._render_op(w, clock), self.export_id(w.id)))
            idx += 1
        return out

    def values(self, obj: str, heads=None, clock=None) -> List[Tuple[object, str]]:
        """Winner (value, id) pairs of an object, map or sequence
        (reference: read.rs values/values_at)."""
        info = self.ops.get_obj(self.import_obj(obj))
        if isinstance(info.data, MapObject):
            return [
                (val, vid) for _, val, vid in self.map_entries(obj, heads=heads, clock=clock)
            ]
        return self.list_items(obj, heads=heads, clock=clock)

    def parents(self, obj: str, heads=None, clock=None) -> List[Tuple[str, object]]:
        """Path from ``obj`` up to the root: [(parent id, key-or-index), ...]
        (reference: read.rs parents/parents_at — sequence indices resolve
        at the given heads)."""
        obj_id = self.import_obj(obj)
        clock = self._resolve_clock(heads, clock)
        path = []
        while obj_id != ROOT_OBJ:
            info = self.ops.get_obj(obj_id)
            parent = info.parent
            if info.parent_key is not None:
                path.append((self.export_id(parent), self.props.get(info.parent_key)))
            else:
                # resolve the element's index in the parent sequence at the
                # read clock (None when invisible there)
                idx = self._elem_index(parent, info.parent_elem, clock)
                path.append((self.export_id(parent), idx))
            obj_id = parent
        return path

    def _elem_index(self, parent: OpId, elem: OpId, clock=None) -> Optional[int]:
        if clock is None:
            # current state: O(sqrt n) via the block order-statistics index
            info = self.ops.get_obj(parent)
            el = info.data.by_id.get(elem)
            if el is None or el.winner() is None:
                return None
            return self.ops.position_of(parent, el)
        for i, (el, _) in enumerate(self.ops.visible_elements(parent, clock)):
            if el.elem_id == elem:
                return i
        return None

    # -- cursors -------------------------------------------------------------

    def get_cursor(self, obj: str, position: int, heads=None, clock=None) -> str:
        """A stable reference to the element at ``position`` — the element
        op's id, exported as "<ctr>@<actorhex>" (reference: cursor.rs)."""
        obj_id = self.import_obj(obj)
        info = self.ops.get_obj(obj_id)
        if not isinstance(info.data, SeqObject):
            raise AutomergeError("cursors only apply to sequences")
        clock = self._resolve_clock(heads, clock)
        enc = TEXT_ENC if info.data.obj_type == ObjType.TEXT else LIST_ENC
        el = self.ops.nth(obj_id, position, enc, clock)
        if el is None:
            raise AutomergeError(f"cursor position {position} out of bounds")
        return self.export_id(el.elem_id)

    def get_cursor_position(self, obj: str, cursor: str, heads=None, clock=None) -> int:
        """Current index of the element ``cursor`` refers to; if that element
        is gone, the index it would occupy (reference: automerge.rs
        seek_opid)."""
        obj_id = self.import_obj(obj)
        info = self.ops.get_obj(obj_id)
        if not isinstance(info.data, SeqObject):
            raise AutomergeError("cursors only apply to sequences")
        clock = self._resolve_clock(heads, clock)
        enc = TEXT_ENC if info.data.obj_type == ObjType.TEXT else LIST_ENC
        target = self.import_id(cursor)
        el = info.data.by_id.get(target)
        if el is None:
            raise AutomergeError(f"cursor {cursor!r} not found in {obj!r}")
        if clock is None:
            # O(blocks + block size) via the order-statistics index
            return self.ops.position_of(obj_id, el, enc)
        index = 0
        for e in info.data.elements():
            if e is el:
                return index
            w = e.winner(clock)
            if w is not None:
                index += w.text_width() if enc == TEXT_ENC else 1
        raise AutomergeError(f"cursor {cursor!r} not found in {obj!r}")

    # -- marks ---------------------------------------------------------------

    def marks(self, obj: str, heads=None, clock=None):
        """Resolved mark spans for a sequence (reference: ReadDoc::marks)."""
        from .marks import calculate_marks

        obj_id = self.import_obj(obj)
        return calculate_marks(self, obj_id, self._resolve_clock(heads, clock))

    # -- diff ----------------------------------------------------------------

    def diff(self, before_heads: List[bytes], after_heads: List[bytes]):
        """Patches transforming the state at ``before_heads`` into the state
        at ``after_heads`` (reference: automerge.rs diff via two clocks)."""
        from ..patches.diff import diff as _diff

        return _diff(self, before_heads, after_heads)

    # -- materialization ---------------------------------------------------

    def dump(self, file=None) -> None:
        """Print the full op table — id/ins/obj/key/value/pred/succ per op,
        in document order (reference: automerge.rs:1190-1239 dump())."""
        import sys

        out = file or sys.stdout

        def short(opid: OpId) -> str:
            if opid[0] == 0:
                return "_root"
            return f"{opid[0]}@{self.actors.get(opid[1]).to_hex()[:4]}"

        def render(op: Op) -> str:
            if is_make_action(op.action):
                return f"make({objtype_for_action(op.action).name.lower()})"
            if op.is_inc:
                return f"inc({op.value.value})"
            if op.is_delete:
                return "del"
            if op.is_mark:
                name = op.mark_name if op.mark_name is not None else "/"
                return f"mark({name},{op.value.to_py()!r})"
            return f"{op.value.tag}:{op.value.to_py()!r}"

        print(
            f"  {'id':12} {'ins':3} {'obj':12} {'key':12} "
            f"{'value':16} {'pred':16} {'succ':16}",
            file=out,
        )
        for obj_id in sorted(
            self.ops.objects, key=lambda o: (o[0], o[1] if o[0] else -1)
        ):
            info = self.ops.get_obj(obj_id)
            rows = []
            if isinstance(info.data, MapObject):
                for key_idx in sorted(
                    info.data.props, key=lambda k: self.props.get(k)
                ):
                    for op in info.data.props[key_idx]:
                        rows.append((self.props.get(key_idx), op))
            else:
                for el, op in info.data.ops_in_order():
                    rows.append((short(el.elem_id), op))
            for key, op in rows:
                pred = ",".join(short(p) for p in op.pred)
                succ = ",".join(short(s) for s in op.succ)
                ins = "t" if op.insert else "f"
                print(
                    f"  {short(op.id):12} {ins:3} {short(obj_id):12} "
                    f"{key:12} {render(op):16} {pred:16} {succ:16}",
                    file=out,
                )

    def convert_scalar_strings_to_text(self) -> None:
        """Replace every visible string scalar in a map or list with a TEXT
        object holding the same content — the reference's StringMigration::
        ConvertToText load option (automerge.rs:1567-1610).

        Parity quirk preserved: a key holding CONFLICTING strings converts
        each visible value in turn, so the last conversion wins and the
        conflict collapses — exactly what the reference's per-op
        ``tx.put_object`` loop does (automerge.rs:1603-1609)."""
        to_convert = []
        for obj_id, info in list(self.ops.objects.items()):
            data = info.data
            if isinstance(data, MapObject):
                if data.obj_type not in (ObjType.MAP, ObjType.TABLE):
                    continue
                for key_idx, run in data.props.items():
                    for op in run:
                        if op.visible() and op.action == Action.PUT and op.value.tag == "str":
                            to_convert.append(
                                (self.export_id(obj_id), self.props.get(key_idx), op.value.value)
                            )
            elif data.obj_type == ObjType.LIST:
                index = 0
                for el in data.elements():
                    w = el.winner()
                    if w is None:
                        continue
                    if w.action == Action.PUT and w.value.tag == "str":
                        to_convert.append((self.export_id(obj_id), index, w.value.value))
                    index += 1
        if not to_convert:
            return
        tx = self.transaction()
        for obj, prop, text in to_convert:
            text_id = tx.put_object(obj, prop, ObjType.TEXT)
            tx.splice_text(text_id, 0, 0, text)
        tx.commit()

    def hydrate(self, obj: str = ROOT, heads=None, clock=None):
        """Materialize an object tree into plain Python values."""
        obj_id = self.import_obj(obj)
        return self._hydrate(obj_id, self._resolve_clock(heads, clock))

    def _hydrate(self, obj_id: OpId, clock):
        info = self.ops.get_obj(obj_id)
        if isinstance(info.data, MapObject):
            out = {}
            for key_idx in self.ops.map_keys(obj_id, clock):
                run = self.ops.visible_map_ops(obj_id, key_idx, clock)
                if run:
                    out[self.props.get(key_idx)] = self._hydrate_op(run[-1], clock)
            return out
        if info.data.obj_type == ObjType.TEXT:
            return self.ops.text(obj_id, clock)
        return [
            self._hydrate_op(w, clock)
            for _, w in self.ops.visible_elements(obj_id, clock)
        ]

    def _hydrate_op(self, op: Op, clock):
        if is_make_action(op.action):
            return self._hydrate(op.id, clock)
        if op.is_counter:
            return op.counter_value_at(clock)
        return op.value.to_py()

    # -- save / load -------------------------------------------------------

    def save(self, deflate: bool = True, retain_orphans: bool = True) -> bytes:
        """Compact document chunk; queued (causally-unready) changes are
        appended as trailing change chunks so they survive a save/load
        cycle (reference: SaveOptions{retain_orphans}, automerge.rs:959-963)
        unless ``retain_orphans=False``."""
        from .. import obs

        self._check_no_pending_tx("save")
        with obs.span("save"):
            data = self._save_document(deflate)
        if retain_orphans:
            for orphan in self.queue:
                if orphan.raw_bytes:
                    data += orphan.raw_bytes
        return data

    def save_and_verify(self, deflate: bool = True) -> bytes:
        """Save, then load the result back before returning — slow, for
        debugging corrupt-save suspicions (reference: automerge.rs:973)."""
        data = self.save(deflate)
        Document.load(data)
        return data

    def _save_document(self, deflate: bool = True) -> bytes:
        import os

        from .. import native

        sorted_idx = self.actors.sorted_order()  # sorted position -> global idx
        remap = [0] * len(sorted_idx)  # global idx -> sorted position
        for pos, g in enumerate(sorted_idx):
            remap[g] = pos
        actors = [self.actors.get(g).bytes for g in sorted_idx]

        op_cols = None
        if native.available():
            try:
                op_cols = self._doc_op_cols_fast(remap)
            except FastSaveUnavailable:
                pass  # documented fallback (empty doc, no column bytes, ...)
            except Exception as e:
                if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                    raise
                import warnings

                warnings.warn(
                    f"array-native save failed unexpectedly ({e!r}); "
                    "falling back to the per-op encoder",
                    RuntimeWarning,
                    stacklevel=2,
                )
        doc_ops = self._doc_ops(remap) if op_cols is None else []
        changes = [
            DocChangeMeta(
                actor=remap[c.actor_idx],
                seq=c.stored.seq,
                max_op=c.stored.max_op,
                timestamp=c.stored.timestamp,
                message=c.stored.message,
                deps=sorted(self.history_index[d] for d in c.stored.dependencies),
                extra=c.stored.extra_bytes,
            )
            for c in self.history
        ]
        heads = [(h, self.history_index[h]) for h in self.get_heads()]
        return build_document(actors, heads, doc_ops, changes, deflate, op_cols=op_cols)

    def _doc_op_cols_fast(self, remap: List[int]):
        """Array-native doc-op columns straight from change history.

        The change history (not the python op store) is the source: the
        native batch decoder flattens it into Lamport-ordered columns
        (ops/oplog.py), the native preorder walk ranks element order
        (host_linearize), and the document-order permutation + succ lists
        are numpy joins — byte-identical output to the per-op
        ``_doc_ops`` + ``encode_doc_ops`` path, without materializing a
        single python op. Raises on anything unusual (slow value heap,
        unresolved refs); the caller falls back to the python path.
        """
        import numpy as np

        from ..ops.extract import LazyValues
        from ..ops.oplog import (
            ACTOR_BITS,
            ACTOR_MASK,
            ELEM_HEAD,
            ELEM_MISSING,
            OpLog,
            host_linearize,
        )
        from ..storage.document import encode_doc_ops_arrays

        log = OpLog.from_changes([a.stored for a in self.history])
        n = log.n
        if n == 0 or not isinstance(log.values, LazyValues):
            raise FastSaveUnavailable("needs a non-empty lazy value heap")
        if np.any(log.elem_ref == ELEM_MISSING):
            raise FastSaveUnavailable("unresolved element reference in history")
        ids = log.id_key
        action = log.action.astype(np.int64)
        insert = log.insert
        rank_to_save = np.asarray(
            [remap[self.actors.lookup(a)] for a in log.actors], np.int64
        )

        # document-order permutation: objects by packed id (root first),
        # map keys by string, sequence runs by element order, then Lamport
        elem_index = host_linearize(
            {
                "action": log.action,
                "insert": log.insert,
                "elem_ref": log.elem_ref,
                "obj_dense": log.obj_dense,
            }
        ).astype(np.int64)
        is_map = log.prop >= 0
        if log.props:
            order_p = sorted(range(len(log.props)), key=lambda i: log.props[i])
            str_rank = np.empty(len(log.props), np.int64)
            for r, i in enumerate(order_p):
                str_rank[i] = r
        else:
            str_rank = np.zeros(1, np.int64)
        rows_all = np.arange(n, dtype=np.int64)
        run_row = np.where(
            insert, rows_all, np.where(log.elem_ref >= 0, log.elem_ref, 0)
        )
        sec = np.where(
            is_map, str_rank[np.clip(log.prop, 0, None)], elem_index[run_row]
        )
        rows = np.flatnonzero(action != int(Action.DELETE))
        perm = rows[np.lexsort((ids[rows], sec[rows], log.obj_key[rows]))]
        m = len(perm)

        ok = log.obj_key[perm]
        kr = log.elem_ref[perm].astype(np.int64)
        seq_m = ~is_map[perm]
        head_m = seq_m & (kr == ELEM_HEAD)
        elem_m = seq_m & (kr >= 0)
        src_ids = ids[np.clip(kr, 0, n - 1)]
        lv = log.values
        code = lv.code[perm].astype(np.int64)
        ln = lv.ln[perm].astype(np.int64)
        off = lv.off[perm].astype(np.int64)
        total = int(ln.sum())
        if total:
            run_start = np.concatenate([[0], np.cumsum(ln)[:-1]])
            pos = np.repeat(off, ln) + (
                np.arange(total, dtype=np.int64) - np.repeat(run_start, ln)
            )
            val_raw = np.frombuffer(lv.raw, np.uint8)[pos].tobytes()
        else:
            val_raw = b""

        # succ lists: pred edges reversed, grouped by doc position of the
        # target, ascending source id (op_store add_succ order)
        pos_of = np.full(n, -1, np.int64)
        pos_of[perm] = np.arange(m, dtype=np.int64)
        et = log.pred_tgt.astype(np.int64)
        es = log.pred_src.astype(np.int64)
        ev = et >= 0
        if ev.any():
            tp = pos_of[et[ev]]
            if np.any(tp < 0):
                raise FastSaveUnavailable("succ targets a non-stored row")
            sid = ids[es[ev]]
            eorder = np.lexsort((sid, tp))
            sid = sid[eorder]
            succ_ctr = (sid >> ACTOR_BITS).astype(np.int64)
            succ_actor = rank_to_save[sid & ACTOR_MASK]
            succ_num = np.bincount(tp, minlength=m).astype(np.int64)
        else:
            succ_ctr = np.empty(0, np.int64)
            succ_actor = np.empty(0, np.int64)
            succ_num = np.zeros(m, np.int64)

        pid = ids[perm]
        return encode_doc_ops_arrays(
            {
                "obj_mask": (ok != 0).astype(np.uint8),
                "obj_ctr": (ok >> ACTOR_BITS).astype(np.int64),
                "obj_actor": np.where(ok != 0, rank_to_save[ok & ACTOR_MASK], 0),
                "key_str_ids": np.where(is_map[perm], log.prop[perm], -1).astype(np.int64),
                "key_str_table": log.props,
                "key_ctr": np.where(elem_m, src_ids >> ACTOR_BITS, 0).astype(np.int64),
                "key_ctr_mask": (head_m | elem_m).astype(np.uint8),
                "key_actor": np.where(elem_m, rank_to_save[src_ids & ACTOR_MASK], 0),
                "key_actor_mask": elem_m.astype(np.uint8),
                "id_ctr": (pid >> ACTOR_BITS).astype(np.int64),
                "id_actor": rank_to_save[pid & ACTOR_MASK],
                "insert": insert[perm].astype(np.uint8),
                "action": action[perm],
                "val_meta": ((ln << 4) | code).astype(np.int64),
                "val_raw": val_raw,
                "succ_num": succ_num,
                "succ_ctr": succ_ctr,
                "succ_actor": succ_actor,
                "expand": log.expand[perm].astype(np.uint8),
                "mark_ids": log.mark_name_idx[perm].astype(np.int64),
                "mark_table": log.mark_names,
            }
        )

    def _doc_ops(self, remap: List[int]) -> List[DocOp]:
        """All stored ops in document order with save-time actor indices."""

        def rid(opid: OpId) -> OpId:
            return (opid[0], remap[opid[1]])

        out: List[DocOp] = []
        objs = sorted(
            self.ops.objects.keys(),
            key=lambda o: (o[0], remap[o[1]] if o[0] else -1),
        )
        for obj_id in objs:
            info = self.ops.get_obj(obj_id)
            stored_obj = ROOT_STORED if obj_id == ROOT_OBJ else rid(obj_id)
            if isinstance(info.data, MapObject):
                for key_idx in sorted(
                    info.data.props.keys(), key=lambda k: self.props.get(k)
                ):
                    for op in info.data.props[key_idx]:
                        out.append(
                            self._doc_op(op, stored_obj, Key.map(self.props.get(key_idx)), rid)
                        )
            else:
                for el in info.data.elements():
                    first = True
                    for op in el.run():
                        if first:
                            e = op.elem
                            key = (
                                Key.seq(HEAD_STORED)
                                if e[0] == 0
                                else Key.seq(rid(e))
                            )
                            first = False
                        else:
                            key = Key.seq(rid(el.elem_id))
                        out.append(self._doc_op(op, stored_obj, key, rid))
        return out

    def _doc_op(self, op: Op, stored_obj, key, rid) -> DocOp:
        return DocOp(
            id=rid(op.id),
            obj=stored_obj,
            key=key,
            insert=op.insert,
            action=op.action,
            value=op.value,
            succ=[rid(s) for s in op.succ],
            expand=op.expand,
            mark_name=op.mark_name,
        )

    def save_incremental_after(self, heads: List[bytes]) -> bytes:
        """Concatenated change chunks for everything not covered by ``heads``."""
        self._check_no_pending_tx("save_incremental_after")
        out = bytearray()
        for c in self.get_changes(heads):
            out += c.raw_bytes
        return bytes(out)

    @classmethod
    def open(cls, path, **kw):
        """Open (or create) a crash-safe durable document at ``path``: every
        committed or absorbed change is journaled before acking, the journal
        compacts into atomic snapshots, and reopening replays snapshot +
        journal with torn-tail recovery (storage/durable.py)."""
        from ..storage.durable import DurableDocument

        return DurableDocument.open(path, doc_factory=cls, **kw)

    @classmethod
    def load(
        cls,
        data: bytes,
        actor: Optional[ActorId] = None,
        verify: bool = True,
        on_partial: str = "error",
        string_migration: str = "none",
        text_encoding: Optional[str] = None,
        on_error: Optional[str] = None,
    ) -> "Document":
        """Strict by default: any malformed chunk rejects the whole load
        (the reference's LoadOptions defaults to OnPartialLoad::Error for
        ``load``; pass on_partial="ignore" to keep the valid prefix —
        automerge.rs:41-47,601-705). ``string_migration="convert_to_text"``
        rewrites scalar strings into TEXT objects after loading
        (StringMigration, automerge.rs:1567-1610). ``text_encoding`` fixes
        the loaded document's text index unit (LoadOptions analogue of the
        reference's per-build TextValue width).

        ``on_error`` is an alias for ``on_partial`` that also admits
        ``"salvage"``: skip checksum-invalid or truncated chunks, resume at
        the next magic marker, apply every chunk that still verifies, and
        leave a ``SalvageReport`` of what was dropped on
        ``doc.salvage_report``.
        """
        from .. import obs

        if on_error is not None:
            on_partial = on_error
        doc = cls(actor, text_encoding=text_encoding)
        with obs.span("load", bytes=len(data)):
            doc.load_incremental(data, verify=verify, on_partial=on_partial)
        if string_migration == "convert_to_text":
            doc.convert_scalar_strings_to_text()
        elif string_migration != "none":
            raise ValueError(f"unknown string_migration {string_migration!r}")
        return doc

    def load_incremental(
        self,
        data: bytes,
        verify: bool = True,
        on_partial: str = "ignore",
        on_error: Optional[str] = None,
    ) -> int:
        """Apply every chunk in ``data``; returns the number applied.

        A malformed tail stops the scan: with ``on_partial="ignore"`` (the
        default, matching the reference's incremental load tolerating
        trailing garbage — automerge.rs:730-769, OnPartialLoad::Ignore
        automerge.rs:41-47) the valid prefix is kept; "error" re-raises;
        "salvage" skips corrupt spans and keeps going (see ``load``).
        """
        if on_error is not None:
            on_partial = on_error
        if on_partial == "salvage":
            return self._load_salvage(data, verify)
        if on_partial not in ("ignore", "error"):
            raise ValueError(f"unknown on_partial {on_partial!r}")
        pos = 0
        applied = 0
        while pos < len(data):
            try:
                if pos + 9 > len(data):
                    raise AutomergeError("truncated chunk header")
                if data[pos : pos + 4] != MAGIC_BYTES:
                    raise AutomergeError("invalid chunk magic bytes")
                chunk_type = data[pos + 8]
                if chunk_type == CHUNK_DOCUMENT:
                    parsed, pos = parse_document(data, pos)
                    changes = _reconstruct(parsed, verify)
                else:
                    change, pos = parse_change(data, pos)
                    changes = [change]
            except Exception:
                if on_partial == "error":
                    raise
                break
            self.apply_changes(changes)
            applied += 1
        return applied

    def _load_salvage(self, data: bytes, verify: bool) -> int:
        """Degrade-gracefully load: apply every verifiable chunk, record
        every dropped span in ``self.salvage_report``, never raise on
        corrupt input."""
        from .. import obs
        from ..storage.change import parse_change_data
        from ..storage.chunk import write_chunk
        from ..storage.document import (
            DroppedChunk,
            parse_document_chunk,
            salvage_scan,
        )

        chunks, report = salvage_scan(data)
        applied = 0
        for chunk in chunks:
            try:
                if chunk.chunk_type == CHUNK_DOCUMENT:
                    changes = _reconstruct(parse_document_chunk(chunk), verify)
                else:
                    # scan_chunks already verified framing + checksum; just
                    # rebuild canonical raw bytes (hash identity + future
                    # sync need them) and parse the body
                    raw = write_chunk(chunk.chunk_type, chunk.data)
                    changes = [parse_change_data(chunk.data, chunk.hash, raw)]
                self.apply_changes(changes)
                applied += 1
            except Exception as e:
                # framing verified but the body (or its application) did
                # not: drop this chunk too, with its real identity
                report.dropped.append(
                    DroppedChunk(
                        offset=chunk.offset,
                        end=-1,  # body-level rejection: span end not tracked
                        reason=f"chunk body rejected: {e}",
                        checksum=chunk.checksum,
                        computed_hash=chunk.hash,
                    )
                )
        report.applied_chunks = applied
        self.salvage_report = report
        obs.count("load.salvaged_chunks", n=applied)
        if report.dropped:
            obs.count("load.dropped_chunks", n=len(report.dropped))
        return applied


def _reconstruct(parsed: ParsedDocument, verify: bool) -> List[StoredChange]:
    """Fast vectorized reconstruction when the native core is present;
    per-op python path otherwise (and as the precise-error fallback)."""
    import os

    from .. import native

    from ..ops.extract import ExtractError

    if native.available():
        try:
            return reconstruct_changes_fast(parsed, verify=verify)
        except ExtractError:
            pass  # irregular input shape: the python path decides
        except AutomergeError:
            raise  # real validation failures carry over as-is
        except Exception:
            if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                raise
    return reconstruct_changes(parsed, verify=verify)


class _ReOp:
    """An op reconstructed from the document format, with rebuilt pred."""

    __slots__ = ("id", "obj", "key", "insert", "action", "value", "pred", "expand", "mark_name")

    def __init__(self, id, obj, key, insert, action, value, pred, expand, mark_name):
        self.id = id
        self.obj = obj
        self.key = key
        self.insert = insert
        self.action = action
        self.value = value
        self.pred = pred
        self.expand = expand
        self.mark_name = mark_name


def reconstruct_changes_fast(doc: ParsedDocument, verify: bool = True) -> List[StoredChange]:
    """Vectorized change reconstruction from a document chunk.

    The array mirror of ``reconstruct_changes`` (reference:
    storage/load/reconstruct_document.rs, load/change_collector.rs):
    native column decode, numpy pred-from-succ + delete synthesis +
    change assignment, array-native per-change column re-encode for head
    hashing. Raises ExtractError (or any decode error) on irregular
    input — the caller falls back to the per-op python path, which
    reports precise errors for genuinely malformed files.
    """
    import numpy as np

    from ..ops.extract import ExtractError, doc_op_arrays, validate_doc_arrays
    from ..storage.change import LazyOps, encode_change_cols_arrays

    a = getattr(doc, "op_arrays", None)
    if a is None:
        a = doc_op_arrays(doc.op_col_data or {})
        validate_doc_arrays(a, len(doc.actors))
    n = a["n"]
    n_actors = len(doc.actors)
    from ..types import ACTOR_BITS as B
    if n_actors >= (1 << B):
        raise ExtractError("too many actors for the packed fast path")

    rid = (a["id_ctr"] << B) | a["id_actor"]
    okey = np.where(a["obj_mask"], (a["obj_ctr"] << B) | a["obj_actor"], 0)

    # object segments (doc ops are object-grouped, objects ascending)
    if n:
        bnd = np.concatenate([[True], okey[1:] != okey[:-1]])
        seg_first = np.flatnonzero(bnd)
        seg_keys = okey[seg_first]
        if len(seg_keys) > 1 and np.any(np.diff(seg_keys) <= 0):
            raise AutomergeError("document ops out of object order")
        seg = (np.cumsum(bnd) - 1).astype(np.int64)
        n_segs = len(seg_first)
    else:
        seg = np.zeros(0, np.int64)
        seg_keys = np.zeros(0, np.int64)
        n_segs = 0

    # succ edges -> stored targets or synthesized deletes
    er = np.repeat(np.arange(n, dtype=np.int64), a["succ_num"])
    eid = (a["succ_ctr"] << B) | a["succ_actor"]
    eseg = seg[er] if len(er) else np.zeros(0, np.int64)
    order = np.lexsort((rid, seg)) if n else np.zeros(0, np.int64)
    srid = rid[order] if n else rid
    sseg = seg[order] if n else seg
    seg_start = np.searchsorted(sseg, np.arange(n_segs))
    seg_end = np.searchsorted(sseg, np.arange(n_segs), side="right")
    etgt = np.full(len(er), -1, np.int64)
    if len(er):
        # eseg is non-decreasing (er ascending, seg non-decreasing): each
        # segment's edges are one contiguous slice — O(E log) total
        e_lo = np.searchsorted(eseg, np.arange(n_segs))
        e_hi = np.searchsorted(eseg, np.arange(n_segs), side="right")
        for s in range(n_segs):
            lo, hi = int(e_lo[s]), int(e_hi[s])
            if lo == hi:
                continue
            idxs = np.arange(lo, hi)
            s0, s1 = int(seg_start[s]), int(seg_end[s])
            block = srid[s0:s1]
            p = np.searchsorted(block, eid[idxs])
            pc = np.clip(p, 0, max(len(block) - 1, 0))
            hit = (p < len(block)) & (block[pc] == eid[idxs]) if len(block) else np.zeros(len(idxs), bool)
            etgt[idxs[hit]] = order[s0 + p[hit]]

    # synthesized delete ops: one per unique dangling (segment, succ id)
    miss = np.flatnonzero(etgt < 0)
    if len(miss):
        dkey = np.stack([eseg[miss], eid[miss]], axis=1)
        uniq, inv = np.unique(dkey, axis=0, return_inverse=True)
        d = len(uniq)
        del_seg = uniq[:, 0]
        del_id = uniq[:, 1]
        # the min-id pred source carries the key the delete targets
        src_id_miss = rid[er[miss]]
        min_src_row = np.full(d, -1, np.int64)
        ordm = np.lexsort((src_id_miss, inv))
        first = np.concatenate([[True], inv[ordm][1:] != inv[ordm][:-1]])
        min_src_row[inv[ordm][first]] = er[miss][ordm][first]
        src_act = a["action"][min_src_row]
        if not np.all(np.isin(src_act, (0, 1, 2, 4, 6))):
            raise AutomergeError("no set op found for delete")
    else:
        d = 0
        del_seg = np.zeros(0, np.int64)
        del_id = np.zeros(0, np.int64)
        min_src_row = np.zeros(0, np.int64)
        inv = np.zeros(0, np.int64)

    # combined op table: stored rows [0, n) + deletes [n, n + d)
    N = n + d
    c_id = np.concatenate([rid, del_id])
    c_obj = np.concatenate([okey, seg_keys[del_seg] if d else np.zeros(0, np.int64)])
    c_action = np.concatenate([a["action"], np.full(d, int(Action.DELETE), np.int64)])
    c_insert = np.concatenate([a["insert"], np.zeros(d, np.uint8)])
    c_expand = np.concatenate([a["expand"], np.zeros(d, np.uint8)])
    c_mark = np.concatenate([a["mark_ids"], np.full(d, -1, np.int32)])
    # delete keys inherit the min source's key (set_keys in the python path):
    # its map key id, or its element (own id when insert, else its key elem)
    ms = min_src_row
    d_key_ids = a["key_ids"][ms] if d else np.zeros(0, np.int32)
    ms_ins = a["insert"][ms].astype(bool) if d else np.zeros(0, bool)
    d_elem_from_key = (a["key_ctr"][ms] << B) | a["key_actor"][ms] if d else np.zeros(0, np.int64)
    d_elem_head = ~ms_ins & (a["key_ctr"][ms] == 0) & ~a["key_actor_mask"][ms] if d else np.zeros(0, bool)
    d_elem = np.where(ms_ins, rid[ms] if d else 0, d_elem_from_key) if d else np.zeros(0, np.int64)
    d_seqkey = d_key_ids < 0
    c_key_ids = np.concatenate([a["key_ids"], d_key_ids])
    # element key per combined op: ctr/actor/masks
    s_head = a["key_ctr_mask"] & (a["key_ctr"] == 0) & ~a["key_actor_mask"]
    s_elem_m = a["key_ctr_mask"] & a["key_actor_mask"]
    bad_key = (a["key_ids"] < 0) & ~s_head & ~s_elem_m
    if bad_key.any():
        raise AutomergeError("neither map key nor elem id present")
    c_key_ctr = np.concatenate([
        np.where(s_head, 0, a["key_ctr"]),
        np.where(d_seqkey & ~d_elem_head, d_elem >> B, 0),
    ])
    c_key_ctr_m = np.concatenate([
        (s_head | s_elem_m).astype(np.uint8),
        (d_seqkey).astype(np.uint8),
    ])
    c_key_actor = np.concatenate([
        np.where(s_elem_m, a["key_actor"], 0),
        np.where(d_seqkey & ~d_elem_head, d_elem & ((1 << B) - 1), 0),
    ])
    c_key_actor_m = np.concatenate([
        s_elem_m.astype(np.uint8),
        (d_seqkey & ~d_elem_head).astype(np.uint8),
    ])
    c_vlen = np.concatenate([a["vlen"], np.zeros(d, np.int64)])
    c_voff = np.concatenate([a["voff"], np.zeros(d, np.int64)])
    c_vcode = np.concatenate([a["vcode"].astype(np.int64), np.zeros(d, np.int64)])

    # pred lists: every succ edge reversed; per combined op, ascending src id
    if len(er):
        e_tgt_all = np.where(etgt >= 0, etgt, n + inv_full(miss, inv, len(er)))
    else:
        e_tgt_all = np.zeros(0, np.int64)
    e_src_id = rid[er] if len(er) else np.zeros(0, np.int64)
    eo = np.lexsort((e_src_id, e_tgt_all)) if len(er) else np.zeros(0, np.int64)
    pred_tgt_sorted = e_tgt_all[eo]
    pred_src_sorted = e_src_id[eo]
    pred_num_c = np.bincount(e_tgt_all, minlength=N).astype(np.int64) if len(er) else np.zeros(N, np.int64)
    pred_off_c = np.concatenate([[0], np.cumsum(pred_num_c)]).astype(np.int64)

    # change assignment: per actor, first change with max_op >= op counter
    metas = doc.changes
    by_actor: Dict[int, List[int]] = {}
    for i, ch in enumerate(metas):
        by_actor.setdefault(ch.actor, []).append(i)
    for lst in by_actor.values():
        prev = -1
        for i in lst:
            if metas[i].max_op < prev:
                raise AutomergeError("document changes out of order")
            prev = metas[i].max_op
    c_actor = (c_id & ((1 << B) - 1)).astype(np.int64)
    c_ctr = (c_id >> B).astype(np.int64)
    change_of = np.full(N, -1, np.int64)
    for act in np.unique(c_actor) if N else []:
        lst = by_actor.get(int(act))
        rows_a = np.flatnonzero(c_actor == act)
        if not lst:
            raise AutomergeError(f"op has no owning change (actor {act})")
        maxops = np.asarray([metas[i].max_op for i in lst], np.int64)
        pos = np.searchsorted(maxops, c_ctr[rows_a], side="left")
        if np.any(pos == len(lst)):
            raise AutomergeError("op beyond last change of its actor")
        change_of[rows_a] = np.asarray(lst, np.int64)[pos]

    # per-change chunk build (ops ascending by id within a change)
    actor_bytes = doc.actors
    rawbuf = np.frombuffer(a["vraw"], np.uint8) if len(a["vraw"]) else np.zeros(0, np.uint8)
    changes_out: List[StoredChange] = []
    hash_by_index: Dict[int, bytes] = {}
    derived_heads: Set[bytes] = set()
    order_c = np.lexsort((c_id, change_of)) if N else np.zeros(0, np.int64)
    co_sorted = change_of[order_c] if N else change_of
    starts = np.searchsorted(co_sorted, np.arange(len(metas)))
    ends = np.searchsorted(co_sorted, np.arange(len(metas)), side="right")
    for idx, meta in enumerate(metas):
        rows_c = order_c[int(starts[idx]) : int(ends[idx])]
        num_ops = len(rows_c)
        if num_ops > meta.max_op:
            raise AutomergeError("incorrect max_op in document change")
        start_op = meta.max_op - num_ops + 1
        if start_op < 1:
            raise AutomergeError("change start_op underflow")
        author = meta.actor
        # ragged pred slice for these ops
        pn = pred_num_c[rows_c]
        tp = int(pn.sum())
        if tp:
            rs = np.concatenate([[0], np.cumsum(pn)[:-1]])
            pidx = np.repeat(pred_off_c[rows_c], pn) + (
                np.arange(tp, dtype=np.int64) - np.repeat(rs, pn)
            )
            p_ids = pred_src_sorted[pidx]
        else:
            p_ids = np.zeros(0, np.int64)
        # chunk-local actor table: author first, referenced sorted by bytes
        refs = set()
        ob = c_obj[rows_c]
        refs.update((ob[ob != 0] & ((1 << B) - 1)).tolist())
        kam = c_key_actor_m[rows_c].astype(bool)
        refs.update(c_key_actor[rows_c][kam].tolist())
        refs.update((p_ids & ((1 << B) - 1)).tolist())
        refs.discard(author)
        other = sorted(refs, key=lambda g: actor_bytes[g])
        lut = np.full(n_actors, -1, np.int64)
        lut[author] = 0
        for j, g in enumerate(other):
            lut[g] = j + 1
        # value raw gather
        vl = c_vlen[rows_c]
        tv = int(vl.sum())
        if tv:
            rs2 = np.concatenate([[0], np.cumsum(vl)[:-1]])
            vpos = np.repeat(c_voff[rows_c], vl) + (
                np.arange(tv, dtype=np.int64) - np.repeat(rs2, vl)
            )
            val_raw = rawbuf[vpos].tobytes()
        else:
            val_raw = b""
        cols = encode_change_cols_arrays(
            {
                "obj_mask": (ob != 0).astype(np.uint8),
                "obj_ctr": (ob >> B).astype(np.int64),
                "obj_actor": np.where(ob != 0, lut[ob & ((1 << B) - 1)], 0),
                "key_str_ids": c_key_ids[rows_c],
                "key_str_table": a["key_table"],
                "key_ctr": c_key_ctr[rows_c],
                "key_ctr_mask": c_key_ctr_m[rows_c],
                "key_actor": np.where(kam, lut[c_key_actor[rows_c]], 0),
                "key_actor_mask": c_key_actor_m[rows_c],
                "insert": c_insert[rows_c],
                "action": c_action[rows_c],
                "val_meta": ((vl << 4) | c_vcode[rows_c]).astype(np.int64),
                "val_raw": val_raw,
                "pred_num": pn.astype(np.int64),
                "pred_ctr": (p_ids >> B).astype(np.int64),
                "pred_actor": lut[p_ids & ((1 << B) - 1)],
                "expand": c_expand[rows_c],
                "mark_ids": c_mark[rows_c],
                "mark_table": a["mark_table"],
            }
        )
        deps = []
        for dd in meta.deps:
            if dd not in hash_by_index:
                raise AutomergeError(f"change {idx} depends on later change {dd}")
            deps.append(hash_by_index[dd])
        stored = StoredChange(
            dependencies=deps,
            actor=actor_bytes[author],
            other_actors=[actor_bytes[g] for g in other],
            seq=meta.seq,
            start_op=start_op,
            timestamp=meta.timestamp,
            message=meta.message,
            ops=LazyOps({}, num_ops),
            extra_bytes=meta.extra,
        )
        change = build_change(stored, cols=cols)
        change.ops = LazyOps(change.op_col_data, num_ops)
        hash_by_index[idx] = change.hash
        for dd in deps:
            derived_heads.discard(dd)
        derived_heads.add(change.hash)
        changes_out.append(change)

    if verify and derived_heads != set(doc.heads):
        raise AutomergeError(
            "mismatching heads: derived "
            f"{sorted(h.hex()[:8] for h in derived_heads)} vs stored "
            f"{sorted(h.hex()[:8] for h in doc.heads)}"
        )
    return changes_out


def inv_full(miss_idx, inv, n_edges):
    """Scatter the unique-delete inverse back onto the full edge array."""
    import numpy as np

    out = np.zeros(n_edges, np.int64)
    out[miss_idx] = inv
    return out


def reconstruct_changes(doc: ParsedDocument, verify: bool = True) -> List[StoredChange]:
    """Rebuild the change chunks encoded in a document chunk.

    Mirrors the reference's reconstruction (reference:
    storage/load/reconstruct_document.rs, load/change_collector.rs):
    rebuild ``pred`` from ``succ``, synthesize delete ops for dangling
    succ entries, regroup ops into per-actor changes by op-counter range,
    re-encode each change, and verify derived head hashes.

    Actor indices in the document are positions in the *sorted* actor table,
    so (counter, index) order equals Lamport order throughout.
    """
    # Changes per actor, ordered by max_op, for counter-range assignment.
    by_actor: Dict[int, List[int]] = {}
    for i, ch in enumerate(doc.changes):
        by_actor.setdefault(ch.actor, []).append(i)
    for lst in by_actor.values():
        prev = -1
        for i in lst:
            if doc.changes[i].max_op < prev:
                raise AutomergeError("document changes out of order")
            prev = doc.changes[i].max_op

    per_change_ops: Dict[int, List[_ReOp]] = {}

    def assign(op: _ReOp) -> None:
        actor_changes = by_actor.get(op.id[1])
        if not actor_changes:
            raise AutomergeError(f"op {op.id} has no owning change")
        lo, hi = 0, len(actor_changes)
        while lo < hi:
            mid = (lo + hi) // 2
            if doc.changes[actor_changes[mid]].max_op < op.id[0]:
                lo = mid + 1
            else:
                hi = mid
        if lo == len(actor_changes):
            raise AutomergeError(f"op {op.id} beyond last change of its actor")
        per_change_ops.setdefault(actor_changes[lo], []).append(op)

    # Walk ops object by object (doc ops are object-grouped), rebuilding
    # pred from succ and synthesizing deletes from dangling succ entries.
    current_obj = None
    preds: Dict[OpId, List[OpId]] = {}
    set_keys: Dict[OpId, Key] = {}
    rows: List[DocOp] = []

    def flush_object() -> None:
        nonlocal preds, set_keys, rows
        if not rows and not preds:
            return
        row_ids = {r.id for r in rows}
        obj = rows[0].obj if rows else ROOT_STORED
        for row in rows:
            assign(
                _ReOp(
                    id=row.id,
                    obj=row.obj,
                    key=row.key,
                    insert=row.insert,
                    action=row.action,
                    value=row.value,
                    pred=sorted(preds.get(row.id, [])),
                    expand=row.expand,
                    mark_name=row.mark_name,
                )
            )
        for opid in sorted(preds.keys()):
            if opid in row_ids:
                continue
            plist = preds[opid]
            key = set_keys.get(plist[0])
            if key is None:
                raise AutomergeError(f"no set op found for delete {opid}")
            assign(
                _ReOp(
                    id=opid,
                    obj=obj,
                    key=key,
                    insert=False,
                    action=int(Action.DELETE),
                    value=ScalarValue.null(),
                    pred=sorted(plist),
                    expand=False,
                    mark_name=None,
                )
            )
        preds, set_keys, rows = {}, {}, []

    last_obj_sort = None
    for row in doc.ops:
        if row.obj != current_obj:
            flush_object()
            current_obj = row.obj
            sort_key = (row.obj[0], row.obj[1]) if row.obj != ROOT_STORED else (-1, -1)
            if last_obj_sort is not None and sort_key < last_obj_sort:
                raise AutomergeError("document ops out of object order")
            last_obj_sort = sort_key
        rows.append(row)
        if row.action in (0, 1, 2, 4, 6):  # put or make: remembers the key
            if row.key.prop is not None:
                set_keys[row.id] = row.key
            else:
                elem = row.id if row.insert else row.key.elem
                set_keys[row.id] = Key.seq(elem)
        for s in row.succ:
            preds.setdefault(s, []).append(row.id)
    flush_object()

    # Build each change chunk: ops sorted by op id, chunk-local actor table.
    changes: List[StoredChange] = []
    hash_by_index: Dict[int, bytes] = {}
    derived_heads: Set[bytes] = set()
    for idx, meta in enumerate(doc.changes):
        ops = sorted(per_change_ops.get(idx, []), key=lambda o: o.id)
        num_ops = len(ops)
        if num_ops > meta.max_op:
            raise AutomergeError("incorrect max_op in document change")
        start_op = meta.max_op - num_ops + 1
        if start_op < 1:
            raise AutomergeError("change start_op underflow")
        author = meta.actor
        change_ops, other, _ = chunk_local_ops(
            ops, author, lambda g: doc.actors[g]
        )
        deps = []
        for d in meta.deps:
            if d not in hash_by_index:
                raise AutomergeError(f"change {idx} depends on later change {d}")
            deps.append(hash_by_index[d])
        change = build_change(
            StoredChange(
                dependencies=deps,
                actor=doc.actors[author],
                other_actors=[doc.actors[g] for g in other],
                seq=meta.seq,
                start_op=start_op,
                timestamp=meta.timestamp,
                message=meta.message,
                ops=change_ops,
                extra_bytes=meta.extra,
            )
        )
        hash_by_index[idx] = change.hash
        for d in deps:
            derived_heads.discard(d)
        derived_heads.add(change.hash)
        changes.append(change)

    if verify and derived_heads != set(doc.heads):
        raise AutomergeError(
            "mismatching heads: derived "
            f"{sorted(h.hex()[:8] for h in derived_heads)} vs stored "
            f"{sorted(h.hex()[:8] for h in doc.heads)}"
        )
    return changes



# -- per-document text-encoding activation ------------------------------------
#
# Every width-sensitive Document entry point runs under the document's text
# encoding (reference: the per-build TextValue width, text_value.rs:5-15).
# Wrapping here — one explicit list — rather than per-def decorators keeps
# the hot paths branch-free for the default case (text_encoding=None skips
# the context entirely) and makes the covered surface auditable at a glance.
# Width math also happens inside Transaction methods; core/transaction.py
# wraps those the same way.


def _width_ctx(fn):
    import functools

    from ..types import using_text_encoding

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        enc = self.text_encoding
        if enc is None:
            return fn(self, *args, **kwargs)
        with using_text_encoding(enc):
            return fn(self, *args, **kwargs)

    return wrapped


for _name in (
    "apply_changes",
    "_materialize_ops",
    "merge",
    "length",
    "text",
    "_stale_text",
    "get",
    "get_all",
    "keys",
    "list_items",
    "map_entries",
    "values",
    "parents",
    "get_cursor",
    "get_cursor_position",
    "marks",
    "diff",
    "hydrate",
    "dump",
    "convert_scalar_strings_to_text",
    "load_incremental",
):
    setattr(Document, _name, _width_ctx(getattr(Document, _name)))
del _name
