"""Transactions: local op creation, commit/rollback, autocommit.

Semantics mirror the reference's transaction layer (reference:
rust/automerge/src/transaction/inner.rs, autocommit.rs): ops apply to the op
store immediately as they are created (local reads see them), commit encodes
a columnar change chunk and updates history, rollback removes ops in reverse
and un-succs their predecessors. ``scope`` (a Clock) gives isolated
transactions at historical heads with an actor suffix to avoid opid
collisions (reference: automerge.rs isolate_actor).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..storage.change import (
    ChangeOp,
    HEAD_STORED,
    LazyOps,
    ROOT_STORED,
    StoredChange,
    build_change,
    chunk_local_ops,
    encode_map_tail_cols,
    encode_ops_with_tail,
)
from ..types import (
    Action,
    ActorId,
    HEAD,
    Key,
    ObjType,
    OpId,
    ScalarValue,
    action_for_objtype,
)
from .clock import Clock
from .document import AppliedChange, AutomergeError, Document, ROOT
from ..errors import InvalidOp, MissingCounter
from .op_store import LIST_ENC, TEXT_ENC, MapObject, Op, ROOT_OBJ, SeqObject


class Transaction:
    """A manual transaction over a Document."""

    def __init__(
        self,
        doc: Document,
        message: Optional[str] = None,
        timestamp: Optional[int] = None,
        scope: Optional[Clock] = None,
        actor: Optional[ActorId] = None,
    ):
        # the reference prevents two live transactions statically via the
        # &mut borrow on Automerge (manual_transaction.rs); here the check
        # is dynamic: a second concurrent transaction would mint colliding
        # op ids from the same doc.max_op and produce a document that
        # fails to reload ("incorrect max_op in document change").
        if doc._live_transaction() is not None:
            raise AutomergeError(
                "a transaction is already open on this document; "
                "commit or roll it back first"
            )
        self.doc = doc
        self.message = message
        self.timestamp = timestamp
        actor = actor or doc.actor
        self.actor_idx = doc.actors.cache(actor)
        self.seq = len(doc.states.get(self.actor_idx, ())) + 1
        self.start_op = doc.max_op + 1
        self.deps = doc.get_heads()
        self.scope = scope
        if scope is not None:
            scope.isolate(self.actor_idx)
        self.operations: List[Tuple[OpId, Op]] = []
        self._done = False
        # native text-edit sessions (native/session.cpp): obj_id -> session.
        # Enabled by AutoDoc; splice_text routes through C++ and ops are
        # exported in bulk at commit (or drained to the python path when a
        # non-splice access touches the document mid-transaction).
        self.enable_sessions = False
        self._sessions: Dict[OpId, object] = {}
        # native map-put sessions (native/map_session.cpp): obj_id -> session.
        # Same lifecycle as text sessions; per-op puts route through the
        # fastcall map_put entry (api.AutoDoc.put cache).
        self._msessions: Dict[OpId, object] = {}
        self._session_ops = 0
        self._had_session_ops = False
        doc.open_transactions.add(self)

    def __del__(self):
        # an abandoned transaction rolls back, like the reference's
        # `impl Drop for Transaction` (manual_transaction.rs): its ops were
        # applied to the op store eagerly and must not outlive it.
        # ONLY when nothing was committed since this transaction opened —
        # rolling back underneath later commits (which Rust's &mut borrow
        # rules out statically) would tear out ops they built on.
        if not getattr(self, "_done", True):
            try:
                if self.doc.max_op == self.start_op - 1:
                    self.rollback()
                else:
                    # can't surgically remove our ops from under later
                    # commits — mark the materialized view stale instead so
                    # the next read rebuilds the store from history, which
                    # erases the uncommitted ops (history is the source of
                    # truth; see Document._materialize_ops).
                    self._done = True
                    self.doc.open_transactions.discard(self)
                    for ent in self._sessions.values():
                        ent[0].close()
                    self._sessions.clear()
                    for ent in self._msessions.values():
                        ent[0].close()
                    self._msessions.clear()
                    self.doc._ops_stale = True
            except Exception:
                pass

    # -- helpers -----------------------------------------------------------

    def _next_id(self) -> OpId:
        return (
            self.start_op + len(self.operations) + self._session_ops,
            self.actor_idx,
        )

    def _check_open(self) -> None:
        if self._done:
            raise AutomergeError("transaction already committed or rolled back")

    def _apply(self, obj_id: OpId, op: Op) -> None:
        self.doc.ops.insert_op(obj_id, op)
        self.operations.append((obj_id, op))

    def _obj(self, obj: str) -> OpId:
        return self.doc.import_obj(obj)

    def _pred_for_map(self, obj_id: OpId, key_idx: int) -> List[OpId]:
        ops = self.doc.ops.visible_map_ops(obj_id, key_idx, self.scope)
        return self.doc.ops.sort_opids([o.id for o in ops])

    def _pred_for_elem(self, el) -> List[OpId]:
        return self.doc.ops.sort_opids(
            [o.id for o in el.visible_ops(self.scope)]
        )

    # -- native edit sessions ----------------------------------------------

    from ..types import ACTOR_BITS as _ID_RANK_BITS  # ctr << bits | doc actor idx

    def _session_for(self, obj_id: OpId, info):
        """Existing or newly-eligible native session for ``obj_id``.

        Eligible: sessions enabled (AutoDoc transactions), current-state
        scope, native core present, TEXT object with no marks and no
        conflicted (multi-winner) elements, all actor indices < 2^20."""
        ent = self._sessions.get(obj_id)
        if ent is not None:
            return ent[0]
        if not self.enable_sessions or self.scope is not None:
            return None
        from .. import native

        lib = native.load()
        if lib is None or not hasattr(lib, "am_edit_create"):
            return None
        if self.actor_idx >= (1 << self._ID_RANK_BITS):
            return None
        data = info.data
        if any(b.marks for b in data.blocks):
            return None
        import numpy as np

        bits = self._ID_RANK_BITS
        lim = 1 << bits
        elem_ids: List[int] = []
        winner_ids: List[int] = []
        widths: List[int] = []
        for el in data.elements():
            vis = el.visible_ops(None)
            if not vis:
                continue
            if len(vis) > 1:
                return None  # conflicted element: python path handles preds
            w = vis[0]
            if el.op.id[1] >= lim or w.id[1] >= lim:
                return None
            elem_ids.append((el.op.id[0] << bits) | el.op.id[1])
            winner_ids.append((w.id[0] << bits) | w.id[1])
            widths.append(w.text_width())
        sess = native.EditSession(self.actor_idx)
        sess.init(
            np.asarray(elem_ids, np.int64),
            np.asarray(winner_ids, np.int64),
            np.asarray(widths, np.int32),
        )
        self._sessions[obj_id] = [sess, 0]  # [session, drained watermark]
        return sess

    def fast_splice_fn(self, obj: str):
        """A minimal per-splice closure for the typing hot path, or None.

        Collapses the AutoDoc -> Transaction -> EditSession -> ctypes chain
        (4+ Python frames and a ~1us libffi call per edit) into one closure
        frame and one METH_FASTCALL C call. The closure returns False when
        it can no longer serve (session drained/closed) so the caller falls
        back to the general path and drops its cache. Raises the same typed
        error as splice_text on out-of-bounds."""
        from .. import native

        fc = native.fastcall()
        if fc is None:
            return None
        obj_id = self.doc.import_id(obj)
        ent = self._sessions.get(obj_id)
        if ent is None:
            return None
        sess = ent[0]
        if not sess._h:
            return None
        h = sess._h
        fsplice = fc.splice
        from ..types import get_text_encoding

        enc = {"unicode": 0, "utf8": 1, "utf16": 2}[get_text_encoding()]
        splice_err = native._splice_error
        start = self.start_op

        def fast(pos: int, ndel: int, text: str) -> bool:
            if sess._h is None or self._done:
                return False
            n = fsplice(
                h,
                start + len(self.operations) + self._session_ops,
                pos, ndel, text, enc,
            )
            if n < 0:
                raise splice_err(n)
            self._session_ops += n
            return True

        return fast

    def map_session_for(self, obj_id: OpId):
        """Existing or newly-eligible native map session for ``obj_id``
        (None when ineligible: non-map object, a conflicted key, wide
        actor ranks, or no native library)."""
        from .. import native

        ent = self._msessions.get(obj_id)
        if ent is not None:
            return ent[0]
        lib = native.load()
        if lib is None or not hasattr(lib, "am_map_create"):
            return None
        info = self.doc.ops.get_obj(obj_id)
        if not isinstance(info.data, MapObject):
            return None
        import numpy as np

        bits = self._ID_RANK_BITS
        lim = 1 << bits
        props = self.doc.props
        keys: List[str] = []
        winners: List[int] = []
        for key_idx, run in info.data.props.items():
            vis = [o for o in run if o.visible_at(None)]
            if not vis:
                continue
            if len(vis) > 1:
                return None  # conflicted key: python path handles preds
            w = vis[0]
            if w.id[1] >= lim:
                return None
            keys.append(props.get(key_idx))
            winners.append((w.id[0] << bits) | w.id[1])
        sess = native.MapSession(self.actor_idx)
        sess.init(keys, np.asarray(winners, np.int64))
        self._msessions[obj_id] = [sess, 0]  # [session, drained watermark]
        return sess

    def fast_put_fn(self, obj: str):
        """A minimal per-put closure for the map hot path, or None.

        The map analogue of fast_splice_fn: collapses AutoDoc -> Transaction
        -> MapSession into one closure frame and one METH_FASTCALL C call
        that dispatches the value type, encodes the column payload, and
        resolves pred (the key's current winner) natively. Returns an int:
        1 = handled, 0 = session gone (caller may rebuild after the generic
        path), -1 = key/value not session-eligible (caller must stop
        rebuilding for this transaction or every ineligible value would pay
        an O(keys) session preload)."""
        from .. import native

        fc = native.fastcall()
        if fc is None or not hasattr(fc, "map_put"):
            return None
        if not self.enable_sessions or self.scope is not None or self._done:
            return None
        if self.actor_idx >= (1 << self._ID_RANK_BITS):
            return None
        sess = self.map_session_for(self._obj(obj))
        if sess is None or not sess._h:
            return None
        h = sess._h
        fput = fc.map_put
        start = self.start_op

        def fast(key, value) -> int:
            if sess._h is None or self._done:
                return 0
            n = fput(
                h,
                start + len(self.operations) + self._session_ops,
                key, value,
            )
            if n < 0:
                return -1
            self._session_ops += n
            return 1

        return fast

    def _drain_all(self, drop: bool = False) -> None:
        """Materialize pending (undrained) session ops through the python
        per-op path (id order), so the op store reflects them.

        With ``drop=False`` (reads) the session stays live — its element
        state and the store now agree, and the drained watermark prevents
        re-materialization; ``drop=True`` (python mutations, which could
        invalidate session state) closes sessions entirely."""
        if not self._sessions and not self._msessions:
            return
        bits = self._ID_RANK_BITS
        mask = (1 << bits) - 1
        rows = []  # (id_int, is_map, obj_id, export dict, row index)
        for obj_id, ent in list(self._sessions.items()):
            e = ent[0].export(ent[1])
            ent[1] += len(e["id"])
            if drop:
                ent[0].close()
                del self._sessions[obj_id]
            for k in range(len(e["id"])):
                rows.append((int(e["id"][k]), False, obj_id, e, k))
        for obj_id, ent in list(self._msessions.items()):
            e = ent[0].export(ent[1])
            ent[1] += len(e["id"])
            if drop:
                ent[0].close()
                del self._msessions[obj_id]
            # per-row payload offsets: prefix-sum of the vmeta byte lengths
            offs = [0]
            for vm in e["vmeta"]:
                offs.append(offs[-1] + (int(vm) >> 4))
            e["raw_off"] = offs
            for k in range(len(e["id"])):
                rows.append((int(e["id"][k]), True, obj_id, e, k))
        self._session_ops = 0
        rows.sort(key=lambda r: r[0])
        for id_int, is_map, obj_id, e, k in rows:
            opid = (id_int >> bits, id_int & mask)
            if is_map:
                key_idx = self.doc.props.cache(e["keys"][int(e["key_idx"][k])])
                vm = int(e["vmeta"][k])
                off = e["raw_off"][k]
                p = int(e["pred"][k])
                op = Op(
                    id=opid,
                    action=Action.PUT,
                    value=_scalar_from_vmeta(vm, e["raw"][off:off + (vm >> 4)]),
                    key=key_idx,
                    pred=[] if p == 0 else [(p >> bits, p & mask)],
                )
            elif e["is_del"][k]:
                ref = int(e["elem_ref"][k])
                p = int(e["pred"][k])
                op = Op(
                    id=opid,
                    action=Action.DELETE,
                    value=ScalarValue.null(),
                    elem=HEAD if ref == 0 else (ref >> bits, ref & mask),
                    pred=[(p >> bits, p & mask)],
                )
            else:
                ref = int(e["elem_ref"][k])
                op = Op(
                    id=opid,
                    action=Action.PUT,
                    value=ScalarValue("str", chr(int(e["cp"][k]))),
                    elem=HEAD if ref == 0 else (ref >> bits, ref & mask),
                    insert=True,
                )
            self.doc.ops.insert_op(obj_id, op)
            self.operations.append((obj_id, op))

    def session_length(self, obj_id: OpId) -> Optional[int]:
        """Width of a session-held object without draining (AutoDoc's
        length fast path); None when no session holds it."""
        ent = self._sessions.get(obj_id)
        return None if ent is None else ent[0].length()

    def _export_change_session(self, obj_id: OpId, ent) -> StoredChange:
        """Array-native commit: encode the session's undrained tail straight
        into change columns (storage/change.encode_ops_with_tail) without
        materializing per-op python objects. Already-drained session ops sit
        in ``operations`` (lower ids), encoded as prefix rows."""
        import numpy as np

        doc = self.doc
        author = self.actor_idx
        bits = self._ID_RANK_BITS
        mask = (1 << bits) - 1
        e = ent[0].export(ent[1])
        for s2 in self._sessions.values():
            s2[0].close()
        self._sessions.clear()
        for s2 in self._msessions.values():
            s2[0].close()
        self._msessions.clear()
        self._had_session_ops = True

        refs = e["elem_ref"]
        preds = e["pred"]
        extra = set((refs[refs != 0] & mask).tolist())
        extra |= set((preds[preds != 0] & mask).tolist())
        extra.add(obj_id[1])
        rows = self._change_rows()
        ops_local, other, local = chunk_local_ops(
            rows, author, lambda g: doc.actors.get(g).bytes,
            extra_refs=sorted(extra),
        )
        lut = np.full(max(local) + 1, -1, np.int64)
        for g, l in local.items():
            lut[g] = l

        is_del = e["is_del"]
        cps = e["cp"]
        ins = ~is_del
        raw = (
            cps[ins].astype("<u4").tobytes().decode("utf-32-le").encode("utf-8")
            if ins.any()
            else b""
        )
        u8len = (
            1 + (cps > 0x7F) + (cps > 0x7FF) + (cps > 0xFFFF)
        ).astype(np.int64)
        tail = {
            "obj_ctr": obj_id[0],
            "obj_actor": local[obj_id[1]],
            "elem_ctr": (refs >> bits).astype(np.int64),
            "elem_actor": np.where(refs == 0, -1, lut[refs & mask]).astype(np.int64),
            "insert": ins.astype(np.uint8),
            "action": np.where(is_del, int(Action.DELETE), int(Action.PUT)).astype(np.int64),
            "val_meta": np.where(is_del, 0, (u8len << 4) | 6).astype(np.int64),
            "val_raw": raw,
            "pred_ctr": np.where(preds == 0, -1, preds >> bits).astype(np.int64),
            "pred_actor": np.where(preds == 0, 0, lut[preds & mask]).astype(np.int64),
        }
        cols = encode_ops_with_tail(ops_local, tail)
        n_total = len(rows) + len(cps)
        ts = self.timestamp if self.timestamp is not None else 0
        stored = StoredChange(
            dependencies=list(self.deps),
            actor=doc.actors.get(author).bytes,
            other_actors=[doc.actors.get(g).bytes for g in other],
            seq=self.seq,
            start_op=self.start_op,
            timestamp=ts,
            message=self.message,
            ops=LazyOps({}, n_total),
        )
        built = build_change(stored, cols=cols)
        built.ops = LazyOps(built.op_col_data, n_total)
        return built

    # -- map mutations -----------------------------------------------------

    def put(self, obj: str, prop, value) -> None:
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        info = self.doc.ops.get_obj(obj_id)
        sv = ScalarValue.from_py(value)
        if isinstance(info.data, MapObject):
            self._map_op(obj_id, prop, Action.PUT, sv)
        else:
            self._seq_set(obj_id, prop, Action.PUT, sv)

    def put_object(self, obj: str, prop, obj_type: ObjType) -> str:
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        info = self.doc.ops.get_obj(obj_id)
        action = action_for_objtype(obj_type)
        if isinstance(info.data, MapObject):
            op = self._map_op(obj_id, prop, action, ScalarValue.null())
        else:
            op = self._seq_set(obj_id, prop, action, ScalarValue.null())
        return self.doc.export_id(op.id)

    def _map_op(self, obj_id: OpId, prop: str, action: int, value: ScalarValue) -> Op:
        if not isinstance(prop, str):
            raise AutomergeError("map keys must be strings")
        if prop == "":
            raise AutomergeError("map keys may not be empty")
        key_idx = self.doc.props.cache(prop)
        pred = self._pred_for_map(obj_id, key_idx)
        op = Op(
            id=self._next_id(),
            action=action,
            value=value,
            key=key_idx,
            pred=pred,
        )
        self._apply(obj_id, op)
        return op

    def delete(self, obj: str, prop) -> None:
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        info = self.doc.ops.get_obj(obj_id)
        if isinstance(info.data, MapObject):
            if not isinstance(prop, str):
                raise AutomergeError(
                    f"map delete requires a string key, got {prop!r}"
                )
            key_idx = self.doc.props.lookup(prop)
            # deleting a missing key is a silent no-op (reference:
            # transaction/inner.rs:422-423 — empty ops + Delete -> Ok(None))
            if key_idx is None:
                return
            pred = self._pred_for_map(obj_id, key_idx)
            if not pred:
                return
            op = Op(
                id=self._next_id(),
                action=Action.DELETE,
                value=ScalarValue.null(),
                key=key_idx,
                pred=pred,
            )
            self._apply(obj_id, op)
        else:
            enc = self._encoding(info.data)
            el = self.doc.ops.nth(obj_id, prop, enc, self.scope)
            if el is None:
                raise AutomergeError(f"index {prop} out of bounds")
            op = Op(
                id=self._next_id(),
                action=Action.DELETE,
                value=ScalarValue.null(),
                elem=el.elem_id,
                pred=self._pred_for_elem(el),
            )
            self._apply(obj_id, op)

    def increment(self, obj: str, prop, by: int) -> None:
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        info = self.doc.ops.get_obj(obj_id)
        if isinstance(info.data, MapObject):
            key_idx = self.doc.props.lookup(prop) if isinstance(prop, str) else None
            pred_ops = (
                self.doc.ops.visible_map_ops(obj_id, key_idx, self.scope)
                if key_idx is not None
                else []
            )
            if not any(o.is_counter for o in pred_ops):
                raise MissingCounter(f"no counter at {prop!r} to increment")
            # pred covers EVERY visible op at the slot: a conflicting
            # non-counter value gains a (non-increment-surviving) successor
            # and disappears (reference: inner.rs local_map_op + the
            # visibility rule types.rs:712-744)
            op = Op(
                id=self._next_id(),
                action=Action.INCREMENT,
                value=ScalarValue("int", by),
                key=key_idx,
                pred=self.doc.ops.sort_opids([o.id for o in pred_ops]),
            )
            self._apply(obj_id, op)
        else:
            enc = self._encoding(info.data)
            el = self.doc.ops.nth(obj_id, prop, enc, self.scope)
            if el is None:
                raise AutomergeError(f"index {prop} out of bounds")
            visible = el.visible_ops(self.scope)
            if not any(o.is_counter for o in visible):
                raise MissingCounter(f"no counter at index {prop} to increment")
            # pred covers every visible op at the element (see the map
            # branch above for why)
            op = Op(
                id=self._next_id(),
                action=Action.INCREMENT,
                value=ScalarValue("int", by),
                elem=el.elem_id,
                pred=self.doc.ops.sort_opids([o.id for o in visible]),
            )
            self._apply(obj_id, op)

    # -- sequence mutations ------------------------------------------------

    @staticmethod
    def _encoding(data: SeqObject) -> int:
        return TEXT_ENC if data.obj_type == ObjType.TEXT else LIST_ENC

    def _seq_set(self, obj_id: OpId, index, action: int, value: ScalarValue) -> Op:
        """Overwrite the element at ``index`` (width-aware for text)."""
        if not isinstance(index, int):
            raise InvalidOp(msg="sequence positions must be integers")
        info = self.doc.ops.get_obj(obj_id)
        enc = self._encoding(info.data)
        el = self.doc.ops.nth(obj_id, index, enc, self.scope)
        if el is None:
            raise AutomergeError(f"index {index} out of bounds")
        op = Op(
            id=self._next_id(),
            action=action,
            value=value,
            elem=el.elem_id,
            pred=self._pred_for_elem(el),
        )
        self._apply(obj_id, op)
        return op

    def insert(self, obj: str, index: int, value) -> None:
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        self._insert_op(obj_id, index, Action.PUT, ScalarValue.from_py(value))

    def insert_object(self, obj: str, index: int, obj_type: ObjType) -> str:
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        op = self._insert_op(
            obj_id, index, action_for_objtype(obj_type), ScalarValue.null()
        )
        return self.doc.export_id(op.id)

    def _insert_ref(self, obj_id: OpId, index: int, enc: int) -> OpId:
        """Reference element for an insert at ``index``.

        Scans forward over invisible elements applying Peritext "sticky"
        mark boundaries (reference: query/insert.rs
        identify_valid_insertion_spot): insertion moves past an expanding
        MarkBegin (new text joins the span) and past a non-expanding
        MarkEnd (new text stays outside the span); a whole begin/end pair
        encountered in between is ignored.
        """
        obj = self.doc.ops.get_obj(obj_id).data
        if index == 0:
            anchor = None
        else:
            anchor = self.doc.ops.nth(obj_id, index - 1, enc, self.scope)
            if anchor is None:
                raise AutomergeError(f"index {index} out of bounds")
        return self._insert_ref_from(obj, anchor)

    def _insert_ref_from(self, obj, anchor) -> OpId:
        """Sticky-boundary scan starting after ``anchor`` (None = HEAD)."""
        from .marks import is_mark_begin, is_mark_end

        if anchor is None:
            floor = HEAD
            cur = obj.head.next
        else:
            floor = anchor.elem_id
            cur = anchor.next
        candidates = []  # mark elements pushing the insertion point right
        current = self.scope is None
        while cur is not None:
            # tombstone runs: jump whole blocks with no visible and no mark
            # elements (only valid against current state, not an isolation
            # clock — a scoped read may see through current tombstones)
            if current:
                b = cur.block
                if b is not None and b.vis == 0 and b.marks == 0:
                    cur = b.els[-1].next
                    continue
            if cur.winner(self.scope) is not None:
                break  # next visible element: insert lands before it
            op = cur.op
            if op.is_mark:
                if is_mark_end(op):
                    begin_id = (op.id[0] - 1, op.id[1])
                    hit = next(
                        (
                            i
                            for i, c in enumerate(candidates)
                            if c.op.id == begin_id
                        ),
                        None,
                    )
                    if hit is not None:
                        # a whole begin/end pair: points inside are invalid
                        del candidates[hit:]
                        cur = cur.next
                        continue
                    if not op.expand:
                        candidates.append(cur)
                elif is_mark_begin(op) and op.expand:
                    candidates.append(cur)
            cur = cur.next
        if candidates:
            return candidates[-1].elem_id
        return floor

    def _insert_op(self, obj_id: OpId, index: int, action: int, value: ScalarValue) -> Op:
        info = self.doc.ops.get_obj(obj_id)
        if not isinstance(info.data, SeqObject):
            raise InvalidOp(msg="insert on a non-sequence object")
        enc = self._encoding(info.data)
        elem = self._insert_ref(obj_id, index, enc)
        op = Op(
            id=self._next_id(),
            action=action,
            value=value,
            elem=elem,
            insert=True,
        )
        self._apply(obj_id, op)
        return op

    def splice_text(self, obj: str, pos: int, delete: int, text: str) -> None:
        self._check_open()
        # hot path: an existing session needs no store access at all
        ent = self._sessions.get(self.doc.import_id(obj)) if self._sessions else None
        if ent is not None:
            n = ent[0].splice(
                self.start_op + len(self.operations) + self._session_ops,
                pos, delete, text,
            )
            self._session_ops += n
            return
        obj_id = self._obj(obj)
        # session creation only reads obj_id's state, which no OTHER
        # session can have touched — no drain needed yet
        info = self.doc.ops.get_obj(obj_id)
        # text splices apply only to TEXT objects (reference: InvalidOp,
        # transaction/inner.rs splice_text via automerge.rs op checks)
        if not isinstance(info.data, SeqObject) or info.data.obj_type != ObjType.TEXT:
            raise InvalidOp(msg="splice_text on a non-text object")
        sess = self._session_for(obj_id, info)
        if sess is not None:
            n = sess.splice(
                self.start_op + len(self.operations) + self._session_ops,
                pos, delete, text,
            )
            self._session_ops += n
            return
        # python fallback: other sessions' pending ops must land in
        # ``operations`` BEFORE this op so the encoded change stays in
        # implicit-id order (ids derive from row position on decode)
        self._drain_all()
        enc = self._encoding(info.data)
        values = [ScalarValue("str", ch) for ch in text]
        self._splice(obj_id, pos, delete, values, enc)

    def splice_text_many(self, obj: str, edits, clamp: bool = True) -> int:
        """Bulk text ingest: apply many (pos, delete, text) splices in one
        native call (requires session eligibility — TEXT object, no marks,
        no conflicts; falls back to per-edit splice_text otherwise).
        Returns the number of ops issued."""
        self._check_open()
        obj_id = self._obj(obj)
        info = self.doc.ops.get_obj(obj_id)
        if not isinstance(info.data, SeqObject) or info.data.obj_type != ObjType.TEXT:
            raise InvalidOp(msg="splice_text_many on a non-text object")
        sess = self._session_for(obj_id, info)
        if sess is None:
            from ..types import str_width

            n0 = self.pending_ops()
            ln = self.length(obj)  # width units, like pos/ndel
            for e in edits:
                pos, ndel = e[0], e[1]
                text = "".join(e[2:]) if len(e) > 2 else ""
                if clamp:
                    pos = min(pos, ln)
                    ndel = min(ndel, ln - pos)
                self.splice_text(obj, pos, ndel, text)
                ln += str_width(text) - ndel
            return self.pending_ops() - n0
        n = sess.splice_batch(
            self.start_op + len(self.operations) + self._session_ops,
            edits, clamp=clamp,
        )
        self._session_ops += n
        return n

    def splice(self, obj: str, pos: int, delete: int, values) -> None:
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        info = self.doc.ops.get_obj(obj_id)
        if not isinstance(info.data, SeqObject):
            raise InvalidOp(msg="splice on a non-sequence object")
        svals = [ScalarValue.from_py(v) for v in values]
        self._splice(obj_id, pos, delete, svals, self._encoding(info.data))

    def _splice(self, obj_id, pos, delete, values, enc) -> None:
        """Delete then insert at ``pos`` (reference: inner.rs inner_splice).

        Anchors once at ``pos - 1`` and walks elements directly instead of
        re-seeking per op; the position cursor is re-seeded afterwards so a
        run of sequential splices costs O(1) seek each — the analogue of the
        reference's ``last_insert`` hint (op_tree.rs:36-45).
        """
        ops = self.doc.ops
        obj = ops.get_obj(obj_id).data
        if delete > 0 and enc == TEXT_ENC:
            target, t_start = ops.nth_with_pos(obj_id, pos, enc, self.scope)
            if target is not None and t_start < pos:
                # deletion begins mid-way through a multi-width element:
                # rewind to the element start and expand the deleted span
                # (reference inner_splice's adjusted_index, inner.rs:631-637)
                delete += pos - t_start
                pos = t_start
        # anchor: the visible element just before pos (None at HEAD)
        if pos == 0:
            anchor = None
            anchor_at = None
        else:
            anchor = ops.nth(obj_id, pos - 1, enc, self.scope)
            if anchor is None:
                raise AutomergeError(f"splice: index {pos} out of bounds")
            anchor_at = obj._cursor[1 if enc == LIST_ENC else 2] if obj._cursor else None

        def next_visible(el):
            if self.scope is None:
                return obj.next_visible_from(el)
            el = el.next if el is not None else obj.head.next
            while el is not None and el.winner(self.scope) is None:
                el = el.next
            return el

        # -- deletes: walk forward from the anchor -------------------------
        remaining = delete
        cur = next_visible(anchor)
        while remaining > 0:
            if cur is None:
                raise AutomergeError(f"splice: delete past end of sequence")
            w = cur.winner(self.scope)
            width = w.text_width() if enc == TEXT_ENC else 1
            op = Op(
                id=self._next_id(),
                action=Action.DELETE,
                value=ScalarValue.null(),
                elem=cur.elem_id,
                pred=self._pred_for_elem(cur),
            )
            self._apply(obj_id, op)
            remaining -= width
            cur = next_visible(cur)

        # -- inserts: chain off one another (reference inner.rs:672-683) ---
        last_el = None
        insert_at = pos
        if values:
            elem = self._insert_ref_from(obj, anchor)
            for v in values:
                op = Op(
                    id=self._next_id(),
                    action=Action.PUT,
                    value=v,
                    elem=elem,
                    insert=True,
                )
                self._apply(obj_id, op)
                elem = op.id
            last_el = obj.by_id[elem]
            insert_at = pos + sum(_sv_width(v, enc) for v in values[:-1])

        # -- re-seed the cursor so the next sequential splice is O(1) ------
        if self.scope is None:
            if last_el is not None:
                ops.seed_cursor(obj, last_el, insert_at, enc)
            elif anchor is not None and anchor_at is not None:
                ops.seed_cursor(obj, anchor, anchor_at, enc)

    # -- marks -------------------------------------------------------------

    def mark(self, obj: str, start: int, end: int, name: str, value, expand="after") -> None:
        """Mark a span [start, end) of a sequence (Peritext-style rich text).

        Begin/end are inserted as zero-width invisible elements so that
        concurrent edits at the boundaries resolve by the expand policy
        (reference: inner.rs mark inserts MarkBegin/MarkEnd via do_insert).
        The end op id is always begin id + 1 — the pairing key.
        """
        self._check_open()
        self._drain_all(drop=True)
        obj_id = self._obj(obj)
        info = self.doc.ops.get_obj(obj_id)
        if not isinstance(info.data, SeqObject):
            raise InvalidOp(msg="mark on a non-sequence object")
        if end <= start:
            raise AutomergeError("mark span must be non-empty")
        enc = self._encoding(info.data)
        # validate both anchors before creating any op: a failed end lookup
        # must not leave a dangling unpaired MarkBegin behind
        if self.doc.ops.nth(obj_id, start, enc, self.scope) is None and start != 0:
            raise AutomergeError(f"mark start {start} out of bounds")
        if self.doc.ops.nth(obj_id, end - 1, enc, self.scope) is None:
            raise AutomergeError(f"mark end {end} out of bounds")
        expand_start = expand in ("before", "both")
        expand_end = expand in ("after", "both")
        begin = Op(
            id=self._next_id(),
            action=Action.MARK,
            value=ScalarValue.from_py(value),
            elem=self._insert_ref(obj_id, start, enc),
            insert=True,
            mark_name=name,
            expand=expand_start,
        )
        self._apply(obj_id, begin)
        end_op = Op(
            id=self._next_id(),
            action=Action.MARK,
            value=ScalarValue.null(),
            elem=self._insert_ref(obj_id, end, enc),
            insert=True,
            mark_name=None,
            expand=expand_end,
        )
        self._apply(obj_id, end_op)

    def unmark(self, obj: str, start: int, end: int, name: str, expand="none") -> None:
        """A null-valued mark span: clears ``name`` over [start, end).
        ``expand`` governs whether edits at the boundaries fall inside the
        cleared span (reference: transaction/inner.rs unmark)."""
        self.mark(obj, start, end, name, None, expand=expand)

    # -- commit / rollback -------------------------------------------------

    def pending_ops(self) -> int:
        return len(self.operations) + self._session_ops

    # -- reads (reference: Transactable is a ReadDoc, transactable.rs) -----
    #
    # Reads resolve through the transaction's scope clock: an isolated
    # transaction sees the historical state plus its own pending ops (the
    # scope pins this transaction's actor), a plain transaction sees the
    # current state plus pending ops. Pending native-session ops drain
    # into the store first so reads observe them.

    def get(self, obj: str, prop):
        self._drain_all()
        return self.doc.get(obj, prop, clock=self.scope)

    def get_all(self, obj: str, prop):
        self._drain_all()
        return self.doc.get_all(obj, prop, clock=self.scope)

    def text(self, obj: str) -> str:
        self._drain_all()
        return self.doc.text(obj, clock=self.scope)

    def length(self, obj: str) -> int:
        n = self.session_length(self.doc.import_id(obj)) if self._sessions else None
        if n is not None:
            return n
        self._drain_all()
        return self.doc.length(obj, clock=self.scope)

    def keys(self, obj: str = ROOT):
        self._drain_all()
        return self.doc.keys(obj, clock=self.scope)

    def list_items(self, obj: str):
        self._drain_all()
        return self.doc.list_items(obj, clock=self.scope)

    def map_entries(self, obj: str = ROOT):
        self._drain_all()
        return self.doc.map_entries(obj, clock=self.scope)

    def commit(self) -> Optional[bytes]:
        """Encode the pending ops as a change and append it to history."""
        self._check_open()
        self._done = True
        self.doc.open_transactions.discard(self)
        if not self.operations and not self._session_ops and self.message is None:
            return None
        from .. import obs

        if obs.enabled():
            obs.event("commit", ops=self.pending_ops(), seq=self.seq)
        change = self._export_change()
        applied = AppliedChange(
            change, self.actor_idx, self._export_actor_map(change)
        )
        self.doc._update_history(applied)
        if self._had_session_ops:
            # the op store never saw the session ops — it is now a stale
            # view of history and rebuilds on the next read
            self.doc._ops_stale = True
        return change.hash

    def rollback(self) -> int:
        self._check_open()
        self._done = True
        self.doc.open_transactions.discard(self)
        n = len(self.operations) + self._session_ops
        for ent in self._sessions.values():
            ent[0].close()
        self._sessions.clear()
        for ent in self._msessions.values():
            ent[0].close()
        self._msessions.clear()
        self._session_ops = 0
        for obj_id, op in reversed(self.operations):
            self.doc.ops.remove_op(obj_id, op)
        self.operations = []
        return n

    def _change_rows(self) -> List[ChangeOp]:
        doc = self.doc
        return [
            ChangeOp(
                obj=ROOT_STORED if obj_id == ROOT_OBJ else obj_id,
                key=(
                    Key.map(doc.props.get(op.key))
                    if op.key is not None
                    else Key.seq(op.elem)
                ),
                insert=op.insert,
                action=op.action,
                value=op.value,
                pred=list(op.pred),
                expand=op.expand,
                mark_name=op.mark_name,
            )
            for obj_id, op in self.operations
        ]

    # session tails at or below this drain through the per-op path at
    # commit: the store stays live (no full-history rebuild on next read),
    # which keeps the commit-per-keystroke pattern O(tail) instead of O(doc)
    SMALL_TAIL_OPS = 256

    def _export_change_map_session(self, obj_id: OpId, ent) -> StoredChange:
        """Array-native commit for a pure map-session transaction: encode
        the session's undrained puts straight into change columns
        (storage/change.encode_map_tail_cols) without materializing per-op
        python objects. Guarded by the caller: ``self.operations`` empty."""
        import numpy as np

        doc = self.doc
        author = self.actor_idx
        bits = self._ID_RANK_BITS
        mask = (1 << bits) - 1
        e = ent[0].export(ent[1])
        for s2 in self._sessions.values():
            s2[0].close()
        self._sessions.clear()
        for s2 in self._msessions.values():
            s2[0].close()
        self._msessions.clear()
        self._had_session_ops = True

        preds = e["pred"]
        extra = set((preds[preds != 0] & mask).tolist())
        if obj_id != ROOT_OBJ:
            extra.add(obj_id[1])
        _, other, local = chunk_local_ops(
            [], author, lambda g: doc.actors.get(g).bytes,
            extra_refs=sorted(extra),
        )
        lut = np.full(max(local) + 1, -1, np.int64)
        for g, l in local.items():
            lut[g] = l

        tail = {
            "obj_ctr": 0 if obj_id == ROOT_OBJ else obj_id[0],
            "obj_actor": -1 if obj_id == ROOT_OBJ else local[obj_id[1]],
            "key_idx": e["key_idx"],
            "keys": e["keys"],
            "val_meta": e["vmeta"],
            "val_raw": e["raw"],
            "pred_ctr": np.where(preds == 0, -1, preds >> bits).astype(np.int64),
            "pred_actor": np.where(preds == 0, 0, lut[preds & mask]).astype(np.int64),
        }
        cols = encode_map_tail_cols(tail)
        n_total = len(e["key_idx"])
        ts = self.timestamp if self.timestamp is not None else 0
        stored = StoredChange(
            dependencies=list(self.deps),
            actor=doc.actors.get(author).bytes,
            other_actors=[doc.actors.get(g).bytes for g in other],
            seq=self.seq,
            start_op=self.start_op,
            timestamp=ts,
            message=self.message,
            ops=LazyOps({}, n_total),
        )
        built = build_change(stored, cols=cols)
        built.ops = LazyOps(built.op_col_data, n_total)
        return built

    def _export_change(self) -> StoredChange:
        live = {
            (False, o): ent for o, ent in self._sessions.items()
            if ent[0].op_count() > ent[1]
        }
        live.update({
            (True, o): ent for o, ent in self._msessions.items()
            if ent[0].op_count() > ent[1]
        })
        undrained = sum(ent[0].op_count() - ent[1] for ent in live.values())
        if live and (
            len(live) > 1
            or (
                undrained <= self.SMALL_TAIL_OPS
                # ...but only when the tail is also a small FRACTION of the
                # document: the session-export path marks the op store stale
                # (next read rebuilds from the whole history), which beats
                # per-op drain only when the tail isn't most of the doc
                and undrained * 4 < self.doc.max_op
            )
        ):
            # multi-session commits interleave objects; small tails are
            # cheaper applied incrementally than via a stale-store rebuild
            self._drain_all(drop=True)
            live = {}
        if live:
            (((is_map, obj_id), ent),) = live.items()
            if is_map:
                if self.operations:
                    # the map tail encoder takes no prefix rows; mixed
                    # commits go through the materialized path
                    self._drain_all(drop=True)
                else:
                    return self._export_change_map_session(obj_id, ent)
            else:
                return self._export_change_session(obj_id, ent)
        for ent in self._sessions.values():
            ent[0].close()
        self._sessions.clear()
        for ent in self._msessions.values():
            ent[0].close()
        self._msessions.clear()
        doc = self.doc
        author = self.actor_idx
        rows = self._change_rows()
        ops, other, _ = chunk_local_ops(
            rows, author, lambda g: doc.actors.get(g).bytes
        )
        ts = self.timestamp if self.timestamp is not None else 0
        return build_change(
            StoredChange(
                dependencies=list(self.deps),
                actor=doc.actors.get(author).bytes,
                other_actors=[doc.actors.get(g).bytes for g in other],
                seq=self.seq,
                start_op=self.start_op,
                timestamp=ts,
                message=self.message,
                ops=ops,
            )
        )

    def _export_actor_map(self, change: StoredChange) -> List[int]:
        return [
            self.doc.actors.cache(ActorId(a)) for a in change.actors
        ]


def _scalar_from_vmeta(vmeta: int, raw: bytes) -> ScalarValue:
    """Decode a map-session payload (value_meta code + raw bytes) back into
    a ScalarValue for the materialized drain path."""
    code = vmeta & 0xF
    if code == 0:
        return ScalarValue.null()
    if code == 1:
        return ScalarValue("bool", False)
    if code == 2:
        return ScalarValue("bool", True)
    if code == 3:
        from ..utils.leb128 import decode_uleb

        return ScalarValue("uint", decode_uleb(raw, 0)[0])
    if code in (4, 8, 9):
        from ..utils.leb128 import decode_sleb

        tag = {4: "int", 8: "counter", 9: "timestamp"}[code]
        return ScalarValue(tag, decode_sleb(raw, 0)[0])
    if code == 5:
        import struct

        return ScalarValue("f64", struct.unpack("<d", raw)[0])
    if code == 6:
        return ScalarValue("str", raw.decode("utf-8"))
    if code == 7:
        return ScalarValue("bytes", raw)
    raise AutomergeError(f"unexpected map-session value code {code}")


def _sv_width(v: ScalarValue, enc: int) -> int:
    if enc == TEXT_ENC and v.tag == "str":
        from ..types import str_width

        return str_width(v.value)
    return 1


# -- per-document text-encoding activation (see core/document.py) -------------


def _tx_width_ctx(fn):
    import functools

    from ..types import using_text_encoding

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        enc = self.doc.text_encoding
        if enc is None:
            return fn(self, *args, **kwargs)
        with using_text_encoding(enc):
            return fn(self, *args, **kwargs)

    return wrapped


for _name in (
    "put",
    "put_object",
    "delete",
    "increment",
    "insert",
    "insert_object",
    "splice_text",
    "splice_text_many",
    "splice",
    "mark",
    "unmark",
    "commit",
    "rollback",
    "get",
    "get_all",
    "text",
    "length",
    "keys",
    "list_items",
    "map_entries",
    "fast_splice_fn",
    "_drain_all",
    "session_length",
):
    setattr(Transaction, _name, _tx_width_ctx(getattr(Transaction, _name)))
del _name
