"""Marks (Peritext-style rich text): span resolution over the op store.

Mark begin/end pairs are zero-width invisible elements in the sequence
(reference: rust/automerge/src/transaction/inner.rs mark → do_insert).
Reading marks walks elements in document order, feeding mark ops through a
state machine that keeps open marks ordered by their begin OpId — the
highest Lamport id wins for each name — and accumulates coalesced spans
(reference: rust/automerge/src/marks.rs MarkStateMachine/MarkAccumulator,
rust/automerge/src/automerge.rs:1370-1413 calculate_marks).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

from ..types import ObjType
from .op_store import LIST_ENC, Op, SeqObject, TEXT_ENC


@dataclass
class Mark:
    start: int
    end: int
    name: str
    value: object


def is_mark_begin(op: Op) -> bool:
    return op.is_mark and op.mark_name is not None


def is_mark_end(op: Op) -> bool:
    return op.is_mark and op.mark_name is None


class MarkStateMachine:
    """Open-mark tracking: list of (begin_id, name, value) sorted by id."""

    def __init__(self, lamport_key):
        self._lamport_key = lamport_key
        self._open: List[Tuple[tuple, str, object]] = []

    def process(self, op: Op) -> None:
        if is_mark_begin(op):
            self._open.append((op.id, op.mark_name, op.value.to_py()))
            self._open.sort(key=lambda e: self._lamport_key(e[0]))
        elif is_mark_end(op):
            begin_id = (op.id[0] - 1, op.id[1])
            self._open = [e for e in self._open if e[0] != begin_id]

    def current(self) -> Dict[str, object]:
        """name -> value of the highest-id open mark per name (null values
        included here — they mask lower marks; outputs filter them)."""
        out: Dict[str, object] = {}
        for _, name, value in self._open:  # already lamport-ascending
            out[name] = value
        return out


def visible_or_mark(op: Op, clock) -> bool:
    if op.is_mark:
        return clock is None or clock.covers(op.id)
    return op.visible_at(clock)


def calculate_marks(doc, obj_id, clock=None) -> List[Mark]:
    """Resolved, coalesced mark spans for a sequence object."""
    from .document import AutomergeError

    info = doc.ops.get_obj(obj_id)
    data = info.data
    if not isinstance(data, SeqObject):
        raise AutomergeError("marks on a non-sequence object")
    enc = TEXT_ENC if data.obj_type == ObjType.TEXT else LIST_ENC
    machine = MarkStateMachine(doc.ops.lamport_key)
    index = 0
    spans: Dict[str, List[Mark]] = {}
    for el in data.elements():
        last = None
        for op in el.run():
            if visible_or_mark(op, clock):
                last = op
        if last is None:
            continue
        if last.is_mark:
            machine.process(last)
            continue
        if last.is_inc or last.is_delete:
            continue
        width = last.text_width() if enc == TEXT_ENC else 1
        current = machine.current()
        for name, value in current.items():
            runs = spans.setdefault(name, [])
            if runs and runs[-1].end == index and runs[-1].value == value:
                runs[-1].end = index + width
            else:
                runs.append(Mark(index, index + width, name, value))
        index += width
    out = [
        m
        for runs in spans.values()
        for m in runs
        if m.value is not None  # null-valued spans are unmarks
    ]
    out.sort(key=lambda m: (m.start, m.name))
    return out
