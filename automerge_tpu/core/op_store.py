"""Host op store: per-object op runs with RGA ordering and visibility.

This is the host-side equivalent of the reference's OpSet/OpTree
(reference: rust/automerge/src/op_set.rs, op_tree.rs) with the same
semantics — Lamport-ordered runs per key/element, succ/pred visibility
flips, RGA sibling ordering — but a different shape: sequences are a doubly
linked list of element runs with O(1) id lookup and a moving cursor for
index resolution (sequential edits cost O(jump distance), the dominant
pattern in real editing traces), and maps are per-prop sorted runs. The
device merge kernel (ops/) is the batched alternative for N-way merges;
this structure serves local edits and incremental remote applies.

Key invariants (reference: types.rs:712-744, op_tree.rs:212-239):
  - op visible iff succ empty; counter put visible iff all succ are incs;
    increment and mark ops are never visible themselves
  - ops for one key/element are in ascending Lamport order (ties broken by
    actor bytes)
  - a new insert op is placed after its reference element, skipping over
    sibling elements whose insert op has a greater Lamport id
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from ..types import Action, ObjType, OpId, ScalarValue, is_make_action, str_width

LIST_ENC = 0
TEXT_ENC = 1


from ..errors import AutomergeError


class OpStoreError(AutomergeError):
    pass


class Op:
    __slots__ = (
        "id",
        "action",
        "key",  # prop index (int) for map ops, None for seq ops
        "elem",  # reference element OpId for seq ops, None for map ops
        "insert",
        "value",
        "pred",  # List[OpId], sorted by lamport
        "succ",  # List[OpId], sorted by lamport
        "mark_name",
        "expand",
        "incs",  # List[(OpId, int)] for counter puts
    )

    def __init__(
        self,
        id: OpId,
        action: int,
        value: ScalarValue,
        key: Optional[int] = None,
        elem: Optional[OpId] = None,
        insert: bool = False,
        pred: Optional[List[OpId]] = None,
        mark_name: Optional[str] = None,
        expand: bool = False,
    ):
        self.id = id
        self.action = action
        self.key = key
        self.elem = elem
        self.insert = insert
        self.value = value
        self.pred = pred or []
        self.succ: List[OpId] = []
        self.mark_name = mark_name
        self.expand = expand
        self.incs: List[Tuple[OpId, int]] = []

    @property
    def is_counter(self) -> bool:
        return self.action == Action.PUT and self.value.tag == "counter"

    @property
    def is_inc(self) -> bool:
        return self.action == Action.INCREMENT

    @property
    def is_mark(self) -> bool:
        return self.action == Action.MARK

    @property
    def is_delete(self) -> bool:
        return self.action == Action.DELETE

    def visible(self) -> bool:
        if self.is_inc or self.is_mark:
            return False
        if self.is_counter:
            return len(self.succ) <= len(self.incs)
        return not self.succ

    def visible_at(self, clock) -> bool:
        """Historical visibility (reference: types.rs visible_at)."""
        if clock is None:
            return self.visible()
        if self.is_inc or self.is_mark:
            return False
        if not clock.covers(self.id):
            return False
        inc_ids = {i for i, _ in self.incs} if self.is_counter else ()
        return not any(clock.covers(s) for s in self.succ if s not in inc_ids)

    def counter_value_at(self, clock=None) -> int:
        base = self.value.value
        for sid, n in self.incs:
            if clock is None or clock.covers(sid):
                base += n
        return base

    def text_width(self) -> int:
        if self.value.tag == "str":
            return str_width(self.value.value)
        return 1

    def __repr__(self):
        return f"Op({self.id}, a={self.action}, v={self.value.tag})"


class Element:
    """A sequence element: its defining insert op plus overwriting ops."""

    __slots__ = ("op", "updates", "prev", "next", "block", "_wcache", "lkey")

    def __init__(self, op: Optional[Op]):
        self.op = op  # None only for the head sentinel
        self.updates: List[Op] = []
        self.prev: Optional["Element"] = None
        self.next: Optional["Element"] = None
        self.block: Optional["Block"] = None
        self.lkey = None  # cached (ctr, actor-bytes) Lamport key
        # cached current-state winner: () = dirty, (op_or_None,) = valid.
        # Walks touch every element ~hundreds of times between visibility
        # changes; recomputing visible_ops each time dominated the replay
        # profile. Mutation paths call dirty_winner().
        self._wcache = ()

    @property
    def elem_id(self) -> OpId:
        return self.op.id

    def dirty_winner(self) -> None:
        self._wcache = ()

    def run(self) -> Iterator[Op]:
        if self.op is not None:
            yield self.op
        yield from self.updates

    def visible_ops(self, clock=None) -> List[Op]:
        return [o for o in self.run() if o.visible_at(clock)]

    def winner(self, clock=None) -> Optional[Op]:
        """Last visible op in Lamport order — the current value."""
        if clock is None:
            cached = self._wcache
            if cached:
                return cached[0]
            vis = self.visible_ops(None)
            w = vis[-1] if vis else None
            self._wcache = (w,)
            return w
        vis = self.visible_ops(clock)
        return vis[-1] if vis else None


class Block:
    """A run of consecutive elements with visibility aggregates.

    The order-statistics index over the element list: blocks carry
    (visible count, visible text width) so index resolution skips whole
    blocks instead of walking elements — the role the reference's B-tree
    node ``Index`` plays (reference: op_tree/node.rs:88-144,
    query/list_state.rs:76-120), in flat-block form.
    """

    __slots__ = ("els", "vis", "width", "min_key", "marks")

    def __init__(self):
        self.els: List[Element] = []
        self.vis = 0
        self.width = 0
        # minimum (ctr, actor-bytes) insert-op key in this block: lets the
        # RGA sibling skip scan jump whole blocks whose every element has a
        # greater Lamport id (the dense-concurrency quadratic case)
        self.min_key = None
        # count of mark begin/end elements: blocks with vis == 0 and
        # marks == 0 are skippable wholesale by insert-reference scans
        self.marks = 0


# block split threshold: nth costs O(#blocks + BLOCK_MAX); with ~n/128
# blocks both terms stay small through million-element sequences
BLOCK_MAX = 256


class SeqObject:
    __slots__ = (
        "obj_type",
        "actors",  # the document's actor cache (Lamport ties use bytes)
        "head",
        "tail",
        "by_id",
        "blocks",
        "visible_len",
        "text_width",
        "_cursor",  # (Element, list_index, text_index) of a visible element
        "_text_cache",  # current-state text (TEXT objects, bulk rebuild)
    )

    def __init__(self, obj_type: ObjType, actors=None):
        self.obj_type = obj_type
        self.actors = actors
        self.head = Element(None)
        self.tail = self.head
        self.by_id: Dict[OpId, Element] = {}
        self.blocks: List[Block] = []
        self.visible_len = 0
        self.text_width = 0
        self._cursor = None
        # current-state text, filled by rebuild_blocks for TEXT objects;
        # any element mutation drops it (every seq mutation path calls
        # invalidate_cursor)
        self._text_cache: Optional[str] = None

    def invalidate_cursor(self) -> None:
        self._cursor = None
        self._text_cache = None

    # -- block index maintenance ------------------------------------------

    def _block_key(self, el: Element):
        k = el.lkey
        if k is None:
            opid = el.op.id
            k = (opid[0], self.actors.get(opid[1]).bytes)
            el.lkey = k
        return k

    def block_insert_after(self, prev: Element, el: Element) -> None:
        """Register ``el`` (just linked after ``prev``) in the block index."""
        if prev.op is None:  # head sentinel -> front of the first block
            if not self.blocks:
                self.blocks.append(Block())
            b = self.blocks[0]
            b.els.insert(0, el)
        else:
            b = prev.block
            b.els.insert(b.els.index(prev) + 1, el)
        el.block = b
        w = el.winner()
        if w is not None:
            b.vis += 1
            b.width += w.text_width()
        if el.op.is_mark:
            b.marks += 1
        key = self._block_key(el)
        if b.min_key is None or key < b.min_key:
            b.min_key = key
        if len(b.els) > BLOCK_MAX:
            self._split_block(b)

    def _split_block(self, b: Block) -> None:
        half = len(b.els) // 2
        nb = Block()
        nb.els = b.els[half:]
        b.els = b.els[:half]
        for el in nb.els:
            el.block = nb
            w = el.winner()
            if w is not None:
                nb.vis += 1
                nb.width += w.text_width()
            if el.op.is_mark:
                nb.marks += 1
        b.vis -= nb.vis
        b.width -= nb.width
        b.marks -= nb.marks
        b.min_key = min(map(self._block_key, b.els)) if b.els else None
        nb.min_key = min(map(self._block_key, nb.els)) if nb.els else None
        self.blocks.insert(self.blocks.index(b) + 1, nb)

    def block_remove(self, el: Element) -> None:
        b = el.block
        if b is None:
            return
        w = el.winner()
        if w is not None:
            b.vis -= 1
            b.width -= w.text_width()
        if el.op.is_mark:
            b.marks -= 1
        b.els.remove(el)
        el.block = None
        if not b.els:
            self.blocks.remove(b)
        elif self._block_key(el) == b.min_key:
            b.min_key = min(map(self._block_key, b.els))

    def block_vis_delta(self, el: Element, dvis: int, dwidth: int) -> None:
        b = el.block
        if b is not None and (dvis or dwidth):
            b.vis += dvis
            b.width += dwidth

    def rebuild_blocks(self) -> None:
        """Partition the element list into fresh blocks (bulk load path).

        For TEXT objects the same winner sweep also assembles the
        current-state text cache, so the first text() read after a bulk
        rebuild (the sync catch-up read pattern) is a plain string return
        instead of a second full element walk."""
        self.blocks = []
        cache_text = self.obj_type == ObjType.TEXT
        parts: List[str] = []
        b = None
        el = self.head.next
        while el is not None:
            if b is None or len(b.els) >= BLOCK_MAX:
                b = Block()
                self.blocks.append(b)
            b.els.append(el)
            el.block = b
            w = el.winner()
            if w is not None:
                b.vis += 1
                b.width += w.text_width()
                if cache_text:
                    v = w.value
                    parts.append(v.value if v.tag == "str" else "￼")
            if el.op.is_mark:
                b.marks += 1
            key = self._block_key(el)
            if b.min_key is None or key < b.min_key:
                b.min_key = key
            el = el.next
        self.visible_len = sum(x.vis for x in self.blocks)
        self.text_width = sum(x.width for x in self.blocks)
        if cache_text:
            self._text_cache = "".join(parts)

    def next_visible_from(self, el: Optional[Element]) -> Optional[Element]:
        """First CURRENT-STATE-visible element strictly after ``el``
        (None = from HEAD). Whole blocks with no visible elements are
        skipped via the index — tombstone runs cost O(#blocks crossed),
        not O(run length) (the never_seen_puts fast path's role,
        reference query/list_state.rs:73-97)."""
        cur = el.next if el is not None else self.head.next
        while cur is not None:
            b = cur.block
            if b is not None and b.vis == 0:
                cur = b.els[-1].next
                continue
            if cur.winner() is not None:
                return cur
            cur = cur.next
        return None

    def seed_cursor(self, el, at: int, encoding: int) -> None:
        """Re-seed the position cursor after local edits (the analogue of
        the reference's last_insert hint, op_tree.rs:36-45)."""
        if encoding == LIST_ENC:
            self._cursor = (el, at, 0, encoding)
        else:
            self._cursor = (el, 0, at, encoding)

    def elements(self) -> Iterator[Element]:
        e = self.head.next
        while e is not None:
            yield e
            e = e.next

    def ops_in_order(self) -> Iterator[Tuple[Element, Op]]:
        for e in self.elements():
            for op in e.run():
                yield e, op


class MapObject:
    __slots__ = ("obj_type", "props")

    def __init__(self, obj_type: ObjType = ObjType.MAP):
        self.obj_type = obj_type
        self.props: Dict[int, List[Op]] = {}


class ObjInfo:
    __slots__ = ("data", "parent", "parent_key", "parent_elem")

    def __init__(self, data, parent: Optional[OpId], parent_key, parent_elem):
        self.data = data  # MapObject | SeqObject
        self.parent = parent
        self.parent_key = parent_key  # prop index in parent map
        self.parent_elem = parent_elem  # elem id in parent seq


ROOT_OBJ: OpId = (0, 0)


class OpStore:
    """All objects of a document, keyed by object id."""

    def __init__(self, actors):
        # ``actors`` is the document's IndexedCache of ActorIds; Lamport
        # comparisons go through it because ties break on actor *bytes*.
        self.actors = actors
        self.objects: Dict[OpId, ObjInfo] = {
            ROOT_OBJ: ObjInfo(MapObject(), None, None, None)
        }

    # -- Lamport order -----------------------------------------------------

    def lamport_key(self, opid: OpId):
        return (opid[0], self.actors.get(opid[1]).bytes)

    def lamport_lt(self, a: OpId, b: OpId) -> bool:
        if a[0] != b[0]:
            return a[0] < b[0]
        return self.actors.get(a[1]).bytes < self.actors.get(b[1]).bytes

    def sort_opids(self, ids: List[OpId]) -> List[OpId]:
        return sorted(ids, key=self.lamport_key)

    # -- object management -------------------------------------------------

    def get_obj(self, obj_id: OpId) -> ObjInfo:
        info = self.objects.get(obj_id)
        if info is None:
            raise OpStoreError(f"missing object {obj_id}")
        return info

    def has_obj(self, obj_id: OpId) -> bool:
        return obj_id in self.objects

    def obj_type(self, obj_id: OpId) -> ObjType:
        return self.get_obj(obj_id).data.obj_type

    def _register_make(self, obj_id: OpId, op: Op) -> None:
        from ..types import objtype_for_action

        t = objtype_for_action(op.action)
        if t is None:
            return
        if op.id in self.objects:
            return
        data = (
            MapObject(t)
            if t in (ObjType.MAP, ObjType.TABLE)
            else SeqObject(t, self.actors)
        )
        # For insert-created objects the element id is the make op's own id
        # (op.elem is only the RGA reference element it was inserted after).
        parent_elem = op.id if op.insert else op.elem
        self.objects[op.id] = ObjInfo(data, obj_id, op.key, parent_elem)

    # -- the apply path ----------------------------------------------------

    def add_succ(self, target: Op, op: Op) -> None:
        if op.id not in target.succ:
            target.succ.append(op.id)
            target.succ.sort(key=self.lamport_key)
        if op.is_inc and target.is_counter:
            target.incs.append((op.id, op.value.value))

    def remove_succ(self, target: Op, op: Op) -> None:
        target.succ = [s for s in target.succ if s != op.id]
        if op.is_inc and target.is_counter:
            target.incs = [(i, n) for i, n in target.incs if i != op.id]

    def insert_op(self, obj_id: OpId, op: Op) -> None:
        """Apply one (already actor-translated) op to an object.

        Mirrors the reference's seek → add_succ → insert flow
        (automerge.rs:1258-1280): predecessors named by ``op.pred`` get this
        op added to their succ (flipping their visibility); the op itself is
        stored unless it is a delete.
        """
        info = self.get_obj(obj_id)
        if is_make_action(op.action):
            self._register_make(obj_id, op)
        if isinstance(info.data, MapObject):
            self._insert_map_op(info.data, op)
        else:
            self._insert_seq_op(info.data, op)

    def _insert_map_op(self, obj: MapObject, op: Op) -> None:
        if op.key is None:
            raise OpStoreError("seq-keyed op applied to map object")
        run = obj.props.setdefault(op.key, [])
        pred = set(op.pred)
        pos = 0
        for i, existing in enumerate(run):
            if existing.id in pred:
                self.add_succ(existing, op)
            if not self.lamport_lt(op.id, existing.id):
                pos = i + 1
        if not op.is_delete:
            run.insert(pos, op)

    def _insert_seq_op(self, obj: SeqObject, op: Op) -> None:
        obj.invalidate_cursor()
        if op.insert:
            self._insert_seq_insert(obj, op)
        else:
            self._insert_seq_update(obj, op)

    def _insert_seq_insert(self, obj: SeqObject, op: Op) -> None:
        if op.elem is None:
            raise OpStoreError("insert op without reference element")
        if op.elem[0] == 0:  # HEAD
            ref = obj.head
        else:
            ref = obj.by_id.get(op.elem)
            if ref is None:
                raise OpStoreError(f"insert references missing element {op.elem}")
        # RGA: skip sibling elements with greater insert-op id
        # (reference: query/opid.rs SimpleOpIdSearch). Whole blocks whose
        # minimum id exceeds ours are skipped in O(1) via the index —
        # without this, dense concurrency (many replicas inserting at the
        # same anchors) makes the element-wise scan quadratic.
        after = self._rga_skip(obj, ref.next, op.id)
        el = Element(op)
        prev = after.prev if after is not None else obj.tail
        el.prev = prev
        el.next = after
        prev.next = el
        if after is not None:
            after.prev = el
        else:
            obj.tail = el
        obj.by_id[op.id] = el
        obj.block_insert_after(prev, el)
        if op.visible():
            obj.visible_len += 1
            obj.text_width += op.text_width()

    def _rga_skip(self, obj: SeqObject, after, op_id: OpId):
        """First element at/after ``after`` whose insert-op id is less than
        ``op_id`` (Lamport); None past the end."""
        key = self.lamport_key(op_id)
        while after is not None:
            b = after.block
            if b is None:  # not indexed (shouldn't happen); element-wise
                if not self.lamport_lt(op_id, after.op.id):
                    return after
                after = after.next
                continue
            i = b.els.index(after)
            if i == 0 and b.min_key is not None and key < b.min_key:
                after = b.els[-1].next  # every id in the block is greater
                continue
            n = len(b.els)
            while i < n:
                el2 = b.els[i]
                if not self.lamport_lt(op_id, el2.op.id):
                    return el2
                i += 1
            after = b.els[n - 1].next

    def _insert_seq_update(self, obj: SeqObject, op: Op) -> None:
        if op.elem is None:
            raise OpStoreError("seq update without element id")
        el = obj.by_id.get(op.elem)
        if el is None:
            raise OpStoreError(f"op targets missing element {op.elem}")
        before_vis, before_w = self._elem_visibility(el)
        el.dirty_winner()
        pred = set(op.pred)
        for existing in el.run():
            if existing.id in pred:
                self.add_succ(existing, op)
        if not op.is_delete:
            pos = 0
            for i, existing in enumerate(el.updates):
                if self.lamport_lt(op.id, existing.id):
                    break
                pos = i + 1
            el.updates.insert(pos, op)
        after_vis, after_w = self._elem_visibility(el)
        obj.visible_len += after_vis - before_vis
        obj.text_width += after_w - before_w
        obj.block_vis_delta(el, after_vis - before_vis, after_w - before_w)

    @staticmethod
    def _elem_visibility(el: Element) -> Tuple[int, int]:
        w = el.winner()
        if w is None:
            return 0, 0
        return 1, w.text_width()

    def remove_op(self, obj_id: OpId, op: Op) -> None:
        """Rollback support: remove the most recently applied op.

        Mirrors reference rollback (transaction/inner.rs:158-184): un-succ
        the op's predecessors and delete the op itself from the store.
        """
        info = self.get_obj(obj_id)
        if is_make_action(op.action) and op.id in self.objects:
            del self.objects[op.id]
        if isinstance(info.data, MapObject):
            run = info.data.props.get(op.key, [])
            for existing in run:
                if existing.id in op.pred:
                    self.remove_succ(existing, op)
            info.data.props[op.key] = [o for o in run if o.id != op.id]
        else:
            obj = info.data
            obj.invalidate_cursor()
            if op.insert:
                el = obj.by_id.pop(op.id, None)
                if el is not None:
                    obj.block_remove(el)
                    if el.op.visible():
                        obj.visible_len -= 1
                        obj.text_width -= el.op.text_width()
                    el.prev.next = el.next
                    if el.next is not None:
                        el.next.prev = el.prev
                    else:
                        obj.tail = el.prev
            else:
                el = obj.by_id.get(op.elem)
                if el is not None:
                    before_vis, before_w = self._elem_visibility(el)
                    el.dirty_winner()
                    for existing in el.run():
                        if existing.id in op.pred:
                            self.remove_succ(existing, op)
                    el.updates = [o for o in el.updates if o.id != op.id]
                    after_vis, after_w = self._elem_visibility(el)
                    obj.visible_len += after_vis - before_vis
                    obj.text_width += after_w - before_w
                    obj.block_vis_delta(el, after_vis - before_vis, after_w - before_w)

    # -- reads -------------------------------------------------------------

    def map_ops(self, obj_id: OpId, key: int) -> List[Op]:
        info = self.get_obj(obj_id)
        if not isinstance(info.data, MapObject):
            raise OpStoreError("map read on sequence object")
        return info.data.props.get(key, [])

    def visible_map_ops(self, obj_id: OpId, key: int, clock=None) -> List[Op]:
        return [o for o in self.map_ops(obj_id, key) if o.visible_at(clock)]

    def seq_length(self, obj_id: OpId, encoding: int = LIST_ENC, clock=None) -> int:
        info = self.get_obj(obj_id)
        obj = info.data
        if not isinstance(obj, SeqObject):
            raise OpStoreError("seq read on map object")
        if clock is None:
            return obj.visible_len if encoding == LIST_ENC else obj.text_width
        total = 0
        for el in obj.elements():
            w = el.winner(clock)
            if w is not None:
                total += 1 if encoding == LIST_ENC else w.text_width()
        return total

    def nth(
        self, obj_id: OpId, index: int, encoding: int = LIST_ENC, clock=None
    ) -> Optional[Element]:
        """The visible element at ``index`` (width-aware for text)."""
        obj = self.get_obj(obj_id).data
        if not isinstance(obj, SeqObject):
            raise OpStoreError("nth on map object")
        if clock is not None:
            return self._nth_scan(obj, index, encoding, clock)[0]
        cur = obj._cursor
        if cur is not None and encoding == cur[3]:
            el, li, ti = cur[0], cur[1], cur[2]
            at = li if encoding == LIST_ENC else ti
            # local walks beat the block scan only for short jumps (the
            # sequential-editing pattern); long jumps go through the index
            if abs(index - at) <= BLOCK_MAX and el.winner() is not None:
                if at <= index:
                    found = self._walk_forward(obj, el, at, index, encoding)
                else:
                    found = self._walk_backward(obj, el, at, index, encoding)
                if found is not None:
                    return found
        return self._nth_scan(obj, index, encoding, None)[0]

    def _walk_forward(self, obj, el, at, index, encoding):
        while el is not None:
            w = el.winner()
            if w is not None:
                width = 1 if encoding == LIST_ENC else w.text_width()
                if at <= index < at + width:
                    self._set_cursor(obj, el, at, encoding)
                    return el
                at += width
            el = el.next
        return None

    def _walk_backward(self, obj, el, at, index, encoding):
        """Walk toward the front from a visible element starting at ``at``."""
        while True:
            p = el.prev
            while p is not None and p.op is not None and p.winner() is None:
                p = p.prev
            if p is None or p.op is None:
                return None  # reached HEAD without covering index
            w = p.winner()
            width = 1 if encoding == LIST_ENC else w.text_width()
            at -= width
            el = p
            if at <= index:
                if index < at + width:
                    self._set_cursor(obj, el, at, encoding)
                    return el
                return None

    def _nth_scan(self, obj, index, encoding, clock):
        """(element, span start) of the visible element covering ``index``."""
        if clock is None:
            return self._nth_blocks(obj, index, encoding)
        at = 0
        for el in obj.elements():
            w = el.winner(clock)
            if w is None:
                continue
            width = 1 if encoding == LIST_ENC else w.text_width()
            if at <= index < at + width:
                return el, at
            at += width
        return None, -1

    def _nth_blocks(self, obj, index, encoding):
        """Current-state nth via the block index: skip whole blocks by
        their visibility aggregates, walk only the target block
        (vectorized Nth/ListState node skipping, query/list_state.rs)."""
        if index < 0:
            return None, -1
        at = 0
        for b in obj.blocks:
            span = b.vis if encoding == LIST_ENC else b.width
            if index < at + span:
                for el in b.els:
                    w = el.winner()
                    if w is None:
                        continue
                    width = 1 if encoding == LIST_ENC else w.text_width()
                    if at <= index < at + width:
                        self._set_cursor(obj, el, at, encoding)
                        return el, at
                    at += width
                return None, -1  # unreachable if aggregates are consistent
            at += span
        return None, -1

    def position_of(self, obj_id: OpId, el: Element, encoding: int = LIST_ENC) -> int:
        """Span-start position of ``el`` in current state: the sum of
        visible widths before it — O(#blocks + block size) via the index
        (reference: seek_opid / SeekOpId resolving a cursor to an index,
        automerge.rs:1484-1518)."""
        obj = self.get_obj(obj_id).data
        if not isinstance(obj, SeqObject):
            raise OpStoreError("position_of on map object")
        b = el.block
        if b is None:
            raise OpStoreError("element not indexed")
        at = 0
        for blk in obj.blocks:
            if blk is b:
                break
            at += blk.vis if encoding == LIST_ENC else blk.width
        for e in b.els:
            if e is el:
                return at
            w = e.winner()
            if w is not None:
                at += 1 if encoding == LIST_ENC else w.text_width()
        raise OpStoreError("element missing from its block")

    def _set_cursor(self, obj, el, at, encoding):
        if encoding == LIST_ENC:
            obj._cursor = (el, at, 0, encoding)
        else:
            obj._cursor = (el, 0, at, encoding)

    def seed_cursor(self, obj, el, at: int, encoding: int) -> None:
        obj.seed_cursor(el, at, encoding)

    def nth_with_pos(
        self, obj_id: OpId, index: int, encoding: int = LIST_ENC, clock=None
    ):
        """(element, start position) of the visible element covering ``index``.

        The start position is where the element's span begins — strictly less
        than ``index`` when a multi-width text element crosses it (the
        reference's Nth query reports this as ``query.index()``,
        transaction/inner.rs:631-637).
        """
        obj = self.get_obj(obj_id).data
        if clock is not None:
            return self._nth_scan(obj, index, encoding, clock)
        el = self.nth(obj_id, index, encoding, None)
        if el is None:
            return None, -1
        cur = obj._cursor
        if cur is not None and cur[0] is el:
            return el, cur[1] if encoding == LIST_ENC else cur[2]
        return self._nth_scan(obj, index, encoding, None)

    def visible_elements(self, obj_id: OpId, clock=None) -> Iterator[Tuple[Element, Op]]:
        obj = self.get_obj(obj_id).data
        if not isinstance(obj, SeqObject):
            raise OpStoreError("sequence read on map object")
        for el in obj.elements():
            w = el.winner(clock)
            if w is not None:
                yield el, w

    def visible_elements_range(
        self, obj_id: OpId, start: int, end: Optional[int] = None, clock=None
    ) -> Iterator[Tuple[Element, Op]]:
        """Visible (element, winner) pairs for list indices in [start, end).

        Current-state reads resolve ``start`` through the block index and
        walk only the requested span instead of rendering the whole list
        (reference: read.rs list_range's bounded ListRange iterator)."""
        start = max(start, 0)
        if end is not None and end <= start:
            return
        if clock is not None:
            idx = 0
            for el, w in self.visible_elements(obj_id, clock):
                if end is not None and idx >= end:
                    return
                if idx >= start:
                    yield el, w
                idx += 1
            return
        el = self.nth(obj_id, start, LIST_ENC, None)
        idx = start
        while el is not None:
            if el.op is not None:
                w = el.winner()
                if w is not None:
                    if end is not None and idx >= end:
                        return
                    yield el, w
                    idx += 1
            el = el.next

    def text(self, obj_id: OpId, clock=None) -> str:
        if clock is None:
            cached = getattr(self.get_obj(obj_id).data, "_text_cache", None)
            if cached is not None:
                return cached
        parts = []
        for _, w in self.visible_elements(obj_id, clock):
            if w.value.tag == "str":
                parts.append(w.value.value)
            else:
                parts.append("￼")  # object replacement char, like the reference
        return "".join(parts)

    def map_keys(self, obj_id: OpId, clock=None) -> List[int]:
        info = self.get_obj(obj_id)
        if not isinstance(info.data, MapObject):
            raise OpStoreError("keys read on sequence object")
        out = []
        for key, run in info.data.props.items():
            if any(o.visible_at(clock) for o in run):
                out.append(key)
        return out
