"""Bulk op-store rebuild: native RGA integrate + vectorized assembly.

Incremental per-op apply (op_store.insert_op) is ideal for small remote
batches, but a large catch-up — sync with a long-divergent peer, an N-way
merge — degenerates in Python: the RGA sibling skip scan
(op_store.py:321-334, mirroring reference op_tree.rs:212-239) touches every
concurrent chain at an anchor per insert. The bulk path instead:

  1. flattens the ENTIRE history (old + new changes) into packed-id arrays,
  2. runs the native sequential integrate once (native/apply.cpp) to get
     every sequence object's element order,
  3. recomputes succ lists / visibility / map runs vectorized in numpy,
  4. assembles fresh OpStore structures in one linear pass.

Same output as replaying insert_op per op — asserted by the differential
tests — at native speed. Reference parallel: the doc-chunk load path also
rebuilds the op set in bulk instead of replaying changes
(storage/load/reconstruct_document.rs, op_set/load.rs).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..types import Action, ActorId, ObjType, ScalarValue, is_make_action, objtype_for_action
from .op_store import Element, MapObject, ObjInfo, Op, OpStore, SeqObject

from ..types import ACTOR_BITS  # shared packed-id layout


def flatten_changes(changes: Sequence) -> Dict[str, object]:
    """Flatten StoredChanges (in order) into packed-id arrays.

    Ids pack as (counter << 20 | byte-sorted actor rank) so int64 order is
    lamport_cmp (types.rs:517-521). Returns the arrays am_seq_apply
    consumes plus the rank table. Uses the native batch column decoder
    when every change retains its column bytes; falls back to the per-op
    Python walk otherwise.
    """
    import os

    try:
        return _flatten_fast(changes)
    except Exception:
        if os.environ.get("AUTOMERGE_TPU_DEBUG"):
            raise
        return _flatten_slow(changes)


def _flatten_fast(changes: Sequence) -> Dict[str, object]:
    """Vectorized flatten via the commit-time ChangeCols caches
    (ops/assemble.ranked_from_caches): changes decoded once per object
    lifetime, flattened with numpy concats + rank gathers."""
    from ..ops.assemble import ranked_from_caches

    actor_bytes = sorted({bytes(a) for ch in changes for a in ch.actors})
    rank_of = {a: i for i, a in enumerate(actor_bytes)}
    if len(actor_bytes) >= (1 << ACTOR_BITS):
        raise ValueError("too many actors for packed id encoding")

    r = ranked_from_caches(list(changes), rank_of)
    a = r["a"]
    return {
        "op_id": r["id_key"].astype(np.int64),
        "obj": r["obj"].astype(np.int64),
        "elem": r["elem"].astype(np.int64),
        "prop": np.where(r["prop_ids"] >= 0, 0, -1).astype(np.int32),
        "action": a["action"].astype(np.int32),
        "insert": a["insert"].astype(np.uint8),
        "is_counter": (a["vcode"] == 8).astype(np.uint8),
        "pred_off": np.concatenate([[0], np.cumsum(a["pred_num"])]).astype(np.int64),
        "pred_flat": r["pred_key"].astype(np.int64),
        "rank_of": rank_of,
        # full ranked batch: lets the rebuild construct store Ops straight
        # from arrays instead of materializing ChangeOp objects
        "rb": r,
    }


def _flatten_slow(changes: Sequence) -> Dict[str, object]:
    actor_bytes = sorted({bytes(a) for ch in changes for a in ch.actors})
    rank_of = {a: i for i, a in enumerate(actor_bytes)}
    if len(actor_bytes) >= (1 << ACTOR_BITS):
        raise ValueError("too many actors for packed id encoding")

    op_id, obj, elem, prop_l, action, insert, is_counter = [], [], [], [], [], [], []
    pred_off, pred_flat = [0], []
    for ch in changes:
        ranks = [rank_of[bytes(a)] for a in ch.actors]
        author = ranks[0]
        for i, cop in enumerate(ch.ops):
            op_id.append(((ch.start_op + i) << ACTOR_BITS) | author)
            obj.append(
                0 if cop.obj[0] == 0 else (cop.obj[0] << ACTOR_BITS) | ranks[cop.obj[1]]
            )
            if cop.key.prop is not None:
                prop_l.append(0)
                elem.append(-1)
            else:
                prop_l.append(-1)
                e = cop.key.elem
                elem.append(0 if e[0] == 0 else (e[0] << ACTOR_BITS) | ranks[e[1]])
            action.append(int(cop.action))
            insert.append(1 if cop.insert else 0)
            is_counter.append(1 if cop.value.tag == "counter" else 0)
            for pc, pa in cop.pred:
                pred_flat.append((pc << ACTOR_BITS) | ranks[pa])
            pred_off.append(len(pred_flat))
    return {
        "op_id": np.asarray(op_id, np.int64),
        "obj": np.asarray(obj, np.int64),
        "elem": np.asarray(elem, np.int64),
        "prop": np.asarray(prop_l, np.int32),
        "action": np.asarray(action, np.int32),
        "insert": np.asarray(insert, np.uint8),
        "is_counter": np.asarray(is_counter, np.uint8),
        "pred_off": np.asarray(pred_off, np.int64),
        "pred_flat": np.asarray(pred_flat, np.int64),
        "rank_of": rank_of,
    }


def _export_via_device(stored, flat):
    """Per-object element order from the batched device merge kernel.

    The native sequential integrate degenerates exactly where the kernel
    shines: dense concurrency (many actors inserting at the same anchors
    turns the per-insert sibling skip scan quadratic). The kernel's
    ``elem_index`` IS the document order, for every insert op including
    tombstones, so it can feed the same (obj_keys, obj_off, elem_rows)
    contract. flatten_changes and ops/oplog.py share the byte-rank id
    packing, so rows translate with one searchsorted.
    """
    from ..ops import OpLog
    from ..ops.merge import merge_columns

    log = OpLog.from_changes(stored)
    if log.n != len(flat["op_id"]):
        raise ValueError("device export: op count mismatch with flat history")
    res = merge_columns(
        log.columns(), fetch=("elem_index",), n_objs=log.n_objs
    )
    elem_index = np.asarray(res["elem_index"][: log.n])

    flat_pos = np.argsort(flat["op_id"], kind="stable")
    sorted_flat = flat["op_id"][flat_pos]
    pos = np.searchsorted(sorted_flat, log.id_key)
    pos = np.clip(pos, 0, max(len(sorted_flat) - 1, 0))
    if len(sorted_flat) == 0 or not np.array_equal(sorted_flat[pos], log.id_key):
        raise ValueError("device export: id mismatch with flat history")
    flat_rows = flat_pos[pos]

    rows = np.flatnonzero(log.insert & (elem_index >= 0))
    order = np.lexsort((elem_index[rows], log.obj_key[rows]))
    rows = rows[order]
    obj_of = log.obj_key[rows]
    bnd = (
        np.flatnonzero(np.concatenate([[True], obj_of[1:] != obj_of[:-1]]))
        if len(rows)
        else np.empty(0, np.int64)
    )
    obj_keys = obj_of[bnd].astype(np.int64)
    obj_off = np.concatenate([bnd, [len(rows)]]).astype(np.int64)
    elem_rows = flat_rows[rows].astype(np.int32)
    return obj_keys, obj_off, elem_rows


def _build_ops_from_changes(doc, stored, ops, objs_of, sort_key) -> None:
    """Per-ChangeOp store-Op construction (fallback when the batch column
    decode is unavailable)."""
    row = 0
    for ch in stored:
        amap = [doc.actors.cache(ActorId(a)) for a in ch.actors]
        author = amap[0]
        start = ch.start_op
        for i, cop in enumerate(ch.ops):
            key = doc.props.cache(cop.key.prop) if cop.key.prop is not None else None
            if key is None:
                e = cop.key.elem
                elem = (0, 0) if e[0] == 0 else (e[0], amap[e[1]])
            else:
                elem = None
            pred = [(p[0], amap[p[1]]) for p in cop.pred]
            if len(pred) > 1:
                pred.sort(key=sort_key)
            op = Op(
                id=(start + i, author),
                action=cop.action,
                value=cop.value,
                key=key,
                elem=elem,
                insert=cop.insert,
                pred=pred,
                mark_name=cop.mark_name,
                expand=cop.expand,
            )
            ops[row] = op
            o = cop.obj
            objs_of[row] = (0, 0) if o[0] == 0 else (o[0], amap[o[1]])
            row += 1


_INT_TAG = {3: "uint", 4: "int", 8: "counter", 9: "timestamp"}


def _build_ops_from_arrays(doc, flat, ops, objs_of, sort_key) -> None:
    """Array-driven store-Op construction: straight from the ranked batch
    columns (no ChangeOp materialization). Value semantics match
    storage/values decoding exactly — common codes inline, everything
    else through _decode_one."""
    from ..storage.values import _decode_one

    rb = flat["rb"]
    a = rb["a"]
    n = len(flat["op_id"])
    mask = (1 << ACTOR_BITS) - 1
    rank_of = flat["rank_of"]
    rank_bytes = sorted(rank_of, key=rank_of.get)
    r2g = [doc.actors.cache(ActorId(b)) for b in rank_bytes]
    key_g = [doc.props.cache(s) for s in a["key_table"]]
    mark_tab = a["mark_table"]

    op_id = flat["op_id"]
    id_ctr = (op_id >> ACTOR_BITS).tolist()
    id_a = [r2g[x] for x in (op_id & mask).tolist()]
    obj_l = flat["obj"].tolist()
    elem_l = flat["elem"].tolist()
    prop_l = rb["prop_ids"].tolist()
    action_l = flat["action"].tolist()
    insert_l = a["insert"].tolist()
    expand_l = a["expand"].tolist()
    mark_l = (
        a["mark_ids"].tolist() if a["mark_ids"] is not None else [-1] * n
    )
    vcode_l = a["vcode"].tolist()
    voff_l = a["voff"].tolist()
    vlen_l = a["vlen"].tolist()
    vint_l = a["value_int"].tolist()
    raw = a["vraw"]
    pred_num = a["pred_num"].tolist()
    pf = flat["pred_flat"]
    pf_ctr = (pf >> ACTOR_BITS).tolist()
    pf_a = [r2g[x] for x in (pf & mask).tolist()]

    NULL_V = ScalarValue("null")
    TRUE_V = ScalarValue("bool", True)
    FALSE_V = ScalarValue("bool", False)
    HEAD_T = (0, 0)
    ROOT_T = (0, 0)
    _new = Op.__new__
    pv = 0
    for i in range(n):
        code = vcode_l[i]
        if code == 6:
            o = voff_l[i]
            v = ScalarValue("str", raw[o : o + vlen_l[i]].decode("utf-8"))
        elif code == 0:
            v = NULL_V
        elif code == 3 or code == 4 or code == 8 or code == 9:
            # the native decoder wraps values outside i64 (uint >= 2^63,
            # overlong LEBs): re-decode those few through the exact path
            if (code == 3 and vint_l[i] < 0) or vlen_l[i] >= 10:
                o = voff_l[i]
                v = _decode_one(code, raw[o : o + vlen_l[i]])
            else:
                v = ScalarValue(_INT_TAG[code], vint_l[i])
        elif code == 2:
            v = TRUE_V
        elif code == 1:
            v = FALSE_V
        else:
            o = voff_l[i]
            v = _decode_one(code, raw[o : o + vlen_l[i]])
        op = _new(Op)
        op.id = (id_ctr[i], id_a[i])
        op.action = action_l[i]
        p = prop_l[i]
        if p >= 0:
            op.key = key_g[p]
            op.elem = None
        else:
            op.key = None
            e = elem_l[i]
            op.elem = HEAD_T if e == 0 else (e >> ACTOR_BITS, r2g[e & mask])
        op.insert = insert_l[i]
        op.value = v
        k = pred_num[i]
        if k == 0:
            op.pred = []
        elif k == 1:
            op.pred = [(pf_ctr[pv], pf_a[pv])]
        else:
            pr = [(pf_ctr[pv + j], pf_a[pv + j]) for j in range(k)]
            pr.sort(key=sort_key)
            op.pred = pr
        pv += k
        op.succ = []
        op.incs = []
        m = mark_l[i]
        op.mark_name = mark_tab[m] if m >= 0 else None
        op.expand = expand_l[i]
        ops[i] = op
        ob = obj_l[i]
        objs_of[i] = ROOT_T if ob == 0 else (ob >> ACTOR_BITS, r2g[ob & mask])


# dense-concurrency threshold: at or past this shape the sequential RGA
# sibling scan loses to one batched kernel pass even counting transport
DEVICE_MIN_OPS = 20_000
DEVICE_MIN_ACTORS = 16


def rebuild_op_store(doc) -> None:
    """Rebuild ``doc.ops`` from the full applied history. Element order
    comes from the native sequential integrate, or — for large dense-
    concurrency histories — from the batched device merge kernel.
    Replaces the store wholesale; the document's history / change graph /
    actor caches are untouched.

    Cyclic GC is paused for the build: it allocates millions of small
    objects and a generational collection mid-build walks every live one
    (measured ~2.4x on a 260k-op rebuild). Nothing in here creates
    garbage cycles — the element list's cycles stay live in the store.
    """
    import gc

    gc_was = gc.isenabled()
    if gc_was:
        gc.disable()
    try:
        _rebuild_op_store(doc)
    finally:
        if gc_was:
            gc.enable()


def _seq_export(stored, flat):
    """(obj_keys, obj_off, elem_rows): every sequence object's element
    order, via the batched device kernel (dense concurrency) or the native
    sequential integrate — the rebuild's engine choice."""
    import os

    from .. import native

    engine = os.environ.get("AUTOMERGE_TPU_BULK")
    if engine is None:
        n_actors = len({bytes(ch.actor) for ch in stored})
        engine = (
            "device"
            if len(flat["op_id"]) >= DEVICE_MIN_OPS and n_actors >= DEVICE_MIN_ACTORS
            else "native"
        )
    if engine == "device":
        try:
            return _export_via_device(stored, flat)
        except Exception:
            if os.environ.get("AUTOMERGE_TPU_DEBUG"):
                raise
    return native.seq_apply_export(
        flat["op_id"], flat["obj"], flat["elem"], flat["prop"], flat["action"],
        flat["insert"], flat["is_counter"], flat["pred_off"], flat["pred_flat"],
    )


def _row_visibility(flat):
    """Vectorized per-row current-state visibility (Op.visible batched).

    Returns (vis, src_rows, tgt_rows): vis[i] = row i is a visible winner
    candidate; src/tgt are the resolved pred-edge endpoints (source op row,
    predecessor-target op row) for succ-list construction."""
    ids = flat["op_id"]
    n = len(ids)
    order = np.argsort(ids, kind="stable")
    sorted_ids = ids[order]

    pred_counts = np.diff(flat["pred_off"])
    src_rows = np.repeat(np.arange(n, dtype=np.int64), pred_counts)
    if len(flat["pred_flat"]):
        pos = np.searchsorted(sorted_ids, flat["pred_flat"])
        posc = np.clip(pos, 0, max(n - 1, 0))
        hit = sorted_ids[posc] == flat["pred_flat"] if n else np.zeros(0, bool)
        tgt_rows = np.where(hit, order[posc], -1)
    else:
        tgt_rows = np.empty(0, np.int64)
    okm = tgt_rows >= 0
    src_rows, tgt_rows = src_rows[okm], tgt_rows[okm]

    act = flat["action"]
    succ_n = np.zeros(n, np.int64)
    inc_n = np.zeros(n, np.int64)
    if len(tgt_rows):
        np.add.at(succ_n, tgt_rows, 1)
        inc_edge = (act[src_rows] == int(Action.INCREMENT)) & (
            (act[tgt_rows] == int(Action.PUT)) & (flat["is_counter"][tgt_rows] != 0)
        )
        if inc_edge.any():
            np.add.at(inc_n, tgt_rows[inc_edge], 1)
    counter_row = (act == int(Action.PUT)) & (flat["is_counter"] != 0)
    never = np.isin(act, (int(Action.DELETE), int(Action.INCREMENT), int(Action.MARK)))
    vis = ~never & np.where(counter_row, succ_n <= inc_n, succ_n == 0)
    return vis, src_rows, tgt_rows


def stale_read_state(doc):
    """Shared intermediates for every stale read at one history length —
    computed once, cached by the Document so N object reads pay one
    history pass, not N. None when the array path can't serve.

    One OpLog extraction + one columnar merge (ops/merge.merge_columns)
    supplies element order, winners, and visibility together — the same
    engine the fan-in merge rides — instead of the former separate
    flatten + element-export + visibility passes (three full scans of the
    op history per catch-up read, the sync-config bottleneck VERDICT r4
    flagged)."""
    stored = [a.stored for a in doc.history]
    if not stored:
        return None
    from ..ops import DeviceDoc, OpLog
    from ..ops.merge import merge_columns

    log = OpLog.from_changes(stored)
    if not hasattr(log.values, "code"):
        # eager-list values (per-op extraction fallback): no value heap to
        # gather from; let the materialized store answer
        return None
    res = merge_columns(
        log.columns(), fetch=DeviceDoc.READ_FETCH, n_objs=log.n_objs,
        n_props=len(log.props),
    )
    return {"log": log, "res": res}


def stale_text(doc, obj_exid: str, state):
    """Current-state text of one object straight from the merge outputs —
    no op-store materialization. None when this path can't serve (caller
    falls back to the materialized store).

    This is the sync-consumer read path: a replica that catches up over
    the wire and is only *read* never pays the Python object build; the
    store materializes lazily on the first local edit (the same
    history-is-source-of-truth stance as Document._materialize_ops)."""
    log, res = state["log"], state["res"]
    try:
        qkey = log.import_id(obj_exid)
    except (KeyError, ValueError):
        return None
    if qkey == 0:
        return None  # root is a map

    # only sequence objects read as text; maps/tables fall back so the
    # store raises the same typed error it would when materialized
    n = log.n
    mk = int(np.searchsorted(log.id_key, qkey))
    if mk >= n or int(log.id_key[mk]) != qkey or int(log.action[mk]) not in (2, 4):
        return None  # unknown object, or not MAKE_LIST/MAKE_TEXT

    # element rows of this object in document order; each element's
    # current value is its merge-group winner (insert overridden by the
    # last visible update — res["winner"] already encodes TopOps)
    from ..ops.device_doc import order_elem_rows

    obj_rows = np.flatnonzero(log.obj_key == qkey)
    erows = order_elem_rows(log, res["elem_index"][:n], obj_rows)
    win = res["winner"][:n][erows]
    sel = win[win >= 0].astype(np.int64)
    vals = log.values
    vc = np.asarray(vals.code)[sel]
    off = np.asarray(vals.off)[sel].astype(np.int64)
    ln = np.asarray(vals.ln)[sel].astype(np.int64)
    raw = vals.raw
    if len(sel) == 0:
        return ""
    if bool((vc == 6).all()):
        # pure-string text (the overwhelmingly common case): gather every
        # value slice with one flat index build + one utf-8 decode instead
        # of a per-element python loop
        tot = int(ln.sum())
        if tot == 0:
            return ""
        base = np.concatenate([[0], np.cumsum(ln)[:-1]])
        idx = np.arange(tot, dtype=np.int64) + np.repeat(off - base, ln)
        return np.frombuffer(raw, np.uint8)[idx].tobytes().decode("utf-8")
    vcl, offl, lnl = vc.tolist(), off.tolist(), ln.tolist()
    parts = []
    for i in range(len(vcl)):
        if vcl[i] == 6:
            o = offl[i]
            parts.append(raw[o : o + lnl[i]].decode("utf-8"))
        else:
            parts.append("￼")
    return "".join(parts)


def _rebuild_op_store(doc) -> None:
    stored = [a.stored for a in doc.history]
    flat = flatten_changes(stored)
    obj_keys, obj_off, elem_rows = _seq_export(stored, flat)

    # ---- build Op objects (linear pass over change ops) -------------------
    n = len(flat["op_id"])
    ops: List[Op] = [None] * n
    objs_of: List[Tuple[int, int]] = [None] * n  # (obj ctr, obj doc-idx)
    sort_key = doc._ops.lamport_key  # direct: doc.ops may be mid-rebuild
    if flat.get("rb") is not None:
        _build_ops_from_arrays(doc, flat, ops, objs_of, sort_key)
    else:
        _build_ops_from_changes(doc, stored, ops, objs_of, sort_key)

    ids = flat["op_id"]

    # ---- succ lists / counter incs + visibility (vectorized) --------------
    vis, src_rows, tgt_rows = _row_visibility(flat)
    edge_order = np.lexsort((ids[src_rows], tgt_rows))
    for k in edge_order:
        s, t = ops[int(src_rows[k])], ops[int(tgt_rows[k])]
        t.succ.append(s.id)
        if s.is_inc and t.is_counter:
            t.incs.append((s.id, s.value.value))

    # ---- object registry --------------------------------------------------
    store = OpStore(doc.actors)
    make_rows = np.flatnonzero(np.isin(flat["action"], (0, 2, 4, 6)))
    for r in make_rows:
        op = ops[int(r)]
        t = objtype_for_action(op.action)
        data = (
            MapObject(t)
            if t in (ObjType.MAP, ObjType.TABLE)
            else SeqObject(t, store.actors)
        )
        parent_elem = op.id if op.insert else op.elem
        store.objects[op.id] = ObjInfo(data, objs_of[int(r)], op.key, parent_elem)

    # ---- structural validation (vectorized) -------------------------------
    # A map-keyed op must target a map object and a seq-keyed op a sequence
    # — the per-op path raises OpStoreError for these; the bulk rebuild must
    # fail loudly too, never silently drop the op (kind mismatch would
    # otherwise diverge from replicas applying the same change per-op).
    obj_arr = flat["obj"]
    kind_is_map = {0: True}  # packed obj key -> is-map (root is a map)
    for r in make_rows:
        t = objtype_for_action(int(flat["action"][r]))
        kind_is_map[int(flat["op_id"][r])] = t in (ObjType.MAP, ObjType.TABLE)
    is_map_key = flat["prop"] == 0
    kkeys = np.fromiter(kind_is_map.keys(), np.int64, len(kind_is_map))
    kvals = np.fromiter(
        (1 if v else 0 for v in kind_is_map.values()), np.int8, len(kind_is_map)
    )
    korder = np.argsort(kkeys)
    kkeys, kvals = kkeys[korder], kvals[korder]
    pos = np.clip(np.searchsorted(kkeys, obj_arr), 0, len(kkeys) - 1)
    if not np.array_equal(kkeys[pos], obj_arr):
        raise ValueError("op targets unknown object")
    obj_map = kvals[pos].astype(bool)
    if np.any(obj_map & ~is_map_key):
        raise ValueError("sequence-keyed op on a map object")
    if np.any(~obj_map & is_map_key):
        raise ValueError("map-keyed op on a sequence object")

    # ---- map runs (ascending lamport per (obj, prop)) ---------------------
    is_map_op = flat["prop"] == 0
    map_rows = np.flatnonzero(is_map_op & (flat["action"] != int(Action.DELETE)))
    if len(map_rows):
        mr_sorted = map_rows[np.argsort(ids[map_rows], kind="stable")]
        for r in mr_sorted:
            r = int(r)
            op = ops[r]
            info = store.objects.get(objs_of[r])
            if info is None or not isinstance(info.data, MapObject):
                raise ValueError("map op on missing/non-map object")
            info.data.props.setdefault(op.key, []).append(op)

    # ---- sequence elements (native order) + update runs -------------------
    elems_by_id: Dict[Tuple[int, int], Element] = {}
    for k in range(len(obj_keys)):
        okey = int(obj_keys[k])
        oid = (0, 0) if okey == 0 else _unpack(okey, flat["rank_of"], doc)
        info = store.objects.get(oid)
        if info is None or not isinstance(info.data, SeqObject):
            raise ValueError("sequence export for missing/non-seq object")
        obj_data = info.data
        prev = obj_data.head
        for r in elem_rows[int(obj_off[k]) : int(obj_off[k + 1])]:
            r = int(r)
            op = ops[r]
            el = Element(op)
            # pre-seed the winner cache from the vectorized visibility —
            # rebuild_blocks then aggregates without recomputing runs
            el._wcache = (op,) if vis[r] else (None,)
            el.prev = prev
            prev.next = el
            prev = el
            obj_data.by_id[op.id] = el
            elems_by_id[op.id] = el
        obj_data.tail = prev
        # visible_len / text_width are filled by the visibility pass below

    seq_upd_rows = np.flatnonzero(
        (flat["prop"] != 0)
        & (flat["insert"] == 0)
        & (flat["action"] != int(Action.DELETE))
    )
    if len(seq_upd_rows):
        su_sorted = seq_upd_rows[np.argsort(ids[seq_upd_rows], kind="stable")]
        for r in su_sorted:
            r = int(r)
            op = ops[r]
            el = elems_by_id.get(op.elem)
            if el is None:
                raise ValueError("seq update targets missing element")
            el.updates.append(op)
            if vis[r]:  # ascending Lamport: the last visible wins
                el._wcache = (op,)

    # ---- visibility counters + block index (one sweep) ---------------------
    for info in store.objects.values():
        data = info.data
        if isinstance(data, SeqObject):
            data.rebuild_blocks()

    doc.ops = store


def _unpack(key: int, rank_of: Dict[bytes, int], doc) -> Tuple[int, int]:
    ctr = key >> ACTOR_BITS
    rank = key & ((1 << ACTOR_BITS) - 1)
    for b, rk in rank_of.items():
        if rk == rank:
            return (ctr, doc.actors.cache(ActorId(b)))
    raise ValueError("unknown actor rank in export")
