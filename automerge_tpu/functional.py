"""The idiomatic functional API: immutable document values + proxy trees.

The analogue of the reference's JS wrapper (reference:
javascript/src/stable.ts:194-1183 init/change/merge/..., proxies.ts:506-567
mapProxy/listProxy/textProxy): documents are treated as immutable values —
``change(doc, fn)`` hands ``fn`` a mutable proxy of the root and returns a
NEW document value; the input is untouched. Under the hood each value
wraps an AutoDoc; "immutability" is by-construction (operations fork
before mutating), not by copying state.

    import automerge_tpu.functional as am

    d1 = am.init()
    d2 = am.change(d1, lambda d: d.update({"title": "hello"}))
    d3 = am.change(d2, lambda d: d["items"].append("first"))
    d4 = am.merge(d3, other)
    data = am.save(d4)
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .api import AutoDoc
from .types import ActorId, ObjType, ScalarValue

__all__ = [
    "Counter",
    "Doc",
    "Text",
    "apply_changes",
    "change",
    "change_at",
    "clone",
    "diff",
    "get_changes",
    "get_conflicts",
    "get_last_local_change",
    "marks",
    "fork",
    "from_dict",
    "get_actor",
    "get_heads",
    "init",
    "load",
    "merge",
    "save",
    "to_dict",
]


class Doc:
    """An immutable document value. Read like a dict; mutate via change()."""

    __slots__ = ("_auto", "_superseded")

    def __init__(self, auto: AutoDoc):
        object.__setattr__(self, "_auto", auto)
        object.__setattr__(self, "_superseded", False)

    # reads (delegate to a read-only proxy of the root)
    def __getitem__(self, key):
        return _read_value(self._auto, "_root", key)

    def __contains__(self, key) -> bool:
        return key in self._auto.keys("_root")

    def __iter__(self):
        return iter(self._auto.keys("_root"))

    def __len__(self) -> int:
        return len(self._auto.keys("_root"))

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def keys(self):
        return self._auto.keys("_root")

    def to_py(self):
        return self._auto.hydrate()

    def __eq__(self, other):
        if isinstance(other, Doc):
            return self._auto.hydrate() == other._auto.hydrate()
        return self._auto.hydrate() == other

    # content equality without content hashing: unhashable, loudly
    __hash__ = None

    def __repr__(self):
        return f"Doc({self._auto.hydrate()!r})"

    def __setattr__(self, *_):
        raise TypeError("documents are immutable; use change(doc, fn)")


class Counter:
    """Wraps an int so change() writes a CRDT counter, not a plain int."""

    __slots__ = ("value",)

    def __init__(self, value: int = 0):
        self.value = value


class Text:
    """Wraps a string so change() creates a TEXT object (char-wise CRDT)."""

    __slots__ = ("value",)

    def __init__(self, value: str = ""):
        self.value = value


# -- construction / lifecycle -------------------------------------------------


def init(actor: Optional[bytes] = None) -> Doc:
    return Doc(AutoDoc(actor=ActorId(actor) if actor else None))


def from_dict(contents: dict, actor: Optional[bytes] = None) -> Doc:
    """init + one change installing ``contents`` (reference: stable.ts from())."""
    return change(init(actor), lambda d: d.update(contents))


def load(data: bytes, actor: Optional[bytes] = None) -> Doc:
    return Doc(AutoDoc.load(data, actor=ActorId(actor) if actor else None))


def save(doc: Doc) -> bytes:
    return doc._auto.save()


def clone(doc: Doc, actor: Optional[bytes] = None) -> Doc:
    return Doc(doc._auto.fork(actor=ActorId(actor) if actor else None))


fork = clone


def get_heads(doc: Doc) -> List[bytes]:
    return doc._auto.get_heads()


def get_actor(doc: Doc) -> bytes:
    return doc._auto.get_actor().bytes


def merge(doc: Doc, other: Doc) -> Doc:
    """A new value containing both histories; the local input is consumed
    (reference: stable.ts:750-763 progressDocument). Merge itself creates
    no changes, but the new value continues the same actor/seq line — a
    later change() on both the pre- and post-merge values would mint two
    different changes with one (actor, seq), splitting the history."""
    merged = _take(doc)
    try:
        merged.merge(other._auto)
    except BaseException:
        _untake(doc)
        raise
    return Doc(merged)


def get_changes(doc: Doc, have_deps: List[bytes] = ()) -> List[bytes]:
    """Raw change chunks not covered by ``have_deps`` (the JS wrapper's
    getChanges, stable.ts getChanges)."""
    return [c.raw_bytes for c in doc._auto.get_changes(list(have_deps))]


def get_last_local_change(doc: Doc) -> Optional[bytes]:
    c = doc._auto.get_last_local_change()
    return c.raw_bytes if c is not None else None


def apply_changes(doc: Doc, changes) -> Doc:
    """A new value with the raw change chunks applied; the input is
    consumed like merge() (stable.ts applyChanges via progressDocument)."""
    out = _take(doc)
    try:
        out.load_incremental(b"".join(changes), on_partial="error")
    except BaseException:
        _untake(doc)
        raise
    return Doc(out)


def diff(doc: Doc, before: List[bytes], after: List[bytes]):
    """Patches transforming the view at ``before`` into the view at
    ``after`` (stable.ts diff)."""
    return doc._auto.diff(list(before), list(after))


def get_conflicts(doc, prop):
    """Conflicting values at ``prop`` as {opid-exid: value}, or None when
    at most one writer is visible (reference: stable.ts:829 getConflicts
    via conflicts.ts conflictAt — the keys are the writers' op ids, the
    values every concurrent candidate including the winner).

    ``doc`` is a Doc (root) or a nested Map/List proxy obtained through
    subscripting, matching the JS idiom ``getConflicts(doc.pets[0],
    "name")``."""
    if isinstance(doc, Doc):
        auto, obj = doc._auto, "_root"
    elif isinstance(doc, (MapProxy, ListProxy, TextProxy)):
        auto, obj = doc._auto, doc._obj
    else:
        raise TypeError("get_conflicts needs a Doc or an object proxy")
    all_vals = auto.get_all(obj, prop)
    if len(all_vals) <= 1:
        return None
    return {exid: _render(auto, rendered) for rendered, exid in all_vals}


def marks(doc: Doc, key: str):
    """Mark spans of a text field: ``doc[key].marks()`` (next.ts marks).
    Nested texts are reached through the proxies: ``doc["a"]["b"].marks()``."""
    v = doc[key]
    if not isinstance(v, TextProxy):
        raise ValueError(f"{key!r} is not a text field")
    return v.marks()


def _take(doc: Doc) -> AutoDoc:
    """Consume ``doc`` for a mutating operation: the new value keeps the
    SAME actor (seq continues), so the old value may no longer author
    changes — using it again raises, exactly like the JS wrapper's
    "attempting to change an outdated document" (stable.ts _change)."""
    if doc._superseded:
        raise RuntimeError(
            "attempting to change an outdated document; clone() it first"
        )
    # mark consumed BEFORE the operation runs so a reentrant take (e.g. a
    # change() callback calling change() on the same value, or a concurrent
    # thread) can't mint two changes with one (actor, seq); _untake() rolls
    # the flag back if the operation fails — the fork never touches
    # doc._auto, so no (actor, seq) was consumed and the value stays usable.
    object.__setattr__(doc, "_superseded", True)
    return doc._auto.fork(actor=doc._auto.get_actor())


def _untake(doc: Doc) -> None:
    object.__setattr__(doc, "_superseded", False)


def change(doc: Doc, fn_or_message, fn: Callable = None) -> Doc:
    """Apply ``fn(root_proxy)`` as one transaction on a NEW document value
    (reference: stable.ts:355 change())."""
    if fn is None:
        message, fn = None, fn_or_message
    else:
        message = fn_or_message
    auto = _take(doc)
    try:
        fn(MapProxy(auto, "_root"))
        auto.commit(message=message)
    except BaseException:
        _untake(doc)
        raise
    return Doc(auto)


def change_at(doc: Doc, heads: List[bytes], fn: Callable) -> Doc:
    """Change the document as of ``heads`` — the edit lands concurrent with
    everything since (reference: stable.ts changeAt / isolation)."""
    auto = _take(doc)
    try:
        auto.isolate(list(heads))
        fn(MapProxy(auto, "_root"))
        auto.integrate()
        auto.commit()
    except BaseException:
        _untake(doc)
        raise
    return Doc(auto)


# -- proxies ------------------------------------------------------------------


def _render(auto: AutoDoc, rendered):
    """One rendered (kind, payload) from get/get_all -> proxy or value."""
    if rendered[0] == "obj":
        t, exid = rendered[1], rendered[2]
        if t in (ObjType.MAP, ObjType.TABLE):
            return MapProxy(auto, exid)
        if t == ObjType.TEXT:
            return TextProxy(auto, exid)
        return ListProxy(auto, exid)
    if rendered[0] == "counter":
        return rendered[1]
    return rendered[1].to_py()


def _read_value(auto: AutoDoc, obj: str, key):
    got = auto.get(obj, key)
    if got is None:
        raise KeyError(key) if isinstance(key, str) else IndexError(key)
    return _render(auto, got[0])


def write_value(
    auto,
    obj: str,
    key,
    value,
    insert: bool = False,
    str_as_text: bool = False,
    sort_keys: bool = False,
):
    """Recursively assign a plain Python value at key/index, creating CRDT
    objects for containers. The one tree writer shared by the functional
    proxies (strings as scalars, like the reference's next API) and the
    CLI JSON importer (strings as TEXT objects, like the reference CLI —
    pass ``str_as_text=True, sort_keys=True``)."""

    def put_or_insert(v):
        if insert:
            auto.insert(obj, key, v)
        else:
            auto.put(obj, key, v)

    def make(obj_type):
        if insert:
            return auto.insert_object(obj, key, obj_type)
        return auto.put_object(obj, key, obj_type)

    if isinstance(value, Counter):
        put_or_insert(ScalarValue("counter", value.value))
    elif isinstance(value, Text) or (str_as_text and isinstance(value, str)):
        text = value.value if isinstance(value, Text) else value
        t = make(ObjType.TEXT)
        if text:
            auto.splice_text(t, 0, 0, text)
    elif isinstance(value, dict):
        m = make(ObjType.MAP)
        for k in sorted(value) if sort_keys else value:
            write_value(auto, m, k, value[k], str_as_text=str_as_text, sort_keys=sort_keys)
    elif isinstance(value, (list, tuple)):
        lst = make(ObjType.LIST)
        for i, v in enumerate(value):
            write_value(
                auto, lst, i, v,
                insert=True, str_as_text=str_as_text, sort_keys=sort_keys,
            )
    elif isinstance(value, (MapProxy, ListProxy, TextProxy)):
        raise TypeError("cannot re-assign a live proxy; build plain values")
    else:
        put_or_insert(value)


_write_value = write_value


class MapProxy:
    """dict-like view over a map object inside an open change()."""

    __slots__ = ("_auto", "_obj")

    def __init__(self, auto: AutoDoc, obj: str):
        self._auto = auto
        self._obj = obj

    def __getitem__(self, key: str):
        return _read_value(self._auto, self._obj, key)

    def __setitem__(self, key: str, value):
        _write_value(self._auto, self._obj, key, value)

    def __delitem__(self, key: str):
        self._auto.delete(self._obj, key)

    def __contains__(self, key: str) -> bool:
        return key in self._auto.keys(self._obj)

    def __iter__(self):
        return iter(self._auto.keys(self._obj))

    def __len__(self) -> int:
        return len(self._auto.keys(self._obj))

    def keys(self):
        return self._auto.keys(self._obj)

    def get(self, key, default=None):
        if key in self:
            return _read_value(self._auto, self._obj, key)
        return default

    def update(self, entries: dict):
        for k, v in entries.items():
            self[k] = v

    def increment(self, key: str, by: int = 1):
        self._auto.increment(self._obj, key, by)

    def to_py(self):
        return self._auto.hydrate(self._obj)

    def __repr__(self):
        return f"MapProxy({self.to_py()!r})"


class ListProxy:
    """list-like view over a list object inside an open change()."""

    __slots__ = ("_auto", "_obj")

    def __init__(self, auto: AutoDoc, obj: str):
        self._auto = auto
        self._obj = obj

    def _norm(self, i: int) -> int:
        n = len(self)
        if i < 0:
            i += n
        return i

    def __getitem__(self, i: int):
        return _read_value(self._auto, self._obj, self._norm(i))

    def __setitem__(self, i: int, value):
        _write_value(self._auto, self._obj, self._norm(i), value)

    def __delitem__(self, i: int):
        self._auto.delete(self._obj, self._norm(i))

    def __len__(self) -> int:
        return self._auto.length(self._obj)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def append(self, value):
        _write_value(self._auto, self._obj, len(self), value, insert=True)

    def insert(self, i: int, value):
        _write_value(self._auto, self._obj, self._norm(i), value, insert=True)

    def extend(self, values):
        for v in values:
            self.append(v)

    def pop(self, i: int = -1):
        i = self._norm(i)
        v = self[i]
        del self[i]
        return v

    def increment(self, i: int, by: int = 1):
        self._auto.increment(self._obj, self._norm(i), by)

    def to_py(self):
        return self._auto.hydrate(self._obj)

    def __repr__(self):
        return f"ListProxy({self.to_py()!r})"


class TextProxy:
    """str-like view over a text object inside an open change()."""

    __slots__ = ("_auto", "_obj")

    def __init__(self, auto: AutoDoc, obj: str):
        self._auto = auto
        self._obj = obj

    def __str__(self) -> str:
        return self._auto.text(self._obj)

    def __len__(self) -> int:
        return self._auto.length(self._obj)

    def splice(self, pos: int, delete: int, text: str = ""):
        self._auto.splice_text(self._obj, pos, delete, text)

    def insert(self, pos: int, text: str):
        self.splice(pos, 0, text)

    def delete(self, pos: int, length: int = 1):
        self.splice(pos, length, "")

    def append(self, text: str):
        self.splice(len(self), 0, text)

    def mark(self, start: int, end: int, name: str, value, expand="after"):
        self._auto.mark(self._obj, start, end, name, value, expand)

    def unmark(self, start: int, end: int, name: str, expand="none"):
        self._auto.unmark(self._obj, start, end, name, expand)

    def marks(self):
        return self._auto.marks(self._obj)

    def to_py(self) -> str:
        return str(self)

    def __repr__(self):
        return f"TextProxy({str(self)!r})"


def to_dict(doc: Doc):
    return doc._auto.hydrate()
