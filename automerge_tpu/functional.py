"""The idiomatic functional API: immutable document values + proxy trees.

The analogue of the reference's JS wrapper (reference:
javascript/src/stable.ts:194-1183 init/change/merge/..., proxies.ts:506-567
mapProxy/listProxy/textProxy): documents are treated as immutable values —
``change(doc, fn)`` hands ``fn`` a mutable proxy of the root and returns a
NEW document value; the input is untouched. Under the hood each value
wraps an AutoDoc; "immutability" is by-construction (operations fork
before mutating), not by copying state.

    import automerge_tpu.functional as am

    d1 = am.init()
    d2 = am.change(d1, lambda d: d.update({"title": "hello"}))
    d3 = am.change(d2, lambda d: d["items"].append("first"))
    d4 = am.merge(d3, other)
    data = am.save(d4)
"""

from __future__ import annotations

import copy as _copy
from typing import Callable, List, Optional

from .api import AutoDoc
from .types import ActorId, ObjType, ScalarValue

__all__ = [
    "Counter",
    "Doc",
    "Text",
    "apply_changes",
    "change",
    "change_at",
    "clone",
    "decode_change",
    "decode_sync_message",
    "decode_sync_state",
    "delete_at",
    "diff",
    "dump",
    "empty_change",
    "encode_change",
    "encode_sync_message",
    "encode_sync_state",
    "equals",
    "free",
    "generate_sync_message",
    "get_all_changes",
    "get_changes",
    "get_conflicts",
    "get_cursor",
    "get_cursor_position",
    "get_history",
    "get_last_local_change",
    "get_missing_deps",
    "get_object_id",
    "init_sync_state",
    "insert_at",
    "is_automerge",
    "load_incremental",
    "mark",
    "marks",
    "fork",
    "from_dict",
    "get_actor",
    "get_heads",
    "init",
    "load",
    "merge",
    "receive_sync_message",
    "save",
    "save_incremental",
    "save_since",
    "splice",
    "to_dict",
    "unmark",
    "view",
]


class Doc:
    """An immutable document value. Read like a dict; mutate via change()."""

    __slots__ = ("_auto", "_superseded", "_saved_heads")

    def __init__(self, auto: AutoDoc):
        object.__setattr__(self, "_auto", auto)
        object.__setattr__(self, "_superseded", False)
        # save_incremental() bookkeeping: heads as of the last save()/
        # save_incremental() on this value line (stable.ts saveIncremental
        # keeps the same cursor inside the wasm handle).
        object.__setattr__(self, "_saved_heads", [])

    # reads (delegate to a read-only proxy of the root)
    def __getitem__(self, key):
        return _read_value(self._auto, "_root", key)

    def __contains__(self, key) -> bool:
        return key in self._auto.keys("_root")

    def __iter__(self):
        return iter(self._auto.keys("_root"))

    def __len__(self) -> int:
        return len(self._auto.keys("_root"))

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def keys(self):
        return self._auto.keys("_root")

    def to_py(self):
        return self._auto.hydrate()

    def __eq__(self, other):
        if isinstance(other, Doc):
            return self._auto.hydrate() == other._auto.hydrate()
        return self._auto.hydrate() == other

    # content equality without content hashing: unhashable, loudly
    __hash__ = None

    def __repr__(self):
        return f"Doc({self._auto.hydrate()!r})"

    def __setattr__(self, *_):
        raise TypeError("documents are immutable; use change(doc, fn)")


class Counter:
    """Wraps an int so change() writes a CRDT counter, not a plain int."""

    __slots__ = ("value",)

    def __init__(self, value: int = 0):
        self.value = value


class Text:
    """Wraps a string so change() creates a TEXT object (char-wise CRDT)."""

    __slots__ = ("value",)

    def __init__(self, value: str = ""):
        self.value = value


# -- construction / lifecycle -------------------------------------------------


def init(actor: Optional[bytes] = None) -> Doc:
    return Doc(AutoDoc(actor=ActorId(actor) if actor else None))


def from_dict(contents: dict, actor: Optional[bytes] = None) -> Doc:
    """init + one change installing ``contents`` (reference: stable.ts from())."""
    return change(init(actor), lambda d: d.update(contents))


def load(data: bytes, actor: Optional[bytes] = None) -> Doc:
    doc = Doc(AutoDoc.load(data, actor=ActorId(actor) if actor else None))
    # loaded history counts as saved: save_incremental() right after load()
    # returns nothing, like the wasm handle (stable.ts load + saveIncremental)
    object.__setattr__(doc, "_saved_heads", doc._auto.get_heads())
    return doc


def save(doc: Doc) -> bytes:
    data = doc._auto.save()
    # save() resets the incremental cursor, like the wasm handle
    # (stable.ts saveIncremental returns nothing new after a save()).
    object.__setattr__(doc, "_saved_heads", doc._auto.get_heads())
    return data


def clone(doc: Doc, actor: Optional[bytes] = None) -> Doc:
    return Doc(doc._auto.fork(actor=ActorId(actor) if actor else None))


fork = clone


def get_heads(doc: Doc) -> List[bytes]:
    return doc._auto.get_heads()


def get_actor(doc: Doc) -> bytes:
    return doc._auto.get_actor().bytes


def merge(doc: Doc, other: Doc) -> Doc:
    """A new value containing both histories; the local input is consumed
    (reference: stable.ts:750-763 progressDocument). Merge itself creates
    no changes, but the new value continues the same actor/seq line — a
    later change() on both the pre- and post-merge values would mint two
    different changes with one (actor, seq), splitting the history."""
    merged = _take(doc)
    try:
        merged.merge(other._auto)
    except BaseException:
        _untake(doc)
        raise
    return _progress(doc, merged)


def get_changes(doc: Doc, have_deps: List[bytes] = ()) -> List[bytes]:
    """Raw change chunks not covered by ``have_deps`` (the JS wrapper's
    getChanges, stable.ts getChanges)."""
    return [c.raw_bytes for c in doc._auto.get_changes(list(have_deps))]


def get_last_local_change(doc: Doc) -> Optional[bytes]:
    c = doc._auto.get_last_local_change()
    return c.raw_bytes if c is not None else None


def apply_changes(doc: Doc, changes) -> Doc:
    """A new value with the raw change chunks applied; the input is
    consumed like merge() (stable.ts applyChanges via progressDocument)."""
    out = _take(doc)
    try:
        out.load_incremental(b"".join(changes), on_partial="error")
    except BaseException:
        _untake(doc)
        raise
    return _progress(doc, out)


def diff(doc: Doc, before: List[bytes], after: List[bytes]):
    """Patches transforming the view at ``before`` into the view at
    ``after`` (stable.ts diff)."""
    return doc._auto.diff(list(before), list(after))


def get_conflicts(doc, prop):
    """Conflicting values at ``prop`` as {opid-exid: value}, or None when
    at most one writer is visible (reference: stable.ts:829 getConflicts
    via conflicts.ts conflictAt — the keys are the writers' op ids, the
    values every concurrent candidate including the winner).

    ``doc`` is a Doc (root) or a nested Map/List proxy obtained through
    subscripting, matching the JS idiom ``getConflicts(doc.pets[0],
    "name")``."""
    if isinstance(doc, Doc):
        auto, obj = doc._auto, "_root"
    elif isinstance(doc, (MapProxy, ListProxy, TextProxy)):
        auto, obj = doc._auto, doc._obj
    else:
        raise TypeError("get_conflicts needs a Doc or an object proxy")
    all_vals = auto.get_all(obj, prop)
    if len(all_vals) <= 1:
        return None
    return {exid: _render(auto, rendered) for rendered, exid in all_vals}


def marks(doc: Doc, key: str):
    """Mark spans of a text field: ``doc[key].marks()`` (next.ts marks).
    Nested texts are reached through the proxies: ``doc["a"]["b"].marks()``."""
    v = doc[key]
    if not isinstance(v, TextProxy):
        raise ValueError(f"{key!r} is not a text field")
    return v.marks()


def _take(doc: Doc) -> AutoDoc:
    """Consume ``doc`` for a mutating operation: the new value keeps the
    SAME actor (seq continues), so the old value may no longer author
    changes — using it again raises, exactly like the JS wrapper's
    "attempting to change an outdated document" (stable.ts _change)."""
    if doc._superseded:
        raise RuntimeError(
            "attempting to change an outdated document; clone() it first"
        )
    # mark consumed BEFORE the operation runs so a reentrant take (e.g. a
    # change() callback calling change() on the same value, or a concurrent
    # thread) can't mint two changes with one (actor, seq); _untake() rolls
    # the flag back if the operation fails — the fork never touches
    # doc._auto, so no (actor, seq) was consumed and the value stays usable.
    object.__setattr__(doc, "_superseded", True)
    return doc._auto.fork(actor=doc._auto.get_actor())


def _untake(doc: Doc) -> None:
    object.__setattr__(doc, "_superseded", False)


def _progress(doc: Doc, auto: AutoDoc) -> Doc:
    """Wrap ``auto`` as the successor value of ``doc``, carrying the
    incremental-save cursor forward (stable.ts progressDocument)."""
    out = Doc(auto)
    object.__setattr__(out, "_saved_heads", list(doc._saved_heads))
    return out


def change(doc: Doc, fn_or_message, fn: Callable = None) -> Doc:
    """Apply ``fn(root_proxy)`` as one transaction on a NEW document value
    (reference: stable.ts:355 change())."""
    if fn is None:
        message, fn = None, fn_or_message
    else:
        message = fn_or_message
    auto = _take(doc)
    try:
        fn(MapProxy(auto, "_root"))
        auto.commit(message=message)
    except BaseException:
        _untake(doc)
        raise
    return _progress(doc, auto)


def change_at(doc: Doc, heads: List[bytes], fn: Callable) -> Doc:
    """Change the document as of ``heads`` — the edit lands concurrent with
    everything since (reference: stable.ts changeAt / isolation)."""
    auto = _take(doc)
    try:
        auto.isolate(list(heads))
        fn(MapProxy(auto, "_root"))
        auto.integrate()
        auto.commit()
    except BaseException:
        _untake(doc)
        raise
    return _progress(doc, auto)


# -- proxies ------------------------------------------------------------------


def _render(auto: AutoDoc, rendered):
    """One rendered (kind, payload) from get/get_all -> proxy or value."""
    if rendered[0] == "obj":
        t, exid = rendered[1], rendered[2]
        if t in (ObjType.MAP, ObjType.TABLE):
            return MapProxy(auto, exid)
        if t == ObjType.TEXT:
            return TextProxy(auto, exid)
        return ListProxy(auto, exid)
    if rendered[0] == "counter":
        return rendered[1]
    return rendered[1].to_py()


def _read_value(auto: AutoDoc, obj: str, key):
    got = auto.get(obj, key)
    if got is None:
        raise KeyError(key) if isinstance(key, str) else IndexError(key)
    return _render(auto, got[0])


def write_value(
    auto,
    obj: str,
    key,
    value,
    insert: bool = False,
    str_as_text: bool = False,
    sort_keys: bool = False,
):
    """Recursively assign a plain Python value at key/index, creating CRDT
    objects for containers. The one tree writer shared by the functional
    proxies (strings as scalars, like the reference's next API) and the
    CLI JSON importer (strings as TEXT objects, like the reference CLI —
    pass ``str_as_text=True, sort_keys=True``)."""

    def put_or_insert(v):
        if insert:
            auto.insert(obj, key, v)
        else:
            auto.put(obj, key, v)

    def make(obj_type):
        if insert:
            return auto.insert_object(obj, key, obj_type)
        return auto.put_object(obj, key, obj_type)

    if isinstance(value, Counter):
        put_or_insert(ScalarValue("counter", value.value))
    elif isinstance(value, Text) or (str_as_text and isinstance(value, str)):
        text = value.value if isinstance(value, Text) else value
        t = make(ObjType.TEXT)
        if text:
            auto.splice_text(t, 0, 0, text)
    elif isinstance(value, dict):
        m = make(ObjType.MAP)
        for k in sorted(value) if sort_keys else value:
            write_value(auto, m, k, value[k], str_as_text=str_as_text, sort_keys=sort_keys)
    elif isinstance(value, (list, tuple)):
        lst = make(ObjType.LIST)
        for i, v in enumerate(value):
            write_value(
                auto, lst, i, v,
                insert=True, str_as_text=str_as_text, sort_keys=sort_keys,
            )
    elif isinstance(value, (MapProxy, ListProxy, TextProxy)):
        raise TypeError("cannot re-assign a live proxy; build plain values")
    else:
        put_or_insert(value)


_write_value = write_value


class MapProxy:
    """dict-like view over a map object inside an open change()."""

    __slots__ = ("_auto", "_obj")

    def __init__(self, auto: AutoDoc, obj: str):
        self._auto = auto
        self._obj = obj

    def __getitem__(self, key: str):
        return _read_value(self._auto, self._obj, key)

    def __setitem__(self, key: str, value):
        _write_value(self._auto, self._obj, key, value)

    def __delitem__(self, key: str):
        self._auto.delete(self._obj, key)

    def __contains__(self, key: str) -> bool:
        return key in self._auto.keys(self._obj)

    def __iter__(self):
        return iter(self._auto.keys(self._obj))

    def __len__(self) -> int:
        return len(self._auto.keys(self._obj))

    def keys(self):
        return self._auto.keys(self._obj)

    def get(self, key, default=None):
        if key in self:
            return _read_value(self._auto, self._obj, key)
        return default

    def update(self, entries: dict):
        for k, v in entries.items():
            self[k] = v

    def increment(self, key: str, by: int = 1):
        self._auto.increment(self._obj, key, by)

    def to_py(self):
        return self._auto.hydrate(self._obj)

    def __repr__(self):
        return f"MapProxy({self.to_py()!r})"


class ListProxy:
    """list-like view over a list object inside an open change()."""

    __slots__ = ("_auto", "_obj")

    def __init__(self, auto: AutoDoc, obj: str):
        self._auto = auto
        self._obj = obj

    def _norm(self, i: int) -> int:
        n = len(self)
        if i < 0:
            i += n
        return i

    def __getitem__(self, i: int):
        return _read_value(self._auto, self._obj, self._norm(i))

    def __setitem__(self, i: int, value):
        _write_value(self._auto, self._obj, self._norm(i), value)

    def __delitem__(self, i: int):
        self._auto.delete(self._obj, self._norm(i))

    def __len__(self) -> int:
        return self._auto.length(self._obj)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def append(self, value):
        _write_value(self._auto, self._obj, len(self), value, insert=True)

    def insert(self, i: int, value):
        _write_value(self._auto, self._obj, self._norm(i), value, insert=True)

    def extend(self, values):
        for v in values:
            self.append(v)

    def pop(self, i: int = -1):
        i = self._norm(i)
        v = self[i]
        del self[i]
        return v

    def entries(self):
        """(index, value) pairs, like the JS list proxy's entries()
        (reference: proxies.ts listMethods entries)."""
        return enumerate(self)

    def values(self):
        return iter(self)

    def keys(self):
        return iter(range(len(self)))

    def splice(self, start: int, delete_count: int = None, *items):
        """JS Array.splice semantics (reference: proxies.ts list splice
        tests): remove ``delete_count`` entries at ``start`` (to the end
        when omitted), insert ``items`` there, return the removed values
        as plain python values."""
        n = len(self)
        start = max(0, min(start + n if start < 0 else start, n))
        if delete_count is None:
            delete_count = n - start
        delete_count = max(0, min(delete_count, n - start))
        removed = [
            v.to_py() if hasattr(v, "to_py") else v
            for v in (self[start + k] for k in range(delete_count))
        ]
        # one ranged primitive for the deletions (api.AutoDoc.splice),
        # then the shared tree writer per inserted item so containers
        # still become CRDT objects
        if delete_count:
            self._auto.splice(self._obj, start, delete_count, [])
        for off, v in enumerate(items):
            self.insert(start + off, v)
        return removed

    def increment(self, i: int, by: int = 1):
        self._auto.increment(self._obj, self._norm(i), by)

    def to_py(self):
        return self._auto.hydrate(self._obj)

    def __repr__(self):
        return f"ListProxy({self.to_py()!r})"


class TextProxy:
    """str-like view over a text object inside an open change()."""

    __slots__ = ("_auto", "_obj")

    def __init__(self, auto: AutoDoc, obj: str):
        self._auto = auto
        self._obj = obj

    def __str__(self) -> str:
        return self._auto.text(self._obj)

    def __len__(self) -> int:
        return self._auto.length(self._obj)

    def splice(self, pos: int, delete: int, text: str = ""):
        self._auto.splice_text(self._obj, pos, delete, text)

    def insert(self, pos: int, text: str):
        self.splice(pos, 0, text)

    def delete(self, pos: int, length: int = 1):
        self.splice(pos, length, "")

    def append(self, text: str):
        self.splice(len(self), 0, text)

    def mark(self, start: int, end: int, name: str, value, expand="after"):
        self._auto.mark(self._obj, start, end, name, value, expand)

    def unmark(self, start: int, end: int, name: str, expand="none"):
        self._auto.unmark(self._obj, start, end, name, expand)

    def marks(self):
        return self._auto.marks(self._obj)

    def to_py(self) -> str:
        return str(self)

    def __repr__(self):
        return f"TextProxy({str(self)!r})"


def to_dict(doc: Doc):
    return doc._auto.hydrate()


# -- lifecycle extras (stable.ts parity) --------------------------------------


def free(doc: Doc) -> None:
    """No-op: memory is GC-managed here (stable.ts:281 free() exists only
    for the wasm heap)."""


def is_automerge(value) -> bool:
    """True when ``value`` is a functional document value (stable.ts:1171)."""
    return isinstance(value, Doc)


def view(doc: Doc, heads: List[bytes]) -> Doc:
    """A read-only value of the document as of ``heads`` (stable.ts:235).
    change() on a view raises, exactly like the reference; clone() it to
    get a writable copy at those heads."""
    v = Doc(doc._auto.fork_at(list(heads)))
    object.__setattr__(v, "_superseded", True)  # writes must go via clone()
    return v


def empty_change(doc: Doc, message: Optional[str] = None,
                 timestamp: Optional[int] = None) -> Doc:
    """A new value with one change containing no ops — useful to ACK merged
    history (stable.ts:579 emptyChange)."""
    auto = _take(doc)
    try:
        # "" and absent encode identically in the chunk; a non-None message
        # is what arms the empty-commit path.
        auto.transaction(message=message or "", timestamp=timestamp).commit()
    except BaseException:
        _untake(doc)
        raise
    return _progress(doc, auto)


def equals(a, b) -> bool:
    """Deep value equality over documents and plain values (stable.ts:999) —
    history and actor ids are NOT compared, only contents. Doc.__eq__
    already hydrates both sides for every Doc/plain combination."""
    return a == b


def get_object_id(value) -> Optional[str]:
    """The exid of an object value, '_root' for a Doc, None for scalars
    (stable.ts:864 getObjectId)."""
    if isinstance(value, Doc):
        return "_root"
    if isinstance(value, (MapProxy, ListProxy, TextProxy)):
        return value._obj
    return None


def dump(doc: Doc, file=None) -> None:
    """Debug-print the op store (stable.ts:1157 dump)."""
    doc._auto.doc.dump(file)


# -- incremental save / load --------------------------------------------------


def save_incremental(doc: Doc) -> bytes:
    """The changes made since the last save()/save_incremental() on this
    value line, as raw chunk bytes (stable.ts:711 saveIncremental). The
    cursor travels with the value through change()/merge()."""
    data = doc._auto.save_incremental_after(list(doc._saved_heads))
    object.__setattr__(doc, "_saved_heads", doc._auto.get_heads())
    return data


def load_incremental(doc: Doc, data: bytes) -> Doc:
    """A new value with the raw chunk bytes applied; the input is consumed
    like merge() (stable.ts:673 loadIncremental)."""
    out = _take(doc)
    try:
        out.load_incremental(data, on_partial="error")
    except BaseException:
        _untake(doc)
        raise
    return _progress(doc, out)


def save_since(doc: Doc, heads: List[bytes]) -> bytes:
    """Changes not covered by ``heads`` as raw chunk bytes
    (stable.ts:1183 saveSince)."""
    return doc._auto.save_incremental_after(list(heads))


def get_all_changes(doc: Doc) -> List[bytes]:
    """Every change in the document's history (stable.ts:895)."""
    return get_changes(doc, [])


def get_missing_deps(doc: Doc, heads: List[bytes] = ()) -> List[bytes]:
    """Dependency hashes referenced but not present (stable.ts:1143)."""
    return doc._auto.get_missing_deps(list(heads))


# -- history ------------------------------------------------------------------


class HistoryState:
    """One entry of get_history(): a lazily-decoded change plus the lazily-
    materialised document snapshot after it (stable.ts:942 State<T>)."""

    __slots__ = ("_raw", "_index")

    def __init__(self, raw: List[bytes], index: int):
        self._raw = raw
        self._index = index

    @property
    def change(self) -> dict:
        return decode_change(self._raw[self._index])

    @property
    def snapshot(self) -> Doc:
        return apply_changes(init(), self._raw[: self._index + 1])

    def __repr__(self):
        return f"HistoryState(#{self._index}: {self.change['hash']})"


def get_history(doc: Doc) -> List[HistoryState]:
    """The document's change history in causal order, with lazy snapshots
    (stable.ts:942 getHistory — snapshot i applies changes 0..i to an
    empty doc, exactly like the reference)."""
    raw = get_all_changes(doc)
    return [HistoryState(raw, i) for i in range(len(raw))]


# -- change codec -------------------------------------------------------------


def decode_change(data: bytes) -> dict:
    """Parse one raw change chunk into its JSON form (stable.ts:1126
    decodeChange): actor/seq/startOp/time/message/deps/hash/ops."""
    from .expanded import expand_change
    from .storage.change import parse_change

    change_, _ = parse_change(bytes(data))
    return expand_change(change_)


def encode_change(expanded: dict) -> bytes:
    """Build the raw chunk bytes for a JSON-form change (stable.ts:1121
    encodeChange); decode_change(encode_change(x)) preserves the hash."""
    from .expanded import collapse_change

    return collapse_change(expanded).raw_bytes


# -- sync ---------------------------------------------------------------------


def init_sync_state():
    """Fresh per-peer sync state (stable.ts:1116 initSyncState)."""
    from .sync.protocol import SyncState

    return SyncState()


def encode_sync_state(state) -> bytes:
    """Persistable form of a sync state — only the durable part
    (shared heads) survives, like the reference (stable.ts:1016)."""
    return state.encode()


def decode_sync_state(data: bytes):
    """Inverse of encode_sync_state (stable.ts:1028)."""
    from .sync.protocol import SyncState

    return SyncState.decode(data)


def generate_sync_message(doc: Doc, state):
    """(new_state, message_bytes | None): the next message for the peer
    tracked by ``state`` (stable.ts:1046 — returns a fresh state instead
    of mutating the argument, matching the functional idiom)."""
    new_state = _copy.deepcopy(state)
    msg = doc._auto.generate_sync_message(new_state)
    return new_state, (msg.encode() if msg is not None else None)


def receive_sync_message(doc: Doc, state, message):
    """(new_doc, new_state) after applying a peer's sync message; the doc
    input is consumed like merge() (stable.ts:1074)."""
    from .sync.protocol import Message

    out = _take(doc)
    new_state = _copy.deepcopy(state)
    try:
        msg = Message.decode(message) if isinstance(message, (bytes, bytearray)) else message
        out.receive_sync_message(new_state, msg)
    except BaseException:
        _untake(doc)
        raise
    return _progress(doc, out), new_state


def encode_sync_message(message) -> bytes:
    """Message object -> wire bytes (stable.ts:1131)."""
    return message.encode()


def decode_sync_message(data: bytes):
    """Wire bytes -> Message object for inspection (stable.ts:1136)."""
    from .sync.protocol import Message

    return Message.decode(data)


# -- path-addressed edits & cursors (next.ts parity) --------------------------


def _resolve_path(root, path):
    cur = root
    for p in path:
        cur = cur[p]
    return cur


def insert_at(list_proxy, index: int, *values):
    """Insert values into a list or text draft inside change()
    (stable.ts:108 insertAt — splice semantics, so a negative index is
    normalised ONCE against the pre-insert length)."""
    if isinstance(list_proxy, TextProxy):
        list_proxy.insert(index if index >= 0 else len(list_proxy) + index,
                          "".join(values))
        return
    if not isinstance(list_proxy, ListProxy):
        raise TypeError("insert_at needs a list or text draft from change()")
    if index < 0:
        index += len(list_proxy)
    for off, v in enumerate(values):
        list_proxy.insert(index + off, v)


def delete_at(list_proxy, index: int, num: int = 1):
    """Delete ``num`` values from a list/text draft (stable.ts:122
    deleteAt — splice semantics, so a negative index is normalised ONCE
    against the pre-delete length)."""
    if index < 0:
        index += len(list_proxy)
    if isinstance(list_proxy, TextProxy):
        list_proxy.delete(index, num)
        return
    if not isinstance(list_proxy, ListProxy):
        raise TypeError("delete_at needs a list or text draft from change()")
    for _ in range(num):
        del list_proxy[index]


def splice(draft, path: list, index, delete: int, new_text: str = ""):
    """Splice a text (or list) found at ``path`` under a change() draft
    (next.ts:289 splice). ``index`` may be a cursor string."""
    target = _resolve_path(draft, path)
    if not isinstance(target, (TextProxy, ListProxy)):
        raise TypeError("splice needs a text or list at the given path")
    if isinstance(index, str):
        index = target._auto.get_cursor_position(target._obj, index)
    if isinstance(target, TextProxy):
        target.splice(index, delete, new_text)
    else:
        delete_at(target, index, delete)
        insert_at(target, index, *new_text)


def get_cursor(doc, path: list, index: int) -> str:
    """A stable cursor for position ``index`` of the text/list at ``path``
    (next.ts:336 getCursor)."""
    target = _resolve_path(doc, path)
    if not isinstance(target, (TextProxy, ListProxy)):
        raise TypeError("get_cursor needs a text or list at the given path")
    return target._auto.get_cursor(target._obj, index)


def get_cursor_position(doc, path: list, cursor: str) -> int:
    """The current index of ``cursor`` in the text/list at ``path``
    (next.ts:366 getCursorPosition)."""
    target = _resolve_path(doc, path)
    if not isinstance(target, (TextProxy, ListProxy)):
        raise TypeError("get_cursor_position needs a text or list at the given path")
    return target._auto.get_cursor_position(target._obj, cursor)


def mark(draft, path: list, range_, name: str, value):
    """Mark a span of the text at ``path`` inside change() (next.ts:387).
    ``range_`` is (start, end) or {'start':..., 'end':..., 'expand':...}."""
    target = _resolve_path(draft, path)
    if not isinstance(target, TextProxy):
        raise TypeError("mark needs a text at the given path")
    if isinstance(range_, dict):
        start, end = range_["start"], range_["end"]
        expand = range_.get("expand", "after")
    else:
        start, end = range_
        expand = "after"
    target.mark(start, end, name, value, expand)


def unmark(draft, path: list, range_, name: str):
    """Remove a mark from a span (next.ts:413 unmark)."""
    target = _resolve_path(draft, path)
    if not isinstance(target, TextProxy):
        raise TypeError("unmark needs a text at the given path")
    if isinstance(range_, dict):
        start, end = range_["start"], range_["end"]
        expand = range_.get("expand", "none")
    else:
        start, end = range_
        expand = "none"
    target.unmark(start, end, name, expand)
