"""Graphviz dot dumps of document structure.

The reference renders its per-object op trees to dot behind the
``optree-visualisation`` feature (reference:
rust/automerge/src/visualisation.rs, op_set.rs:265-285 visualise,
automerge.rs:1241-1256 visualise_optree). There is no B-tree here, so the
faithful analogue renders what this design actually is: one cluster per
object, element/op nodes in document order with the RGA insert-parent
edges, winners highlighted, tombstones greyed — plus a change-graph view
(the causal DAG, change_graph.rs's structure).

Usage::

    from automerge_tpu.visualisation import doc_to_dot, changes_to_dot
    open("doc.dot", "w").write(doc_to_dot(doc))   # dot -Tsvg doc.dot
"""

from __future__ import annotations

from typing import List

from .core.op_store import MapObject


def _esc(s: str) -> str:
    return str(s).replace("\\", "\\\\").replace('"', '\\"')


def _value_label(op) -> str:
    from .types import is_make_action, objtype_for_action

    if is_make_action(op.action):
        return f"make {objtype_for_action(op.action).name.lower()}"
    v = op.value
    if v.tag == "str":
        return repr(v.value)
    return f"{v.tag} {v.value!r}"


def doc_to_dot(doc) -> str:
    """The document's objects/ops as a dot graph (current materialized
    state; accepts Document or AutoDoc)."""
    d = getattr(doc, "doc", doc)
    lines: List[str] = [
        "digraph automerge {",
        "  rankdir=LR; node [shape=box, fontsize=9, fontname=monospace];",
    ]
    store = d.ops
    for n, obj_id in enumerate(store.objects):
        info = store.get_obj(obj_id)
        exid = d.export_id(obj_id)
        lines.append(f'  subgraph cluster_{n} {{ label="{_esc(exid)}";')
        if isinstance(info.data, MapObject):
            for key_idx in sorted(info.data.props):
                key = d.props.get(key_idx)
                for op in info.data.props[key_idx]:
                    oid = d.export_id(op.id)
                    vis = op.visible_at(None)
                    style = "filled" if vis else "dashed"
                    fill = ', fillcolor="lightblue"' if vis else ""
                    lines.append(
                        f'    "{_esc(oid)}" [label="{_esc(key)} = '
                        f'{_esc(_value_label(op))}\\n{_esc(oid)}", '
                        f'style="{style}"{fill}];'
                    )
        else:
            from .types import Action

            prev = None
            for el in info.data.elements():
                eid = d.export_id(el.elem_id)
                w = el.winner()
                if w is not None:
                    label, style, fill = (
                        _value_label(w), "filled", ', fillcolor="lightyellow"'
                    )
                elif el.op is not None and el.op.action == Action.MARK:
                    name = el.op.mark_name or "(end)"
                    label, style, fill = (
                        f"mark {name}", "dotted", ', fillcolor="mistyrose"'
                    )
                else:
                    label, style, fill = "(tombstone)", "dashed", ""
                lines.append(
                    f'    "{_esc(eid)}" [label="{_esc(label)}\\n{_esc(eid)}", '
                    f'style="{style}"{fill}];'
                )
                if prev is not None:
                    lines.append(f'    "{_esc(prev)}" -> "{_esc(eid)}";')
                prev = eid
        lines.append("  }")
    lines.append("}")
    return "\n".join(lines)


def changes_to_dot(doc) -> str:
    """The causal change DAG as dot: one node per change (short hash,
    actor, seq, op count), edges to dependencies."""
    d = getattr(doc, "doc", doc)
    lines = [
        "digraph changes {",
        "  rankdir=BT; node [shape=box, fontsize=9, fontname=monospace];",
    ]
    heads = set(d.get_heads())
    for a in d.history:
        st = a.stored
        h = st.hash.hex()[:8]
        actor_hex = bytes(st.actor).hex()[:8]
        fill = ', style="filled", fillcolor="palegreen"' if st.hash in heads else ""
        lines.append(
            f'  "{h}" [label="{h}\\n{actor_hex} seq {st.seq}\\n'
            f'{len(st.ops)} ops"{fill}];'
        )
        for dep in st.dependencies:
            lines.append(f'  "{h}" -> "{dep.hex()[:8]}";')
    lines.append("}")
    return "\n".join(lines)
