"""automerge_tpu: a TPU-native CRDT framework with Automerge's capabilities.

A JSON-like document (nested maps / lists / text / counters) that any number
of actors mutate independently and merge deterministically, with a
byte-compatible columnar storage format and Bloom-filter sync protocol —
re-architected for TPU: op logs live as columnar JAX device arrays and N-way
replica merge runs as batched kernels (segmented Lamport sort + pred/succ
resolution + visibility masking).

Reference behavior: aasthaagarwal2003/automerge (see SURVEY.md).
"""

__version__ = "0.3.0"

from .api import AutoDoc  # noqa: F401
from .core.document import AutomergeError, Document, ROOT  # noqa: F401
from .core.transaction import Transaction  # noqa: F401
from .types import (  # noqa: F401
    Action,
    ActorId,
    ObjType,
    ScalarValue,
    get_text_encoding,
    set_text_encoding,
)

# subsystem entry points (imported lazily by most callers):
#   .ops        device op log + batched merge (DeviceDoc, OpLog)
#   .functional idiomatic immutable-value API (init/change/merge)
#   .sync       Bloom-filter sync protocol
#   .patches    patch log / diff / materialization
#   .testing    conflict-aware test DSL (assert_doc / map_ / list_)
#   .errors     typed error hierarchy
#   .capi       C ABI frontend build helpers
#   .obs        observability: labeled metrics registry, hierarchical
#               spans (Perfetto export), Prometheus exposition
#   .trace      tracing shims over .obs (count/time/span/event)
