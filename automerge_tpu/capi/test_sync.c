/* Sync-suite scenarios ported from the reference's wasm/C sync tests
 * (behavioral port of rust/automerge-c/test/ported_wasm/sync_tests.c,
 * re-expressed against this framework's am.h; no code copied) plus the
 * round-3 sync-state encode/decode surface.
 */
#include <stdio.h>
#include <string.h>

#include "am.h"
#include "test_util.h"

static uint8_t msg[1 << 20];
static uint8_t buf[1 << 20];
static char sbuf[1024];

/* run the full sync loop between two docs; returns rounds (-1 = no
 * convergence within the budget) */
static int sync_loop(AMdoc *a, AMdoc *b, AMsyncState *sa, AMsyncState *sb) {
  for (int round = 0; round < 40; round++) {
    AMresult *ma = am_generate_sync_message(a, sa);
    AMresult *mb = am_generate_sync_message(b, sb);
    if (!res_ok(ma) || !res_ok(mb)) {
      am_result_free(ma);
      am_result_free(mb);
      return -1;
    }
    int quiet = am_result_size(ma) == 0 && am_result_size(mb) == 0;
    if (am_result_size(ma) > 0) {
      size_t len = 0;
      const uint8_t *p = am_item_bytes(ma, 0, &len);
      memcpy(msg, p, len);
      AMresult *r = am_receive_sync_message(b, sb, msg, len);
      if (!res_ok(r)) quiet = -1;
      am_result_free(r);
    }
    if (am_result_size(mb) > 0) {
      size_t len = 0;
      const uint8_t *p = am_item_bytes(mb, 0, &len);
      memcpy(msg, p, len);
      AMresult *r = am_receive_sync_message(a, sa, msg, len);
      if (!res_ok(r)) quiet = -1;
      am_result_free(r);
    }
    am_result_free(ma);
    am_result_free(mb);
    if (quiet == 1) return round;
    if (quiet < 0) return -1;
  }
  return -1;
}

static int heads_equal(AMdoc *a, AMdoc *b) {
  static uint8_t ha[32 * 64], hb[32 * 64];
  size_t na = res_heads(am_get_heads(a), ha, 64);
  size_t nb = res_heads(am_get_heads(b), hb, 64);
  return na == nb && memcmp(ha, hb, 32 * na) == 0;
}

/* -- an empty local doc still announces itself ----------------------------- */
static void test_empty_doc_sends_message(void) {
  AMdoc *a = am_create(NULL, 0);
  AMsyncState *s = am_sync_state_new();
  AMresult *m = am_generate_sync_message(a, s);
  CHECK(res_ok(m) && am_result_size(m) == 1); /* heads+need+have, no changes */
  am_result_free(m);
  am_sync_state_free(s);
  am_doc_free(a);
}

/* -- two empty docs converge to silence ------------------------------------- */
static void test_empty_docs_converge(void) {
  AMdoc *a = am_create(NULL, 0);
  AMdoc *b = am_create(NULL, 0);
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(heads_equal(a, b));
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- n1 offers everything to an empty n2 ------------------------------------ */
static void test_offer_all_changes_from_nothing(void) {
  uint8_t a1[1] = {1};
  AMdoc *a = am_create(a1, 1);
  AMresult *r = am_map_put_object(a, AM_ROOT, "l", AM_OBJ_LIST);
  char l[128];
  strncpy(l, am_item_str(r, 0), sizeof l - 1);
  am_result_free(r);
  for (int i = 0; i < 10; i++) {
    CHECK_OK(am_list_insert_int(a, l, (size_t)i, i));
    CHECK_OK(am_commit(a, NULL));
  }
  AMdoc *b = am_create(NULL, 0);
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(heads_equal(a, b));
  CHECK(res_int(am_length(b, l)) == 10);
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- sync peers where one has commits the other lacks ----------------------- */
static void test_one_sided_commits(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *a = am_create(a1, 1);
  CHECK_OK(am_map_put_int(a, AM_ROOT, "base", 0));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_fork(a, a2, 1);
  for (int i = 0; i < 5; i++) {
    char key[16];
    snprintf(key, sizeof key, "k%d", i);
    CHECK_OK(am_map_put_int(a, AM_ROOT, key, i));
    CHECK_OK(am_commit(a, NULL));
  }
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(heads_equal(a, b));
  CHECK(res_int(am_map_get(b, AM_ROOT, "k4")) == 4);
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- bidirectional concurrent edits converge -------------------------------- */
static void test_bidirectional_concurrent(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *a = am_create(a1, 1);
  AMresult *r = am_map_put_object(a, AM_ROOT, "t", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(a, t, 0, 0, "shared"));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_fork(a, a2, 1);
  CHECK_OK(am_splice_text(a, t, 0, 0, "A:"));
  CHECK_OK(am_map_put_int(a, AM_ROOT, "from_a", 1));
  CHECK_OK(am_commit(a, NULL));
  CHECK_OK(am_splice_text(b, t, 6, 0, ":B"));
  CHECK_OK(am_map_put_int(b, AM_ROOT, "from_b", 2));
  CHECK_OK(am_commit(b, NULL));
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(heads_equal(a, b));
  char ta[64], tb[64];
  res_str(am_text(a, t), ta, sizeof ta);
  res_str(am_text(b, t), tb, sizeof tb);
  CHECK(strcmp(ta, tb) == 0);
  CHECK(res_int(am_map_get(a, AM_ROOT, "from_b")) == 2);
  CHECK(res_int(am_map_get(b, AM_ROOT, "from_a")) == 1);
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- no messages once synced ------------------------------------------------ */
static void test_quiet_once_synced(void) {
  uint8_t a1[1] = {1};
  AMdoc *a = am_create(a1, 1);
  CHECK_OK(am_map_put_int(a, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_create(NULL, 0);
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  /* both generators now return empty */
  AMresult *m = am_generate_sync_message(a, sa);
  CHECK(res_ok(m) && am_result_size(m) == 0);
  am_result_free(m);
  m = am_generate_sync_message(b, sb);
  CHECK(res_ok(m) && am_result_size(m) == 0);
  am_result_free(m);
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- works with (persisted) prior sync state -------------------------------- */
static void test_prior_sync_state_roundtrip(void) {
  uint8_t a1[1] = {1};
  AMdoc *a = am_create(a1, 1);
  CHECK_OK(am_map_put_int(a, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_create(NULL, 0);
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);

  /* after convergence both peers record the same shared heads */
  AMresult *sh = am_sync_state_shared_heads(sa);
  CHECK(res_ok(sh) && am_result_size(sh) == 1);
  am_result_free(sh);

  /* persist both states (only shared_heads survives, by design) */
  size_t la = res_bytes(am_sync_state_encode(sa), buf, sizeof buf);
  CHECK(la > 0);
  AMsyncState *sa2 = am_sync_state_decode(buf, la);
  CHECK(sa2 != NULL);
  size_t lb = res_bytes(am_sync_state_encode(sb), buf, sizeof buf);
  AMsyncState *sb2 = am_sync_state_decode(buf, lb);
  CHECK(sb2 != NULL);
  am_sync_state_free(sa);
  am_sync_state_free(sb);

  /* more edits on a; resumed states catch b up without a full resync */
  CHECK_OK(am_map_put_int(a, AM_ROOT, "y", 2));
  CHECK_OK(am_commit(a, NULL));
  CHECK(sync_loop(a, b, sa2, sb2) >= 0);
  CHECK(heads_equal(a, b));
  CHECK(res_int(am_map_get(b, AM_ROOT, "y")) == 2);
  am_sync_state_free(sa2);
  am_sync_state_free(sb2);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- resync after one peer crashes with data loss --------------------------- */
static void test_resync_after_data_loss(void) {
  uint8_t a1[1] = {1};
  AMdoc *a = am_create(a1, 1);
  CHECK_OK(am_map_put_int(a, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_create(NULL, 0);
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(heads_equal(a, b));

  /* b crashes and restarts empty with a FRESH state; a keeps its old
   * state that believes b has everything — sync must still recover */
  am_doc_free(b);
  am_sync_state_free(sb);
  b = am_create(NULL, 0);
  sb = am_sync_state_new();
  am_sync_state_free(sa);
  sa = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(heads_equal(a, b));
  CHECK(res_int(am_map_get(b, AM_ROOT, "x")) == 1);
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- heavy branching / merging histories ------------------------------------ */
static void test_branching_histories(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *a = am_create(a1, 1);
  CHECK_OK(am_map_put_int(a, AM_ROOT, "seed", 0));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_fork(a, a2, 1);
  /* alternating concurrent rounds with periodic merges */
  for (int i = 0; i < 8; i++) {
    char ka[16], kb[16];
    snprintf(ka, sizeof ka, "a%d", i);
    snprintf(kb, sizeof kb, "b%d", i);
    CHECK_OK(am_map_put_int(a, AM_ROOT, ka, i));
    CHECK_OK(am_commit(a, NULL));
    CHECK_OK(am_map_put_int(b, AM_ROOT, kb, i));
    CHECK_OK(am_commit(b, NULL));
    if (i % 3 == 2) {
      CHECK_OK(am_merge(a, b));
    }
  }
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(heads_equal(a, b));
  CHECK(res_int(am_map_get(b, AM_ROOT, "a7")) == 7);
  CHECK(res_int(am_map_get(a, AM_ROOT, "b7")) == 7);
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- three peers in a chain converge ---------------------------------------- */
static void test_three_peer_chain(void) {
  uint8_t a1[1] = {1}, a2[1] = {2}, a3[1] = {3};
  AMdoc *a = am_create(a1, 1);
  CHECK_OK(am_map_put_int(a, AM_ROOT, "origin", 1));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_fork(a, a2, 1);
  AMdoc *c = am_fork(a, a3, 1);
  CHECK_OK(am_map_put_int(a, AM_ROOT, "from_a", 1));
  CHECK_OK(am_commit(a, NULL));
  CHECK_OK(am_map_put_int(c, AM_ROOT, "from_c", 3));
  CHECK_OK(am_commit(c, NULL));
  /* a <-> b, then b <-> c: c's and a's edits flow through b */
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  AMsyncState *s3 = am_sync_state_new(), *s4 = am_sync_state_new();
  CHECK(sync_loop(a, b, s1, s2) >= 0);
  CHECK(sync_loop(b, c, s3, s4) >= 0);
  CHECK(res_int(am_map_get(c, AM_ROOT, "from_a")) == 1);
  CHECK(res_int(am_map_get(b, AM_ROOT, "from_c")) == 3);
  CHECK(sync_loop(a, b, s1, s2) >= 0);
  CHECK(res_int(am_map_get(a, AM_ROOT, "from_c")) == 3);
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_sync_state_free(s3);
  am_sync_state_free(s4);
  am_doc_free(c);
  am_doc_free(b);
  am_doc_free(a);
}

/* -- sync transfers marks and counters intact -------------------------------- */
static void test_sync_rich_content(void) {
  uint8_t a1[1] = {1};
  AMdoc *a = am_create(a1, 1);
  AMresult *r = am_map_put_object(a, AM_ROOT, "t", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(a, t, 0, 0, "rich content"));
  CHECK_OK(am_mark_str(a, t, 0, 4, "style", "heading", "after"));
  CHECK_OK(am_map_put_counter(a, AM_ROOT, "n", 5));
  CHECK_OK(am_map_increment(a, AM_ROOT, "n", 2));
  CHECK_OK(am_commit(a, NULL));
  AMdoc *b = am_create(NULL, 0);
  AMsyncState *sa = am_sync_state_new(), *sb = am_sync_state_new();
  CHECK(sync_loop(a, b, sa, sb) >= 0);
  CHECK(strcmp(res_str(am_text(b, t), sbuf, sizeof sbuf), "rich content") == 0);
  CHECK(res_int(am_map_get(b, AM_ROOT, "n")) == 7);
  AMresult *ms = am_marks(b, t);
  CHECK(res_ok(ms) && am_result_size(ms) == 4);
  CHECK(strcmp(am_item_str(ms, 3), "heading") == 0);
  am_result_free(ms);
  am_sync_state_free(sa);
  am_sync_state_free(sb);
  am_doc_free(b);
  am_doc_free(a);
}

int main(void) {
  if (am_init() != 0) {
    fprintf(stderr, "am_init failed\n");
    return 2;
  }
  test_empty_doc_sends_message();
  test_empty_docs_converge();
  test_offer_all_changes_from_nothing();
  test_one_sided_commits();
  test_bidirectional_concurrent();
  test_quiet_once_synced();
  test_prior_sync_state_roundtrip();
  test_resync_after_data_loss();
  test_branching_histories();
  test_three_peer_chain();
  test_sync_rich_content();
  int rc = am_test_finish("test_sync");
  am_shutdown();
  return rc;
}
