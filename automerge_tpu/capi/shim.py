"""Python side of the C ABI frontend (see am.h / am_embed.cpp).

The embedded interpreter calls ONE entry point, ``call(fn, *args)``, which
returns a flat list of (tag, payload) item tuples — the AMitem model of
the reference's C frontend (reference: automerge-c/src/item.rs tagged
AMitem values, result.rs AMresult item sequences). Keeping the
marshalling here means the C layer never touches framework objects, only
ints/floats/str/bytes.

Documents and sync states are held in registries keyed by int64 handles;
the C ``AMdoc``/``AMsyncState`` structs wrap those handles.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..api import AutoDoc
from ..sync import SyncState
from ..types import ActorId, ObjType, ScalarValue

# item tags — MUST match the AMvalType enum in am.h
VOID = 0
NULL = 1
BOOL = 2
INT = 3
UINT = 4
F64 = 5
STR = 6
BYTES = 7
COUNTER = 8
TIMESTAMP = 9
OBJ_ID = 10
HANDLE = 11

_OBJTYPE = {0: ObjType.MAP, 1: ObjType.LIST, 2: ObjType.TEXT, 3: ObjType.TABLE}
_OBJTYPE_CODE = {v: k for k, v in _OBJTYPE.items()}

_docs: Dict[int, AutoDoc] = {}
_syncs: Dict[int, SyncState] = {}
_next_handle = 1

Item = Tuple[int, object]


def _register(table, value) -> int:
    global _next_handle
    h = _next_handle
    _next_handle += 1
    table[h] = value
    return h


def _doc(h: int) -> AutoDoc:
    doc = _docs.get(h)
    if doc is None:
        raise ValueError(f"invalid document handle {h}")
    return doc


def _scalar(tag: int, payload) -> object:
    if tag == NULL:
        return ScalarValue("null")
    if tag == BOOL:
        return ScalarValue("bool", bool(payload))
    if tag == INT:
        return ScalarValue("int", int(payload))
    if tag == UINT:
        return ScalarValue("uint", int(payload))
    if tag == F64:
        return ScalarValue("f64", float(payload))
    if tag == STR:
        return ScalarValue("str", payload)
    if tag == BYTES:
        return ScalarValue("bytes", payload)
    if tag == COUNTER:
        return ScalarValue("counter", int(payload))
    if tag == TIMESTAMP:
        return ScalarValue("timestamp", int(payload))
    raise ValueError(f"unsupported value tag {tag}")


def _render_item(rendered, exid) -> List[Item]:
    kind = rendered[0]
    if kind == "obj":
        return [(OBJ_ID, exid)]
    if kind == "counter":
        return [(COUNTER, int(rendered[1]))]
    sv = rendered[1]
    tag = {
        "null": NULL, "bool": BOOL, "int": INT, "uint": UINT, "f64": F64,
        "str": STR, "bytes": BYTES, "counter": COUNTER, "timestamp": TIMESTAMP,
    }.get(sv.tag)
    if tag is None:
        return [(BYTES, bytes(sv.value[1]))]  # unknown: raw payload
    if tag == BOOL:
        return [(BOOL, 1 if sv.value else 0)]
    if tag == NULL:
        return [(NULL, 0)]
    return [(tag, sv.value)]


# -- entry points (dispatched by name from C) ---------------------------------


def create(actor: bytes) -> List[Item]:
    doc = AutoDoc(actor=ActorId(actor) if actor else None)
    return [(HANDLE, _register(_docs, doc))]


def load(data: bytes) -> List[Item]:
    return [(HANDLE, _register(_docs, AutoDoc.load(data)))]


def fork(h: int, actor: bytes) -> List[Item]:
    doc = _doc(h).fork(actor=ActorId(actor) if actor else None)
    return [(HANDLE, _register(_docs, doc))]


def free(h: int) -> List[Item]:
    _docs.pop(h, None)
    return []


def save(h: int) -> List[Item]:
    return [(BYTES, _doc(h).save())]


def commit(h: int, message) -> List[Item]:
    hash_ = _doc(h).commit(message=message or None)
    return [(BYTES, hash_)] if hash_ is not None else []


def merge(h: int, other: int) -> List[Item]:
    return [(BYTES, x) for x in _doc(h).merge(_doc(other))]


def put(h: int, obj: str, key: str, tag: int, payload) -> List[Item]:
    _doc(h).put(obj, key, _scalar(tag, payload))
    return []


def put_object(h: int, obj: str, key: str, objtype: int) -> List[Item]:
    return [(OBJ_ID, _doc(h).put_object(obj, key, _OBJTYPE[objtype]))]


def insert(h: int, obj: str, index: int, tag: int, payload) -> List[Item]:
    _doc(h).insert(obj, index, _scalar(tag, payload))
    return []


def insert_object(h: int, obj: str, index: int, objtype: int) -> List[Item]:
    return [(OBJ_ID, _doc(h).insert_object(obj, index, _OBJTYPE[objtype]))]


def list_put(h: int, obj: str, index: int, tag: int, payload) -> List[Item]:
    _doc(h).put(obj, index, _scalar(tag, payload))
    return []


def delete(h: int, obj: str, key: str) -> List[Item]:
    _doc(h).delete(obj, key)
    return []


def list_delete(h: int, obj: str, index: int) -> List[Item]:
    _doc(h).delete(obj, index)
    return []


def increment(h: int, obj: str, key: str, by: int) -> List[Item]:
    _doc(h).increment(obj, key, by)
    return []


def list_increment(h: int, obj: str, index: int, by: int) -> List[Item]:
    _doc(h).increment(obj, index, by)
    return []


def splice_text(h: int, obj: str, pos: int, delete_n: int, text: str) -> List[Item]:
    _doc(h).splice_text(obj, pos, delete_n, text)
    return []


def text(h: int, obj: str) -> List[Item]:
    return [(STR, _doc(h).text(obj))]


def length(h: int, obj: str) -> List[Item]:
    return [(UINT, _doc(h).length(obj))]


def keys(h: int, obj: str) -> List[Item]:
    return [(STR, k) for k in _doc(h).keys(obj)]


def get(h: int, obj: str, key: str) -> List[Item]:
    got = _doc(h).get(obj, key)
    return _render_item(*got) if got is not None else []


def list_get(h: int, obj: str, index: int) -> List[Item]:
    got = _doc(h).get(obj, index)
    return _render_item(*got) if got is not None else []


def get_all(h: int, obj: str, key) -> List[Item]:
    out: List[Item] = []
    for rendered, exid in _doc(h).get_all(obj, key):
        out.extend(_render_item(rendered, exid))
    return out


def get_heads(h: int) -> List[Item]:
    return [(BYTES, x) for x in _doc(h).get_heads()]


def actor_id(h: int) -> List[Item]:
    return [(BYTES, _doc(h).get_actor().bytes)]


_EXPANDS = ("none", "before", "after", "both")


def _check_expand(expand: str) -> str:
    if expand not in _EXPANDS:
        raise ValueError(f"expand must be one of {_EXPANDS}, got {expand!r}")
    return expand


def mark_str(h: int, obj: str, start: int, end: int, name: str, value: str, expand: str) -> List[Item]:
    _doc(h).mark(obj, start, end, name, value, expand=_check_expand(expand))
    return []


def mark_null(h: int, obj: str, start: int, end: int, name: str, expand: str) -> List[Item]:
    # a null-valued mark clears ``name`` over the span (Peritext unmark)
    _doc(h).mark(obj, start, end, name, None, expand=_check_expand(expand))
    return []


def mark_bool(h: int, obj: str, start: int, end: int, name: str, value: int, expand: str) -> List[Item]:
    _doc(h).mark(obj, start, end, name, bool(value), expand=_check_expand(expand))
    return []


def unmark(h: int, obj: str, start: int, end: int, name: str) -> List[Item]:
    _doc(h).unmark(obj, start, end, name)
    return []


def marks(h: int, obj: str) -> List[Item]:
    return _marks_items(_doc(h).marks(obj))


def get_cursor(h: int, obj: str, pos: int) -> List[Item]:
    return [(STR, _doc(h).get_cursor(obj, pos))]


def get_cursor_position(h: int, obj: str, cursor: str) -> List[Item]:
    return [(UINT, _doc(h).get_cursor_position(obj, cursor))]


def apply_change_bytes(h: int, data: bytes) -> List[Item]:
    _doc(h).load_incremental(data, on_partial="error")
    return []


def save_incremental(h: int, heads_blob: bytes) -> List[Item]:
    return [(BYTES, _doc(h).save_incremental_after(_heads(heads_blob)))]


def sync_state_new() -> List[Item]:
    return [(HANDLE, _register(_syncs, SyncState()))]


def sync_state_free(h: int) -> List[Item]:
    _syncs.pop(h, None)
    return []


def generate_sync_message(h: int, sh: int) -> List[Item]:
    msg = _doc(h).generate_sync_message(_syncs[sh])
    return [(BYTES, msg.encode())] if msg is not None else []


def receive_sync_message(h: int, sh: int, data: bytes) -> List[Item]:
    from ..sync.protocol import Message

    _doc(h).receive_sync_message(_syncs[sh], Message.decode(data))
    return []


# -- historical reads (*_at) --------------------------------------------------
#
# Heads travel as concatenated 32-byte hashes (the am_get_heads item bytes
# back to back) — the same convention am_save_incremental established.


def _heads(blob: bytes) -> List[bytes]:
    if len(blob) % 32:
        raise ValueError("heads blob must be a multiple of 32 bytes")
    return [blob[i : i + 32] for i in range(0, len(blob), 32)]


def get_at(h: int, obj: str, key: str, heads: bytes) -> List[Item]:
    got = _doc(h).get(obj, key, heads=_heads(heads))
    return _render_item(*got) if got is not None else []


def get_all_at(h: int, obj: str, key: str, heads: bytes) -> List[Item]:
    out: List[Item] = []
    for rendered, exid in _doc(h).get_all(obj, key, heads=_heads(heads)):
        out.extend(_render_item(rendered, exid))
    return out


def list_get_at(h: int, obj: str, index: int, heads: bytes) -> List[Item]:
    got = _doc(h).get(obj, index, heads=_heads(heads))
    return _render_item(*got) if got is not None else []


def keys_at(h: int, obj: str, heads: bytes) -> List[Item]:
    return [(STR, k) for k in _doc(h).keys(obj, heads=_heads(heads))]


def length_at(h: int, obj: str, heads: bytes) -> List[Item]:
    return [(UINT, _doc(h).length(obj, heads=_heads(heads)))]


def text_at(h: int, obj: str, heads: bytes) -> List[Item]:
    return [(STR, _doc(h).text(obj, heads=_heads(heads)))]


def marks_at(h: int, obj: str, heads: bytes) -> List[Item]:
    return _marks_items(_doc(h).marks(obj, heads=_heads(heads)))


def fork_at(h: int, heads: bytes, actor: bytes) -> List[Item]:
    doc = _doc(h).fork_at(_heads(heads), actor=ActorId(actor) if actor else None)
    return [(HANDLE, _register(_docs, doc))]


# -- richer object/item surface ----------------------------------------------


def object_type(h: int, obj: str) -> List[Item]:
    return [(UINT, _OBJTYPE_CODE[_doc(h).object_type(obj)])]


def list_put_object(h: int, obj: str, index: int, objtype: int) -> List[Item]:
    return [(OBJ_ID, _doc(h).put_object(obj, index, _OBJTYPE[objtype]))]


def list_items(h: int, obj: str) -> List[Item]:
    out: List[Item] = []
    for rendered, exid in _doc(h).list_items(obj):
        out.extend(_render_item(rendered, exid))
    return out


def map_entries(h: int, obj: str) -> List[Item]:
    """Per entry: STR key then the value item (2 items per entry)."""
    out: List[Item] = []
    for key, rendered, exid in _doc(h).map_entries(obj):
        out.append((STR, key))
        out.extend(_render_item(rendered, exid))
    return out


def get_changes(h: int, heads: bytes) -> List[Item]:
    return [(BYTES, c.raw_bytes) for c in _doc(h).get_changes(_heads(heads))]


# -- patches ------------------------------------------------------------------
#
# Each patch flattens to a fixed 6-item record so C callers can walk
# results without variable framing:
#   STR obj exid | STR path ("key/3/sub") | STR kind | STR prop |
#   UINT index-or-length | value item (VOID when the kind carries none)
# Insert patches emit one record per inserted value (index ascending),
# matching the reference's per-value patch items.


def _patch_records(patches) -> List[Item]:
    out: List[Item] = []

    def rec(p, kind, prop, index, value_item):
        path = "/".join(str(k) for _, k in p.path)
        out.extend(
            [(STR, p.obj), (STR, path), (STR, kind), (STR, prop), (UINT, index)]
        )
        out.append(value_item)

    val_item = _scalar_item

    for p in patches:
        a = p.action
        k = type(a).__name__
        if k == "PutMap":
            rec(p, "put_map", a.key, 0, val_item(a.value))
        elif k == "PutSeq":
            rec(p, "put_seq", "", a.index, val_item(a.value))
        elif k == "Insert":
            for j, v in enumerate(a.values):
                rec(p, "insert", "", a.index + j, val_item(v))
        elif k == "SpliceText":
            rec(p, "splice_text", "", a.index, (STR, a.value))
        elif k == "DeleteMap":
            rec(p, "del_map", a.key, 0, (VOID, 0))
        elif k == "DeleteSeq":
            rec(p, "del_seq", "", a.index, (UINT, a.length))
        elif k == "IncrementPatch":
            prop = a.prop if isinstance(a.prop, str) else ""
            idx = a.prop if isinstance(a.prop, int) else 0
            rec(p, "increment", prop, idx, (INT, a.value))
        elif k == "FlagConflict":
            prop = a.prop if isinstance(a.prop, str) else ""
            idx = a.prop if isinstance(a.prop, int) else 0
            rec(p, "flag_conflict", prop, idx, (VOID, 0))
        elif k == "MarkPatch":
            # replace-all framing: one ("mark_clear") record, then two
            # records per span — ("mark", name, start, value) and
            # ("mark_end", name, end, VOID). The clear record makes the
            # empty set (unmark removed the last span) observable and lets
            # C consumers implement replace-all without extra state.
            rec(p, "mark_clear", "", 0, (VOID, 0))
            for m in a.marks:
                rec(p, "mark", m.name, m.start, _scalar_item(m.value))
                rec(p, "mark_end", m.name, m.end, (VOID, 0))
        else:
            rec(p, k.lower(), "", 0, (VOID, 0))
    return out


def diff(h: int, before: bytes, after: bytes) -> List[Item]:
    return _patch_records(_doc(h).diff(_heads(before), _heads(after)))


def pop_patches(h: int) -> List[Item]:
    """Drain patches since the last pop (the observer surface from C); the
    first call activates the log at the current heads."""
    doc = _doc(h)
    if not doc.patch_log.is_active():
        doc.patch_log.set_active(True)
        doc.patch_log.reset(doc.doc)
        return []
    return _patch_records(doc.make_patches())


# -- round-3 breadth: the remaining reference doc.rs surface ------------------


def clone(h: int) -> List[Item]:
    """AMclone: same history, same actor (fork mints a fresh actor)."""
    doc = _doc(h)
    cloned = doc.fork(actor=doc.get_actor())
    return [(HANDLE, _register(_docs, cloned))]


def set_actor(h: int, actor: bytes) -> List[Item]:
    _doc(h).set_actor(ActorId(actor))
    return []


def equal(h: int, other: int) -> List[Item]:
    """AMequal: get_heads() equality after autocommit (reference:
    automerge-c doc.rs:42-44 is_equal_to) — same history heads, not
    content. Two docs with identical content but different histories are
    NOT equal; see equal_content for the content semantic."""
    return [(BOOL, 1 if sorted(_doc(h).get_heads()) == sorted(_doc(other).get_heads()) else 0)]


def equal_content(h: int, other: int) -> List[Item]:
    """am_equal_content: current-state content equality (hydrated trees) —
    an extension beyond the reference's AMequal for callers that want
    value comparison across divergent histories."""
    return [(BOOL, 1 if _doc(h).hydrate() == _doc(other).hydrate() else 0)]


def get_change_by_hash(h: int, hash_: bytes) -> List[Item]:
    doc = _doc(h)
    doc.commit()  # autocommit boundary, like every history accessor
    ch = doc.doc.get_change_by_hash(hash_)
    return [(BYTES, ch.raw_bytes)] if ch is not None else []


def get_changes_added(h: int, other: int) -> List[Item]:
    doc, src = _doc(h), _doc(other)
    doc.commit()
    src.commit()  # the result must equal what am_merge would apply
    added = doc.doc.get_changes_added(src.doc)
    return [(BYTES, c.raw_bytes) for c in added]


def get_missing_deps(h: int, heads: bytes) -> List[Item]:
    doc = _doc(h)
    doc.commit()
    return [(BYTES, x) for x in doc.doc.get_missing_deps(_heads(heads))]


def get_last_local_change(h: int) -> List[Item]:
    ch = _doc(h).get_last_local_change()
    return [(BYTES, ch.raw_bytes)] if ch is not None else []


def pending_ops(h: int) -> List[Item]:
    return [(UINT, _doc(h).pending_ops())]


def rollback(h: int) -> List[Item]:
    return [(UINT, _doc(h).rollback())]


def list_range(h: int, obj: str, start: int, end: int) -> List[Item]:
    """AMlistRange: value items for visible indices in [start, end)."""
    doc = _doc(h)
    out: List[Item] = []
    for i, (rendered, exid) in enumerate(doc.list_items(obj)):
        if start <= i < end:
            out.extend(_render_item(rendered, exid))
    return out


def map_range(h: int, obj: str, begin: str, end: str) -> List[Item]:
    """AMmapRange: (STR key, value item) pairs for keys in [begin, end)
    (empty ``end`` = unbounded)."""
    doc = _doc(h)
    out: List[Item] = []
    for key, rendered, exid in doc.map_entries(obj):
        if key >= begin and (not end or key < end):
            out.append((STR, key))
            out.extend(_render_item(rendered, exid))
    return out


def list_splice(h: int, obj: str, pos: int, delete_n: int) -> List[Item]:
    """AMsplice's delete side; insertions go through the typed insert
    calls (the item-array marshalling the reference uses has no analogue
    in this frontend's scalar ABI)."""
    _doc(h).splice(obj, pos, delete_n, [])
    return []


def sync_state_shared_heads(sh: int) -> List[Item]:
    return [(BYTES, x) for x in _syncs[sh].shared_heads]


# -- sync state codecs --------------------------------------------------------


def sync_state_encode(sh: int) -> List[Item]:
    return [(BYTES, _syncs[sh].encode())]


def sync_state_decode(data: bytes) -> List[Item]:
    return [(HANDLE, _register(_syncs, SyncState.decode(data)))]


def _scalar_item(v) -> Item:
    """One raw Python value -> item (shared by marks + patch records)."""
    if isinstance(v, bool):
        return (BOOL, 1 if v else 0)
    if isinstance(v, int):
        return (INT, v)
    if isinstance(v, float):
        return (F64, v)
    if isinstance(v, (bytes, bytearray)):
        return (BYTES, bytes(v))
    if isinstance(v, str):
        return (STR, v)
    if v is None:
        return (NULL, 0)
    return (STR, str(v))  # hydrated subtree: stringified


def _marks_items(marks_list) -> List[Item]:
    out: List[Item] = []
    for m in marks_list:
        out.append((UINT, m.start))
        out.append((UINT, m.end))
        out.append((STR, m.name))
        out.append(_scalar_item(m.value))
    return out


# -- C fast path (am_embed.cpp hot-call cache) --------------------------------
#
# Per-op C callers (am_splice_text / am_map_put_*) were interpreter-bound:
# every call built a Python tuple and ran shim dispatch (~600k ops/s).
# fast_begin exposes the SAME native session the Python fast paths use
# (core/transaction.py fast_splice_fn / fast_put_fn) as raw handles, so the
# embedder drives am_edit_splice / am_map_put directly with NO Python in
# the loop. Safety contract: the C side clears its cache and dispatches
# fast_sync before ANY other shim call, so Python-side op-id accounting
# (tx._session_ops) resynchronizes before anything else can mint ids.


def fast_addrs() -> List[Item]:
    """Native entry addresses for the C fast path (or [] when absent)."""
    import ctypes

    from .. import native

    lib = native.load()
    if lib is None or not hasattr(lib, "am_map_put"):
        return []
    cast = lambda f: (UINT, ctypes.cast(f, ctypes.c_void_p).value)  # noqa: E731
    return [
        cast(lib.am_edit_splice), cast(lib.am_edit_op_count),
        cast(lib.am_map_put), cast(lib.am_map_op_count),
    ]


_ENC_CODE = {"unicode": 0, "utf8": 1, "utf16": 2}


def fast_begin(h: int, obj: str, kind: int) -> List[Item]:
    """Arm the C hot-call cache for (doc, obj): kind 0 = text splice,
    1 = map put. Returns [(HANDLE, session_addr), (INT, base_ctr),
    (INT, enc_code)] — the next op counter is base_ctr + the session's
    live op_count — or [] when the object is ineligible (the C side then
    neg-caches and keeps dispatching)."""
    from ..types import get_text_encoding

    doc = _doc(h)
    tx = doc._ensure_tx()
    obj_id = tx._obj(obj)
    if kind == 0:
        info = tx.doc.ops.get_obj(obj_id)
        from ..core.op_store import SeqObject

        # TEXT only: splice_text on a LIST must keep raising through the
        # dispatch path exactly like the python frontend
        if not isinstance(info.data, SeqObject) or info.data.obj_type != ObjType.TEXT:
            return []
        sess = tx._session_for(obj_id, info)
    else:
        if not tx.enable_sessions or tx.scope is not None:
            return []
        if tx.actor_idx >= (1 << tx._ID_RANK_BITS):
            return []
        sess = tx.map_session_for(obj_id)
    if sess is None or not sess._h:
        return []
    base = tx.start_op + len(tx.operations) + tx._session_ops - sess.op_count()
    enc = _ENC_CODE[doc.doc.text_encoding or get_text_encoding()]
    return [(HANDLE, sess._h), (INT, base), (INT, enc)]


def fast_sync(h: int) -> List[Item]:
    """Re-account ops the C fast path pushed straight into native
    sessions (their op ids are consumed; tx._session_ops must agree
    before any other operation mints ids)."""
    doc = _docs.get(h)
    tx = doc._tx if doc is not None else None
    if tx is not None:
        tx._session_ops = sum(
            s[0].op_count() - s[1] for s in tx._sessions.values()
        ) + sum(
            s[0].op_count() - s[1] for s in tx._msessions.values()
        )
    return []


def call(fn: str, *args) -> List[Item]:
    """The single dispatch point the C layer uses."""
    impl = globals().get(fn)
    if impl is None or fn.startswith("_"):
        raise ValueError(f"unknown C API function {fn!r}")
    return impl(*args)
