/* C-side exercise of the am.h ABI: create / edit / save / load / merge /
 * sync entirely through the shared library — the analogue of the
 * reference's cmocka suites (reference: automerge-c/test/doc_tests.c,
 * ported_wasm/basic_tests.c, sync_tests.c), with plain asserts.
 */
#include "am.h"

#include <assert.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static AMresult *ok(AMresult *r) {
  if (am_result_status(r) != AM_STATUS_OK) {
    fprintf(stderr, "FAIL: %s\n", am_result_error(r));
    exit(1);
  }
  return r;
}

static void expect_error(AMresult *r, const char *what) {
  if (am_result_status(r) == AM_STATUS_OK) {
    fprintf(stderr, "FAIL: expected error from %s\n", what);
    exit(1);
  }
  assert(am_result_error(r) != NULL);
  am_result_free(r);
}

int main(void) {
  assert(am_init() == 0);

  uint8_t actor1[16], actor2[16];
  memset(actor1, 0x11, sizeof actor1);
  memset(actor2, 0x22, sizeof actor2);

  /* -- create + scalar puts + reads -- */
  AMdoc *doc1 = am_create(actor1, sizeof actor1);
  assert(doc1 != NULL);
  am_result_free(ok(am_map_put_str(doc1, AM_ROOT, "title", "hello c")));
  am_result_free(ok(am_map_put_int(doc1, AM_ROOT, "n", -42)));
  am_result_free(ok(am_map_put_uint(doc1, AM_ROOT, "u", 7)));
  am_result_free(ok(am_map_put_f64(doc1, AM_ROOT, "pi", 3.25)));
  am_result_free(ok(am_map_put_bool(doc1, AM_ROOT, "flag", 1)));
  am_result_free(ok(am_map_put_null(doc1, AM_ROOT, "nil")));
  am_result_free(ok(am_map_put_counter(doc1, AM_ROOT, "votes", 10)));
  am_result_free(ok(am_map_increment(doc1, AM_ROOT, "votes", 5)));
  uint8_t blob[3] = {1, 2, 3};
  am_result_free(ok(am_map_put_bytes(doc1, AM_ROOT, "blob", blob, 3)));

  AMresult *r = ok(am_map_get(doc1, AM_ROOT, "title"));
  assert(am_result_size(r) == 1);
  assert(am_item_type(r, 0) == AM_VAL_STR);
  assert(strcmp(am_item_str(r, 0), "hello c") == 0);
  am_result_free(r);

  r = ok(am_map_get(doc1, AM_ROOT, "n"));
  assert(am_item_type(r, 0) == AM_VAL_INT && am_item_int(r, 0) == -42);
  am_result_free(r);

  r = ok(am_map_get(doc1, AM_ROOT, "votes"));
  assert(am_item_type(r, 0) == AM_VAL_COUNTER && am_item_int(r, 0) == 15);
  am_result_free(r);

  r = ok(am_map_get(doc1, AM_ROOT, "pi"));
  assert(am_item_type(r, 0) == AM_VAL_F64 && am_item_f64(r, 0) == 3.25);
  am_result_free(r);

  r = ok(am_map_get(doc1, AM_ROOT, "blob"));
  size_t blen = 0;
  const uint8_t *b = am_item_bytes(r, 0, &blen);
  assert(am_item_type(r, 0) == AM_VAL_BYTES && blen == 3 && b[1] == 2);
  am_result_free(r);

  r = ok(am_keys(doc1, AM_ROOT));
  assert(am_result_size(r) == 8);
  am_result_free(r);

  /* -- text object -- */
  r = ok(am_map_put_object(doc1, AM_ROOT, "text", AM_OBJ_TEXT));
  assert(am_item_type(r, 0) == AM_VAL_OBJ_ID);
  char text_id[128];
  snprintf(text_id, sizeof text_id, "%s", am_item_str(r, 0));
  am_result_free(r);
  am_result_free(ok(am_splice_text(doc1, text_id, 0, 0, "hello world")));
  am_result_free(ok(am_splice_text(doc1, text_id, 5, 6, " c!")));
  r = ok(am_text(doc1, text_id));
  assert(strcmp(am_item_str(r, 0), "hello c!") == 0);
  am_result_free(r);
  r = ok(am_length(doc1, text_id));
  assert(am_item_int(r, 0) == 8);
  am_result_free(r);

  /* -- list object -- */
  r = ok(am_map_put_object(doc1, AM_ROOT, "list", AM_OBJ_LIST));
  char list_id[128];
  snprintf(list_id, sizeof list_id, "%s", am_item_str(r, 0));
  am_result_free(r);
  am_result_free(ok(am_list_insert_int(doc1, list_id, 0, 1)));
  am_result_free(ok(am_list_insert_str(doc1, list_id, 1, "two")));
  am_result_free(ok(am_list_insert_counter(doc1, list_id, 2, 100)));
  am_result_free(ok(am_list_increment(doc1, list_id, 2, 1)));
  am_result_free(ok(am_list_delete(doc1, list_id, 0)));
  r = ok(am_length(doc1, list_id));
  assert(am_item_int(r, 0) == 2);
  am_result_free(r);
  r = ok(am_list_get(doc1, list_id, 1));
  assert(am_item_type(r, 0) == AM_VAL_COUNTER && am_item_int(r, 0) == 101);
  am_result_free(r);

  /* -- commit / save / load -- */
  r = ok(am_commit(doc1, "from c"));
  assert(am_result_size(r) == 1 && am_item_type(r, 0) == AM_VAL_BYTES);
  am_result_free(r);
  r = ok(am_save(doc1));
  size_t saved_len = 0;
  const uint8_t *saved = am_item_bytes(r, 0, &saved_len);
  assert(saved_len > 0);
  AMdoc *loaded = am_load(saved, saved_len);
  assert(loaded != NULL);
  am_result_free(r);
  r = ok(am_text(loaded, text_id));
  assert(strcmp(am_item_str(r, 0), "hello c!") == 0);
  am_result_free(r);

  /* -- fork + concurrent edits + merge (both orders converge) -- */
  AMdoc *doc2 = am_fork(doc1, actor2, sizeof actor2);
  assert(doc2 != NULL);
  am_result_free(ok(am_splice_text(doc1, text_id, 0, 0, "1:")));
  am_result_free(ok(am_splice_text(doc2, text_id, 8, 0, " [2]")));
  am_result_free(ok(am_map_put_str(doc1, AM_ROOT, "who", "one")));
  am_result_free(ok(am_map_put_str(doc2, AM_ROOT, "who", "two")));
  AMdoc *m1 = am_fork(doc1, NULL, 0);
  AMdoc *m2 = am_fork(doc2, NULL, 0);
  am_result_free(ok(am_merge(m1, doc2)));
  am_result_free(ok(am_merge(m2, doc1)));
  AMresult *t1 = ok(am_text(m1, text_id));
  AMresult *t2 = ok(am_text(m2, text_id));
  assert(strcmp(am_item_str(t1, 0), am_item_str(t2, 0)) == 0);
  am_result_free(t1);
  am_result_free(t2);
  r = ok(am_map_get_all(m1, AM_ROOT, "who")); /* conflict: both values */
  assert(am_result_size(r) == 2);
  am_result_free(r);

  /* -- sync protocol over the ABI -- */
  AMdoc *peer = am_create(NULL, 0);
  AMsyncState *s1 = am_sync_state_new();
  AMsyncState *s2 = am_sync_state_new();
  assert(peer && s1 && s2);
  for (int round = 0; round < 32; round++) {
    AMresult *ma = ok(am_generate_sync_message(m1, s1));
    AMresult *mb = ok(am_generate_sync_message(peer, s2));
    int done = am_result_size(ma) == 0 && am_result_size(mb) == 0;
    if (am_result_size(ma)) {
      size_t len = 0;
      const uint8_t *msg = am_item_bytes(ma, 0, &len);
      am_result_free(ok(am_receive_sync_message(peer, s2, msg, len)));
    }
    if (am_result_size(mb)) {
      size_t len = 0;
      const uint8_t *msg = am_item_bytes(mb, 0, &len);
      am_result_free(ok(am_receive_sync_message(m1, s1, msg, len)));
    }
    am_result_free(ma);
    am_result_free(mb);
    if (done) break;
  }
  AMresult *h1 = ok(am_get_heads(m1));
  AMresult *h2 = ok(am_get_heads(peer));
  assert(am_result_size(h1) == am_result_size(h2));
  for (size_t i = 0; i < am_result_size(h1); i++) {
    size_t l1, l2;
    const uint8_t *x = am_item_bytes(h1, i, &l1);
    const uint8_t *y = am_item_bytes(h2, i, &l2);
    assert(l1 == 32 && l2 == 32 && memcmp(x, y, 32) == 0);
  }
  am_result_free(h1);
  am_result_free(h2);
  AMresult *pt = ok(am_text(peer, text_id));
  AMresult *mt = ok(am_text(m1, text_id));
  assert(strcmp(am_item_str(pt, 0), am_item_str(mt, 0)) == 0);
  am_result_free(pt);
  am_result_free(mt);

  /* -- marks + cursors (reference: automerge-c marks/cursor surface) -- */
  AMdoc *md = am_create(NULL, 0);
  assert(md != NULL);
  r = ok(am_map_put_object(md, AM_ROOT, "note", AM_OBJ_TEXT));
  char note_id[64];
  strncpy(note_id, am_item_str(r, 0), sizeof(note_id) - 1);
  note_id[sizeof(note_id) - 1] = 0;
  am_result_free(r);
  am_result_free(ok(am_splice_text(md, note_id, 0, 0, "mark me up")));
  am_result_free(ok(am_mark_bool(md, note_id, 0, 4, "bold", 1, "both")));
  am_result_free(ok(am_mark_str(md, note_id, 5, 7, "link", "https://x", "none")));
  r = ok(am_marks(md, note_id));
  assert(am_result_size(r) == 8); /* 2 marks x (start, end, name, value) */
  assert(am_item_int(r, 0) == 0 && am_item_int(r, 1) == 4);
  assert(strcmp(am_item_str(r, 2), "bold") == 0);
  assert(am_item_type(r, 3) == AM_VAL_BOOL && am_item_int(r, 3) == 1);
  assert(strcmp(am_item_str(r, 6), "link") == 0);
  assert(strcmp(am_item_str(r, 7), "https://x") == 0);
  am_result_free(r);
  am_result_free(ok(am_unmark(md, note_id, 0, 4, "bold")));
  r = ok(am_marks(md, note_id));
  assert(am_result_size(r) == 4); /* only the link span remains */
  am_result_free(r);
  /* NULL value = null mark (clears the name over the span) */
  am_result_free(ok(am_mark_str(md, note_id, 5, 7, "link", NULL, "none")));
  r = ok(am_marks(md, note_id));
  assert(am_result_size(r) == 0);
  am_result_free(r);
  expect_error(am_mark_bool(md, note_id, 0, 2, "b", 1, "sideways"),
               "invalid expand policy");
  r = ok(am_get_cursor(md, note_id, 5));
  char cursor[64];
  strncpy(cursor, am_item_str(r, 0), sizeof(cursor) - 1);
  cursor[sizeof(cursor) - 1] = 0;
  am_result_free(r);
  am_result_free(ok(am_splice_text(md, note_id, 0, 0, ">> ")));
  r = ok(am_get_cursor_position(md, note_id, cursor));
  assert(am_item_int(r, 0) == 8); /* cursor tracked the insertion */
  am_result_free(r);

  /* -- incremental history exchange -- */
  am_result_free(ok(am_commit(md, NULL)));
  AMresult *heads0 = ok(am_get_heads(md));
  size_t nh = am_result_size(heads0);
  uint8_t heads_blob[8 * 32];
  assert(nh <= 8);
  for (size_t i = 0; i < nh; i++) {
    size_t hl;
    const uint8_t *hb = am_item_bytes(heads0, i, &hl);
    assert(hl == 32);
    memcpy(heads_blob + i * 32, hb, 32);
  }
  am_result_free(heads0);
  AMdoc *mirror = am_create(NULL, 0);
  r = ok(am_save(md));
  {
    size_t sl;
    const uint8_t *sb = am_item_bytes(r, 0, &sl);
    am_result_free(ok(am_apply_changes(mirror, sb, sl)));
  }
  am_result_free(r);
  am_result_free(ok(am_splice_text(md, note_id, 0, 0, "A")));
  am_result_free(ok(am_commit(md, NULL)));
  /* NULL heads = the full history */
  r = ok(am_save_incremental(md, NULL, 0));
  {
    size_t fl;
    am_item_bytes(r, 0, &fl);
    assert(fl > 0);
  }
  am_result_free(r);
  r = ok(am_save_incremental(md, heads_blob, nh));
  {
    size_t il;
    const uint8_t *ib = am_item_bytes(r, 0, &il);
    assert(il > 0);
    am_result_free(ok(am_apply_changes(mirror, ib, il)));
  }
  am_result_free(r);
  AMresult *mdt = ok(am_text(md, note_id));
  AMresult *mrt = ok(am_text(mirror, note_id));
  assert(strcmp(am_item_str(mdt, 0), am_item_str(mrt, 0)) == 0);
  am_result_free(mdt);
  am_result_free(mrt);
  am_doc_free(mirror);
  am_doc_free(md);

  /* -- error paths -- */
  expect_error(am_map_get(doc1, "7@deadbeef", "x"), "get on unknown object");
  expect_error(am_map_increment(doc1, AM_ROOT, "title", 1),
               "increment of a non-counter");
  assert(am_load((const uint8_t *)"garbage", 7) == NULL);

  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_doc_free(peer);
  am_doc_free(m1);
  am_doc_free(m2);
  am_doc_free(doc2);
  am_doc_free(loaded);
  am_doc_free(doc1);
  am_shutdown();
  printf("capi: all assertions passed\n");
  return 0;
}
