/* Basic-suite scenarios ported from the reference's wasm/C test corpus
 * (behavioral port of rust/automerge-c/test/ported_wasm/basic_tests.c,
 * re-expressed against this framework's am.h; no code copied) plus the
 * round-3 surface: historical reads, fork_at, the full list scalar
 * matrix, patches, map entries / list items, object types.
 */
#include <stdio.h>
#include <string.h>

#include "am.h"
#include "test_util.h"

static char sbuf[4096];
static uint8_t bbuf[1 << 20];
static uint8_t heads1[32 * 64], heads2[32 * 64];

/* -- create / clone / free ------------------------------------------------ */
static void test_create_fork_free(void) {
  uint8_t actor[2] = {0xAA, 0xBB};
  AMdoc *d = am_create(actor, 2);
  CHECK(d != NULL);
  AMresult *r = am_actor_id(d);
  CHECK(res_ok(r) && am_result_size(r) == 1);
  size_t len = 0;
  const uint8_t *p = am_item_bytes(r, 0, &len);
  CHECK(len == 2 && p[0] == 0xAA && p[1] == 0xBB);
  am_result_free(r);
  AMdoc *f = am_fork(d, NULL, 0);
  CHECK(f != NULL);
  am_doc_free(f);
  am_doc_free(d);
}

/* -- start and commit ----------------------------------------------------- */
static void test_start_and_commit(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "n", 1));
  AMresult *r = am_commit(d, "first");
  CHECK(res_ok(r) && am_result_size(r) == 1);
  am_result_free(r);
  CHECK(res_heads(am_get_heads(d), heads1, 64) == 1);
  am_doc_free(d);
}

/* -- getting a nonexistent prop does not error ---------------------------- */
static void test_nonexistent_prop(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_get(d, AM_ROOT, "missing");
  CHECK(res_ok(r) && am_result_size(r) == 0);
  am_result_free(r);
  am_doc_free(d);
}

/* -- set and get the whole scalar matrix on a map ------------------------- */
static void test_simple_values(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_null(d, AM_ROOT, "nul"));
  CHECK_OK(am_map_put_bool(d, AM_ROOT, "yes", 1));
  CHECK_OK(am_map_put_bool(d, AM_ROOT, "no", 0));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "int", -42));
  CHECK_OK(am_map_put_uint(d, AM_ROOT, "uint", 42));
  CHECK_OK(am_map_put_f64(d, AM_ROOT, "pi", 3.5));
  CHECK_OK(am_map_put_str(d, AM_ROOT, "s", "hello"));
  CHECK_OK(am_map_put_counter(d, AM_ROOT, "c", 10));
  CHECK_OK(am_map_put_timestamp(d, AM_ROOT, "t", 1234567890));

  AMresult *r = am_map_get(d, AM_ROOT, "nul");
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_NULL);
  am_result_free(r);
  r = am_map_get(d, AM_ROOT, "yes");
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_BOOL && am_item_int(r, 0) == 1);
  am_result_free(r);
  r = am_map_get(d, AM_ROOT, "no");
  CHECK(res_ok(r) && am_item_int(r, 0) == 0);
  am_result_free(r);
  CHECK(res_int(am_map_get(d, AM_ROOT, "int")) == -42);
  CHECK(res_int(am_map_get(d, AM_ROOT, "uint")) == 42);
  CHECK(res_f64(am_map_get(d, AM_ROOT, "pi")) == 3.5);
  CHECK(strcmp(res_str(am_map_get(d, AM_ROOT, "s"), sbuf, sizeof sbuf),
               "hello") == 0);
  r = am_map_get(d, AM_ROOT, "c");
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_COUNTER && am_item_int(r, 0) == 10);
  am_result_free(r);
  r = am_map_get(d, AM_ROOT, "t");
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_TIMESTAMP &&
        am_item_int(r, 0) == 1234567890);
  am_result_free(r);
  am_doc_free(d);
}

/* -- bytes round-trip ------------------------------------------------------ */
static void test_bytes(void) {
  AMdoc *d = am_create(NULL, 0);
  const uint8_t data[5] = {0, 1, 2, 255, 128};
  CHECK_OK(am_map_put_bytes(d, AM_ROOT, "b", data, 5));
  AMresult *r = am_map_get(d, AM_ROOT, "b");
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_BYTES);
  size_t len = 0;
  const uint8_t *p = am_item_bytes(r, 0, &len);
  CHECK(len == 5 && memcmp(p, data, 5) == 0);
  am_result_free(r);
  am_doc_free(d);
}

/* -- subobjects ------------------------------------------------------------ */
static void test_subobjects(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "cfg", AM_OBJ_MAP);
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_OBJ_ID);
  char cfg[128];
  strncpy(cfg, am_item_str(r, 0), sizeof cfg - 1);
  am_result_free(r);
  CHECK_OK(am_map_put_bool(d, cfg, "logging", 1));
  r = am_map_get(d, cfg, "logging");
  CHECK(res_ok(r) && am_item_int(r, 0) == 1);
  am_result_free(r);
  CHECK(res_int(am_object_type(d, cfg)) == AM_OBJ_MAP);
  /* overwriting the key makes the old object unreachable */
  CHECK_OK(am_map_put_int(d, AM_ROOT, "cfg", 7));
  CHECK(res_int(am_map_get(d, AM_ROOT, "cfg")) == 7);
  am_doc_free(d);
}

/* -- lists: the whole verb x scalar matrix --------------------------------- */
static void test_lists(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "l", AM_OBJ_LIST);
  char l[128];
  strncpy(l, am_item_str(r, 0), sizeof l - 1);
  am_result_free(r);
  CHECK(res_int(am_object_type(d, l)) == AM_OBJ_LIST);

  CHECK_OK(am_list_insert_int(d, l, 0, 1));
  CHECK_OK(am_list_insert_str(d, l, 1, "two"));
  CHECK_OK(am_list_insert_bool(d, l, 2, 1));
  CHECK_OK(am_list_insert_uint(d, l, 3, 9));
  CHECK_OK(am_list_insert_f64(d, l, 4, 2.25));
  CHECK_OK(am_list_insert_null(d, l, 5));
  const uint8_t raw[3] = {9, 8, 7};
  CHECK_OK(am_list_insert_bytes(d, l, 6, raw, 3));
  /* NULL bytes = empty payload (review regression: must not store None) */
  CHECK_OK(am_list_insert_bytes(d, l, 6, NULL, 0));
  AMresult *eb = am_list_get(d, l, 6);
  size_t elen = 99;
  CHECK(res_ok(eb) && am_item_type(eb, 0) == AM_VAL_BYTES);
  am_item_bytes(eb, 0, &elen);
  CHECK(elen == 0);
  am_result_free(eb);
  CHECK_OK(am_list_delete(d, l, 6));
  CHECK_OK(am_list_insert_counter(d, l, 7, 5));
  CHECK_OK(am_list_insert_timestamp(d, l, 8, 999));
  CHECK(res_int(am_length(d, l)) == 9);

  CHECK(res_int(am_list_get(d, l, 0)) == 1);
  CHECK(strcmp(res_str(am_list_get(d, l, 1), sbuf, sizeof sbuf), "two") == 0);
  AMresult *g = am_list_get(d, l, 5);
  CHECK(res_ok(g) && am_item_type(g, 0) == AM_VAL_NULL);
  am_result_free(g);
  g = am_list_get(d, l, 6);
  size_t blen = 0;
  const uint8_t *bp = am_item_bytes(g, 0, &blen);
  CHECK(blen == 3 && bp[1] == 8);
  am_result_free(g);

  /* puts overwrite in place (no length change) */
  CHECK_OK(am_list_put_str(d, l, 0, "one"));
  CHECK_OK(am_list_put_bool(d, l, 2, 0));
  CHECK_OK(am_list_put_uint(d, l, 3, 10));
  CHECK_OK(am_list_put_f64(d, l, 4, 1.5));
  CHECK_OK(am_list_put_null(d, l, 5));
  const uint8_t raw2[2] = {1, 2};
  CHECK_OK(am_list_put_bytes(d, l, 6, raw2, 2));
  CHECK_OK(am_list_put_counter(d, l, 7, 100));
  CHECK_OK(am_list_put_timestamp(d, l, 8, 1000));
  CHECK_OK(am_list_put_int(d, l, 1, 22));
  CHECK(res_int(am_length(d, l)) == 9);
  CHECK(strcmp(res_str(am_list_get(d, l, 0), sbuf, sizeof sbuf), "one") == 0);
  CHECK(res_int(am_list_get(d, l, 1)) == 22);
  CHECK(res_f64(am_list_get(d, l, 4)) == 1.5);
  CHECK(res_int(am_list_get(d, l, 7)) == 100);

  /* item iteration covers every element */
  AMresult *items = am_list_items(d, l);
  CHECK(res_ok(items) && am_result_size(items) == 9);
  CHECK(am_item_type(items, 0) == AM_VAL_STR);
  CHECK(am_item_type(items, 7) == AM_VAL_COUNTER);
  am_result_free(items);

  /* delete shrinks */
  CHECK_OK(am_list_delete(d, l, 5));
  CHECK(res_int(am_length(d, l)) == 8);

  /* nested object via both verbs */
  r = am_list_insert_object(d, l, 0, AM_OBJ_MAP);
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_OBJ_ID);
  char sub[128];
  strncpy(sub, am_item_str(r, 0), sizeof sub - 1);
  am_result_free(r);
  CHECK_OK(am_map_put_int(d, sub, "x", 1));
  r = am_list_put_object(d, l, 1, AM_OBJ_TEXT);
  CHECK(res_ok(r) && am_item_type(r, 0) == AM_VAL_OBJ_ID);
  char txt[128];
  strncpy(txt, am_item_str(r, 0), sizeof txt - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(d, txt, 0, 0, "in list"));
  CHECK(strcmp(res_str(am_text(d, txt), sbuf, sizeof sbuf), "in list") == 0);
  am_doc_free(d);
}

/* -- deleting (incl. nonexistent) ------------------------------------------ */
static void test_delete(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_str(d, AM_ROOT, "k", "v"));
  CHECK_OK(am_map_delete(d, AM_ROOT, "k"));
  AMresult *r = am_map_get(d, AM_ROOT, "k");
  CHECK(res_ok(r) && am_result_size(r) == 0);
  am_result_free(r);
  /* deleting a prop that does not exist is a silent no-op (reference:
   * transaction/inner.rs:422-423, ported_wasm delete_non_existent_props) */
  CHECK_OK(am_map_delete(d, AM_ROOT, "never"));
  am_doc_free(d);
}

/* -- counters -------------------------------------------------------------- */
static void test_counters(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_counter(d, AM_ROOT, "c", 10));
  CHECK_OK(am_map_increment(d, AM_ROOT, "c", 5));
  CHECK_OK(am_map_increment(d, AM_ROOT, "c", -3));
  CHECK(res_int(am_map_get(d, AM_ROOT, "c")) == 12);
  am_doc_free(d);
}

/* local increment bumps every visible (conflicting) counter — the merge
 * keeps both actors' counters under one key and increments hit all */
static void test_inc_increments_all_visible_counters(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1);
  CHECK_OK(am_commit(d1, NULL));
  AMdoc *d2 = am_fork(d1, a2, 1);
  CHECK_OK(am_map_put_counter(d1, AM_ROOT, "n", 10));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_map_put_counter(d2, AM_ROOT, "n", 100));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_merge(d1, d2));
  AMresult *all = am_map_get_all(d1, AM_ROOT, "n");
  CHECK(res_ok(all) && am_result_size(all) == 2);
  am_result_free(all);
  CHECK_OK(am_map_increment(d1, AM_ROOT, "n", 1));
  all = am_map_get_all(d1, AM_ROOT, "n");
  CHECK(res_ok(all) && am_result_size(all) == 2);
  CHECK(am_item_int(all, 0) + am_item_int(all, 1) == 10 + 100 + 2);
  am_result_free(all);
  am_doc_free(d2);
  am_doc_free(d1);
}

/* -- text splices ----------------------------------------------------------- */
static void test_splice_text(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "text", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(d, t, 0, 0, "hello world"));
  CHECK_OK(am_splice_text(d, t, 6, 5, "there"));
  CHECK(strcmp(res_str(am_text(d, t), sbuf, sizeof sbuf), "hello there") == 0);
  CHECK(res_int(am_length(d, t)) == 11);
  /* out-of-bounds errors, does not abort */
  r = am_splice_text(d, t, 999, 0, "x");
  CHECK(am_result_status(r) == AM_STATUS_ERROR);
  am_result_free(r);
  am_doc_free(d);
}

/* -- save all / incrementally ---------------------------------------------- */
static void test_save_all_or_incrementally(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "a", 1));
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), heads1, 64);
  CHECK(n1 == 1);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "b", 2));
  CHECK_OK(am_commit(d, NULL));

  /* incremental after the first head = just the second change */
  AMresult *inc = am_save_incremental(d, heads1, n1);
  CHECK(res_ok(inc));
  size_t inc_len = 0;
  const uint8_t *inc_p = am_item_bytes(inc, 0, &inc_len);
  CHECK(inc_len > 0);

  /* a fork at the first head + the incremental bytes = the full doc */
  AMdoc *early = am_fork_at(d, heads1, n1, NULL, 0);
  CHECK(early != NULL);
  AMresult *probe = am_map_get(early, AM_ROOT, "b");
  CHECK(res_ok(probe) && am_result_size(probe) == 0);
  am_result_free(probe);
  CHECK_OK(am_apply_changes(early, inc_p, inc_len));
  am_result_free(inc);
  CHECK(res_int(am_map_get(early, AM_ROOT, "b")) == 2);
  am_doc_free(early);

  /* full save loads back */
  size_t n = res_bytes(am_save(d), bbuf, sizeof bbuf);
  CHECK(n > 0);
  AMdoc *l = am_load(bbuf, n);
  CHECK(l != NULL);
  CHECK(res_int(am_map_get(l, AM_ROOT, "a")) == 1);
  CHECK(res_int(am_map_get(l, AM_ROOT, "b")) == 2);
  am_doc_free(l);
  am_doc_free(d);
}

/* -- fetch changes by heads ------------------------------------------------- */
static void test_fetch_changes(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "a", 1));
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), heads1, 64);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "b", 2));
  CHECK_OK(am_commit(d, NULL));
  AMresult *all = am_get_changes(d, NULL, 0);
  CHECK(res_ok(all) && am_result_size(all) == 2);
  am_result_free(all);
  AMresult *tail = am_get_changes(d, heads1, n1);
  CHECK(res_ok(tail) && am_result_size(tail) == 1);
  am_result_free(tail);
  am_doc_free(d);
}

/* -- recursive sets --------------------------------------------------------- */
static void test_recursive_sets(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "l", AM_OBJ_LIST);
  char l[128];
  strncpy(l, am_item_str(r, 0), sizeof l - 1);
  am_result_free(r);
  r = am_list_insert_object(d, l, 0, AM_OBJ_MAP);
  char m[128];
  strncpy(m, am_item_str(r, 0), sizeof m - 1);
  am_result_free(r);
  CHECK_OK(am_map_put_str(d, m, "name", "deep"));
  r = am_map_put_object(d, m, "inner", AM_OBJ_LIST);
  char il[128];
  strncpy(il, am_item_str(r, 0), sizeof il - 1);
  am_result_free(r);
  CHECK_OK(am_list_insert_int(d, il, 0, 7));
  CHECK(res_int(am_list_get(d, il, 0)) == 7);
  CHECK(strcmp(res_str(am_map_get(d, m, "name"), sbuf, sizeof sbuf), "deep") == 0);
  /* map entries pair key + value items */
  AMresult *ents = am_map_entries(d, m);
  CHECK(res_ok(ents) && am_result_size(ents) == 4);
  CHECK(am_item_type(ents, 0) == AM_VAL_STR);
  am_result_free(ents);
  am_doc_free(d);
}

/* -- objects without properties are preserved across save/load -------------- */
static void test_empty_objects_preserved(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "empty", AM_OBJ_MAP);
  am_result_free(r);
  CHECK_OK(am_commit(d, NULL));
  size_t n = res_bytes(am_save(d), bbuf, sizeof bbuf);
  AMdoc *l = am_load(bbuf, n);
  AMresult *g = am_map_get(l, AM_ROOT, "empty");
  CHECK(res_ok(g) && am_item_type(g, 0) == AM_VAL_OBJ_ID);
  am_result_free(g);
  am_doc_free(l);
  am_doc_free(d);
}

/* -- fork_at heads + historical reads --------------------------------------- */
static void test_fork_at_and_historical_reads(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(d, t, 0, 0, "version one"));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "v", 1));
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), heads1, 64);

  CHECK_OK(am_splice_text(d, t, 8, 3, "two"));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "v", 2));
  CHECK_OK(am_map_put_str(d, AM_ROOT, "extra", "x"));
  CHECK_OK(am_commit(d, NULL));

  /* current reads see v2 */
  CHECK(res_int(am_map_get(d, AM_ROOT, "v")) == 2);
  CHECK(strcmp(res_str(am_text(d, t), sbuf, sizeof sbuf), "version two") == 0);

  /* *_at reads pin the first commit */
  CHECK(res_int(am_map_get_at(d, AM_ROOT, "v", heads1, n1)) == 1);
  CHECK(strcmp(res_str(am_text_at(d, t, heads1, n1), sbuf, sizeof sbuf),
               "version one") == 0);
  CHECK(res_int(am_length_at(d, t, heads1, n1)) == 11);
  AMresult *k = am_keys_at(d, AM_ROOT, heads1, n1);
  CHECK(res_ok(k) && am_result_size(k) == 2); /* t, v — no "extra" yet */
  am_result_free(k);
  AMresult *ga = am_map_get_all_at(d, AM_ROOT, "v", heads1, n1);
  CHECK(res_ok(ga) && am_result_size(ga) == 1 && am_item_int(ga, 0) == 1);
  am_result_free(ga);

  /* fork_at reproduces the historical doc exactly */
  AMdoc *old = am_fork_at(d, heads1, n1, NULL, 0);
  CHECK(old != NULL);
  CHECK(res_int(am_map_get(old, AM_ROOT, "v")) == 1);
  CHECK(strcmp(res_str(am_text(old, t), sbuf, sizeof sbuf), "version one") == 0);
  size_t nf = res_heads(am_get_heads(old), heads2, 64);
  CHECK(nf == n1 && memcmp(heads1, heads2, 32 * n1) == 0);
  am_doc_free(old);
  am_doc_free(d);
}

/* -- merging text conflicts then saving and loading ------------------------- */
static void test_merge_text_conflicts_save_load(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1);
  AMresult *r = am_map_put_object(d1, AM_ROOT, "t", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(d1, t, 0, 0, "base"));
  CHECK_OK(am_commit(d1, NULL));
  AMdoc *d2 = am_fork(d1, a2, 1);
  CHECK_OK(am_splice_text(d1, t, 4, 0, " one"));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_splice_text(d2, t, 4, 0, " two"));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_merge(d1, d2));
  CHECK_OK(am_merge(d2, d1));
  char t1[64], t2[64];
  res_str(am_text(d1, t), t1, sizeof t1);
  res_str(am_text(d2, t), t2, sizeof t2);
  CHECK(strcmp(t1, t2) == 0);
  size_t n = res_bytes(am_save(d1), bbuf, sizeof bbuf);
  AMdoc *l = am_load(bbuf, n);
  res_str(am_text(l, t), t2, sizeof t2);
  CHECK(strcmp(t1, t2) == 0);
  am_doc_free(l);
  am_doc_free(d2);
  am_doc_free(d1);
}

/* -- conflicts surface through get_all -------------------------------------- */
static void test_conflicts(void) {
  uint8_t a1[1] = {1}, a2[1] = {9};
  AMdoc *d1 = am_create(a1, 1);
  CHECK_OK(am_map_put_str(d1, AM_ROOT, "k", "base"));
  CHECK_OK(am_commit(d1, NULL));
  AMdoc *d2 = am_fork(d1, a2, 1);
  CHECK_OK(am_map_put_str(d1, AM_ROOT, "k", "one"));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_map_put_str(d2, AM_ROOT, "k", "two"));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_merge(d1, d2));
  AMresult *all = am_map_get_all(d1, AM_ROOT, "k");
  CHECK(res_ok(all) && am_result_size(all) == 2);
  am_result_free(all);
  /* winner = higher actor id (lamport tie-break) */
  CHECK(strcmp(res_str(am_map_get(d1, AM_ROOT, "k"), sbuf, sizeof sbuf),
               "two") == 0);
  am_doc_free(d2);
  am_doc_free(d1);
}

/* -- marks ------------------------------------------------------------------ */
static void test_marks(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(d, t, 0, 0, "styled text"));
  CHECK_OK(am_mark_bool(d, t, 0, 6, "bold", 1, "after"));
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), heads1, 64);
  AMresult *ms = am_marks(d, t);
  CHECK(res_ok(ms) && am_result_size(ms) == 4);
  CHECK(am_item_int(ms, 0) == 0 && am_item_int(ms, 1) == 6);
  CHECK(strcmp(am_item_str(ms, 2), "bold") == 0);
  am_result_free(ms);
  CHECK_OK(am_unmark(d, t, 0, 6, "bold"));
  ms = am_marks(d, t);
  CHECK(res_ok(ms) && am_result_size(ms) == 0);
  am_result_free(ms);
  /* the mark is still visible at the old heads */
  ms = am_marks_at(d, t, heads1, n1);
  CHECK(res_ok(ms) && am_result_size(ms) == 4);
  am_result_free(ms);
  am_doc_free(d);
}

/* -- cursors ---------------------------------------------------------------- */
static void test_cursors(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(d, t, 0, 0, "abcdef"));
  char cur[128];
  res_str(am_get_cursor(d, t, 3), cur, sizeof cur);
  CHECK(cur[0] != '\0');
  CHECK_OK(am_splice_text(d, t, 0, 0, "XY"));
  CHECK(res_int(am_get_cursor_position(d, t, cur)) == 5);
  am_doc_free(d);
}

/* -- patches: diff between heads + observer pops ---------------------------- */
static void test_patches(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "a", 1));
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), heads1, 64);
  CHECK_OK(am_map_put_str(d, AM_ROOT, "b", "hi"));
  CHECK_OK(am_map_delete(d, AM_ROOT, "a"));
  CHECK_OK(am_commit(d, NULL));
  size_t n2 = res_heads(am_get_heads(d), heads2, 64);

  AMresult *p = am_diff(d, heads1, n1, heads2, n2);
  CHECK(res_ok(p) && am_result_size(p) == 12); /* 2 patches x 6 items */
  /* record 1: del_map a ; record 2: put_map b (sorted by key) */
  CHECK(strcmp(am_item_str(p, 2), "del_map") == 0 ||
        strcmp(am_item_str(p, 2), "put_map") == 0);
  int found_put = 0, found_del = 0;
  for (size_t i = 0; i + 5 < am_result_size(p); i += 6) {
    const char *kind = am_item_str(p, i + 2);
    if (strcmp(kind, "put_map") == 0 && strcmp(am_item_str(p, i + 3), "b") == 0) {
      found_put = strcmp(am_item_str(p, i + 5), "hi") == 0;
    }
    if (strcmp(kind, "del_map") == 0 && strcmp(am_item_str(p, i + 3), "a") == 0)
      found_del = 1;
  }
  CHECK(found_put && found_del);
  am_result_free(p);

  /* observer pops: first activates, then drains per commit batch */
  CHECK_OK(am_pop_patches(d));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "c", 3));
  CHECK_OK(am_commit(d, NULL));
  p = am_pop_patches(d);
  CHECK(res_ok(p) && am_result_size(p) == 6);
  CHECK(strcmp(am_item_str(p, 2), "put_map") == 0);
  CHECK(strcmp(am_item_str(p, 3), "c") == 0);
  CHECK(am_item_int(p, 5) == 3);
  am_result_free(p);
  /* nothing new -> empty pop */
  p = am_pop_patches(d);
  CHECK(res_ok(p) && am_result_size(p) == 0);
  am_result_free(p);
  am_doc_free(d);
}

/* -- splice_text with a list of seq patches (text diff) --------------------- */
static void test_text_diff_patches(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT);
  char t[128];
  strncpy(t, am_item_str(r, 0), sizeof t - 1);
  am_result_free(r);
  CHECK_OK(am_splice_text(d, t, 0, 0, "hello"));
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), heads1, 64);
  CHECK_OK(am_splice_text(d, t, 5, 0, " world"));
  CHECK_OK(am_commit(d, NULL));
  size_t n2 = res_heads(am_get_heads(d), heads2, 64);
  AMresult *p = am_diff(d, heads1, n1, heads2, n2);
  CHECK(res_ok(p) && am_result_size(p) == 6);
  CHECK(strcmp(am_item_str(p, 2), "splice_text") == 0);
  CHECK(am_item_int(p, 4) == 5);
  CHECK(strcmp(am_item_str(p, 5), " world") == 0);
  am_result_free(p);
  am_doc_free(d);
}

/* -- clone / equality / actor id / rollback --------------------------------- */
static void test_clone_equal_actor_rollback(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d = am_create(a1, 1);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(d, NULL));
  AMdoc *c = am_clone(d);
  CHECK(c != NULL);
  /* clone keeps the actor; fork mints/uses another */
  AMresult *r = am_actor_id(c);
  size_t len = 0;
  const uint8_t *p = am_item_bytes(r, 0, &len);
  CHECK(len == 1 && p[0] == 1);
  am_result_free(r);
  CHECK(res_int(am_equal(d, c)) == 1);
  CHECK_OK(am_set_actor_id(c, a2, 1));
  r = am_actor_id(c);
  p = am_item_bytes(r, 0, &len);
  CHECK(len == 1 && p[0] == 2);
  am_result_free(r);
  /* divergence flips equality; rollback discards pending ops */
  CHECK_OK(am_map_put_int(c, AM_ROOT, "y", 2));
  CHECK(res_int(am_pending_ops(c)) == 1);
  CHECK(res_int(am_rollback(c)) == 1);
  CHECK(res_int(am_pending_ops(c)) == 0);
  CHECK(res_int(am_equal(d, c)) == 1);
  CHECK_OK(am_map_put_int(c, AM_ROOT, "y", 2));
  CHECK_OK(am_commit(c, NULL));
  CHECK(res_int(am_equal(d, c)) == 0);
  am_doc_free(c);
  /* am_equal is HEADS equality (reference AMequal, doc.rs:42-44): two
   * docs converging to identical content via different histories are not
   * equal; am_equal_content compares the hydrated values instead */
  uint8_t a3[1] = {3};
  AMdoc *e = am_create(a3, 1);
  CHECK_OK(am_map_put_int(e, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(e, NULL));
  CHECK(res_int(am_equal(d, e)) == 0);
  CHECK(res_int(am_equal_content(d, e)) == 1);
  am_doc_free(e);
  am_doc_free(d);
}

/* -- change-level history accessors ------------------------------------------ */
static void test_change_accessors(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d = am_create(a1, 1);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), heads1, 64);
  CHECK(n1 == 1);
  /* fetch the head change by hash; an unknown hash is empty, not an error */
  AMresult *r = am_get_change_by_hash(d, heads1);
  CHECK(res_ok(r) && am_result_size(r) == 1);
  am_result_free(r);
  uint8_t bogus[32] = {0};
  r = am_get_change_by_hash(d, bogus);
  CHECK(res_ok(r) && am_result_size(r) == 0);
  am_result_free(r);
  /* last local change commits pending ops and returns the chunk */
  CHECK_OK(am_map_put_int(d, AM_ROOT, "y", 2));
  r = am_get_last_local_change(d);
  CHECK(res_ok(r) && am_result_size(r) == 1);
  am_result_free(r);
  /* changes a stale fork would pull from us (the merge direction) */
  AMdoc *old = am_fork_at(d, heads1, n1, a2, 1);
  r = am_get_changes_added(old, d);
  CHECK(res_ok(r) && am_result_size(r) == 1);
  am_result_free(r);
  /* nothing missing when history is complete */
  r = am_get_missing_deps(d, NULL, 0);
  CHECK(res_ok(r) && am_result_size(r) == 0);
  am_result_free(r);
  am_doc_free(old);
  am_doc_free(d);
}

/* -- range reads + list splice ----------------------------------------------- */
static void test_ranges_and_splice(void) {
  AMdoc *d = am_create(NULL, 0);
  AMresult *r = am_map_put_object(d, AM_ROOT, "l", AM_OBJ_LIST);
  char l[128];
  strncpy(l, am_item_str(r, 0), sizeof l - 1);
  am_result_free(r);
  for (int i = 0; i < 8; i++) CHECK_OK(am_list_insert_int(d, l, (size_t)i, i * 10));
  r = am_list_range(d, l, 2, 5);
  CHECK(res_ok(r) && am_result_size(r) == 3);
  CHECK(am_item_int(r, 0) == 20 && am_item_int(r, 2) == 40);
  am_result_free(r);
  CHECK_OK(am_list_splice(d, l, 1, 3)); /* delete 3 at 1 */
  CHECK(res_int(am_length(d, l)) == 5);
  CHECK(res_int(am_list_get(d, l, 1)) == 40);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "alpha", 1));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "beta", 2));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "gamma", 3));
  r = am_map_range(d, AM_ROOT, "alpha", "gamma");
  CHECK(res_ok(r) && am_result_size(r) == 4); /* alpha, beta x (key,value) */
  CHECK(strcmp(am_item_str(r, 0), "alpha") == 0 && am_item_int(r, 3) == 2);
  am_result_free(r);
  r = am_map_range(d, AM_ROOT, "beta", "");
  CHECK(res_ok(r) && am_result_size(r) == 6); /* beta, gamma, l */
  am_result_free(r);
  am_doc_free(d);
}

int main(void) {
  if (am_init() != 0) {
    fprintf(stderr, "am_init failed\n");
    return 2;
  }
  test_clone_equal_actor_rollback();
  test_change_accessors();
  test_ranges_and_splice();
  test_create_fork_free();
  test_start_and_commit();
  test_nonexistent_prop();
  test_simple_values();
  test_bytes();
  test_subobjects();
  test_lists();
  test_delete();
  test_counters();
  test_inc_increments_all_visible_counters();
  test_splice_text();
  test_save_all_or_incrementally();
  test_fetch_changes();
  test_recursive_sets();
  test_empty_objects_preserved();
  test_fork_at_and_historical_reads();
  test_merge_text_conflicts_save_load();
  test_conflicts();
  test_marks();
  test_cursors();
  test_patches();
  test_text_diff_patches();
  int rc = am_test_finish("test_basic");
  am_shutdown();
  return rc;
}
