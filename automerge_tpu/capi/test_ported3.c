/* Third C-corpus suite: the change-exchange surface, deep history,
 * sync-state persistence, the error-path matrix, and a measured C-ABI
 * throughput probe (behavioral ports of scenarios from the reference's
 * automerge-c test corpus — doc_tests, item/result discipline, the
 * byte_span and actor-id tests, plus the criterion-style bulk-call
 * timing discipline — re-expressed against this framework's am.h; no
 * code copied).
 *
 * Throughput note (BASELINE.md "C ABI throughput is Python-bound"): the
 * probe prints per-op and bulk-call rates to stderr so CI logs carry
 * the measured boundary cost; the bulk idiom (am_splice_text with a
 * whole run, am_apply_changes with a whole chunk set) is what C
 * embedders should use on hot paths.
 */
#include <stdio.h>
#include <string.h>
#include <time.h>

#include "am.h"
#include "test_util.h"

static uint8_t blob[1 << 20];
static uint8_t blob2[1 << 20];
static char sbuf[1 << 16];

static double now_s(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + 1e-9 * ts.tv_nsec;
}

static void obj_of(AMresult *r, char *out, size_t cap) {
  out[0] = '\0';
  if (res_ok(r) && am_result_size(r) > 0) {
    strncpy(out, am_item_str(r, 0), cap - 1);
    out[cap - 1] = '\0';
  }
  am_result_free(r);
}

/* -- incremental save / apply matrix ---------------------------------------- */
/* (reference doc.rs AMsaveIncremental/AMloadIncremental discipline) */
static void test_incremental_save_apply_matrix(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *src = am_create(a1, 1);
  char t[128];
  obj_of(am_map_put_object(src, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  CHECK_OK(am_splice_text(src, t, 0, 0, "one"));
  CHECK_OK(am_commit(src, "c1"));
  uint8_t h1[32 * 4];
  size_t n1 = res_heads(am_get_heads(src), h1, 4);

  CHECK_OK(am_splice_text(src, t, 3, 0, " two"));
  CHECK_OK(am_commit(src, "c2"));
  uint8_t h2[32 * 4];
  size_t n2 = res_heads(am_get_heads(src), h2, 4);

  CHECK_OK(am_splice_text(src, t, 7, 0, " three"));
  CHECK_OK(am_commit(src, "c3"));

  /* save_incremental(NULL) = everything; (h1) = c2+c3; (h2) = c3 */
  size_t all = res_bytes(am_save_incremental(src, NULL, 0), blob, sizeof blob);
  size_t after1 = res_bytes(am_save_incremental(src, h1, n1), blob2, sizeof blob2);
  CHECK(all > after1 && after1 > 0);

  /* a replica fed everything converges */
  AMdoc *dst = am_create(a2, 1);
  CHECK_OK(am_apply_changes(dst, blob, all));
  CHECK(strcmp(res_str(am_text(dst, t), sbuf, sizeof sbuf), "one two three")
        == 0);
  CHECK(res_int(am_equal(src, dst)) == 1);

  /* a replica at h1 fed only the delta converges too */
  AMdoc *mid = am_fork_at(src, h1, n1, a2, 1);
  CHECK(strcmp(res_str(am_text(mid, t), sbuf, sizeof sbuf), "one") == 0);
  CHECK_OK(am_apply_changes(mid, blob2, after1));
  CHECK(strcmp(res_str(am_text(mid, t), sbuf, sizeof sbuf), "one two three")
        == 0);
  am_doc_free(mid);
  am_doc_free(dst);
  am_doc_free(src);
}

/* -- get_changes / by-hash / added / last-local ------------------------------ */
static void test_change_exchange_accessors(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1);
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(d1, NULL));
  AMdoc *d2 = am_fork(d1, a2, 1);
  CHECK_OK(am_map_put_int(d2, AM_ROOT, "y", 2));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "z", 3));
  CHECK_OK(am_commit(d1, NULL));

  /* changes_added(d1, d2) = what a merge would carry over */
  AMresult *added = am_get_changes_added(d1, d2);
  CHECK(am_result_size(added) == 1);
  size_t clen = 0;
  const uint8_t *cp = am_item_bytes(added, 0, &clen);
  memcpy(blob, cp, clen);
  am_result_free(added);
  CHECK_OK(am_apply_changes(d1, blob, clen));
  CHECK(res_int(am_map_get(d1, AM_ROOT, "y")) == 2);

  /* get_changes(heads=NULL) walks the whole history (3 changes now) */
  AMresult *all = am_get_changes(d1, NULL, 0);
  CHECK(am_result_size(all) == 3);
  am_result_free(all);

  /* by-hash round trip: every head hash resolves to a chunk */
  uint8_t hs[32 * 4];
  size_t nh = res_heads(am_get_heads(d1), hs, 4);
  CHECK(nh >= 1);
  for (size_t i = 0; i < nh; i++) {
    AMresult *ch = am_get_change_by_hash(d1, hs + 32 * i);
    CHECK(am_result_size(ch) == 1);
    am_result_free(ch);
  }
  uint8_t bogus[32] = {0};
  AMresult *missing = am_get_change_by_hash(d1, bogus);
  CHECK(res_ok(missing) && am_result_size(missing) == 0);
  am_result_free(missing);

  /* last local change belongs to this doc's actor */
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "w", 4));
  AMresult *last = am_get_last_local_change(d1);
  CHECK(am_result_size(last) == 1);
  am_result_free(last);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* -- sync-state persistence across a process restart ------------------------- */
/* (reference sync/state.rs: only shared_heads survives encode) */
static void test_sync_state_persistence(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
  char l[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "l", AM_OBJ_LIST), l, sizeof l);
  for (int i = 0; i < 5; i++) {
    CHECK_OK(am_list_insert_int(d1, l, (size_t)i, i));
    CHECK_OK(am_commit(d1, NULL));
  }
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  for (int round = 0; round < 40; round++) {
    AMresult *m1 = am_generate_sync_message(d1, s1);
    AMresult *m2 = am_generate_sync_message(d2, s2);
    int quiet = am_result_size(m1) == 0 && am_result_size(m2) == 0;
    if (am_result_size(m1)) {
      size_t ln = 0;
      const uint8_t *p = am_item_bytes(m1, 0, &ln);
      memcpy(blob, p, ln);
      CHECK_OK(am_receive_sync_message(d2, s2, blob, ln));
    }
    if (am_result_size(m2)) {
      size_t ln = 0;
      const uint8_t *p = am_item_bytes(m2, 0, &ln);
      memcpy(blob, p, ln);
      CHECK_OK(am_receive_sync_message(d1, s1, blob, ln));
    }
    am_result_free(m1);
    am_result_free(m2);
    if (quiet) break;
  }
  AMresult *sh = am_sync_state_shared_heads(s1);
  CHECK(am_result_size(sh) >= 1);
  am_result_free(sh);

  /* persist both states; "restart"; resume with NEW divergence */
  size_t e1 = res_bytes(am_sync_state_encode(s1), blob, sizeof blob);
  size_t e2 = res_bytes(am_sync_state_encode(s2), blob2, sizeof blob2);
  CHECK(e1 > 0 && e2 > 0);
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  AMsyncState *r1 = am_sync_state_decode(blob, e1);
  AMsyncState *r2 = am_sync_state_decode(blob2, e2);
  CHECK(r1 && r2);
  sh = am_sync_state_shared_heads(r1);
  CHECK(am_result_size(sh) >= 1); /* shared_heads survived the roundtrip */
  am_result_free(sh);

  CHECK_OK(am_list_insert_int(d1, l, 5, 99));
  CHECK_OK(am_commit(d1, NULL));
  int rounds = 0;
  for (; rounds < 40; rounds++) {
    AMresult *m1 = am_generate_sync_message(d1, r1);
    AMresult *m2 = am_generate_sync_message(d2, r2);
    int quiet = am_result_size(m1) == 0 && am_result_size(m2) == 0;
    if (am_result_size(m1)) {
      size_t ln = 0;
      const uint8_t *p = am_item_bytes(m1, 0, &ln);
      memcpy(blob, p, ln);
      CHECK_OK(am_receive_sync_message(d2, r2, blob, ln));
    }
    if (am_result_size(m2)) {
      size_t ln = 0;
      const uint8_t *p = am_item_bytes(m2, 0, &ln);
      memcpy(blob, p, ln);
      CHECK_OK(am_receive_sync_message(d1, r1, blob, ln));
    }
    am_result_free(m1);
    am_result_free(m2);
    if (quiet) break;
  }
  CHECK(rounds < 40);
  CHECK(res_int(am_length(d2, l)) == 6);
  am_sync_state_free(r1);
  am_sync_state_free(r2);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* -- error-path matrix: bad handles, ids, indexes, types --------------------- */
/* (reference result.rs/item.rs discipline: errors come back as AMresult
 * status, never crashes) */
static void test_error_paths(void) {
  AMdoc *d = am_create(NULL, 0);
  /* unknown object id */
  AMresult *r = am_map_get(d, "999@ffffffffffffffffffffffffffffffff", "k");
  CHECK(am_result_status(r) == AM_STATUS_ERROR);
  am_result_free(r);
  /* malformed object id */
  r = am_map_put_int(d, "not-an-id", "k", 1);
  CHECK(am_result_status(r) == AM_STATUS_ERROR);
  am_result_free(r);
  /* list index out of range */
  char l[128];
  obj_of(am_map_put_object(d, AM_ROOT, "l", AM_OBJ_LIST), l, sizeof l);
  r = am_list_put_int(d, l, 5, 1); /* put beyond length errors */
  CHECK(am_result_status(r) == AM_STATUS_ERROR);
  am_result_free(r);
  CHECK_OK(am_list_insert_int(d, l, 0, 1)); /* insert at len is push */
  /* map ops on a list object */
  r = am_map_put_int(d, l, "k", 1);
  CHECK(am_result_status(r) == AM_STATUS_ERROR);
  am_result_free(r);
  /* text ops on a map */
  r = am_splice_text(d, AM_ROOT, 0, 0, "x");
  CHECK(am_result_status(r) == AM_STATUS_ERROR);
  am_result_free(r);
  /* increment of a non-counter */
  CHECK_OK(am_map_put_int(d, AM_ROOT, "n", 1));
  r = am_map_increment(d, AM_ROOT, "n", 1);
  CHECK(am_result_status(r) == AM_STATUS_ERROR);
  am_result_free(r);
  /* corrupt load returns NULL, not a crash */
  uint8_t junk[16] = {1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16};
  AMdoc *bad = am_load(junk, sizeof junk);
  CHECK(bad == NULL);
  /* item accessors out of range return benign defaults */
  r = am_get_heads(d);
  CHECK(am_item_type(r, 99) == AM_VAL_VOID);
  CHECK(am_item_str(r, 99) == NULL || am_item_str(r, 99)[0] == '\0');
  am_result_free(r);
  am_doc_free(d);
}

/* -- deep history: many commits, reads at every recorded point --------------- */
static void test_deep_history_reads(void) {
  AMdoc *d = am_create(NULL, 0);
  char t[128];
  obj_of(am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  static uint8_t heads[24][32 * 2];
  static size_t nheads[24];
  char expect[25][32];
  expect[0][0] = '\0';
  for (int i = 0; i < 24; i++) {
    char c[2] = {(char)('a' + i), 0};
    CHECK_OK(am_splice_text(d, t, (size_t)i, 0, c));
    CHECK_OK(am_commit(d, NULL));
    nheads[i] = res_heads(am_get_heads(d), heads[i], 2);
    snprintf(expect[i + 1], sizeof expect[i + 1], "%s%s", expect[i], c);
  }
  /* every historical point reads back its exact text + length */
  for (int i = 0; i < 24; i++) {
    CHECK(strcmp(res_str(am_text_at(d, t, heads[i], nheads[i]), sbuf,
                         sizeof sbuf),
                 expect[i + 1]) == 0);
    CHECK(res_int(am_length_at(d, t, heads[i], nheads[i])) == i + 1);
  }
  /* historical single-element read */
  AMresult *r = am_list_get_at(d, t, 0, heads[3], nheads[3]);
  CHECK(am_result_size(r) == 1);
  am_result_free(r);
  am_doc_free(d);
}

/* -- concurrent counters in maps across three peers -------------------------- */
static void test_three_peer_counter_convergence(void) {
  uint8_t a1[1] = {1}, a2[1] = {2}, a3[1] = {3};
  AMdoc *d1 = am_create(a1, 1);
  CHECK_OK(am_map_put_counter(d1, AM_ROOT, "hits", 0));
  CHECK_OK(am_commit(d1, NULL));
  AMdoc *d2 = am_fork(d1, a2, 1), *d3 = am_fork(d1, a3, 1);
  for (int i = 0; i < 10; i++) {
    CHECK_OK(am_map_increment(d1, AM_ROOT, "hits", 1));
    CHECK_OK(am_map_increment(d2, AM_ROOT, "hits", 2));
    CHECK_OK(am_map_increment(d3, AM_ROOT, "hits", 3));
  }
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_commit(d3, NULL));
  /* merge in both directions and orders: totals must agree everywhere */
  CHECK_OK(am_merge(d1, d2));
  CHECK_OK(am_merge(d1, d3));
  CHECK_OK(am_merge(d3, d2));
  CHECK_OK(am_merge(d3, d1));
  CHECK_OK(am_merge(d2, d3));
  AMresult *r = am_map_get(d1, AM_ROOT, "hits");
  CHECK(am_item_type(r, 0) == AM_VAL_COUNTER);
  CHECK(am_item_int(r, 0) == 60);
  am_result_free(r);
  CHECK(res_int(am_map_get(d2, AM_ROOT, "hits")) == 60);
  CHECK(res_int(am_map_get(d3, AM_ROOT, "hits")) == 60);
  am_doc_free(d1);
  am_doc_free(d2);
  am_doc_free(d3);
}

/* -- unicode text through the C boundary ------------------------------------- */
static void test_unicode_text(void) {
  AMdoc *d = am_create(NULL, 0);
  char t[128];
  obj_of(am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  /* 2-byte, 3-byte and 4-byte UTF-8 sequences */
  CHECK_OK(am_splice_text(d, t, 0, 0, "caf\xc3\xa9 \xe2\x82\xac \xf0\x9f\x98\x80"));
  /* length counts the configured text units, not bytes */
  int64_t len = res_int(am_length(d, t));
  CHECK(len > 0 && len < 12);
  /* round-trips through save/load byte-identically */
  size_t sl = res_bytes(am_save(d), blob, sizeof blob);
  AMdoc *d2 = am_load(blob, sl);
  CHECK(d2 != NULL);
  res_str(am_text(d2, t), sbuf, sizeof sbuf);
  CHECK(strcmp(sbuf, "caf\xc3\xa9 \xe2\x82\xac \xf0\x9f\x98\x80") == 0);
  /* splice after the emoji keeps units consistent */
  CHECK_OK(am_splice_text(d2, t, (size_t)res_int(am_length(d2, t)), 0, "!"));
  res_str(am_text(d2, t), sbuf, sizeof sbuf);
  CHECK(sbuf[strlen(sbuf) - 1] == '!');
  am_doc_free(d);
  am_doc_free(d2);
}

/* -- measured throughput probe ----------------------------------------------- */
/* Not an assertion (the boundary crosses into the embedded runtime, and
 * BASELINE.md documents it as interpreter-bound per call); prints per-op
 * vs bulk rates so CI logs track the boundary cost and the bulk idiom's
 * advantage stays visible. */
/* -- hot-call fast-path edge cases ------------------------------------------- */
/* The am_embed hot-call cache must agree with the dispatch path on every
 * rejection: invalid utf-8, splices on non-text objects, empty keys, and
 * op-id accounting across fast/slow interleavings. */
static void test_fast_path_edges(void) {
  AMdoc *d = am_create(NULL, 0);
  char t[128], l[128];
  obj_of(am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  obj_of(am_map_put_object(d, AM_ROOT, "l", AM_OBJ_LIST), l, sizeof l);

  /* arm the fast path, then feed it input only the dispatch path rejects */
  CHECK_OK(am_splice_text(d, t, 0, 0, "ok"));
  AMresult *r = am_splice_text(d, t, 0, 0, "\xff\xfe");
  CHECK(am_result_status(r) != AM_STATUS_OK); /* stray lead bytes */
  am_result_free(r);
  r = am_splice_text(d, t, 0, 0, "\xf8\x80\x80\x80");
  CHECK(am_result_status(r) != AM_STATUS_OK); /* > 4-byte lead */
  am_result_free(r);
  r = am_splice_text(d, t, 0, 0, "\xed\xa0\x80");
  CHECK(am_result_status(r) != AM_STATUS_OK); /* surrogate half */
  am_result_free(r);
  r = am_splice_text(d, t, 0, 0, "\xc0\xaf");
  CHECK(am_result_status(r) != AM_STATUS_OK); /* overlong */
  am_result_free(r);
  CHECK_OK(am_splice_text(d, t, 2, 0, " \xf0\x9f\x9a\x80")); /* valid 4-byte */

  /* splice on a LIST object must error exactly like the python frontend */
  r = am_splice_text(d, l, 0, 0, "nope");
  CHECK(am_result_status(r) != AM_STATUS_OK);
  am_result_free(r);

  /* empty / invalid-utf8 keys: dispatch path raises */
  r = am_map_put_int(d, AM_ROOT, "", 1);
  CHECK(am_result_status(r) != AM_STATUS_OK);
  am_result_free(r);
  r = am_map_put_str(d, AM_ROOT, "k", "\xff");
  CHECK(am_result_status(r) != AM_STATUS_OK); /* invalid utf-8 value */
  am_result_free(r);

  /* fast/slow interleave: map puts (fast), delete (dispatch), puts again;
   * op-id accounting must stay consistent through commit + reload */
  CHECK_OK(am_map_put_int(d, AM_ROOT, "a", 1));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "b", 2));
  CHECK_OK(am_map_delete(d, AM_ROOT, "a"));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "c", 3));
  CHECK_OK(am_splice_text(d, t, 0, 0, ">"));
  CHECK_OK(am_map_put_counter(d, AM_ROOT, "n", 5));
  CHECK_OK(am_map_increment(d, AM_ROOT, "n", 2));
  CHECK_OK(am_commit(d, NULL));
  CHECK(res_int(am_map_get(d, AM_ROOT, "n")) == 7);
  CHECK(res_int(am_map_get(d, AM_ROOT, "c")) == 3);
  r = am_map_get(d, AM_ROOT, "a");
  CHECK(am_result_status(r) == AM_STATUS_OK && am_result_size(r) == 0);
  am_result_free(r);
  /* save/load roundtrip proves the ids encoded consistently */
  uint8_t buf[1 << 16];
  size_t n = res_bytes(am_save(d), buf, sizeof buf);
  AMdoc *d2 = am_load(buf, n);
  CHECK(d2 != NULL);
  CHECK(res_int(am_map_get(d2, AM_ROOT, "n")) == 7);
  char s1[256], s2[256];
  res_str(am_text(d2, t), s1, sizeof s1);
  res_str(am_text(d, t), s2, sizeof s2);
  CHECK(strcmp(s1, s2) == 0);
  am_doc_free(d);
  am_doc_free(d2);
}

static void test_throughput_probe(void) {
  AMdoc *d = am_create(NULL, 0);
  char t[128];
  obj_of(am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  const int N = 20000;
  double t0 = now_s();
  for (int i = 0; i < N; i++) {
    CHECK_OK(am_splice_text(d, t, (size_t)i, 0, "x"));
  }
  double per_op = N / (now_s() - t0);
  /* per-call map puts (the am_embed hot-call cache drives the native
   * map session directly — no Python in the loop) */
  char key[32];
  t0 = now_s();
  for (int i = 0; i < N; i++) {
    snprintf(key, sizeof key, "k%06d", i);
    CHECK_OK(am_map_put_int(d, AM_ROOT, key, i));
  }
  double per_put = N / (now_s() - t0);
  /* bulk idiom: one boundary crossing for the whole run */
  char big[8193];
  memset(big, 'y', 8192);
  big[8192] = 0;
  t0 = now_s();
  CHECK_OK(am_splice_text(d, t, (size_t)N, 0, big));
  double bulk = 8192 / (now_s() - t0);
  fprintf(stderr,
          "capi throughput: %.0f splice ops/s per-call, %.0f map puts/s "
          "per-call, %.0f chars/s bulk\n",
          per_op, per_put, bulk);
  CHECK(res_int(am_length(d, t)) == N + 8192);
  CHECK(res_int(am_map_get(d, AM_ROOT, "k000007")) == 7);
  am_doc_free(d);
}

/* -- conflicting values at historical heads ---------------------------------- */
/* (reference read.rs get_all_at: every conflicting writer visible, and
 * the view at older heads must not see later resolutions) */
static void test_get_all_at_conflict_history(void) {
  uint8_t a1[1] = {1}, a2[1] = {2}, a3[1] = {3};
  AMdoc *d1 = am_create(a1, 1);
  CHECK_OK(am_map_put_str(d1, AM_ROOT, "k", "base"));
  CHECK_OK(am_commit(d1, NULL));
  AMdoc *d2 = am_fork(d1, a2, 1), *d3 = am_fork(d1, a3, 1);
  CHECK_OK(am_map_put_str(d1, AM_ROOT, "k", "one"));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_map_put_str(d2, AM_ROOT, "k", "two"));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_map_put_str(d3, AM_ROOT, "k", "three"));
  CHECK_OK(am_commit(d3, NULL));
  CHECK_OK(am_merge(d1, d2));
  CHECK_OK(am_merge(d1, d3));
  uint8_t h3[32 * 4];
  size_t n3 = res_heads(am_get_heads(d1), h3, 4);
  CHECK(n3 == 3); /* three concurrent heads */

  /* all three writers visible as conflicts */
  AMresult *all = am_map_get_all(d1, AM_ROOT, "k");
  CHECK(am_result_size(all) == 3);
  am_result_free(all);

  /* a later overwrite collapses the conflict... */
  CHECK_OK(am_map_put_str(d1, AM_ROOT, "k", "winner"));
  CHECK_OK(am_commit(d1, NULL));
  all = am_map_get_all(d1, AM_ROOT, "k");
  CHECK(am_result_size(all) == 1);
  CHECK(strcmp(am_item_str(all, 0), "winner") == 0);
  am_result_free(all);

  /* ...but the historical view still shows all three */
  all = am_map_get_all_at(d1, AM_ROOT, "k", h3, n3);
  CHECK(am_result_size(all) == 3);
  int saw_one = 0, saw_two = 0, saw_three = 0;
  for (size_t i = 0; i < 3; i++) {
    const char *s = am_item_str(all, i);
    if (s && strcmp(s, "one") == 0) saw_one = 1;
    if (s && strcmp(s, "two") == 0) saw_two = 1;
    if (s && strcmp(s, "three") == 0) saw_three = 1;
  }
  CHECK(saw_one && saw_two && saw_three);
  am_result_free(all);
  am_doc_free(d1);
  am_doc_free(d2);
  am_doc_free(d3);
}

/* -- deep nesting: lists of lists of maps, reads at every level -------------- */
static void test_deep_nesting(void) {
  AMdoc *d = am_create(NULL, 0);
  char grid[128];
  obj_of(am_map_put_object(d, AM_ROOT, "grid", AM_OBJ_LIST), grid,
         sizeof grid);
  char rows[3][128];
  for (int r = 0; r < 3; r++) {
    obj_of(am_list_insert_object(d, grid, (size_t)r, AM_OBJ_LIST), rows[r],
           sizeof rows[r]);
    for (int c = 0; c < 3; c++) {
      char cell[128];
      obj_of(am_list_insert_object(d, rows[r], (size_t)c, AM_OBJ_MAP), cell,
             sizeof cell);
      CHECK_OK(am_map_put_int(d, cell, "v", r * 3 + c));
    }
  }
  CHECK_OK(am_commit(d, NULL));
  CHECK(res_int(am_length(d, grid)) == 3);
  /* read a middle cell back through the id chain */
  AMresult *row1 = am_list_get(d, grid, 1);
  CHECK(am_item_type(row1, 0) == AM_VAL_OBJ_ID);
  char row1_id[128];
  strncpy(row1_id, am_item_str(row1, 0), sizeof row1_id - 1);
  row1_id[sizeof row1_id - 1] = 0;
  am_result_free(row1);
  AMresult *cell = am_list_get(d, row1_id, 2);
  CHECK(am_item_type(cell, 0) == AM_VAL_OBJ_ID);
  char cell_id[128];
  strncpy(cell_id, am_item_str(cell, 0), sizeof cell_id - 1);
  cell_id[sizeof cell_id - 1] = 0;
  am_result_free(cell);
  CHECK(res_int(am_map_get(d, cell_id, "v")) == 5);
  /* object_type reports each level correctly */
  CHECK(res_int(am_object_type(d, grid)) == AM_OBJ_LIST);
  CHECK(res_int(am_object_type(d, row1_id)) == AM_OBJ_LIST);
  CHECK(res_int(am_object_type(d, cell_id)) == AM_OBJ_MAP);
  /* survives save/load with every level intact */
  size_t sl = res_bytes(am_save(d), blob, sizeof blob);
  AMdoc *d2 = am_load(blob, sl);
  CHECK(d2 != NULL);
  CHECK(res_int(am_map_get(d2, cell_id, "v")) == 5);
  am_doc_free(d2);
  am_doc_free(d);
}

/* -- clone vs fork: actor identity and divergence ---------------------------- */
static void test_clone_vs_fork_actors(void) {
  uint8_t a1[4] = {0xDE, 0xAD, 0xBE, 0xEF}, a2[2] = {0xCA, 0xFE};
  AMdoc *d = am_create(a1, 4);
  CHECK_OK(am_map_put_int(d, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(d, NULL));
  /* clone keeps the actor bytes exactly */
  AMdoc *c = am_clone(d);
  size_t ln = 0;
  AMresult *r = am_actor_id(c);
  const uint8_t *p = am_item_bytes(r, 0, &ln);
  CHECK(ln == 4 && memcmp(p, a1, 4) == 0);
  am_result_free(r);
  /* fork with an explicit actor uses it */
  AMdoc *f = am_fork(d, a2, 2);
  r = am_actor_id(f);
  p = am_item_bytes(r, 0, &ln);
  CHECK(ln == 2 && memcmp(p, a2, 2) == 0);
  am_result_free(r);
  /* fork with no actor mints a fresh one (not the parent's) */
  AMdoc *g = am_fork(d, NULL, 0);
  r = am_actor_id(g);
  p = am_item_bytes(r, 0, &ln);
  CHECK(!(ln == 4 && memcmp(p, a1, 4) == 0));
  am_result_free(r);
  /* divergent clones merge cleanly (same history root) */
  CHECK_OK(am_map_put_int(c, AM_ROOT, "from_clone", 1));
  CHECK_OK(am_commit(c, NULL));
  CHECK_OK(am_map_put_int(f, AM_ROOT, "from_fork", 2));
  CHECK_OK(am_commit(f, NULL));
  CHECK_OK(am_merge(d, c));
  CHECK_OK(am_merge(d, f));
  CHECK(res_int(am_map_get(d, AM_ROOT, "from_clone")) == 1);
  CHECK(res_int(am_map_get(d, AM_ROOT, "from_fork")) == 2);
  am_doc_free(c);
  am_doc_free(f);
  am_doc_free(g);
  am_doc_free(d);
}

/* -- keys ordering and map_entries with many keys ---------------------------- */
static void test_many_keys_ordering(void) {
  AMdoc *d = am_create(NULL, 0);
  /* insert in reverse order; keys() must come back sorted */
  for (int i = 63; i >= 0; i--) {
    char k[16];
    snprintf(k, sizeof k, "key%02d", i);
    CHECK_OK(am_map_put_int(d, AM_ROOT, k, i));
  }
  CHECK_OK(am_commit(d, NULL));
  AMresult *keys = am_keys(d, AM_ROOT);
  CHECK(am_result_size(keys) == 64);
  for (size_t i = 1; i < 64; i++)
    CHECK(strcmp(am_item_str(keys, i - 1), am_item_str(keys, i)) < 0);
  am_result_free(keys);
  /* map_entries pairs every key with its value */
  AMresult *ent = am_map_entries(d, AM_ROOT);
  CHECK(am_result_size(ent) == 128);
  CHECK(strcmp(am_item_str(ent, 0), "key00") == 0);
  CHECK(am_item_int(ent, 1) == 0);
  am_result_free(ent);
  /* deleting odd keys halves the count */
  for (int i = 1; i < 64; i += 2) {
    char k[16];
    snprintf(k, sizeof k, "key%02d", i);
    CHECK_OK(am_map_delete(d, AM_ROOT, k));
  }
  CHECK_OK(am_commit(d, NULL));
  keys = am_keys(d, AM_ROOT);
  CHECK(am_result_size(keys) == 32);
  am_result_free(keys);
  am_doc_free(d);
}

/* -- diff between arbitrary head pairs --------------------------------------- */
static void test_diff_between_heads(void) {
  AMdoc *d = am_create(NULL, 0);
  char t[128];
  obj_of(am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  CHECK_OK(am_splice_text(d, t, 0, 0, "abc"));
  CHECK_OK(am_commit(d, NULL));
  uint8_t h1[32 * 2];
  size_t n1 = res_heads(am_get_heads(d), h1, 2);
  CHECK_OK(am_splice_text(d, t, 3, 0, "def"));
  CHECK_OK(am_map_put_int(d, AM_ROOT, "n", 1));
  CHECK_OK(am_commit(d, NULL));
  uint8_t h2[32 * 2];
  size_t n2 = res_heads(am_get_heads(d), h2, 2);

  /* forward diff: a splice_text and a put_map record */
  AMresult *p = am_diff(d, h1, n1, h2, n2);
  int saw_splice = 0, saw_put = 0;
  for (size_t i = 0; i + 5 < am_result_size(p); i += 6) {
    const char *kind = am_item_str(p, i + 2);
    if (kind && strcmp(kind, "splice_text") == 0) saw_splice = 1;
    if (kind && strcmp(kind, "put_map") == 0) saw_put = 1;
  }
  CHECK(saw_splice && saw_put);
  am_result_free(p);

  /* reverse diff: the put shows as a delete, the splice as a del */
  p = am_diff(d, h2, n2, h1, n1);
  int saw_del = 0;
  for (size_t i = 0; i + 5 < am_result_size(p); i += 6) {
    const char *kind = am_item_str(p, i + 2);
    if (kind && (strcmp(kind, "del_map") == 0 || strcmp(kind, "del_seq") == 0))
      saw_del = 1;
  }
  CHECK(saw_del);
  am_result_free(p);

  /* identical heads diff to nothing */
  p = am_diff(d, h2, n2, h2, n2);
  CHECK(am_result_size(p) == 0);
  am_result_free(p);
  am_doc_free(d);
}

/* -- rollback interleaved with committed sync -------------------------------- */
static void test_rollback_vs_sync(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "keep", 1));
  CHECK_OK(am_commit(d1, NULL));
  /* pending (uncommitted) ops roll back; sync ships only commits */
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "discard", 2));
  CHECK(res_int(am_pending_ops(d1)) == 1);
  CHECK(res_int(am_rollback(d1)) == 1);
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  for (int round = 0; round < 40; round++) {
    AMresult *m1 = am_generate_sync_message(d1, s1);
    AMresult *m2 = am_generate_sync_message(d2, s2);
    int quiet = am_result_size(m1) == 0 && am_result_size(m2) == 0;
    if (am_result_size(m1)) {
      size_t ln = 0;
      const uint8_t *p = am_item_bytes(m1, 0, &ln);
      memcpy(blob, p, ln);
      CHECK_OK(am_receive_sync_message(d2, s2, blob, ln));
    }
    if (am_result_size(m2)) {
      size_t ln = 0;
      const uint8_t *p = am_item_bytes(m2, 0, &ln);
      memcpy(blob, p, ln);
      CHECK_OK(am_receive_sync_message(d1, s1, blob, ln));
    }
    am_result_free(m1);
    am_result_free(m2);
    if (quiet) break;
  }
  CHECK(res_int(am_map_get(d2, AM_ROOT, "keep")) == 1);
  AMresult *r = am_map_get(d2, AM_ROOT, "discard");
  CHECK(res_ok(r) && am_result_size(r) == 0);
  am_result_free(r);
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* -- cursors across history and merges --------------------------------------- */
static void test_cursor_matrix(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1);
  char t[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  CHECK_OK(am_splice_text(d1, t, 0, 0, "0123456789"));
  CHECK_OK(am_commit(d1, NULL));
  uint8_t h1[32 * 2];
  size_t n1 = res_heads(am_get_heads(d1), h1, 2);

  /* cursors at the start, middle and end all resolve */
  char c0[160], c5[160], c9[160];
  res_str(am_get_cursor(d1, t, 0), c0, sizeof c0);
  res_str(am_get_cursor(d1, t, 5), c5, sizeof c5);
  res_str(am_get_cursor(d1, t, 9), c9, sizeof c9);
  CHECK(c0[0] && c5[0] && c9[0]);
  CHECK(res_int(am_get_cursor_position(d1, t, c0)) == 0);
  CHECK(res_int(am_get_cursor_position(d1, t, c5)) == 5);
  CHECK(res_int(am_get_cursor_position(d1, t, c9)) == 9);

  /* a merge shifting everything moves all cursors coherently */
  AMdoc *d2 = am_fork(d1, a2, 1);
  CHECK_OK(am_splice_text(d2, t, 0, 0, "<<<"));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_merge(d1, d2));
  CHECK(res_int(am_get_cursor_position(d1, t, c0)) == 3);
  CHECK(res_int(am_get_cursor_position(d1, t, c5)) == 8);
  CHECK(res_int(am_get_cursor_position(d1, t, c9)) == 12);

  /* the cursor's element, read at the OLD heads, has the old position */
  char cat[160];
  res_str(am_get_cursor(d1, t, 8), cat, sizeof cat); /* == c5's element */
  CHECK(strcmp(cat, c5) == 0);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* -- ranges with historical heads through the range reads -------------------- */
static void test_range_reads(void) {
  AMdoc *d = am_create(NULL, 0);
  char l[128];
  obj_of(am_map_put_object(d, AM_ROOT, "l", AM_OBJ_LIST), l, sizeof l);
  for (int i = 0; i < 20; i++)
    CHECK_OK(am_list_insert_int(d, l, (size_t)i, i * 10));
  CHECK_OK(am_commit(d, NULL));

  /* bounded range */
  AMresult *r = am_list_range(d, l, 5, 9);
  CHECK(am_result_size(r) == 4);
  CHECK(am_item_int(r, 0) == 50 && am_item_int(r, 3) == 80);
  am_result_free(r);
  /* empty + inverted + beyond-length ranges are benign */
  r = am_list_range(d, l, 7, 7);
  CHECK(res_ok(r) && am_result_size(r) == 0);
  am_result_free(r);
  r = am_list_range(d, l, 12, 5);
  CHECK(res_ok(r) && am_result_size(r) == 0);
  am_result_free(r);
  r = am_list_range(d, l, 18, 500);
  CHECK(am_result_size(r) == 2);
  am_result_free(r);

  /* map_range begin/end bounds with real keys */
  for (int i = 0; i < 8; i++) {
    char k[8];
    snprintf(k, sizeof k, "m%d", i);
    CHECK_OK(am_map_put_int(d, AM_ROOT, k, i));
  }
  CHECK_OK(am_commit(d, NULL));
  r = am_map_range(d, AM_ROOT, "m2", "m6");
  CHECK(am_result_size(r) == 8); /* m2..m5: 4 entries x (key, value) */
  CHECK(strcmp(am_item_str(r, 0), "m2") == 0);
  am_result_free(r);
  am_doc_free(d);
}

int main(void) {
  if (am_init() != 0) {
    fprintf(stderr, "am_init failed\n");
    return 1;
  }
  test_cursor_matrix();
  test_range_reads();
  test_incremental_save_apply_matrix();
  test_change_exchange_accessors();
  test_sync_state_persistence();
  test_error_paths();
  test_deep_history_reads();
  test_three_peer_counter_convergence();
  test_unicode_text();
  test_fast_path_edges();
  test_throughput_probe();
  test_get_all_at_conflict_history();
  test_deep_nesting();
  test_clone_vs_fork_actors();
  test_many_keys_ordering();
  test_diff_between_heads();
  test_rollback_vs_sync();
  am_shutdown();
  return am_test_finish("test_ported3");
}
