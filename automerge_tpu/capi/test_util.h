/* Minimal assertion harness for the C ABI test programs (no cmocka in
 * this image). Each CHECK counts; a failure prints location + expression
 * and the program exits 1 at the end of main via am_test_finish(). */
#ifndef AM_TEST_UTIL_H
#define AM_TEST_UTIL_H

#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "am.h"

static int am_checks = 0;
static int am_failures = 0;

#define CHECK(cond)                                                        \
  do {                                                                     \
    am_checks++;                                                           \
    if (!(cond)) {                                                         \
      am_failures++;                                                       \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__,   \
              #cond);                                                      \
    }                                                                      \
  } while (0)

/* Result helpers: assert OK (printing the error if not) and free. */
static int res_ok(AMresult *r) {
  int ok = r && am_result_status(r) == AM_STATUS_OK;
  if (!ok && r)
    fprintf(stderr, "  result error: %s\n", am_result_error(r));
  return ok;
}

#define CHECK_OK(r)                                                        \
  do {                                                                     \
    AMresult *_r = (r);                                                    \
    CHECK(res_ok(_r));                                                     \
    am_result_free(_r);                                                    \
  } while (0)

/* One-item accessors that free the result. */
static int64_t res_int(AMresult *r) {
  int64_t v = res_ok(r) && am_result_size(r) > 0 ? am_item_int(r, 0) : -999999;
  am_result_free(r);
  return v;
}

static double res_f64(AMresult *r) {
  double v = res_ok(r) && am_result_size(r) > 0 ? am_item_f64(r, 0) : -1e300;
  am_result_free(r);
  return v;
}

/* Copies the first item's string into buf (NUL-terminated). */
static const char *res_str(AMresult *r, char *buf, size_t cap) {
  buf[0] = '\0';
  if (res_ok(r) && am_result_size(r) > 0) {
    strncpy(buf, am_item_str(r, 0), cap - 1);
    buf[cap - 1] = '\0';
  }
  am_result_free(r);
  return buf;
}

/* Copies the first item's bytes; returns the length. */
static size_t res_bytes(AMresult *r, uint8_t *buf, size_t cap) {
  size_t n = 0;
  if (res_ok(r) && am_result_size(r) > 0) {
    size_t len = 0;
    const uint8_t *p = am_item_bytes(r, 0, &len);
    n = len < cap ? len : cap;
    if (p) memcpy(buf, p, n);
  }
  am_result_free(r);
  return n;
}

/* Concatenate every BYTES item (the heads-blob convention); returns the
 * number of items copied. */
static size_t res_heads(AMresult *r, uint8_t *blob, size_t max_heads) {
  size_t n = 0;
  if (res_ok(r)) {
    size_t count = am_result_size(r);
    for (size_t i = 0; i < count && n < max_heads; i++) {
      size_t len = 0;
      const uint8_t *p = am_item_bytes(r, i, &len);
      if (p && len == 32) memcpy(blob + 32 * n++, p, 32);
    }
  }
  am_result_free(r);
  return n;
}

static int am_test_finish(const char *name) {
  if (am_failures) {
    fprintf(stderr, "%s: %d/%d assertions FAILED\n", name, am_failures,
            am_checks);
    return 1;
  }
  printf("%s: all assertions passed (%d)\n", name, am_checks);
  return 0;
}

#endif /* AM_TEST_UTIL_H */
