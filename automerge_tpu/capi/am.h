/* automerge_tpu C ABI — the analogue of the reference's automerge-c
 * frontend (reference: rust/automerge-c/src/doc.rs, result.rs, item.rs).
 *
 * Memory model: every operation returns an AMresult owning a sequence of
 * tagged AMitems; the caller frees it with am_result_free. Strings and
 * byte spans returned by item accessors are owned by the result and live
 * until it is freed. Documents and sync states are opaque handles freed
 * with their own destructors.
 *
 * Call am_init() once before anything else (it boots the embedded
 * runtime; set AUTOMERGE_TPU_PYROOT if the framework is not importable
 * from the default path), and am_shutdown() at exit.
 */
#ifndef AUTOMERGE_TPU_AM_H
#define AUTOMERGE_TPU_AM_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct AMdoc AMdoc;
typedef struct AMresult AMresult;
typedef struct AMsyncState AMsyncState;

typedef enum {
  AM_STATUS_OK = 0,
  AM_STATUS_ERROR = 1,
} AMstatus;

/* Matches automerge_tpu/capi/shim.py item tags. */
typedef enum {
  AM_VAL_VOID = 0,
  AM_VAL_NULL = 1,
  AM_VAL_BOOL = 2,
  AM_VAL_INT = 3,
  AM_VAL_UINT = 4,
  AM_VAL_F64 = 5,
  AM_VAL_STR = 6,
  AM_VAL_BYTES = 7,
  AM_VAL_COUNTER = 8,
  AM_VAL_TIMESTAMP = 9,
  AM_VAL_OBJ_ID = 10,
} AMvalType;

typedef enum {
  AM_OBJ_MAP = 0,
  AM_OBJ_LIST = 1,
  AM_OBJ_TEXT = 2,
  AM_OBJ_TABLE = 3,
} AMobjType;

#define AM_ROOT "_root"

/* -- runtime ------------------------------------------------------------- */
int am_init(void);
void am_shutdown(void);

/* -- document lifecycle (see also am_create/am_load/am_fork below) -------- */
/* Same history AND same actor id (am_fork mints a fresh actor). */
AMdoc *am_clone(AMdoc *doc);

/* -- results / items ------------------------------------------------------ */
AMstatus am_result_status(const AMresult *r);
const char *am_result_error(const AMresult *r); /* NULL when OK */
size_t am_result_size(const AMresult *r);
AMvalType am_item_type(const AMresult *r, size_t i);
int64_t am_item_int(const AMresult *r, size_t i); /* INT/UINT/COUNTER/TIMESTAMP/BOOL */
double am_item_f64(const AMresult *r, size_t i);
const char *am_item_str(const AMresult *r, size_t i); /* STR / OBJ_ID */
const uint8_t *am_item_bytes(const AMresult *r, size_t i, size_t *len);
void am_result_free(AMresult *r);

/* -- documents ------------------------------------------------------------ */
AMdoc *am_create(const uint8_t *actor, size_t actor_len); /* NULL on error */
AMdoc *am_load(const uint8_t *data, size_t len);
AMdoc *am_fork(AMdoc *doc, const uint8_t *actor, size_t actor_len);
void am_doc_free(AMdoc *doc);

AMresult *am_save(AMdoc *doc);                       /* item: BYTES */
AMresult *am_commit(AMdoc *doc, const char *message); /* item: BYTES hash (or empty) */
AMresult *am_merge(AMdoc *doc, AMdoc *other);         /* items: BYTES hashes */
AMresult *am_get_heads(AMdoc *doc);                   /* items: BYTES */
AMresult *am_actor_id(AMdoc *doc);                    /* item: BYTES */
AMresult *am_set_actor_id(AMdoc *doc, const uint8_t *actor, size_t actor_len);
/* History-heads equality after autocommit (reference AMequal,
 * automerge-c doc.rs:42-44): identical content with different histories
 * compares NOT equal. For content equality use am_equal_content. */
AMresult *am_equal(AMdoc *doc, AMdoc *other);         /* item: BOOL */
/* Current-content equality (hydrated trees; histories may differ). */
AMresult *am_equal_content(AMdoc *doc, AMdoc *other); /* item: BOOL */
/* Uncommitted op count / discard the open transaction (count discarded). */
AMresult *am_pending_ops(AMdoc *doc);                 /* item: UINT */
AMresult *am_rollback(AMdoc *doc);                    /* item: UINT */

/* -- map / list mutation --------------------------------------------------- */
AMresult *am_map_put_null(AMdoc *doc, const char *obj, const char *key);
AMresult *am_map_put_bool(AMdoc *doc, const char *obj, const char *key, int v);
AMresult *am_map_put_int(AMdoc *doc, const char *obj, const char *key, int64_t v);
AMresult *am_map_put_uint(AMdoc *doc, const char *obj, const char *key, uint64_t v);
AMresult *am_map_put_f64(AMdoc *doc, const char *obj, const char *key, double v);
AMresult *am_map_put_str(AMdoc *doc, const char *obj, const char *key, const char *v);
AMresult *am_map_put_bytes(AMdoc *doc, const char *obj, const char *key,
                           const uint8_t *v, size_t len);
AMresult *am_map_put_counter(AMdoc *doc, const char *obj, const char *key, int64_t v);
AMresult *am_map_put_timestamp(AMdoc *doc, const char *obj, const char *key, int64_t v);
AMresult *am_map_put_object(AMdoc *doc, const char *obj, const char *key,
                            AMobjType t); /* item: OBJ_ID */
AMresult *am_map_delete(AMdoc *doc, const char *obj, const char *key);
AMresult *am_map_increment(AMdoc *doc, const char *obj, const char *key, int64_t by);

AMresult *am_list_put_null(AMdoc *doc, const char *obj, size_t index);
AMresult *am_list_put_bool(AMdoc *doc, const char *obj, size_t index, int v);
AMresult *am_list_put_int(AMdoc *doc, const char *obj, size_t index, int64_t v);
AMresult *am_list_put_uint(AMdoc *doc, const char *obj, size_t index, uint64_t v);
AMresult *am_list_put_f64(AMdoc *doc, const char *obj, size_t index, double v);
AMresult *am_list_put_str(AMdoc *doc, const char *obj, size_t index, const char *v);
AMresult *am_list_put_bytes(AMdoc *doc, const char *obj, size_t index,
                            const uint8_t *v, size_t len);
AMresult *am_list_put_counter(AMdoc *doc, const char *obj, size_t index, int64_t v);
AMresult *am_list_put_timestamp(AMdoc *doc, const char *obj, size_t index, int64_t v);
AMresult *am_list_put_object(AMdoc *doc, const char *obj, size_t index,
                             AMobjType t); /* item: OBJ_ID */
AMresult *am_list_insert_null(AMdoc *doc, const char *obj, size_t index);
AMresult *am_list_insert_bool(AMdoc *doc, const char *obj, size_t index, int v);
AMresult *am_list_insert_int(AMdoc *doc, const char *obj, size_t index, int64_t v);
AMresult *am_list_insert_uint(AMdoc *doc, const char *obj, size_t index, uint64_t v);
AMresult *am_list_insert_f64(AMdoc *doc, const char *obj, size_t index, double v);
AMresult *am_list_insert_str(AMdoc *doc, const char *obj, size_t index, const char *v);
AMresult *am_list_insert_bytes(AMdoc *doc, const char *obj, size_t index,
                               const uint8_t *v, size_t len);
AMresult *am_list_insert_counter(AMdoc *doc, const char *obj, size_t index, int64_t v);
AMresult *am_list_insert_timestamp(AMdoc *doc, const char *obj, size_t index,
                                   int64_t v);
AMresult *am_list_insert_object(AMdoc *doc, const char *obj, size_t index,
                                AMobjType t); /* item: OBJ_ID */
AMresult *am_list_delete(AMdoc *doc, const char *obj, size_t index);
AMresult *am_list_increment(AMdoc *doc, const char *obj, size_t index, int64_t by);

/* -- text ------------------------------------------------------------------ */
AMresult *am_splice_text(AMdoc *doc, const char *obj, size_t pos, size_t del,
                         const char *text);
AMresult *am_text(AMdoc *doc, const char *obj); /* item: STR */

/* -- reads ----------------------------------------------------------------- */
AMresult *am_map_get(AMdoc *doc, const char *obj, const char *key);
AMresult *am_map_get_all(AMdoc *doc, const char *obj, const char *key);
AMresult *am_list_get(AMdoc *doc, const char *obj, size_t index);
AMresult *am_keys(AMdoc *doc, const char *obj);   /* items: STR */
AMresult *am_length(AMdoc *doc, const char *obj); /* item: UINT */
/* item: UINT AMobjType code */
AMresult *am_object_type(AMdoc *doc, const char *obj);
/* one value/OBJ_ID item per visible element */
AMresult *am_list_items(AMdoc *doc, const char *obj);
/* per entry: STR key then the value item (2 items each) */
AMresult *am_map_entries(AMdoc *doc, const char *obj);
/* value items for visible indices in [start, end) */
/* end = SIZE_MAX means unbounded (reference AMlistRange convention). */
AMresult *am_list_range(AMdoc *doc, const char *obj, size_t start, size_t end);
/* (STR key, value item) pairs for keys in [begin, end); "" end = unbounded */
AMresult *am_map_range(AMdoc *doc, const char *obj, const char *begin,
                       const char *end);
/* delete ``del`` elements at ``pos`` (AMsplice's delete side; insertions
 * go through the typed am_list_insert_* calls) */
AMresult *am_list_splice(AMdoc *doc, const char *obj, size_t pos, size_t del);

/* -- historical reads (*_at) ----------------------------------------------- */
/* ``heads`` = n_heads concatenated 32-byte change hashes (the bytes of
 * am_get_heads items back to back) — the reference's *_at read surface
 * (reference: rust/automerge/src/read.rs parents_at/keys_at/...). */
AMresult *am_map_get_at(AMdoc *doc, const char *obj, const char *key,
                        const uint8_t *heads, size_t n_heads);
AMresult *am_map_get_all_at(AMdoc *doc, const char *obj, const char *key,
                            const uint8_t *heads, size_t n_heads);
AMresult *am_list_get_at(AMdoc *doc, const char *obj, size_t index,
                         const uint8_t *heads, size_t n_heads);
AMresult *am_keys_at(AMdoc *doc, const char *obj, const uint8_t *heads,
                     size_t n_heads);
AMresult *am_length_at(AMdoc *doc, const char *obj, const uint8_t *heads,
                       size_t n_heads);
AMresult *am_text_at(AMdoc *doc, const char *obj, const uint8_t *heads,
                     size_t n_heads);
AMresult *am_marks_at(AMdoc *doc, const char *obj, const uint8_t *heads,
                      size_t n_heads);
/* Fork pinned at historical heads (reference: automerge.rs fork_at). */
AMdoc *am_fork_at(AMdoc *doc, const uint8_t *heads, size_t n_heads,
                  const uint8_t *actor, size_t actor_len);

/* -- patches ---------------------------------------------------------------- */
/* Both return flat 6-item records per patch:
 *   STR obj exid | STR path ("key/3/sub") | STR kind | STR prop |
 *   UINT index-or-length | value item (VOID when the kind carries none)
 * kinds: put_map put_seq insert splice_text del_map del_seq increment
 * flag_conflict mark_clear mark mark_end. Insert emits one record per
 * inserted value. Mark changes use replace-all framing: one mark_clear
 * record for the object, then per surviving span a ("mark", name, start,
 * value) record paired with a ("mark_end", name, end, VOID) record —
 * replace the object's marks with the set between mark_clear records.
 * Patch value items carry counter values as INT (the materialized
 * number); read accessors (am_map_get &c.) are the source of
 * counter-ness. */
AMresult *am_diff(AMdoc *doc, const uint8_t *before, size_t n_before,
                  const uint8_t *after, size_t n_after);
/* Patches since the last pop; the first call activates the observer log
 * at the current heads and returns an empty result. */
AMresult *am_pop_patches(AMdoc *doc);

/* -- marks / cursors ------------------------------------------------------- */
/* expand: "none" | "before" | "after" | "both" (reference ExpandMark). */
AMresult *am_mark_str(AMdoc *doc, const char *obj, size_t start, size_t end,
                      const char *name, const char *value, const char *expand);
AMresult *am_mark_bool(AMdoc *doc, const char *obj, size_t start, size_t end,
                       const char *name, int value, const char *expand);
AMresult *am_unmark(AMdoc *doc, const char *obj, size_t start, size_t end,
                    const char *name);
/* items per mark: UINT start, UINT end, STR name, then the value item */
AMresult *am_marks(AMdoc *doc, const char *obj);
AMresult *am_get_cursor(AMdoc *doc, const char *obj, size_t pos); /* item: STR */
AMresult *am_get_cursor_position(AMdoc *doc, const char *obj,
                                 const char *cursor); /* item: UINT */

/* -- history exchange ------------------------------------------------------ */
/* Apply raw change/document chunk bytes (a peer's save_incremental output). */
AMresult *am_apply_changes(AMdoc *doc, const uint8_t *data, size_t len);
/* Change chunks not covered by the given 32-byte head hashes (concatenated
 * AMresult BYTES items from am_get_heads); item: BYTES. */
AMresult *am_save_incremental(AMdoc *doc, const uint8_t *heads, size_t n_heads);
/* Raw change chunks not reachable from the given heads; items: BYTES. */
AMresult *am_get_changes(AMdoc *doc, const uint8_t *heads, size_t n_heads);
/* One raw change chunk by its 32-byte hash (empty result = unknown). */
AMresult *am_get_change_by_hash(AMdoc *doc, const uint8_t *hash);
/* Raw change chunks present in ``other`` but absent from ``doc`` — what a
 * merge of ``other`` into ``doc`` would apply. */
AMresult *am_get_changes_added(AMdoc *doc, AMdoc *other);
/* The author's most recent change (commits pending ops first). */
AMresult *am_get_last_local_change(AMdoc *doc);       /* BYTES or empty */
/* Dependency hashes referenced but not yet applied (the causal queue's
 * wait set), given additional target heads. */
AMresult *am_get_missing_deps(AMdoc *doc, const uint8_t *heads, size_t n_heads);

/* -- sync ------------------------------------------------------------------ */
AMsyncState *am_sync_state_new(void);
void am_sync_state_free(AMsyncState *s);
AMresult *am_generate_sync_message(AMdoc *doc, AMsyncState *s); /* BYTES or empty */
AMresult *am_receive_sync_message(AMdoc *doc, AMsyncState *s, const uint8_t *msg,
                                  size_t len);
/* Persistable sync-state codec (reference: sync/state.rs encode/decode —
 * only shared_heads survive the roundtrip, by design). */
AMresult *am_sync_state_encode(AMsyncState *s); /* item: BYTES */
AMsyncState *am_sync_state_decode(const uint8_t *data, size_t len);
/* Heads both peers are known to share (BYTES items). */
AMresult *am_sync_state_shared_heads(AMsyncState *s);

#ifdef __cplusplus
}
#endif
#endif /* AUTOMERGE_TPU_AM_H */
