/* Second ported-scenario suite: the reference wasm/C scenarios not yet
 * covered by test_basic.c / test_sync.c, re-expressed against this
 * framework's am.h (behavioral ports of
 * rust/automerge-c/test/ported_wasm/basic_tests.c and sync_tests.c —
 * no code copied; scenario names cite the originals).
 *
 * Covers: the list insert/put/push/splice matrix, delete of
 * non-existent props, counters in sequences under concurrent puts,
 * mark expand policies + overlap + unmark + historical marks, cursor
 * stability under concurrent edits and deletion, deep historical
 * reads, recursive subtree deletion, out-of-order change application
 * (causal queue + missing deps), and the sync scenarios: equal heads,
 * either initiator, simultaneous crossing messages, no-resend
 * backpressure, non-empty state after sync, data loss with and
 * without disconnecting, concurrent-to-last-sync heads, and
 * branching/merging storms.
 */
#include <stdio.h>
#include <string.h>

#include "am.h"
#include "test_util.h"

static uint8_t msg[1 << 20];
static uint8_t blob[1 << 20];
static char sbuf[4096];

/* -- helpers ---------------------------------------------------------------- */

static int sync_rounds(AMdoc *a, AMdoc *b, AMsyncState *sa, AMsyncState *sb) {
  for (int round = 0; round < 64; round++) {
    AMresult *ma = am_generate_sync_message(a, sa);
    AMresult *mb = am_generate_sync_message(b, sb);
    if (!res_ok(ma) || !res_ok(mb)) {
      am_result_free(ma);
      am_result_free(mb);
      return -1;
    }
    int quiet = am_result_size(ma) == 0 && am_result_size(mb) == 0;
    if (am_result_size(ma) > 0) {
      size_t len = 0;
      const uint8_t *p = am_item_bytes(ma, 0, &len);
      memcpy(msg, p, len);
      AMresult *r = am_receive_sync_message(b, sb, msg, len);
      if (!res_ok(r)) quiet = -1;
      am_result_free(r);
    }
    if (am_result_size(mb) > 0) {
      size_t len = 0;
      const uint8_t *p = am_item_bytes(mb, 0, &len);
      memcpy(msg, p, len);
      AMresult *r = am_receive_sync_message(a, sa, msg, len);
      if (!res_ok(r)) quiet = -1;
      am_result_free(r);
    }
    am_result_free(ma);
    am_result_free(mb);
    if (quiet == 1) return round;
    if (quiet < 0) return -1;
  }
  return -1;
}

static int docs_equal_heads(AMdoc *a, AMdoc *b) {
  static uint8_t ha[32 * 64], hb[32 * 64];
  size_t na = res_heads(am_get_heads(a), ha, 64);
  size_t nb = res_heads(am_get_heads(b), hb, 64);
  return na == nb && memcmp(ha, hb, 32 * na) == 0;
}

static void obj_of(AMresult *r, char *out, size_t cap) {
  out[0] = '\0';
  if (res_ok(r) && am_result_size(r) > 0) {
    strncpy(out, am_item_str(r, 0), cap - 1);
    out[cap - 1] = '\0';
  }
  am_result_free(r);
}

/* -- lists have insert, put, push and splice ops ---------------------------- */
/* (reference basic_tests.c test_lists_have_insert_set_splice_and_push_ops) */
static void test_list_op_matrix(void) {
  AMdoc *d = am_create(NULL, 0);
  char l[128];
  obj_of(am_map_put_object(d, AM_ROOT, "l", AM_OBJ_LIST), l, sizeof l);
  CHECK(l[0] != '\0');

  /* push == insert at length */
  CHECK_OK(am_list_insert_int(d, l, 0, 1));
  CHECK_OK(am_list_insert_int(d, l, 1, 2));
  CHECK_OK(am_list_insert_int(d, l, 2, 3));
  CHECK(res_int(am_length(d, l)) == 3);

  /* put overwrites in place (no length change) */
  CHECK_OK(am_list_put_str(d, l, 1, "two"));
  CHECK(res_int(am_length(d, l)) == 3);
  AMresult *r = am_list_get(d, l, 1);
  CHECK(am_item_type(r, 0) == AM_VAL_STR);
  CHECK(strcmp(am_item_str(r, 0), "two") == 0);
  am_result_free(r);

  /* insert in the middle shifts the tail */
  CHECK_OK(am_list_insert_f64(d, l, 1, 2.5));
  CHECK(res_int(am_length(d, l)) == 4);
  CHECK(res_f64(am_list_get(d, l, 1)) == 2.5);
  r = am_list_get(d, l, 2);
  CHECK(strcmp(am_item_str(r, 0), "two") == 0);
  am_result_free(r);

  /* every scalar type survives a put + read back */
  CHECK_OK(am_list_put_null(d, l, 0));
  r = am_list_get(d, l, 0);
  CHECK(am_item_type(r, 0) == AM_VAL_NULL);
  am_result_free(r);
  CHECK_OK(am_list_put_bool(d, l, 0, 1));
  CHECK(res_int(am_list_get(d, l, 0)) == 1);
  CHECK_OK(am_list_put_uint(d, l, 0, 77));
  r = am_list_get(d, l, 0);
  CHECK(am_item_type(r, 0) == AM_VAL_UINT && am_item_int(r, 0) == 77);
  am_result_free(r);
  CHECK_OK(am_list_put_timestamp(d, l, 0, 1700000000));
  r = am_list_get(d, l, 0);
  CHECK(am_item_type(r, 0) == AM_VAL_TIMESTAMP);
  CHECK(am_item_int(r, 0) == 1700000000);
  am_result_free(r);
  uint8_t raw[3] = {9, 8, 7};
  CHECK_OK(am_list_put_bytes(d, l, 0, raw, 3));
  r = am_list_get(d, l, 0);
  size_t bl = 0;
  const uint8_t *bp = am_item_bytes(r, 0, &bl);
  CHECK(bl == 3 && bp[0] == 9 && bp[2] == 7);
  am_result_free(r);

  /* splice-delete removes a run */
  CHECK_OK(am_list_splice(d, l, 1, 2));
  CHECK(res_int(am_length(d, l)) == 2);

  /* nested object put returns its id and reads back as OBJ_ID */
  char sub[128];
  obj_of(am_list_put_object(d, l, 0, AM_OBJ_MAP), sub, sizeof sub);
  CHECK(sub[0] != '\0');
  CHECK_OK(am_map_put_int(d, sub, "deep", 42));
  r = am_list_get(d, l, 0);
  CHECK(am_item_type(r, 0) == AM_VAL_OBJ_ID);
  am_result_free(r);
  CHECK(res_int(am_map_get(d, sub, "deep")) == 42);

  /* list_items walks visible values in order */
  r = am_list_items(d, l);
  CHECK(am_result_size(r) == 2);
  CHECK(am_item_type(r, 0) == AM_VAL_OBJ_ID);
  am_result_free(r);

  /* list_range subranges */
  CHECK_OK(am_list_insert_int(d, l, 2, 10));
  CHECK_OK(am_list_insert_int(d, l, 3, 11));
  r = am_list_range(d, l, 1, 3);
  CHECK(am_result_size(r) == 2);
  am_result_free(r);
  am_doc_free(d);
}

/* -- deleting non-existent props is a no-op --------------------------------- */
/* (reference basic_tests.c test_should_be_able_to_delete_non_existent_props) */
static void test_delete_nonexistent_props(void) {
  AMdoc *d = am_create(NULL, 0);
  CHECK_OK(am_map_put_str(d, AM_ROOT, "foo", "bar"));
  CHECK_OK(am_map_put_str(d, AM_ROOT, "bip", "bap"));
  uint8_t h1[32 * 4];
  CHECK_OK(am_commit(d, NULL));
  size_t n1 = res_heads(am_get_heads(d), h1, 4);
  CHECK(n1 == 1);

  AMresult *keys = am_keys(d, AM_ROOT);
  CHECK(am_result_size(keys) == 2);
  CHECK(strcmp(am_item_str(keys, 0), "bip") == 0);
  CHECK(strcmp(am_item_str(keys, 1), "foo") == 0);
  am_result_free(keys);

  CHECK_OK(am_map_delete(d, AM_ROOT, "foo"));
  CHECK_OK(am_map_delete(d, AM_ROOT, "baz")); /* non-existent: no-op */
  CHECK_OK(am_commit(d, NULL));

  keys = am_keys(d, AM_ROOT);
  CHECK(am_result_size(keys) == 1);
  CHECK(strcmp(am_item_str(keys, 0), "bip") == 0);
  am_result_free(keys);

  /* the historical view still shows both */
  keys = am_keys_at(d, AM_ROOT, h1, n1);
  CHECK(am_result_size(keys) == 2);
  am_result_free(keys);
  am_doc_free(d);
}

/* -- counters in a sequence under concurrent puts ---------------------------- */
/* (reference test_local_inc_increments_all_visible_counters_in_a_sequence) */
static void test_counters_in_sequence(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1);
  char l[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "l", AM_OBJ_LIST), l, sizeof l);
  CHECK_OK(am_list_insert_str(d1, l, 0, "seed"));
  CHECK_OK(am_commit(d1, NULL));

  AMdoc *d2 = am_fork(d1, a2, 1);
  /* concurrent: both replace index 0 with a counter */
  CHECK_OK(am_list_put_counter(d1, l, 0, 10));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_list_put_counter(d2, l, 0, 100));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_merge(d1, d2));

  /* one increment bumps EVERY visible (conflicting) counter */
  CHECK_OK(am_list_increment(d1, l, 0, 5));
  CHECK_OK(am_commit(d1, NULL));
  AMresult *all = am_map_get_all(d1, l, "0"); /* not a map: expect error */
  am_result_free(all);
  /* winner value reflects its own increment */
  AMresult *r = am_list_get(d1, l, 0);
  CHECK(am_item_type(r, 0) == AM_VAL_COUNTER);
  int64_t winner = am_item_int(r, 0);
  CHECK(winner == 15 || winner == 105);
  am_result_free(r);

  /* merge back into d2 and increment there too: totals stay coherent */
  CHECK_OK(am_merge(d2, d1));
  r = am_list_get(d2, l, 0);
  CHECK(am_item_type(r, 0) == AM_VAL_COUNTER);
  CHECK(am_item_int(r, 0) == winner);
  am_result_free(r);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* -- mark expand policies, overlap, unmark, historical marks ----------------- */
static void test_marks_depth(void) {
  AMdoc *d = am_create(NULL, 0);
  char t[128];
  obj_of(am_map_put_object(d, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  CHECK_OK(am_splice_text(d, t, 0, 0, "hello world"));
  CHECK_OK(am_commit(d, NULL));
  uint8_t h1[32 * 4];
  size_t n1 = res_heads(am_get_heads(d), h1, 4);

  /* overlapping marks of different names coexist */
  CHECK_OK(am_mark_bool(d, t, 0, 5, "bold", 1, "none"));
  CHECK_OK(am_mark_str(d, t, 3, 8, "comment", "hi", "none"));
  CHECK_OK(am_commit(d, NULL));
  AMresult *m = am_marks(d, t);
  CHECK(am_result_size(m) == 8); /* 2 marks x 4 items */
  am_result_free(m);

  /* unmark a subrange splits the span */
  CHECK_OK(am_unmark(d, t, 1, 3, "bold"));
  CHECK_OK(am_commit(d, NULL));
  m = am_marks(d, t);
  /* bold [0,1) + bold [3,5) + comment [3,8) = 3 spans */
  CHECK(am_result_size(m) == 12);
  am_result_free(m);

  /* historical view: before any marks there were none */
  m = am_marks_at(d, t, h1, n1);
  CHECK(am_result_size(m) == 0);
  am_result_free(m);

  /* expand policies: after/both grow over an insertion at the end edge */
  char t2[128];
  obj_of(am_map_put_object(d, AM_ROOT, "t2", AM_OBJ_TEXT), t2, sizeof t2);
  CHECK_OK(am_splice_text(d, t2, 0, 0, "abcd"));
  CHECK_OK(am_mark_bool(d, t2, 1, 3, "grow", 1, "both"));
  CHECK_OK(am_mark_bool(d, t2, 1, 3, "stay", 1, "none"));
  CHECK_OK(am_commit(d, NULL));
  CHECK_OK(am_splice_text(d, t2, 3, 0, "XY")); /* insert at the end edge */
  CHECK_OK(am_commit(d, NULL));
  m = am_marks(d, t2);
  int found_grow = 0, found_stay = 0;
  for (size_t i = 0; i + 3 < am_result_size(m); i += 4) {
    const char *name = am_item_str(m, i + 2);
    int64_t start = am_item_int(m, i), end = am_item_int(m, i + 1);
    if (name && strcmp(name, "grow") == 0) {
      found_grow = 1;
      CHECK(start == 1 && end == 5); /* swallowed the insertion */
    }
    if (name && strcmp(name, "stay") == 0) {
      found_stay = 1;
      CHECK(start == 1 && end == 3); /* did not */
    }
  }
  CHECK(found_grow && found_stay);
  am_result_free(m);

  /* marks survive save/load */
  size_t sl = res_bytes(am_save(d), blob, sizeof blob);
  AMdoc *d2 = am_load(blob, sl);
  CHECK(d2 != NULL);
  m = am_marks(d2, t2);
  CHECK(am_result_size(m) >= 8);
  am_result_free(m);
  am_doc_free(d2);
  am_doc_free(d);
}

/* -- cursors track elements through concurrent edits and deletion ------------ */
static void test_cursor_stability(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1);
  char t[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "t", AM_OBJ_TEXT), t, sizeof t);
  CHECK_OK(am_splice_text(d1, t, 0, 0, "abcdef"));
  CHECK_OK(am_commit(d1, NULL));
  char cur[160];
  res_str(am_get_cursor(d1, t, 3), cur, sizeof cur); /* element 'd' */
  CHECK(cur[0] != '\0');

  /* concurrent edits on a fork move the cursor's element */
  AMdoc *d2 = am_fork(d1, a2, 1);
  CHECK_OK(am_splice_text(d2, t, 0, 0, "..."));
  CHECK_OK(am_commit(d2, NULL));
  CHECK_OK(am_splice_text(d1, t, 5, 1, "F"));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_merge(d1, d2));
  CHECK(res_int(am_get_cursor_position(d1, t, cur)) == 6);

  /* cursor survives in the fork that never saw the original doc object */
  CHECK_OK(am_merge(d2, d1));
  CHECK(res_int(am_get_cursor_position(d2, t, cur)) == 6);

  /* deleting the element: position degrades to the nearest survivor */
  CHECK_OK(am_splice_text(d1, t, 6, 1, ""));
  CHECK_OK(am_commit(d1, NULL));
  int64_t pos = res_int(am_get_cursor_position(d1, t, cur));
  CHECK(pos >= 0 && pos <= (int64_t)6);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* -- recursive subtree deletion + re-put ------------------------------------- */
static void test_recursive_delete_and_reput(void) {
  AMdoc *d = am_create(NULL, 0);
  char outer[128], inner[128], list[128];
  obj_of(am_map_put_object(d, AM_ROOT, "cfg", AM_OBJ_MAP), outer, sizeof outer);
  obj_of(am_map_put_object(d, outer, "nested", AM_OBJ_MAP), inner, sizeof inner);
  obj_of(am_map_put_object(d, inner, "items", AM_OBJ_LIST), list, sizeof list);
  CHECK_OK(am_list_insert_int(d, list, 0, 1));
  CHECK_OK(am_commit(d, NULL));
  uint8_t h1[32 * 4];
  size_t n1 = res_heads(am_get_heads(d), h1, 4);

  /* delete the whole subtree at its root */
  CHECK_OK(am_map_delete(d, AM_ROOT, "cfg"));
  CHECK_OK(am_commit(d, NULL));
  AMresult *r = am_map_get(d, AM_ROOT, "cfg");
  CHECK(am_result_size(r) == 0);
  am_result_free(r);

  /* re-put the same key: a FRESH object, not the old one */
  char outer2[128];
  obj_of(am_map_put_object(d, AM_ROOT, "cfg", AM_OBJ_MAP), outer2, sizeof outer2);
  CHECK(strcmp(outer, outer2) != 0);
  CHECK_OK(am_map_put_int(d, outer2, "v", 2));
  CHECK_OK(am_commit(d, NULL));
  CHECK(res_int(am_map_get(d, outer2, "v")) == 2);

  /* the old subtree is still reachable at the old heads */
  r = am_map_get_at(d, AM_ROOT, "cfg", h1, n1);
  CHECK(am_result_size(r) == 1 && am_item_type(r, 0) == AM_VAL_OBJ_ID);
  am_result_free(r);
  CHECK(res_int(am_length_at(d, list, h1, n1)) == 1);
  am_doc_free(d);
}

/* -- out-of-order change application: causal queue + missing deps ------------ */
static void test_out_of_order_changes(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *src = am_create(a1, 1);
  CHECK_OK(am_map_put_int(src, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(src, NULL));
  uint8_t h1[32 * 4];
  size_t n1 = res_heads(am_get_heads(src), h1, 4);
  size_t c1 = res_bytes(am_save_incremental(src, NULL, 0), blob, sizeof blob);
  CHECK(c1 > 0);

  CHECK_OK(am_map_put_int(src, AM_ROOT, "x", 2));
  CHECK_OK(am_commit(src, NULL));
  static uint8_t c2buf[1 << 16];
  size_t c2 = res_bytes(am_save_incremental(src, h1, n1), c2buf, sizeof c2buf);
  CHECK(c2 > 0);

  /* apply the SECOND change first: doc must queue it and report the
   * missing dependency, showing nothing until the gap fills */
  AMdoc *dst = am_create(a2, 1);
  CHECK_OK(am_apply_changes(dst, c2buf, c2));
  AMresult *r = am_map_get(dst, AM_ROOT, "x");
  CHECK(am_result_size(r) == 0);
  am_result_free(r);
  r = am_get_missing_deps(dst, NULL, 0);
  CHECK(am_result_size(r) == 1);
  am_result_free(r);

  CHECK_OK(am_apply_changes(dst, blob, c1));
  CHECK(res_int(am_map_get(dst, AM_ROOT, "x")) == 2);
  r = am_get_missing_deps(dst, NULL, 0);
  CHECK(am_result_size(r) == 0);
  am_result_free(r);
  CHECK(docs_equal_heads(src, dst));
  am_doc_free(src);
  am_doc_free(dst);
}

/* ======================= sync scenarios ==================================== */

/* (reference sync_tests.c test_repos_with_equal_heads_do_not_need_a_reply) */
static void test_sync_equal_heads_quick_quiet(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1);
  char l[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "n", AM_OBJ_LIST), l, sizeof l);
  for (int i = 0; i < 10; i++) {
    CHECK_OK(am_list_insert_int(d1, l, (size_t)i, i));
    CHECK_OK(am_commit(d1, NULL));
  }
  size_t sl = res_bytes(am_save(d1), blob, sizeof blob);
  AMdoc *d2 = am_load(blob, sl);
  CHECK(d2 && docs_equal_heads(d1, d2));

  /* both already share everything: one round trip goes quiet */
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  int rounds = sync_rounds(d1, d2, s1, s2);
  CHECK(rounds >= 0 && rounds <= 2);
  CHECK(docs_equal_heads(d1, d2));
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* (reference test_should_work_regardless_of_who_initiates_the_exchange) */
static void test_sync_either_initiator(void) {
  for (int initiator = 0; initiator < 2; initiator++) {
    uint8_t a1[1] = {1}, a2[1] = {2};
    AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
    char l[128];
    obj_of(am_map_put_object(d1, AM_ROOT, "n", AM_OBJ_LIST), l, sizeof l);
    for (int i = 0; i < 5; i++) {
      CHECK_OK(am_list_insert_int(d1, l, (size_t)i, i));
      CHECK_OK(am_commit(d1, NULL));
    }
    AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
    int rounds = initiator == 0 ? sync_rounds(d1, d2, s1, s2)
                                : sync_rounds(d2, d1, s2, s1);
    CHECK(rounds >= 0);
    CHECK(docs_equal_heads(d1, d2));
    CHECK(res_int(am_length(d2, l)) == 5);
    am_sync_state_free(s1);
    am_sync_state_free(s2);
    am_doc_free(d1);
    am_doc_free(d2);
  }
}

/* (reference test_should_allow_simultaneous_messages_during_synchronization)
 * Both peers keep generating before receiving — messages cross in flight
 * every round — and the protocol still converges. */
static void test_sync_simultaneous_messages(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
  char l1[128], l2[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "a", AM_OBJ_LIST), l1, sizeof l1);
  obj_of(am_map_put_object(d2, AM_ROOT, "b", AM_OBJ_LIST), l2, sizeof l2);
  for (int i = 0; i < 8; i++) {
    CHECK_OK(am_list_insert_int(d1, l1, (size_t)i, i));
    CHECK_OK(am_commit(d1, NULL));
    CHECK_OK(am_list_insert_int(d2, l2, (size_t)i, 100 + i));
    CHECK_OK(am_commit(d2, NULL));
  }
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  static uint8_t m1[1 << 18], m2[1 << 18];
  int converged = 0;
  for (int round = 0; round < 64 && !converged; round++) {
    /* generate BOTH first (simultaneous), then deliver both */
    AMresult *r1 = am_generate_sync_message(d1, s1);
    AMresult *r2 = am_generate_sync_message(d2, s2);
    size_t n1 = 0, n2 = 0;
    if (am_result_size(r1)) {
      const uint8_t *p = am_item_bytes(r1, 0, &n1);
      memcpy(m1, p, n1);
    }
    if (am_result_size(r2)) {
      const uint8_t *p = am_item_bytes(r2, 0, &n2);
      memcpy(m2, p, n2);
    }
    converged = n1 == 0 && n2 == 0;
    am_result_free(r1);
    am_result_free(r2);
    if (n1) CHECK_OK(am_receive_sync_message(d2, s2, m1, n1));
    if (n2) CHECK_OK(am_receive_sync_message(d1, s1, m2, n2));
  }
  CHECK(converged);
  CHECK(docs_equal_heads(d1, d2));
  CHECK(res_int(am_length(d1, l2)) == 8);
  CHECK(res_int(am_length(d2, l1)) == 8);
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* (reference test_should_assume_sent_changes_were_received...) — a peer
 * must not re-send the same changes while they are in flight. */
static void test_sync_no_resend_in_flight(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
  char l[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "n", AM_OBJ_LIST), l, sizeof l);
  CHECK_OK(am_commit(d1, NULL));
  /* establish the session so d1 knows d2's wants */
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  CHECK(sync_rounds(d1, d2, s1, s2) >= 0);

  for (int i = 0; i < 20; i++) {
    CHECK_OK(am_list_insert_int(d1, l, (size_t)i, i));
    CHECK_OK(am_commit(d1, NULL));
  }
  /* first message carries the 20 new changes */
  AMresult *r = am_generate_sync_message(d1, s1);
  CHECK(am_result_size(r) == 1);
  size_t first = 0;
  am_item_bytes(r, 0, &first);
  am_result_free(r);
  /* generating AGAIN without hearing back must not re-carry them */
  r = am_generate_sync_message(d1, s1);
  size_t second = 0;
  if (am_result_size(r)) am_item_bytes(r, 0, &second);
  am_result_free(r);
  CHECK(second < first / 2);
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* (reference test_should_ensure_non_empty_state_after_sync) */
static void test_sync_non_empty_state(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "x", 1));
  CHECK_OK(am_commit(d1, NULL));
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  CHECK(sync_rounds(d1, d2, s1, s2) >= 0);
  AMresult *r = am_sync_state_shared_heads(s1);
  CHECK(am_result_size(r) == 1);
  am_result_free(r);
  r = am_sync_state_shared_heads(s2);
  CHECK(am_result_size(r) == 1);
  am_result_free(r);
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* (reference test_should_resync_after_one_node_experiences_data_loss_
 * without_disconnecting) — the lossy peer RESTARTS from an old save but
 * the healthy peer keeps its session state. */
static void test_sync_data_loss_without_disconnect(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
  char l[128];
  obj_of(am_map_put_object(d1, AM_ROOT, "n", AM_OBJ_LIST), l, sizeof l);
  CHECK_OK(am_commit(d1, NULL));
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  CHECK(sync_rounds(d1, d2, s1, s2) >= 0);
  size_t old_len = res_bytes(am_save(d2), blob, sizeof blob);

  for (int i = 0; i < 6; i++) {
    CHECK_OK(am_list_insert_int(d1, l, (size_t)i, i));
    CHECK_OK(am_commit(d1, NULL));
  }
  CHECK(sync_rounds(d1, d2, s1, s2) >= 0);
  CHECK(docs_equal_heads(d1, d2));

  /* d2 crashes and reloads the stale save; ITS state is fresh but d1
   * still believes the old session */
  am_doc_free(d2);
  d2 = am_load(blob, old_len);
  CHECK(d2 != NULL);
  AMsyncState *s2b = am_sync_state_new();
  CHECK(sync_rounds(d1, d2, s1, s2b) >= 0);
  CHECK(docs_equal_heads(d1, d2));
  CHECK(res_int(am_length(d2, l)) == 6);
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_sync_state_free(s2b);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* (reference test_should_handle_changes_concurrent_to_the_last_sync_heads) */
static void test_sync_concurrent_to_last_heads(void) {
  uint8_t a1[1] = {1}, a2[1] = {2};
  AMdoc *d1 = am_create(a1, 1), *d2 = am_create(a2, 1);
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "seed", 0));
  CHECK_OK(am_commit(d1, NULL));
  AMsyncState *s1 = am_sync_state_new(), *s2 = am_sync_state_new();
  CHECK(sync_rounds(d1, d2, s1, s2) >= 0);

  /* both edit concurrently AFTER the session established */
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "from1", 1));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_map_put_int(d2, AM_ROOT, "from2", 2));
  CHECK_OK(am_commit(d2, NULL));
  CHECK(sync_rounds(d1, d2, s1, s2) >= 0);
  CHECK(docs_equal_heads(d1, d2));
  CHECK(res_int(am_map_get(d1, AM_ROOT, "from2")) == 2);
  CHECK(res_int(am_map_get(d2, AM_ROOT, "from1")) == 1);

  /* and again: a second wave reusing the same states */
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "w2a", 3));
  CHECK_OK(am_commit(d1, NULL));
  CHECK_OK(am_map_put_int(d2, AM_ROOT, "w2b", 4));
  CHECK_OK(am_commit(d2, NULL));
  CHECK(sync_rounds(d1, d2, s1, s2) >= 0);
  CHECK(docs_equal_heads(d1, d2));
  am_sync_state_free(s1);
  am_sync_state_free(s2);
  am_doc_free(d1);
  am_doc_free(d2);
}

/* (reference test_should_handle_histories_with_lots_of_branching_and_merging) */
static void test_sync_branching_merging_storm(void) {
  uint8_t a1[1] = {1}, a2[1] = {2}, a3[1] = {3};
  AMdoc *d1 = am_create(a1, 1);
  CHECK_OK(am_map_put_int(d1, AM_ROOT, "seed", 0));
  CHECK_OK(am_commit(d1, NULL));
  size_t sl = res_bytes(am_save(d1), blob, sizeof blob);
  AMdoc *d2 = am_load(blob, sl);
  AMdoc *d3 = am_load(blob, sl);
  CHECK(d2 && d3);
  CHECK_OK(am_set_actor_id(d2, a2, 1));
  CHECK_OK(am_set_actor_id(d3, a3, 1));

  /* rounds of independent edits + partial merges build a wide DAG */
  for (int i = 0; i < 6; i++) {
    char key[16];
    snprintf(key, sizeof key, "k1_%d", i);
    CHECK_OK(am_map_put_int(d1, AM_ROOT, key, i));
    CHECK_OK(am_commit(d1, NULL));
    snprintf(key, sizeof key, "k2_%d", i);
    CHECK_OK(am_map_put_int(d2, AM_ROOT, key, i));
    CHECK_OK(am_commit(d2, NULL));
    snprintf(key, sizeof key, "k3_%d", i);
    CHECK_OK(am_map_put_int(d3, AM_ROOT, key, i));
    CHECK_OK(am_commit(d3, NULL));
    if (i % 2 == 0) {
      CHECK_OK(am_merge(d1, d2));
      CHECK_OK(am_merge(d2, d3));
    } else {
      CHECK_OK(am_merge(d3, d1));
    }
  }
  /* pairwise sync all three to a single converged state */
  AMsyncState *s12 = am_sync_state_new(), *s21 = am_sync_state_new();
  AMsyncState *s13 = am_sync_state_new(), *s31 = am_sync_state_new();
  CHECK(sync_rounds(d1, d2, s12, s21) >= 0);
  CHECK(sync_rounds(d1, d3, s13, s31) >= 0);
  CHECK(sync_rounds(d1, d2, s12, s21) >= 0);
  CHECK(docs_equal_heads(d1, d2));
  CHECK(docs_equal_heads(d1, d3));
  /* every branch's keys are visible everywhere */
  AMresult *keys = am_keys(d3, AM_ROOT);
  CHECK(am_result_size(keys) == 1 + 18);
  am_result_free(keys);
  am_sync_state_free(s12);
  am_sync_state_free(s21);
  am_sync_state_free(s13);
  am_sync_state_free(s31);
  am_doc_free(d1);
  am_doc_free(d2);
  am_doc_free(d3);
}

/* -- map_range / keys_at interplay across history ---------------------------- */
static void test_map_range_and_history(void) {
  AMdoc *d = am_create(NULL, 0);
  const char *names[] = {"alpha", "beta", "gamma", "delta", "epsilon"};
  for (int i = 0; i < 5; i++) {
    CHECK_OK(am_map_put_int(d, AM_ROOT, names[i], i));
  }
  CHECK_OK(am_commit(d, NULL));
  /* [beta, delta) in key order: beta, gamma — wait: order is lexicographic:
   * alpha beta delta epsilon gamma; [beta, delta) = beta only */
  AMresult *r = am_map_range(d, AM_ROOT, "beta", "delta");
  CHECK(am_result_size(r) == 2); /* 1 entry = key + value */
  CHECK(strcmp(am_item_str(r, 0), "beta") == 0);
  am_result_free(r);
  r = am_map_range(d, AM_ROOT, "b", "");
  CHECK(am_result_size(r) == 8); /* beta delta epsilon gamma */
  am_result_free(r);
  am_doc_free(d);
}

int main(void) {
  if (am_init() != 0) {
    fprintf(stderr, "am_init failed\n");
    return 1;
  }
  test_list_op_matrix();
  test_delete_nonexistent_props();
  test_counters_in_sequence();
  test_marks_depth();
  test_cursor_stability();
  test_recursive_delete_and_reput();
  test_out_of_order_changes();
  test_sync_equal_heads_quick_quiet();
  test_sync_either_initiator();
  test_sync_simultaneous_messages();
  test_sync_no_resend_in_flight();
  test_sync_non_empty_state();
  test_sync_data_loss_without_disconnect();
  test_sync_concurrent_to_last_heads();
  test_sync_branching_merging_storm();
  test_map_range_and_history();
  am_shutdown();
  return am_test_finish("test_ported2");
}
