"""C ABI frontend build helper (see am.h / am_embed.cpp / shim.py).

``build()`` compiles the cdylib (libautomerge_tpu.so) on demand with the
same content-hash naming discipline as the codec core: a stale build of
older sources can never be loaded by mistake. The library embeds the
Python runtime, so consumers link only against the .so and include am.h
(reference analogue: rust/automerge-c's cdylib + cbindgen header).
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sysconfig
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
HEADER = os.path.join(_HERE, "am.h")
_SRC = os.path.join(_HERE, "am_embed.cpp")
_REPO_ROOT = os.path.dirname(os.path.dirname(_HERE))


def _lib_name() -> str:
    h = hashlib.sha256()
    for p in (_SRC, HEADER, os.path.join(_HERE, "shim.py")):
        with open(p, "rb") as f:
            h.update(f.read())
    return f"libautomerge_tpu-{h.hexdigest()[:16]}.so"


def _prune_stale(dirname: str, keep: str) -> None:
    """Remove superseded content-hash cdylib builds (package dir only)."""
    try:
        for name in os.listdir(dirname):
            if (
                name.startswith("libautomerge_tpu-")
                and name.endswith(".so")
                and name != keep
            ):
                try:
                    os.remove(os.path.join(dirname, name))
                except OSError:
                    pass
    except OSError:
        pass


def _embed_flags() -> tuple[list, list]:
    inc = sysconfig.get_path("include")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    version = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    return [f"-I{inc}"], [f"-L{libdir}", f"-lpython{version}", "-ldl", "-lm"]


def build(out_dir: Optional[str] = None) -> Optional[str]:
    """Build (or reuse) the cdylib; returns its path, None if no compiler."""
    out_dir = out_dir or _HERE
    path = os.path.join(out_dir, _lib_name())
    if os.path.exists(path):
        return path
    cflags, ldflags = _embed_flags()
    tmp = f"{path}.tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        f'-DAM_PYROOT="{_REPO_ROOT}"',
        *cflags, "-o", tmp, _SRC, *ldflags,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=180)
        if r.returncode != 0 or not os.path.exists(tmp):
            return None
        os.replace(tmp, path)
        if out_dir == _HERE:  # never prune shared/external output dirs
            _prune_stale(_HERE, os.path.basename(path))
        return path
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


TEST_SOURCES = (
    "test_am.c", "test_basic.c", "test_sync.c", "test_ported2.c",
    "test_ported3.c",
)


def build_test(
    lib_path: str, out_dir: Optional[str] = None, source: str = "test_am.c"
) -> Optional[str]:
    """Compile one C test program against the cdylib; returns its path."""
    out_dir = out_dir or _HERE
    src = os.path.join(_HERE, source)
    exe = os.path.join(out_dir, os.path.splitext(source)[0])
    cmd = [
        "gcc", "-O1", "-o", exe, src,
        f"-I{_HERE}", lib_path, f"-Wl,-rpath,{os.path.dirname(lib_path)}",
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            import sys

            sys.stderr.write(r.stderr.decode(errors="replace"))
            return None
        return exe
    except (OSError, subprocess.TimeoutExpired):
        return None
