/* C ABI implementation: embeds the Python runtime and dispatches every
 * call through automerge_tpu.capi.shim.call(fn, *args), converting the
 * returned (tag, payload) tuples into AMresult items.
 *
 * The reference's C frontend wraps its Rust core the same way — a thin
 * marshalling layer over the real document engine (reference:
 * rust/automerge-c/src/doc.rs); here the engine is the Python/JAX
 * framework, reached through one embedded interpreter.
 */
#include "am.h"

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

namespace {

struct Item {
  AMvalType type = AM_VAL_VOID;
  int64_t i = 0;
  double f = 0.0;
  std::string s;          // STR / OBJ_ID (NUL-terminated via c_str)
  std::vector<uint8_t> b; // BYTES
};

} // namespace

struct AMresult {
  AMstatus status = AM_STATUS_OK;
  std::string error;
  std::vector<Item> items;
};

struct AMdoc {
  int64_t handle;
};

struct AMsyncState {
  int64_t handle;
};

static PyObject *g_shim = nullptr; // the shim module (owned)

/* -- hot-call fast cache ----------------------------------------------------
 *
 * Per-op callers (am_splice_text / am_map_put_*) were interpreter-bound:
 * every call crossed into Python dispatch. The shim's fast_begin exposes
 * the SAME native session the Python hot paths use (core/transaction.py
 * fast_splice_fn / fast_put_fn) as raw handles; while armed, this layer
 * calls am_edit_splice / am_map_put directly — no GIL, no Python. The
 * safety contract: dispatch() is the single funnel for everything else,
 * and it resyncs Python's op-id accounting (shim.fast_sync) and disarms
 * BEFORE running any other function. kind -2 is the neg-cache: the object
 * proved ineligible, keep dispatching without re-probing per call. */
typedef int64_t (*am_edit_splice_fn)(void *, int64_t, int64_t, int64_t,
                                     const int32_t *, const int32_t *,
                                     int64_t);
typedef int64_t (*am_op_count_fn)(void *);
typedef int64_t (*am_map_put_fn)(void *, int64_t, const char *, int64_t,
                                 int32_t, int64_t, double, const uint8_t *,
                                 int64_t);

static struct {
  int64_t handle = 0;    /* doc handle (0 = inactive) */
  std::string obj;
  int kind = -1;         /* 0 text, 1 map, -2 neg-cached, -1 inactive */
  int neg = 0;           /* per-kind neg bits: 1<<kind proved ineligible */
  void *sess = nullptr;
  int64_t base = 0;      /* next ctr = base + op_count(sess) */
  int64_t enc = 0;       /* 0 codepoints, 1 utf-8 units, 2 utf-16 units */
} g_fast;
static am_edit_splice_fn g_f_splice = nullptr;
static am_op_count_fn g_f_splice_count = nullptr;
static am_map_put_fn g_f_map_put = nullptr;
static am_op_count_fn g_f_map_count = nullptr;
static bool g_f_addrs_tried = false;

static AMresult *dispatch(const char *fn, PyObject *args);
static int64_t g_sync_pending = 0; /* handle whose resync failed (OOM) */

static bool fast_sync_dispatch(long long h) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(L)", h);
  PyGILState_Release(gil);
  if (!args) return false;
  AMresult *r = dispatch("fast_sync", args);
  const bool ok = r->status == AM_STATUS_OK;
  am_result_free(r);
  return ok;
}

static void fast_disarm_sync(void) {
  if (g_fast.kind != 0 && g_fast.kind != 1) return;
  const long long h = (long long)g_fast.handle;
  g_fast.kind = -1;
  g_fast.handle = 0;
  g_fast.sess = nullptr;
  /* the resync is a hard invariant (op-id accounting); if it cannot run
   * now (OOM building the args tuple), dispatch() retries it before the
   * next operation and refuses to proceed until it lands */
  if (!fast_sync_dispatch(h)) g_sync_pending = h;
}

/* Strict UTF-8: reject what CPython would (stray/overlong leads,
 * surrogates, > U+10FFFF) so the fast path never accepts bytes the
 * dispatch path errors on. Appends to cps/ws when given (enc selects the
 * width unit); pure validation otherwise. */
static bool utf8_next(const char *s, size_t n, size_t *i, uint32_t *out,
                      int *blen) {
  const uint8_t c = (uint8_t)s[*i];
  if (c < 0x80) {
    *out = c;
    *blen = 1;
    (*i)++;
    return true;
  }
  int len;
  uint32_t cp;
  uint8_t lo = 0x80, hi = 0xBF;
  if (c >= 0xC2 && c <= 0xDF) {
    len = 2;
    cp = c & 0x1F;
  } else if (c == 0xE0) {
    len = 3;
    cp = 0;
    lo = 0xA0;
  } else if (c >= 0xE1 && c <= 0xEC) {
    len = 3;
    cp = c & 0x0F;
  } else if (c == 0xED) {
    len = 3;
    cp = 0x0D;
    hi = 0x9F; /* no surrogates */
  } else if (c >= 0xEE && c <= 0xEF) {
    len = 3;
    cp = c & 0x0F;
  } else if (c == 0xF0) {
    len = 4;
    cp = 0;
    lo = 0x90;
  } else if (c >= 0xF1 && c <= 0xF3) {
    len = 4;
    cp = c & 0x07;
  } else if (c == 0xF4) {
    len = 4;
    cp = 4;
    hi = 0x8F; /* <= U+10FFFF */
  } else {
    return false; /* 0x80-0xC1, 0xF5-0xFF */
  }
  if (*i + (size_t)len > n) return false;
  for (int k = 1; k < len; k++) {
    const uint8_t cc = (uint8_t)s[*i + k];
    const uint8_t l = k == 1 ? lo : 0x80, h = k == 1 ? hi : 0xBF;
    if (cc < l || cc > h) return false;
    cp = (cp << 6) | (cc & 0x3F);
  }
  *i += (size_t)len;
  *out = cp;
  *blen = len;
  return true;
}

static bool utf8_valid(const char *s, size_t n) {
  uint32_t cp;
  int blen;
  for (size_t i = 0; i < n;)
    if (!utf8_next(s, n, &i, &cp, &blen)) return false;
  return true;
}

extern "C" int am_init(void) {
  if (g_shim) return 0;
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    we_initialized = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  const char *root = getenv("AUTOMERGE_TPU_PYROOT");
#ifdef AM_PYROOT
  if (!root) root = AM_PYROOT;
#endif
  if (root) {
    PyObject *sys_path = PySys_GetObject("path"); // borrowed
    PyObject *p = PyUnicode_FromString(root);
    if (sys_path && p) PyList_Insert(sys_path, 0, p);
    Py_XDECREF(p);
  }
  g_shim = PyImport_ImportModule("automerge_tpu.capi.shim");
  if (!g_shim) {
    PyErr_Print();
    PyGILState_Release(gil);
    if (we_initialized) PyEval_SaveThread(); // never exit still holding the GIL
    return -1;
  }
  PyGILState_Release(gil);
  if (we_initialized) {
    // Py_InitializeEx leaves this thread holding the GIL; release it so
    // other threads' PyGILState_Ensure calls can ever succeed
    PyEval_SaveThread();
  }
  return 0;
}

extern "C" void am_shutdown(void) {
  if (!g_shim) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_CLEAR(g_shim);
  PyGILState_Release(gil);
  // the interpreter stays up: cheap, and safe for repeated init cycles
}

static std::string format_exception() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "unknown error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  if (type) {
    PyObject *n = PyObject_GetAttrString(type, "__name__");
    if (n) {
      const char *c = PyUnicode_AsUTF8(n);
      if (c) msg = std::string(c) + ": " + msg;
      Py_DECREF(n);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

/* Convert shim items [(tag, payload), ...] into the result. */
static bool convert_items(PyObject *list, AMresult *r) {
  PyObject *seq = PySequence_Fast(list, "shim must return a sequence");
  if (!seq) return false;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject *t = PySequence_Fast_GET_ITEM(seq, i); // borrowed
    PyObject *tag_o = PyTuple_GetItem(t, 0);
    PyObject *val = PyTuple_GetItem(t, 1);
    if (!tag_o || !val) {
      Py_DECREF(seq);
      return false;
    }
    Item item;
    item.type = static_cast<AMvalType>(PyLong_AsLong(tag_o));
    switch (item.type) {
      case AM_VAL_F64:
        item.f = PyFloat_AsDouble(val);
        break;
      case AM_VAL_STR:
      case AM_VAL_OBJ_ID: {
        const char *c = PyUnicode_AsUTF8(val);
        if (!c) {
          Py_DECREF(seq);
          return false;
        }
        item.s = c;
        break;
      }
      case AM_VAL_BYTES: {
        char *buf = nullptr;
        Py_ssize_t len = 0;
        if (PyBytes_AsStringAndSize(val, &buf, &len) != 0) {
          Py_DECREF(seq);
          return false;
        }
        item.b.assign(buf, buf + len);
        break;
      }
      case AM_VAL_NULL:
      case AM_VAL_VOID:
        break;
      default: // ints, bools, counters, timestamps, handles
        item.i = PyLong_AsLongLong(val);
        break;
    }
    if (PyErr_Occurred()) {
      Py_DECREF(seq);
      return false;
    }
    r->items.push_back(std::move(item));
  }
  Py_DECREF(seq);
  return true;
}

/* Call shim.call(fn, *args); args is a NEW reference to a tuple (stolen). */
static AMresult *dispatch(const char *fn, PyObject *args) {
  /* the single funnel: resync + disarm the hot-call cache before any
   * other operation can mint op ids or change session state. The
   * neg-cache survives put/splice dispatches (value-shape fallbacks on
   * the same hot loop) but clears on anything that could change
   * eligibility (commit, merge, mark, load, ...). */
  if (fn[0] != 'f' || strncmp(fn, "fast_", 5) != 0) {
    if (g_fast.kind >= 0) fast_disarm_sync();
    if (g_fast.kind == -2 && strcmp(fn, "put") != 0 &&
        strcmp(fn, "splice_text") != 0) {
      g_fast.kind = -1;
      g_fast.neg = 0;
    }
    if (g_sync_pending) {
      if (fast_sync_dispatch((long long)g_sync_pending)) {
        g_sync_pending = 0;
      } else {
        AMresult *err = new AMresult();
        err->status = AM_STATUS_ERROR;
        err->error = "op-id accounting desynchronized (out of memory "
                     "during fast-path resync)";
        if (args) {
          PyGILState_STATE gil = PyGILState_Ensure();
          Py_DECREF(args);
          PyGILState_Release(gil);
        }
        return err;
      }
    }
  }
  AMresult *r = new AMresult();
  if (!g_shim) {
    Py_XDECREF(args);
    r->status = AM_STATUS_ERROR;
    r->error = "am_init() has not been called";
    return r;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *out = nullptr;
  if (args) {
    PyObject *call = PyObject_GetAttrString(g_shim, "call");
    PyObject *fn_o = PyUnicode_FromString(fn);
    Py_ssize_t n = PyTuple_GET_SIZE(args);
    PyObject *full = PyTuple_New(n + 1);
    if (call && fn_o && full) {
      PyTuple_SET_ITEM(full, 0, fn_o); // stolen
      fn_o = nullptr;
      for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = PyTuple_GET_ITEM(args, i);
        Py_INCREF(it);
        PyTuple_SET_ITEM(full, i + 1, it);
      }
      out = PyObject_CallObject(call, full);
    }
    Py_XDECREF(call);
    Py_XDECREF(fn_o);
    Py_XDECREF(full);
    Py_DECREF(args);
  } else {
    r->status = AM_STATUS_ERROR;
    r->error = g_shim ? "argument marshalling failed"
                      : "am_init() has not been called";
    if (g_shim && PyErr_Occurred()) PyErr_Clear();
  }
  if (out) {
    if (!convert_items(out, r)) {
      r->status = AM_STATUS_ERROR;
      r->error = format_exception();
      r->items.clear();
    }
    Py_DECREF(out);
  } else if (r->status == AM_STATUS_OK) {
    r->status = AM_STATUS_ERROR;
    r->error = format_exception();
  }
  PyGILState_Release(gil);
  return r;
}

/* -- hot-call cache: arming + direct entries -------------------------------*/

static bool fast_fetch_addrs(void) {
  if (g_f_addrs_tried) return g_f_map_put != nullptr;
  g_f_addrs_tried = true;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = PyTuple_New(0);
  PyGILState_Release(gil);
  if (!args) return false;
  AMresult *r = dispatch("fast_addrs", args);
  if (r->status == AM_STATUS_OK && r->items.size() >= 4) {
    g_f_splice = (am_edit_splice_fn)(uintptr_t)r->items[0].i;
    g_f_splice_count = (am_op_count_fn)(uintptr_t)r->items[1].i;
    g_f_map_put = (am_map_put_fn)(uintptr_t)r->items[2].i;
    g_f_map_count = (am_op_count_fn)(uintptr_t)r->items[3].i;
  }
  am_result_free(r);
  return g_f_map_put != nullptr;
}

/* Arm the cache for (doc, obj, kind); on an eligible session returns true.
 * An ineligible object neg-caches so per-call re-probing stops. */
static bool fast_arm(AMdoc *d, const char *obj, int kind) {
  if (!fast_fetch_addrs()) return false;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(Lsi)", (long long)d->handle, obj, kind);
  PyGILState_Release(gil);
  if (!args) return false;
  AMresult *r = dispatch("fast_begin", args);
  const bool ok = r->status == AM_STATUS_OK && r->items.size() >= 3 &&
                  r->items[0].i != 0;
  if (g_fast.handle != d->handle || g_fast.obj != obj) g_fast.neg = 0;
  g_fast.handle = d->handle;
  g_fast.obj = obj;
  if (ok) {
    g_fast.kind = kind;
    g_fast.sess = (void *)(uintptr_t)r->items[0].i;
    g_fast.base = r->items[1].i;
    g_fast.enc = r->items[2].i;
  } else {
    /* per-kind neg-cache (also on errors: the dispatch path reports);
     * a text-ineligible object can still arm the map fast path & v.v. */
    g_fast.kind = -2;
    g_fast.neg |= 1 << kind;
    g_fast.sess = nullptr;
  }
  am_result_free(r);
  return ok;
}

/* Armed text splice: utf-8 -> codepoints + per-codepoint widths in the
 * document's index unit, then one native call. nullptr = fall back to the
 * dispatch path (malformed utf-8). */
static AMresult *fast_splice_armed(const char *text, size_t pos, size_t del) {
  const size_t n = text ? strlen(text) : 0;
  std::vector<int32_t> cps, ws;
  cps.reserve(n);
  ws.reserve(n);
  for (size_t i = 0; i < n;) {
    uint32_t c;
    int blen;
    if (!utf8_next(text, n, &i, &c, &blen))
      return nullptr; /* invalid utf-8: dispatch path reports the error */
    const int32_t w =
        g_fast.enc == 1 ? blen : (g_fast.enc == 2 ? 1 + (c > 0xFFFF) : 1);
    cps.push_back((int32_t)c);
    ws.push_back(w);
  }
  const int64_t ctr = g_fast.base + g_f_splice_count(g_fast.sess);
  const int64_t rr = g_f_splice(g_fast.sess, ctr, (int64_t)pos, (int64_t)del,
                                cps.data(), ws.data(), (int64_t)cps.size());
  AMresult *r = new AMresult();
  if (rr < 0) {
    r->status = AM_STATUS_ERROR;
    r->error = rr == -2 ? "splice: delete past end of sequence"
                        : "splice: index out of bounds";
  }
  return r;
}

/* Armed (or arm-now) check shared by the splice and map-put entries:
 * true = g_fast holds a live session for (doc, obj, kind). */
static bool fast_ready(AMdoc *d, const char *o, int kind) {
  if (g_fast.handle == d->handle && g_fast.obj == o) {
    if (g_fast.kind == kind) return true;
    if (g_fast.kind == -2 && (g_fast.neg & (1 << kind))) return false;
  }
  fast_disarm_sync();
  return fast_arm(d, o, kind);
}

/* Armed map put; nullptr = use the dispatch path (ineligible object,
 * empty/invalid key, or a value shape the session rejects). */
static AMresult *fast_map_put_try(AMdoc *d, const char *o, const char *k,
                                  int32_t code, int64_t ival, double fval,
                                  const uint8_t *raw, int64_t rawlen) {
  if (!g_shim || !d || !o || !k || !k[0]) return nullptr;
  const size_t klen = strlen(k);
  if (!utf8_valid(k, klen)) return nullptr;
  if (code == 6 && !utf8_valid((const char *)raw, (size_t)rawlen))
    return nullptr; /* invalid utf-8 value: dispatch path reports */
  if (!fast_ready(d, o, 1)) return nullptr;
  const int64_t ctr = g_fast.base + g_f_map_count(g_fast.sess);
  const int64_t rr = g_f_map_put(g_fast.sess, ctr, k, (int64_t)klen, code,
                                 ival, fval, raw, rawlen);
  if (rr < 0) {
    fast_disarm_sync();
    return nullptr;
  }
  return new AMresult();
}

/* -- results / items -------------------------------------------------------*/

extern "C" AMstatus am_result_status(const AMresult *r) { return r->status; }

extern "C" const char *am_result_error(const AMresult *r) {
  return r->status == AM_STATUS_OK ? nullptr : r->error.c_str();
}

extern "C" size_t am_result_size(const AMresult *r) { return r->items.size(); }

extern "C" AMvalType am_item_type(const AMresult *r, size_t i) {
  return i < r->items.size() ? r->items[i].type : AM_VAL_VOID;
}

extern "C" int64_t am_item_int(const AMresult *r, size_t i) {
  return i < r->items.size() ? r->items[i].i : 0;
}

extern "C" double am_item_f64(const AMresult *r, size_t i) {
  return i < r->items.size() ? r->items[i].f : 0.0;
}

extern "C" const char *am_item_str(const AMresult *r, size_t i) {
  return i < r->items.size() ? r->items[i].s.c_str() : "";
}

extern "C" const uint8_t *am_item_bytes(const AMresult *r, size_t i, size_t *len) {
  if (i >= r->items.size()) {
    if (len) *len = 0;
    return nullptr;
  }
  if (len) *len = r->items[i].b.size();
  return r->items[i].b.data();
}

extern "C" void am_result_free(AMresult *r) { delete r; }

/* -- documents -------------------------------------------------------------*/

static AMdoc *handle_doc(AMresult *r) {
  AMdoc *doc = nullptr;
  if (r->status == AM_STATUS_OK && !r->items.empty()) {
    doc = new AMdoc{r->items[0].i};
  }
  am_result_free(r);
  return doc;
}

extern "C" AMdoc *am_create(const uint8_t *actor, size_t actor_len) {
  if (!g_shim) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(y#)", (const char *)actor, (Py_ssize_t)actor_len);
  PyGILState_Release(gil);
  return handle_doc(dispatch("create", args));
}

extern "C" AMdoc *am_load(const uint8_t *data, size_t len) {
  if (!g_shim) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(y#)", (const char *)data, (Py_ssize_t)len);
  PyGILState_Release(gil);
  return handle_doc(dispatch("load", args));
}

extern "C" AMdoc *am_fork(AMdoc *doc, const uint8_t *actor, size_t actor_len) {
  if (!g_shim) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(Ly#)", (long long)doc->handle,
                                 (const char *)actor, (Py_ssize_t)actor_len);
  PyGILState_Release(gil);
  return handle_doc(dispatch("fork", args));
}

extern "C" void am_doc_free(AMdoc *doc) {
  if (!doc) return;
  if (!g_shim) { delete doc; return; }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(L)", (long long)doc->handle);
  PyGILState_Release(gil);
  am_result_free(dispatch("free", args));
  delete doc;
}

/* convenience: build args under the GIL, then dispatch */
/* build args under the GIL — but only once am_init has run; calling
 * PyGILState_Ensure on an uninitialized interpreter aborts the process,
 * so an un-initialized library must flow through dispatch's error path */
#define AM_ARGS(...)                                        \
  PyObject *args = nullptr;                                 \
  if (g_shim) {                                             \
    PyGILState_STATE gil = PyGILState_Ensure();             \
    args = Py_BuildValue(__VA_ARGS__);                      \
    PyGILState_Release(gil);                                \
  }

extern "C" AMresult *am_save(AMdoc *doc) {
  AM_ARGS("(L)", (long long)doc->handle);
  return dispatch("save", args);
}

extern "C" AMresult *am_commit(AMdoc *doc, const char *message) {
  AM_ARGS("(Ls)", (long long)doc->handle, message ? message : "");
  return dispatch("commit", args);
}

extern "C" AMresult *am_merge(AMdoc *doc, AMdoc *other) {
  AM_ARGS("(LL)", (long long)doc->handle, (long long)other->handle);
  return dispatch("merge", args);
}

extern "C" AMresult *am_get_heads(AMdoc *doc) {
  AM_ARGS("(L)", (long long)doc->handle);
  return dispatch("get_heads", args);
}

extern "C" AMresult *am_actor_id(AMdoc *doc) {
  AM_ARGS("(L)", (long long)doc->handle);
  return dispatch("actor_id", args);
}

/* -- map mutation ----------------------------------------------------------*/

static AMresult *put_tagged(AMdoc *doc, const char *obj, const char *key,
                            int tag, PyObject *payload /* stolen */) {
  if (!g_shim) return dispatch("put", nullptr);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = payload
      ? Py_BuildValue("(LssiN)", (long long)doc->handle, obj, key, tag, payload)
      : nullptr;
  PyGILState_Release(gil);
  return dispatch("put", args);
}

extern "C" AMresult *am_map_put_null(AMdoc *d, const char *o, const char *k) {
  if (!g_shim) return dispatch("put", nullptr);
  if (AMresult *fr = fast_map_put_try(d, o, k, 0, 0, 0.0, nullptr, 0)) return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *zero = PyLong_FromLong(0);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_NULL, zero);
}

extern "C" AMresult *am_map_put_bool(AMdoc *d, const char *o, const char *k, int v) {
  if (!g_shim) return dispatch("put", nullptr);
  if (AMresult *fr = fast_map_put_try(d, o, k, v ? 2 : 1, 0, 0.0, nullptr, 0)) return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyLong_FromLong(v ? 1 : 0);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_BOOL, p);
}

extern "C" AMresult *am_map_put_int(AMdoc *d, const char *o, const char *k, int64_t v) {
  if (!g_shim) return dispatch("put", nullptr);
  if (AMresult *fr = fast_map_put_try(d, o, k, 4, v, 0.0, nullptr, 0)) return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyLong_FromLongLong(v);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_INT, p);
}

extern "C" AMresult *am_map_put_uint(AMdoc *d, const char *o, const char *k, uint64_t v) {
  if (!g_shim) return dispatch("put", nullptr);
  if (v <= (uint64_t)INT64_MAX)
    if (AMresult *fr = fast_map_put_try(d, o, k, 3, (int64_t)v, 0.0, nullptr, 0)) return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyLong_FromUnsignedLongLong(v);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_UINT, p);
}

extern "C" AMresult *am_map_put_f64(AMdoc *d, const char *o, const char *k, double v) {
  if (!g_shim) return dispatch("put", nullptr);
  if (AMresult *fr = fast_map_put_try(d, o, k, 5, 0, v, nullptr, 0)) return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyFloat_FromDouble(v);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_F64, p);
}

extern "C" AMresult *am_map_put_str(AMdoc *d, const char *o, const char *k,
                                    const char *v) {
  if (!g_shim) return dispatch("put", nullptr);
  if (AMresult *fr = fast_map_put_try(
          d, o, k, 6, 0, 0.0, (const uint8_t *)(v ? v : ""),
          (int64_t)strlen(v ? v : "")))
    return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyUnicode_FromString(v ? v : "");
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_STR, p);
}

extern "C" AMresult *am_map_put_bytes(AMdoc *d, const char *o, const char *k,
                                      const uint8_t *v, size_t len) {
  if (!g_shim) return dispatch("put", nullptr);
  if (v || len == 0)
    if (AMresult *fr = fast_map_put_try(d, o, k, 7, 0, 0.0, v, (int64_t)len))
      return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyBytes_FromStringAndSize((const char *)v, (Py_ssize_t)len);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_BYTES, p);
}

extern "C" AMresult *am_map_put_counter(AMdoc *d, const char *o, const char *k,
                                        int64_t v) {
  if (!g_shim) return dispatch("put", nullptr);
  if (AMresult *fr = fast_map_put_try(d, o, k, 8, v, 0.0, nullptr, 0)) return fr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyLong_FromLongLong(v);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_COUNTER, p);
}

extern "C" AMresult *am_map_put_timestamp(AMdoc *d, const char *o, const char *k,
                                          int64_t v) {
  if (!g_shim) return dispatch("put", nullptr);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *p = PyLong_FromLongLong(v);
  PyGILState_Release(gil);
  return put_tagged(d, o, k, AM_VAL_TIMESTAMP, p);
}

extern "C" AMresult *am_map_put_object(AMdoc *d, const char *o, const char *k,
                                       AMobjType t) {
  AM_ARGS("(Lssi)", (long long)d->handle, o, k, (int)t);
  return dispatch("put_object", args);
}

extern "C" AMresult *am_map_delete(AMdoc *d, const char *o, const char *k) {
  AM_ARGS("(Lss)", (long long)d->handle, o, k);
  return dispatch("delete", args);
}

extern "C" AMresult *am_map_increment(AMdoc *d, const char *o, const char *k,
                                      int64_t by) {
  AM_ARGS("(LssL)", (long long)d->handle, o, k, (long long)by);
  return dispatch("increment", args);
}

/* -- list mutation ---------------------------------------------------------*/

/* the full scalar matrix for both list verbs routes through ONE pair of
 * shim entries (list_put / insert) with a tag + payload, so each wrapper
 * is a marshalling one-liner — the reference needs a macro forest for the
 * same surface (automerge-c/src/doc/list.rs) */
#define AM_LIST_SCALAR(name, verb, tag, fmt, ...)                            \
  extern "C" AMresult *name {                                                \
    AM_ARGS("(Lsni" fmt ")", (long long)d->handle, o, (Py_ssize_t)i, tag,    \
            __VA_ARGS__);                                                    \
    return dispatch(verb, args);                                             \
  }

AM_LIST_SCALAR(am_list_put_null(AMdoc *d, const char *o, size_t i),
               "list_put", AM_VAL_NULL, "i", 0)
AM_LIST_SCALAR(am_list_put_bool(AMdoc *d, const char *o, size_t i, int v),
               "list_put", AM_VAL_BOOL, "i", v ? 1 : 0)
AM_LIST_SCALAR(am_list_put_int(AMdoc *d, const char *o, size_t i, int64_t v),
               "list_put", AM_VAL_INT, "L", (long long)v)
AM_LIST_SCALAR(am_list_put_uint(AMdoc *d, const char *o, size_t i, uint64_t v),
               "list_put", AM_VAL_UINT, "K", (unsigned long long)v)
AM_LIST_SCALAR(am_list_put_f64(AMdoc *d, const char *o, size_t i, double v),
               "list_put", AM_VAL_F64, "d", v)
AM_LIST_SCALAR(am_list_put_str(AMdoc *d, const char *o, size_t i, const char *v),
               "list_put", AM_VAL_STR, "s", v ? v : "")
/* NULL bytes marshal as an empty payload, never None (same hazard the
 * AM_HEADS macro guards) */
AM_LIST_SCALAR(am_list_put_bytes(AMdoc *d, const char *o, size_t i,
                                 const uint8_t *v, size_t len),
               "list_put", AM_VAL_BYTES, "y#", v ? (const char *)v : "",
               (Py_ssize_t)(v ? len : 0))
AM_LIST_SCALAR(am_list_put_counter(AMdoc *d, const char *o, size_t i, int64_t v),
               "list_put", AM_VAL_COUNTER, "L", (long long)v)
AM_LIST_SCALAR(am_list_put_timestamp(AMdoc *d, const char *o, size_t i, int64_t v),
               "list_put", AM_VAL_TIMESTAMP, "L", (long long)v)

AM_LIST_SCALAR(am_list_insert_null(AMdoc *d, const char *o, size_t i),
               "insert", AM_VAL_NULL, "i", 0)
AM_LIST_SCALAR(am_list_insert_bool(AMdoc *d, const char *o, size_t i, int v),
               "insert", AM_VAL_BOOL, "i", v ? 1 : 0)
AM_LIST_SCALAR(am_list_insert_int(AMdoc *d, const char *o, size_t i, int64_t v),
               "insert", AM_VAL_INT, "L", (long long)v)
AM_LIST_SCALAR(am_list_insert_uint(AMdoc *d, const char *o, size_t i, uint64_t v),
               "insert", AM_VAL_UINT, "K", (unsigned long long)v)
AM_LIST_SCALAR(am_list_insert_f64(AMdoc *d, const char *o, size_t i, double v),
               "insert", AM_VAL_F64, "d", v)
AM_LIST_SCALAR(am_list_insert_str(AMdoc *d, const char *o, size_t i, const char *v),
               "insert", AM_VAL_STR, "s", v ? v : "")
AM_LIST_SCALAR(am_list_insert_bytes(AMdoc *d, const char *o, size_t i,
                                    const uint8_t *v, size_t len),
               "insert", AM_VAL_BYTES, "y#", v ? (const char *)v : "",
               (Py_ssize_t)(v ? len : 0))
AM_LIST_SCALAR(am_list_insert_counter(AMdoc *d, const char *o, size_t i, int64_t v),
               "insert", AM_VAL_COUNTER, "L", (long long)v)
AM_LIST_SCALAR(am_list_insert_timestamp(AMdoc *d, const char *o, size_t i,
                                        int64_t v),
               "insert", AM_VAL_TIMESTAMP, "L", (long long)v)

extern "C" AMresult *am_list_put_object(AMdoc *d, const char *o, size_t i,
                                        AMobjType t) {
  AM_ARGS("(Lsni)", (long long)d->handle, o, (Py_ssize_t)i, (int)t);
  return dispatch("list_put_object", args);
}

extern "C" AMresult *am_list_insert_object(AMdoc *d, const char *o, size_t i,
                                           AMobjType t) {
  AM_ARGS("(Lsni)", (long long)d->handle, o, (Py_ssize_t)i, (int)t);
  return dispatch("insert_object", args);
}

extern "C" AMresult *am_list_delete(AMdoc *d, const char *o, size_t i) {
  AM_ARGS("(Lsn)", (long long)d->handle, o, (Py_ssize_t)i);
  return dispatch("list_delete", args);
}

extern "C" AMresult *am_list_increment(AMdoc *d, const char *o, size_t i, int64_t by) {
  AM_ARGS("(LsnL)", (long long)d->handle, o, (Py_ssize_t)i, (long long)by);
  return dispatch("list_increment", args);
}

/* -- text ------------------------------------------------------------------*/

extern "C" AMresult *am_splice_text(AMdoc *d, const char *o, size_t pos, size_t del,
                                    const char *text) {
  if (g_shim && d && o && fast_ready(d, o, 0)) {
    AMresult *fr = fast_splice_armed(text, pos, del);
    if (fr) return fr;
    fast_disarm_sync(); /* malformed utf-8: report via dispatch */
  }
  AM_ARGS("(Lsnns)", (long long)d->handle, o, (Py_ssize_t)pos, (Py_ssize_t)del,
          text ? text : "");
  return dispatch("splice_text", args);
}

extern "C" AMresult *am_text(AMdoc *d, const char *o) {
  AM_ARGS("(Ls)", (long long)d->handle, o);
  return dispatch("text", args);
}

/* -- reads -----------------------------------------------------------------*/

extern "C" AMresult *am_map_get(AMdoc *d, const char *o, const char *k) {
  AM_ARGS("(Lss)", (long long)d->handle, o, k);
  return dispatch("get", args);
}

extern "C" AMresult *am_map_get_all(AMdoc *d, const char *o, const char *k) {
  AM_ARGS("(Lss)", (long long)d->handle, o, k);
  return dispatch("get_all", args);
}

extern "C" AMresult *am_list_get(AMdoc *d, const char *o, size_t i) {
  AM_ARGS("(Lsn)", (long long)d->handle, o, (Py_ssize_t)i);
  return dispatch("list_get", args);
}

extern "C" AMresult *am_keys(AMdoc *d, const char *o) {
  AM_ARGS("(Ls)", (long long)d->handle, o);
  return dispatch("keys", args);
}

extern "C" AMresult *am_length(AMdoc *d, const char *o) {
  AM_ARGS("(Ls)", (long long)d->handle, o);
  return dispatch("length", args);
}

extern "C" AMresult *am_object_type(AMdoc *d, const char *o) {
  AM_ARGS("(Ls)", (long long)d->handle, o);
  return dispatch("object_type", args);
}

extern "C" AMresult *am_list_items(AMdoc *d, const char *o) {
  AM_ARGS("(Ls)", (long long)d->handle, o);
  return dispatch("list_items", args);
}

extern "C" AMresult *am_map_entries(AMdoc *d, const char *o) {
  AM_ARGS("(Ls)", (long long)d->handle, o);
  return dispatch("map_entries", args);
}

/* -- historical reads ------------------------------------------------------*/

/* NULL heads = "no heads": marshal an empty byte string, never a NULL
 * pointer (Py_BuildValue "y#" would turn NULL into None) */
#define AM_HEADS(h, n)                                      \
  (const char *)((h) ? (const char *)(h) : ""),             \
      (Py_ssize_t)((h) ? (n) * 32 : 0)

extern "C" AMresult *am_map_get_at(AMdoc *d, const char *o, const char *k,
                                   const uint8_t *heads, size_t n_heads) {
  AM_ARGS("(Lssy#)", (long long)d->handle, o, k, AM_HEADS(heads, n_heads));
  return dispatch("get_at", args);
}

extern "C" AMresult *am_map_get_all_at(AMdoc *d, const char *o, const char *k,
                                       const uint8_t *heads, size_t n_heads) {
  AM_ARGS("(Lssy#)", (long long)d->handle, o, k, AM_HEADS(heads, n_heads));
  return dispatch("get_all_at", args);
}

extern "C" AMresult *am_list_get_at(AMdoc *d, const char *o, size_t i,
                                    const uint8_t *heads, size_t n_heads) {
  AM_ARGS("(Lsny#)", (long long)d->handle, o, (Py_ssize_t)i,
          AM_HEADS(heads, n_heads));
  return dispatch("list_get_at", args);
}

extern "C" AMresult *am_keys_at(AMdoc *d, const char *o, const uint8_t *heads,
                                size_t n_heads) {
  AM_ARGS("(Lsy#)", (long long)d->handle, o, AM_HEADS(heads, n_heads));
  return dispatch("keys_at", args);
}

extern "C" AMresult *am_length_at(AMdoc *d, const char *o, const uint8_t *heads,
                                  size_t n_heads) {
  AM_ARGS("(Lsy#)", (long long)d->handle, o, AM_HEADS(heads, n_heads));
  return dispatch("length_at", args);
}

extern "C" AMresult *am_text_at(AMdoc *d, const char *o, const uint8_t *heads,
                                size_t n_heads) {
  AM_ARGS("(Lsy#)", (long long)d->handle, o, AM_HEADS(heads, n_heads));
  return dispatch("text_at", args);
}

extern "C" AMresult *am_marks_at(AMdoc *d, const char *o, const uint8_t *heads,
                                 size_t n_heads) {
  AM_ARGS("(Lsy#)", (long long)d->handle, o, AM_HEADS(heads, n_heads));
  return dispatch("marks_at", args);
}

extern "C" AMdoc *am_fork_at(AMdoc *d, const uint8_t *heads, size_t n_heads,
                             const uint8_t *actor, size_t actor_len) {
  if (!g_shim) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(Ly#y#)", (long long)d->handle,
                                 AM_HEADS(heads, n_heads),
                                 (const char *)actor, (Py_ssize_t)actor_len);
  PyGILState_Release(gil);
  return handle_doc(dispatch("fork_at", args));
}

/* -- patches ---------------------------------------------------------------*/

extern "C" AMresult *am_diff(AMdoc *d, const uint8_t *before, size_t n_before,
                             const uint8_t *after, size_t n_after) {
  AM_ARGS("(Ly#y#)", (long long)d->handle, AM_HEADS(before, n_before),
          AM_HEADS(after, n_after));
  return dispatch("diff", args);
}

extern "C" AMresult *am_pop_patches(AMdoc *d) {
  AM_ARGS("(L)", (long long)d->handle);
  return dispatch("pop_patches", args);
}

extern "C" AMresult *am_get_changes(AMdoc *d, const uint8_t *heads,
                                    size_t n_heads) {
  AM_ARGS("(Ly#)", (long long)d->handle, AM_HEADS(heads, n_heads));
  return dispatch("get_changes", args);
}

/* -- round-3 breadth -------------------------------------------------------*/

extern "C" AMdoc *am_clone(AMdoc *d) {
  if (!g_shim) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(L)", (long long)d->handle);
  PyGILState_Release(gil);
  return handle_doc(dispatch("clone", args));
}

extern "C" AMresult *am_set_actor_id(AMdoc *d, const uint8_t *actor,
                                     size_t actor_len) {
  AM_ARGS("(Ly#)", (long long)d->handle, (const char *)actor,
          (Py_ssize_t)actor_len);
  return dispatch("set_actor", args);
}

extern "C" AMresult *am_equal(AMdoc *d, AMdoc *other) {
  AM_ARGS("(LL)", (long long)d->handle, (long long)other->handle);
  return dispatch("equal", args);
}

extern "C" AMresult *am_equal_content(AMdoc *d, AMdoc *other) {
  AM_ARGS("(LL)", (long long)d->handle, (long long)other->handle);
  return dispatch("equal_content", args);
}

extern "C" AMresult *am_pending_ops(AMdoc *d) {
  AM_ARGS("(L)", (long long)d->handle);
  return dispatch("pending_ops", args);
}

extern "C" AMresult *am_rollback(AMdoc *d) {
  AM_ARGS("(L)", (long long)d->handle);
  return dispatch("rollback", args);
}

extern "C" AMresult *am_get_change_by_hash(AMdoc *d, const uint8_t *hash) {
  /* NULL hash = empty payload (never dereferenced), same convention as
   * AM_HEADS; the shim answers with an empty result */
  AM_ARGS("(Ly#)", (long long)d->handle, hash ? (const char *)hash : "",
          (Py_ssize_t)(hash ? 32 : 0));
  return dispatch("get_change_by_hash", args);
}

extern "C" AMresult *am_get_changes_added(AMdoc *d, AMdoc *other) {
  AM_ARGS("(LL)", (long long)d->handle, (long long)other->handle);
  return dispatch("get_changes_added", args);
}

extern "C" AMresult *am_get_last_local_change(AMdoc *d) {
  AM_ARGS("(L)", (long long)d->handle);
  return dispatch("get_last_local_change", args);
}

extern "C" AMresult *am_get_missing_deps(AMdoc *d, const uint8_t *heads,
                                         size_t n_heads) {
  AM_ARGS("(Ly#)", (long long)d->handle, AM_HEADS(heads, n_heads));
  return dispatch("get_missing_deps", args);
}

extern "C" AMresult *am_list_range(AMdoc *d, const char *o, size_t start,
                                   size_t end) {
  // reference idiom: end = SIZE_MAX means unbounded (automerge-c
  // AMlistRange) — clamp before the size_t -> Py_ssize_t narrowing,
  // which would otherwise turn it into -1 and yield an empty range
  if (end > (size_t)PY_SSIZE_T_MAX) end = (size_t)PY_SSIZE_T_MAX;
  if (start > (size_t)PY_SSIZE_T_MAX) start = (size_t)PY_SSIZE_T_MAX;
  AM_ARGS("(Lsnn)", (long long)d->handle, o, (Py_ssize_t)start,
          (Py_ssize_t)end);
  return dispatch("list_range", args);
}

extern "C" AMresult *am_map_range(AMdoc *d, const char *o, const char *begin,
                                  const char *end) {
  AM_ARGS("(Lsss)", (long long)d->handle, o, begin ? begin : "",
          end ? end : "");
  return dispatch("map_range", args);
}

extern "C" AMresult *am_list_splice(AMdoc *d, const char *o, size_t pos,
                                    size_t del) {
  AM_ARGS("(Lsnn)", (long long)d->handle, o, (Py_ssize_t)pos, (Py_ssize_t)del);
  return dispatch("list_splice", args);
}

extern "C" AMresult *am_sync_state_shared_heads(AMsyncState *s) {
  AM_ARGS("(L)", (long long)s->handle);
  return dispatch("sync_state_shared_heads", args);
}

/* -- sync ------------------------------------------------------------------*/

extern "C" AMsyncState *am_sync_state_new(void) {
  if (!g_shim) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *empty = PyTuple_New(0);
  PyGILState_Release(gil);
  AMresult *r = dispatch("sync_state_new", empty);
  AMsyncState *s = nullptr;
  if (r->status == AM_STATUS_OK && !r->items.empty()) {
    s = new AMsyncState{r->items[0].i};
  }
  am_result_free(r);
  return s;
}

extern "C" void am_sync_state_free(AMsyncState *s) {
  if (!s) return;
  if (!g_shim) { delete s; return; }
  AM_ARGS("(L)", (long long)s->handle);
  am_result_free(dispatch("sync_state_free", args));
  delete s;
}

extern "C" AMresult *am_sync_state_encode(AMsyncState *s) {
  AM_ARGS("(L)", (long long)s->handle);
  return dispatch("sync_state_encode", args);
}

extern "C" AMsyncState *am_sync_state_decode(const uint8_t *data, size_t len) {
  if (!g_shim) return nullptr;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject *args = Py_BuildValue("(y#)", (const char *)data, (Py_ssize_t)len);
  PyGILState_Release(gil);
  AMresult *r = dispatch("sync_state_decode", args);
  AMsyncState *s = nullptr;
  if (r->status == AM_STATUS_OK && !r->items.empty()) {
    s = new AMsyncState{r->items[0].i};
  }
  am_result_free(r);
  return s;
}

/* -- marks / cursors -------------------------------------------------------*/

extern "C" AMresult *am_mark_str(AMdoc *d, const char *o, size_t start, size_t end,
                                 const char *name, const char *value,
                                 const char *expand) {
  if (value == NULL) {
    /* a NULL value means a null-valued mark: clears the name (Peritext) */
    AM_ARGS("(Lsnnss)", (long long)d->handle, o, (Py_ssize_t)start,
            (Py_ssize_t)end, name, expand ? expand : "after");
    return dispatch("mark_null", args);
  }
  AM_ARGS("(Lsnnsss)", (long long)d->handle, o, (Py_ssize_t)start,
          (Py_ssize_t)end, name, value, expand ? expand : "after");
  return dispatch("mark_str", args);
}

extern "C" AMresult *am_mark_bool(AMdoc *d, const char *o, size_t start, size_t end,
                                  const char *name, int value,
                                  const char *expand) {
  AM_ARGS("(Lsnnsis)", (long long)d->handle, o, (Py_ssize_t)start,
          (Py_ssize_t)end, name, value, expand ? expand : "after");
  return dispatch("mark_bool", args);
}

extern "C" AMresult *am_unmark(AMdoc *d, const char *o, size_t start, size_t end,
                               const char *name) {
  AM_ARGS("(Lsnns)", (long long)d->handle, o, (Py_ssize_t)start,
          (Py_ssize_t)end, name);
  return dispatch("unmark", args);
}

extern "C" AMresult *am_marks(AMdoc *d, const char *o) {
  AM_ARGS("(Ls)", (long long)d->handle, o);
  return dispatch("marks", args);
}

extern "C" AMresult *am_get_cursor(AMdoc *d, const char *o, size_t pos) {
  AM_ARGS("(Lsn)", (long long)d->handle, o, (Py_ssize_t)pos);
  return dispatch("get_cursor", args);
}

extern "C" AMresult *am_get_cursor_position(AMdoc *d, const char *o,
                                            const char *cursor) {
  AM_ARGS("(Lss)", (long long)d->handle, o, cursor);
  return dispatch("get_cursor_position", args);
}

/* -- history exchange ------------------------------------------------------*/

extern "C" AMresult *am_apply_changes(AMdoc *d, const uint8_t *data, size_t len) {
  AM_ARGS("(Ly#)", (long long)d->handle, (const char *)data, (Py_ssize_t)len);
  return dispatch("apply_change_bytes", args);
}

extern "C" AMresult *am_save_incremental(AMdoc *d, const uint8_t *heads,
                                         size_t n_heads) {
  /* NULL/0 means "everything": full change history */
  static const uint8_t empty[1] = {0};
  const uint8_t *p = (heads && n_heads) ? heads : empty;
  size_t len = heads ? n_heads * 32 : 0;
  AM_ARGS("(Ly#)", (long long)d->handle, (const char *)p, (Py_ssize_t)len);
  return dispatch("save_incremental", args);
}

extern "C" AMresult *am_generate_sync_message(AMdoc *d, AMsyncState *s) {
  AM_ARGS("(LL)", (long long)d->handle, (long long)s->handle);
  return dispatch("generate_sync_message", args);
}

extern "C" AMresult *am_receive_sync_message(AMdoc *d, AMsyncState *s,
                                             const uint8_t *msg, size_t len) {
  AM_ARGS("(LLy#)", (long long)d->handle, (long long)s->handle, (const char *)msg,
          (Py_ssize_t)len);
  return dispatch("receive_sync_message", args);
}
