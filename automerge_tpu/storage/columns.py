"""Column specifications and raw column metadata blocks.

Byte-compatible with the reference (reference:
rust/automerge/src/storage/columns/column_specification.rs, raw_column.rs).

A column spec packs into a u32: ``(column_id << 4) | (deflate << 3) | type``
with types Group=0, Actor=1, Integer=2, DeltaInteger=3, Boolean=4, String=5,
ValueMetadata=6, Value=7. Column metadata is ULEB(count) then per column
ULEB(spec), ULEB(byte length); data follows concatenated in the same order.
Empty columns are omitted. Columns must appear in ascending normalized
(deflate-bit-cleared) spec order.
"""

from __future__ import annotations

import zlib
from typing import List, Tuple

from ..utils.leb128 import decode_uleb, encode_uleb

TYPE_GROUP = 0
TYPE_ACTOR = 1
TYPE_INTEGER = 2
TYPE_DELTA = 3
TYPE_BOOLEAN = 4
TYPE_STRING = 5
TYPE_VALUE_META = 6
TYPE_VALUE = 7

DEFLATE_BIT = 0b1000


def spec(column_id: int, col_type: int, deflate: bool = False) -> int:
    return (column_id << 4) | (DEFLATE_BIT if deflate else 0) | col_type


def spec_id(s: int) -> int:
    return s >> 4


def spec_type(s: int) -> int:
    return s & 0b0111


def spec_deflate(s: int) -> bool:
    return bool(s & DEFLATE_BIT)


def normalize(s: int) -> int:
    return s & ~DEFLATE_BIT


from ..errors import AutomergeError


class ColumnLayoutError(AutomergeError):
    pass


def write_columns(
    cols: List[Tuple[int, bytes]],
    out: bytearray,
    deflate_threshold: int | None = None,
) -> None:
    """Write column metadata + data for ``cols`` (list of (spec, bytes)).

    Empty columns are filtered. If ``deflate_threshold`` is set, columns whose
    data meets the threshold are DEFLATE-compressed and flagged (reference:
    raw_column.rs compress / document/compression.rs).
    """
    present = [(s, d) for s, d in cols if d]
    encoded = []
    for s, d in present:
        if deflate_threshold is not None and len(d) >= deflate_threshold:
            co = zlib.compressobj(level=6, wbits=-15)
            encoded.append((s | DEFLATE_BIT, co.compress(d) + co.flush()))
        else:
            encoded.append((s, d))
    encode_uleb(len(encoded), out)
    for s, d in encoded:
        encode_uleb(s, out)
        encode_uleb(len(d), out)
    for _, d in encoded:
        out += d


def parse_columns(buf: bytes, pos: int) -> tuple[List[Tuple[int, int]], int]:
    """Parse column metadata at ``pos``; returns ([(spec, length)], new_pos)."""
    count, pos = decode_uleb(buf, pos)
    metas: List[Tuple[int, int]] = []
    last_norm = -1
    for _ in range(count):
        s, pos = decode_uleb(buf, pos)
        length, pos = decode_uleb(buf, pos)
        ns = normalize(s)
        if ns < last_norm:
            raise ColumnLayoutError("columns not in normalized order")
        last_norm = ns
        metas.append((s, length))
    return metas, pos


def slice_column_data(
    buf: bytes, metas: List[Tuple[int, int]], data_start: int
) -> dict[int, bytes]:
    """Slice (and inflate if flagged) each column's bytes out of ``buf``.

    Returns a dict keyed by normalized spec.
    """
    out: dict[int, bytes] = {}
    offset = data_start
    for s, length in metas:
        data = bytes(buf[offset : offset + length])
        if len(data) != length:
            raise ColumnLayoutError("column data out of range")
        offset += length
        if spec_deflate(s):
            try:
                data = zlib.decompress(data, wbits=-15)
            except zlib.error as e:
                raise ColumnLayoutError(f"bad deflate column: {e}") from e
        out[normalize(s)] = data
    return out


def total_column_len(metas: List[Tuple[int, int]]) -> int:
    return sum(length for _, length in metas)
