"""Append-only write-ahead journal of change chunks.

The durable write path (storage/durable.py) routes every change through
this journal before acking; recovery replays it on top of the latest
snapshot. The format is deliberately dumb — a fixed header followed by a
flat sequence of CRC-framed records — because torn-write recovery must
be decidable by a forward scan alone:

    header  := b"AMJ1"
    record  := checksum (4 bytes) | rec_type (1 byte) | ULEB(len) | payload

The checksum is the first 4 bytes of ``chunk_hash(rec_type, payload)`` —
the exact machinery that frames automerge chunks (storage/chunk.py), so a
journal record and a chunk verify identically. Unlike a document save the
journal never resynchronises past damage: it is append-only, so the first
record that fails to verify IS the torn tail — everything before it is
intact, everything after it is dropped and the file is truncated back to
the valid prefix (``obs.count("journal.truncated_tail")`` reports the
bytes lost).

Record types:

* ``REC_CHANGE`` (1): payload is a raw change chunk (magic + checksum +
  type + data), exactly the bytes sync puts on the wire.
* ``REC_META`` (3): payload is ``ULEB(len(name)) | name | blob`` — small
  latest-wins key/value state that must ride with the journal (e.g. a
  sync peer's persisted ``shared_heads``).

Durability is governed by the fsync policy:

* ``"always"``  — fsync after every append (an acked record is durable)
* ``"interval"``— fsync every ``fsync_interval`` appends (bounded loss)
* ``"never"``   — no automatic fsync (crash loses the OS write-back
  window; the journal is still torn-tail-consistent)

The journal is thread-safe: appends serialize under an internal lock and
``sync()`` is a leader-elected fsync **combiner** (group commit).
Concurrent callers that arrive while an fsync is in flight wait for the
NEXT one; exactly one leader issues it and every record appended before
the leader sampled the sequence counter is covered — so N threads
committing concurrently pay far fewer than N fsyncs. Each physical fsync
records how many appends it covered in the ``group_commit.batch_size``
histogram, and a caller whose records were made durable by another
thread's fsync counts ``journal.fsync_combined``.

All file operations go through an injectable filesystem object (``fs``)
so the crash-injection harness (storage/crashsim.py) can simulate
kill-at-every-write-boundary, torn writes, and rename reordering; the
default ``OS_FS`` is the real OS.
"""

from __future__ import annotations

import os
import threading
from typing import List, NamedTuple, Optional, Tuple

from .. import obs
from ..utils.leb128 import LEBDecodeError, decode_uleb, encode_uleb
from .chunk import chunk_hash

JOURNAL_MAGIC = b"AMJ1"

REC_CHANGE = 1
REC_META = 3

_REC_TYPES = frozenset({REC_CHANGE, REC_META})

FSYNC_POLICIES = ("always", "interval", "never")


class JournalError(Exception):
    pass


class JournalPoisoned(JournalError):
    """The journal closed itself after an unrecoverable I/O fault (a
    failed fsync, or a failed append whose cleanup also failed): nothing
    more will be acked through it until the document is compacted or
    reopened. Marked retriable — in a cluster the covering document
    answers requests with this error while a failover, reopen, or
    compaction restores service, so clients should back off and retry
    rather than treat the write as permanently rejected."""

    retriable = True


class OsFS:
    """The real filesystem, behind the narrow interface the durable layer
    uses (so storage/crashsim.py can substitute a fault-injecting one)."""

    def open(self, path: str, mode: str):
        return open(path, mode)

    def fsync(self, f) -> None:
        f.flush()
        os.fsync(f.fileno())

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def sync_dir(self, path: str) -> None:
        """Make preceding renames in ``path`` durable (POSIX dir fsync;
        best-effort where the platform cannot)."""
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(fd)
        except OSError:
            pass
        finally:
            os.close(fd)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def remove(self, path: str) -> None:
        os.remove(path)

    def lock(self, f) -> None:
        """Advisory exclusive lock on an open file, released automatically
        when the process dies (never a stale-lockfile hazard). Raises
        ``JournalError`` when another live process holds it."""
        try:
            import fcntl
        except ImportError:  # non-POSIX: no cross-process guard
            return
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError as e:
            raise JournalError(
                f"journal is locked by another process: {e}"
            ) from e


OS_FS = OsFS()


class JournalRecord(NamedTuple):
    rec_type: int
    payload: bytes
    offset: int  # byte position of the record header in the file
    end: int  # byte position just past the payload


class TailReport(NamedTuple):
    """What a scan found past the valid prefix."""

    valid_bytes: int  # file is intact up to here
    total_bytes: int  # physical file size at scan time
    records: int  # records in the valid prefix
    reason: str  # "" when the file ends exactly on a record boundary

    @property
    def torn(self) -> bool:
        return self.valid_bytes < self.total_bytes

    @property
    def dropped_bytes(self) -> int:
        return self.total_bytes - self.valid_bytes


def encode_record(rec_type: int, payload: bytes) -> bytes:
    out = bytearray(chunk_hash(rec_type, payload)[:4])
    out.append(rec_type)
    encode_uleb(len(payload), out)
    out += payload
    return bytes(out)


def encode_meta(name: str, blob: bytes) -> bytes:
    nb = name.encode("utf-8")
    out = bytearray()
    encode_uleb(len(nb), out)
    out += nb
    out += blob
    return bytes(out)


def decode_meta(payload: bytes) -> Tuple[str, bytes]:
    n, pos = decode_uleb(payload, 0)
    if pos + n > len(payload):
        raise JournalError("meta record name runs past payload end")
    return payload[pos : pos + n].decode("utf-8"), bytes(payload[pos + n :])


def scan_records(data: bytes) -> Tuple[List[JournalRecord], TailReport]:
    """Forward scan: every verifiable record plus where the tail tore.

    Read-only — callers that own the file decide whether to truncate
    (``Journal.open`` does; ``journal-info`` reports without modifying).
    """
    n = len(data)
    if n < len(JOURNAL_MAGIC):
        # includes the 0-byte file a crashed create leaves behind: the
        # caller re-initialises it with a fresh header
        return [], TailReport(0, n, 0, "missing journal header")
    if data[: len(JOURNAL_MAGIC)] != JOURNAL_MAGIC:
        return [], TailReport(0, n, 0, "bad journal magic")
    records: List[JournalRecord] = []
    pos = len(JOURNAL_MAGIC)
    reason = ""
    while pos < n:
        # checksum(4) + type(1) before the length field
        if pos + 5 > n:
            reason = "truncated record header"
            break
        checksum = bytes(data[pos : pos + 4])
        rec_type = data[pos + 4]
        if rec_type not in _REC_TYPES:
            reason = f"unknown record type {rec_type}"
            break
        try:
            length, body = decode_uleb(data, pos + 5)
        except LEBDecodeError:
            reason = "truncated record length"
            break
        end = body + length
        if end > n:
            reason = "record payload extends past end of file"
            break
        payload = bytes(data[body:end])
        if chunk_hash(rec_type, payload)[:4] != checksum:
            reason = "record checksum mismatch"
            break
        records.append(JournalRecord(rec_type, payload, pos, end))
        pos = end
    # a clean scan consumes the whole file, so valid == n there; after a
    # break the valid prefix ends at the last verified record
    valid = records[-1].end if records else len(JOURNAL_MAGIC)
    if not reason:
        valid = n
    return records, TailReport(valid, n, len(records), reason)


def scan_record_seq(data: bytes) -> List[JournalRecord]:
    """Parse a bare record sequence (no journal header) — the exact bytes
    the replication layer puts on the wire (cluster/replication.py ships
    journal records verbatim, so a replicated batch and a journal file
    verify through the same scan). Unlike ``scan_records`` a torn or
    corrupt record here is an error: TCP delivered these bytes intact, so
    damage means a framing bug, not a crash mid-write."""
    records, tail = scan_records(JOURNAL_MAGIC + data)
    if tail.torn:
        raise JournalError(
            f"replicated record batch damaged at byte "
            f"{tail.valid_bytes - len(JOURNAL_MAGIC)}: {tail.reason}"
        )
    return records


def salvage_header_scan(data: bytes) -> List[JournalRecord]:
    """Records recoverable from a file whose 4-byte header is damaged:
    they are individually CRC-framed, so they re-verify under a synthetic
    good header. The single source of truth for what ``Journal.open``'s
    header salvage (and ``journal-info``'s report of it) will keep."""
    if len(data) <= len(JOURNAL_MAGIC):
        return []
    records, _ = scan_records(JOURNAL_MAGIC + bytes(data[len(JOURNAL_MAGIC):]))
    return records


class Journal:
    """One open journal file: appends with a configurable fsync policy.

    Construct via ``Journal.open`` — it scans the existing file, truncates
    any torn tail back to the last verifiable record, and returns the
    surviving records for replay.
    """

    def __init__(self, path: str, f, *, fs, fsync: str, fsync_interval: int,
                 size: int, count: int):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"unknown fsync policy {fsync!r}")
        self.path = path
        self.fs = fs
        self.fsync_policy = fsync
        self.fsync_interval = max(1, int(fsync_interval))
        self._f = f
        self._size = size
        self._count = count
        # chaos-soak invariant as a scrapeable level: one open journal ==
        # one held flock; close() (and the append-poison path) decrement,
        # so a nonzero residue after shutdown means a stranded lock
        obs.registry.gauge("serve.flocks_held").add(1)
        # group-commit state: appends bump _append_seq; _synced_seq is the
        # durable prefix. Both only move under _cond's lock, which also
        # serializes the file writes themselves (interleaved buffered
        # writes from two threads would corrupt the record framing).
        self._cond = threading.Condition()
        self._append_seq = 0
        self._synced_seq = 0
        self._fsync_leader = False
        # replication hooks (cluster/replication.py): on_record fires for
        # every successful append (under the journal lock, so callbacks
        # observe appends in exact file order), on_synced after each fsync
        # with the covering append seq (the records now durable locally).
        # A failing hook is counted, never raised — replication is a
        # sidecar of the local durability path, and a follower that
        # misses a record recovers through the cursor-mismatch snapshot
        # catch-up.
        self.on_record = None  # callable(rec_type, payload, append_seq)
        self.on_synced = None  # callable(covering_append_seq)
        # trace contexts of appends not yet covered by an fsync (bounded):
        # the group-commit leader attaches them as span links, so one
        # combined fsync is attributable to every request it covered
        self._pending_traces: List[tuple] = []
        # non-None once an I/O fault closed the journal for good; names
        # the faulting operation (journal.poisoned{reason})
        self.poisoned_reason: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    @classmethod
    def open(
        cls,
        path: str,
        *,
        fs=None,
        fsync: str = "always",
        fsync_interval: int = 16,
    ) -> Tuple["Journal", List[JournalRecord], TailReport]:
        """Open (creating if absent), recover, and position for appends.

        Returns ``(journal, records, tail_report)``; when the tail was
        torn the file has already been truncated back to the valid prefix
        and ``obs.count("journal.truncated_tail")`` records the bytes
        dropped.
        """
        fs = fs or OS_FS
        # open append-mode: creates the file if absent but NEVER truncates
        # — a losing opener in a create race must not destroy the winner's
        # live journal before its own lock attempt fails. The lock comes
        # before any read or write; O_APPEND keeps every write at the
        # physical end, which is exactly the journal discipline anyway.
        f = fs.open(path, "ab")
        try:
            fs.lock(f)
        except Exception:
            f.close()
            raise
        data = fs.read_bytes(path)
        records, tail = scan_records(data)
        if tail.reason in ("missing journal header", "bad journal magic"):
            # brand new file, a fresh create that crashed mid-header, or a
            # header hit by localized damage. The records BEYOND a corrupt
            # header are still individually CRC-framed
            # (salvage_header_scan); rebuild ATOMICALLY — write the rescued
            # content to a temp file, fsync, rename over the journal — so a
            # crash mid-salvage leaves either the old damaged file (salvage
            # reruns) or the complete new one, never an empty husk.
            salvaged = (
                salvage_header_scan(data)
                if tail.reason == "bad journal magic"
                else []
            )
            kept = sum(r.end - r.offset for r in salvaged)
            dropped = len(data) - kept
            if dropped:
                obs.count("journal.truncated_tail", n=dropped)
            tmp = path + ".tmp"
            nf = fs.open(tmp, "wb")
            try:
                fs.lock(nf)
                nf.write(JOURNAL_MAGIC)
                for r in salvaged:
                    nf.write(encode_record(r.rec_type, r.payload))
                fs.fsync(nf)
                fs.replace(tmp, path)
                # the file's DIRECTORY ENTRY must be durable too, or a
                # crash loses the whole journal regardless of record fsyncs
                fs.sync_dir(os.path.dirname(path) or ".")
            except Exception:
                nf.close()
                raise
            # nf IS the inode now at `path` (and holds its lock); the old
            # handle's inode is unlinked, so its lock guards nothing
            f.close()
            size = len(JOURNAL_MAGIC) + kept
            if not len(data):
                tail = TailReport(size, size, len(salvaged), "")
            return (
                cls(path, nf, fs=fs, fsync=fsync, fsync_interval=fsync_interval,
                    size=size, count=len(salvaged)),
                salvaged,
                tail,
            )
        if tail.torn:
            obs.count("journal.truncated_tail", n=tail.dropped_bytes)
            f.truncate(tail.valid_bytes)
            fs.fsync(f)
        return (
            cls(path, f, fs=fs, fsync=fsync, fsync_interval=fsync_interval,
                size=tail.valid_bytes, count=len(records)),
            records,
            tail,
        )

    @property
    def closed(self) -> bool:
        """True once closed (explicitly, or poisoned by an fsync failure
        or a double fault in ``append``): every further append/sync
        raises."""
        return self._f is None

    @property
    def poisoned(self) -> bool:
        """True when an unrecoverable I/O fault closed the journal (as
        opposed to an orderly ``close()``)."""
        return self.poisoned_reason is not None

    def _poison_locked(self, reason: str) -> None:
        """Close the journal for good after an unrecoverable I/O fault
        (``_cond`` held). Every waiter parked in the fsync combiner wakes
        and raises; nothing is ever acked through this journal again —
        the only recovery is compaction (fresh snapshot) or a reopen."""
        if self._f is None:
            return
        try:
            self._f.close()
        except Exception:  # noqa: BLE001 — the fd is lost either way
            pass
        self._f = None
        self.poisoned_reason = reason
        obs.registry.gauge("serve.flocks_held").add(-1)
        obs.count("journal.poisoned", labels={"reason": reason})
        obs.event("journal.poisoned", path=self.path, reason=reason)
        self._cond.notify_all()

    def _closed_error(self) -> JournalError:
        if self.poisoned_reason is not None:
            return JournalPoisoned(
                f"journal poisoned by a failed {self.poisoned_reason}; "
                "compact or reopen the document to recover"
            )
        return JournalError("journal is closed")

    def close(self) -> None:
        if self._f is None:
            return
        try:
            if self._unsynced:
                self.sync()
        finally:
            with self._cond:
                if self._f is not None:
                    self._f.close()
                    self._f = None
                    obs.registry.gauge("serve.flocks_held").add(-1)
                    self._cond.notify_all()

    @property
    def _unsynced(self) -> int:
        """Appends not yet covered by an fsync."""
        return self._append_seq - self._synced_seq

    @property
    def append_seq(self) -> int:
        """Monotone per-open append counter (does not reset on truncate)."""
        return self._append_seq

    @property
    def acked_seq(self) -> int:
        """The durable acked prefix: every append with seq <= this has
        been covered by an fsync (the replication layer ships exactly
        this prefix, and promotion compares followers by it)."""
        return self._synced_seq

    # -- appends -------------------------------------------------------------

    @property
    def record_count(self) -> int:
        return self._count

    @property
    def size_bytes(self) -> int:
        return self._size

    def append(self, rec_type: int, payload: bytes,
               auto_sync: bool = True) -> None:
        """Append one record; durable on return iff the policy says so.

        ``auto_sync=False`` defers the policy fsync — the caller promises
        to invoke ``policy_sync()`` before acking (the durable layer uses
        this to pay ONE fsync per public call instead of one per change
        in a merge/sync batch)."""
        rec = encode_record(rec_type, payload)
        with obs.span("journal.append", bytes=len(rec)):
            with self._cond:
                if self._f is None:
                    raise self._closed_error()
                try:
                    self._f.write(rec)
                except Exception:
                    # a partial write (ENOSPC/EIO mid-record) would leave
                    # torn bytes MID-file: later successful appends would
                    # land after the tear and be dropped at recovery. Cut
                    # back to the last known-good size; if even that
                    # fails, poison the journal.
                    try:
                        self._f.truncate(self._size)
                    except Exception:
                        self._poison_locked("append")
                    raise
                self._size += len(rec)
                self._count += 1
                self._append_seq += 1
                ctx = obs.current_trace_context()
                if ctx is not None and len(self._pending_traces) < 16:
                    self._pending_traces.append(ctx)
                if self.on_record is not None:
                    try:
                        self.on_record(rec_type, payload, self._append_seq)
                    except Exception as e:  # noqa: BLE001 — sidecar only
                        obs.count("journal.hook_error", error=str(e)[:200])
        if auto_sync:
            self.policy_sync()

    def policy_sync(self) -> None:
        """Apply the fsync policy to whatever is pending: "always" syncs,
        "interval" syncs when the pending count crosses the interval,
        "never" does nothing."""
        if self._unsynced and (
            self.fsync_policy == "always"
            or (
                self.fsync_policy == "interval"
                and self._unsynced >= self.fsync_interval
            )
        ):
            self.sync()

    def append_change(self, raw_chunk: bytes) -> None:
        self.append(REC_CHANGE, raw_chunk)

    def append_meta(self, name: str, blob: bytes) -> None:
        self.append(REC_META, encode_meta(name, blob))

    def sync(self) -> None:
        """Force everything appended so far onto stable storage.

        This is the group-commit combiner: the caller's records are
        durable on return, but not necessarily via its own fsync. If an
        fsync is already in flight the caller waits for it; when that
        fsync (issued before our appends) does not cover us, exactly one
        waiter becomes the next leader and its single fsync covers every
        append made in the meantime — N concurrent committers collapse
        into ~2 physical fsyncs instead of N."""
        with self._cond:
            if self._f is None:
                raise self._closed_error()
            target = self._append_seq
            if self._synced_seq >= target:
                return
            while self._fsync_leader:
                self._cond.wait()
                if self._synced_seq >= target:
                    # another thread's fsync covered our records
                    obs.count("journal.fsync_combined")
                    return
                if self._f is None:
                    raise self._closed_error()
            self._fsync_leader = True
            covering = self._append_seq
            f = self._f
            links, self._pending_traces = self._pending_traces, []
        try:
            with obs.span("journal.fsync", links=links,
                          labels={"policy": self.fsync_policy}):
                self.fs.fsync(f)
        except Exception:
            # a failed fsync POISONS the journal — no retry. After EIO the
            # kernel may have dropped the dirty pages, so a later fsync
            # can "succeed" while the records it claims to cover were
            # never written (the classic fsync-gate). Closing the file
            # here converts every combined-fsync waiter parked above into
            # an error too: an un-fsynced ack is no ack, for every caller
            # this fsync covered. Recovery is compact() (fresh snapshot
            # re-establishes disk >= memory) or a reopen.
            with self._cond:
                self._fsync_leader = False
                self._poison_locked("fsync")
            raise
        with self._cond:
            batch = covering - self._synced_seq
            self._synced_seq = covering
            self._fsync_leader = False
            self._cond.notify_all()
        obs.observe("group_commit.batch_size", batch)
        if self.on_synced is not None:
            try:
                self.on_synced(covering)
            except Exception as e:  # noqa: BLE001 — sidecar only
                obs.count("journal.hook_error", error=str(e)[:200])

    def truncate(self) -> None:
        """Reset to an empty journal (post-compaction): the truncation is
        fsynced before return so stale records cannot resurrect."""
        with self._cond:
            if self._f is None:
                raise self._closed_error()
            # wait out any in-flight fsync: its covering seq refers to
            # the pre-truncation file
            while self._fsync_leader:
                self._cond.wait()
                if self._f is None:
                    raise self._closed_error()
            self._f.truncate(len(JOURNAL_MAGIC))
            self._f.seek(len(JOURNAL_MAGIC))
            with obs.span("journal.fsync",
                          labels={"policy": self.fsync_policy}):
                self.fs.fsync(self._f)
            self._synced_seq = self._append_seq
            self._size = len(JOURNAL_MAGIC)
            self._count = 0

    def revive(self) -> None:
        """Re-open a POISONED journal in place as an empty journal.

        Only the compaction path may call this, and only after a snapshot
        covering the full in-memory history is durable on disk — the
        on-disk journal's contents past the poison point are unknowable
        (the failed fsync may or may not have persisted them), so they
        are discarded wholesale and the snapshot becomes the only truth.
        The Journal object (and the replication hooks installed on it)
        survives; the file handle and flock are re-acquired. Counters:
        the durable acked prefix jumps to cover every append — the
        snapshot now holds them all."""
        with self._cond:
            if self._f is not None:
                return  # live journal: nothing to revive
            if self.poisoned_reason is None:
                raise JournalError("cannot revive an orderly-closed journal")
            # append-mode first (never truncates a file another process
            # may own), lock, THEN cut back to a bare header
            f = self.fs.open(self.path, "ab")
            try:
                self.fs.lock(f)
                f.truncate(len(JOURNAL_MAGIC))
                self.fs.fsync(f)
            except Exception:
                f.close()
                raise
            self._f = f
            self.poisoned_reason = None
            self._size = len(JOURNAL_MAGIC)
            self._count = 0
            self._synced_seq = self._append_seq
            self._fsync_leader = False
            obs.registry.gauge("serve.flocks_held").add(1)
            obs.count("journal.revived")
            self._cond.notify_all()
