"""Crash-injection filesystem: kill-at-every-write-boundary, torn writes,
and rename reordering for the durable layer.

``SimFS`` implements the same narrow interface as ``journal.OsFS`` but
keeps everything in memory and models *durability* separately from
*visibility*:

* every file tracks the bytes the live process sees (``data``) and the
  bytes known to have reached stable storage (``synced`` — updated only
  by ``fsync``);
* ``replace`` (atomic rename) takes effect immediately for the live
  process but stays on a *pending* list until ``sync_dir`` commits it —
  so a crash can observe a rename that never became durable, or (the
  classic reordering bug) a durable rename pointing at a file whose
  un-fsynced contents were lost.

Every mutating operation (write / fsync / replace / truncate / sync_dir /
create) is a numbered *crash boundary*: constructing the FS with
``crash_at=k`` raises ``CrashPoint`` instead of performing boundary
``k``. A harness first runs its workload with ``crash_at=None`` to count
boundaries, then sweeps ``k`` over all of them.

After a ``CrashPoint``, ``crash_states(rng)`` enumerates plausible
post-crash disk images: the conservative one (only fsynced bytes and
committed renames survive), the optimistic one (everything visible
survives), and seeded intermediates with *torn* files (a prefix of the
un-fsynced tail persisted) and partially-applied rename queues. Each
image reopens via ``SimFS.from_disk`` — a fresh, fault-free FS — and the
property suite asserts the durable layer recovers to a prefix-consistent
document from every one of them.

Modeling limits (documented, deliberate): file *creation* is treated as
immediately durable (only rename and write-content durability are
modeled), and writes are applied straight to the file image (no separate
userspace buffer — torn-tail states subsume it).
"""

from __future__ import annotations

import errno as _errno
import posixpath
import random
import threading
from typing import Dict, List, Optional, Tuple


class CrashPoint(Exception):
    """The scheduled crash boundary was reached; the workload is dead."""


class _Node:
    __slots__ = ("data", "synced")

    def __init__(self, data: bytes = b"", synced: bytes = b""):
        self.data = bytearray(data)
        self.synced = bytes(synced)


class SimFile:
    """A file handle over a SimFS node; mutations tick the crash clock."""

    def __init__(self, fs: "SimFS", node: _Node, pos: int, readable: bool,
                 writable: bool, append: bool = False):
        self._fs = fs
        self._node = node
        self._pos = pos
        self._readable = readable
        self._writable = writable
        self._append = append  # O_APPEND: every write lands at current EOF
        self.closed = False

    def _check(self, write: bool) -> None:
        if self.closed:
            raise ValueError("I/O operation on closed file")
        if write and not self._writable:
            raise ValueError("file not open for writing")
        if not write and not self._readable:
            raise ValueError("file not open for reading")

    def write(self, data: bytes) -> int:
        self._check(write=True)
        self._fs._tick(("write", len(data)))
        d = self._node.data
        pos = len(d) if self._append else self._pos
        end = pos + len(data)
        if pos > len(d):  # sparse seek past EOF: zero-fill like POSIX
            d.extend(b"\x00" * (pos - len(d)))
        d[pos:end] = data
        self._pos = end
        return len(data)

    def read(self, n: int = -1) -> bytes:
        self._check(write=False)
        d = self._node.data
        if n is None or n < 0:
            out = bytes(d[self._pos :])
        else:
            out = bytes(d[self._pos : self._pos + n])
        self._pos += len(out)
        return out

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = pos
        elif whence == 1:
            self._pos += pos
        elif whence == 2:
            self._pos = len(self._node.data) + pos
        else:
            raise ValueError(f"bad whence {whence}")
        return self._pos

    def tell(self) -> int:
        return self._pos

    def truncate(self, size: Optional[int] = None) -> int:
        self._check(write=True)
        if size is None:
            size = self._pos
        self._fs._tick(("truncate", size))
        del self._node.data[size:]
        return size

    def flush(self) -> None:
        pass  # no userspace buffer to flush (see module docstring)

    def close(self) -> None:
        self.closed = True

    def fileno(self):  # real os.fsync must never be handed a SimFile
        raise OSError("SimFile has no OS-level file descriptor")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class SimFS:
    """In-memory filesystem with crash-boundary accounting.

    Interface-compatible with ``journal.OsFS``; see the module docstring
    for the durability model.
    """

    def __init__(self, crash_at: Optional[int] = None):
        self.files: Dict[str, _Node] = {}  # visible namespace
        # renames visible to the process but not yet committed to the
        # durable namespace: (dst, node-now-at-dst, node-previously-at-dst)
        self.pending_renames: List[Tuple[str, _Node, Optional[Tuple[str, _Node]]]] = []
        self.ops = 0
        self.crash_at = crash_at
        self.crashed = False
        self.op_trace: List[tuple] = []  # (kind, detail) per boundary

    # -- crash clock ---------------------------------------------------------

    def _tick(self, what: tuple) -> None:
        if self.crashed:
            raise CrashPoint("filesystem already crashed")
        self.ops += 1
        self.op_trace.append(what)
        if self.crash_at is not None and self.ops >= self.crash_at:
            self.crashed = True
            raise CrashPoint(f"crash at boundary {self.ops}: {what}")

    # -- OsFS interface ------------------------------------------------------

    def open(self, path: str, mode: str):
        path = self._norm(path)
        node = self.files.get(path)
        if mode == "rb":
            if node is None:
                raise FileNotFoundError(path)
            return SimFile(self, node, 0, readable=True, writable=False)
        if mode == "wb":
            self._tick(("create", path))
            node = _Node()
            self.files[path] = node
            return SimFile(self, node, 0, readable=False, writable=True)
        if mode == "ab":
            if node is None:
                self._tick(("create", path))
                node = _Node()
                self.files[path] = node
            return SimFile(self, node, len(node.data), readable=False,
                           writable=True, append=True)
        if mode == "r+b":
            if node is None:
                raise FileNotFoundError(path)
            return SimFile(self, node, 0, readable=True, writable=True)
        raise ValueError(f"unsupported mode {mode!r}")

    def fsync(self, f: SimFile) -> None:
        self._tick(("fsync",))
        f._node.synced = bytes(f._node.data)

    def replace(self, src: str, dst: str) -> None:
        src, dst = self._norm(src), self._norm(dst)
        node = self.files.get(src)
        if node is None:
            raise FileNotFoundError(src)
        self._tick(("replace", src, dst))
        prev = self.files.get(dst)
        prev_entry = (dst, prev) if prev is not None else None
        self.files[dst] = node
        del self.files[src]
        self.pending_renames.append((dst, node, prev_entry))

    def sync_dir(self, path: str) -> None:
        p = self._norm(path)
        self._tick(("sync_dir", p))
        # commits only renames into THIS directory — an fsync of the wrong
        # directory must be as ineffective in the sweep as on a real fs
        self.pending_renames = [
            e for e in self.pending_renames
            if posixpath.dirname(self._norm(e[0])) != p
        ]

    def exists(self, path: str) -> bool:
        return self._norm(path) in self.files

    def getsize(self, path: str) -> int:
        return len(self.files[self._norm(path)].data)

    def read_bytes(self, path: str) -> bytes:
        return bytes(self.files[self._norm(path)].data)

    def makedirs(self, path: str) -> None:
        pass  # flat namespace: directories are implicit

    def lock(self, f) -> None:
        pass  # one SimFS instance models one process: no cross-process races

    def remove(self, path: str) -> None:
        path = self._norm(path)
        self._tick(("remove", path))
        self.files.pop(path, None)

    @staticmethod
    def _norm(path: str) -> str:
        return posixpath.normpath(str(path))

    # -- crash-state enumeration ---------------------------------------------

    def _namespace_at(self, renames_applied: int) -> Dict[str, _Node]:
        """The durable namespace with only the first ``renames_applied``
        pending renames committed: later ones are undone in reverse."""
        ns = dict(self.files)
        for dst, node, prev_entry in reversed(
            self.pending_renames[renames_applied:]
        ):
            # undo: dst reverts to its previous occupant (or nothing); the
            # renamed node reappears under a synthetic .tmp-limbo name only
            # if it never became visible elsewhere — recovery must not rely
            # on it, so it is simply dropped from the image.
            if ns.get(dst) is node:
                if prev_entry is not None:
                    ns[dst] = prev_entry[1]
                else:
                    ns.pop(dst, None)
        return ns

    @staticmethod
    def _content_candidates(node: _Node, rng: random.Random, mode: str) -> bytes:
        """One plausible post-crash content for ``node`` under ``mode``:
        'clean' (fsynced bytes only), 'all' (everything), 'torn' (a seeded
        prefix of the un-fsynced delta survives)."""
        data, synced = bytes(node.data), node.synced
        if mode == "all":
            return data
        if data.startswith(synced):
            if mode == "clean":
                return synced
            extra = len(data) - len(synced)
            keep = rng.randrange(extra + 1) if extra else 0
            return data[: len(synced) + keep]
        # data diverged from synced (unsynced truncate/rewrite): the disk
        # may hold the old image, the new one, or a prefix of the new one
        if mode == "clean":
            return synced
        return data[: rng.randrange(len(data) + 1)] if data else b""

    def crash_states(
        self, rng: Optional[random.Random] = None, variants: int = 3
    ) -> List[Dict[str, bytes]]:
        """Plausible disk images after the crash: conservative, optimistic,
        and ``variants`` seeded torn/reordered intermediates."""
        rng = rng or random.Random(0)
        states: List[Dict[str, bytes]] = []
        n_pend = len(self.pending_renames)
        # conservative: nothing un-fsynced survives, no pending rename landed
        states.append(
            {p: n.synced for p, n in self._namespace_at(0).items()}
        )
        # optimistic: everything visible survives
        states.append(
            {p: bytes(n.data) for p, n in self._namespace_at(n_pend).items()}
        )
        for _ in range(variants):
            applied = rng.randint(0, n_pend)
            ns = self._namespace_at(applied)
            mode_for = {
                p: rng.choice(("clean", "torn", "all")) for p in ns
            }
            states.append(
                {
                    p: self._content_candidates(n, rng, mode_for[p])
                    for p, n in ns.items()
                }
            )
        return states

    @classmethod
    def from_disk(cls, state: Dict[str, bytes]) -> "SimFS":
        """A fresh, fault-free FS whose durable content is ``state`` —
        what a process sees when it restarts after the crash."""
        fs = cls(crash_at=None)
        for path, data in state.items():
            fs.files[cls._norm(path)] = _Node(data, data)
        return fs


# -- live disk-fault injection ------------------------------------------------


class _FaultyFile:
    """File handle issued by ``FaultyFS``: write-path calls consult the
    armed faults before delegating; everything else passes through."""

    def __init__(self, fs: "FaultyFS", inner):
        self._fs = fs
        self._inner = inner

    def write(self, data):
        self._fs._maybe_fail("write")
        return self._inner.write(data)

    def truncate(self, size=None):
        self._fs._maybe_fail("truncate")
        return self._inner.truncate(size)

    def __getattr__(self, name):  # read/seek/flush/fileno/close/...
        return getattr(self._inner, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._inner.close()
        return False


class FaultyFS:
    """Live disk-fault injection over a real (or simulated) filesystem.

    Where ``SimFS`` models crash boundaries for offline sweeps, this
    wrapper deals I/O errors to a *running* process: arm ``ENOSPC`` on
    write and the journal's next append raises mid-record; arm ``EIO``
    on fsync and the next group-commit fsync fails — which the journal
    answers by poisoning itself (storage/journal.py). Thread-safe and
    zero-overhead-ish when nothing is armed (one lock-free dict read per
    faultable call).

        fs = FaultyFS()
        dd = AutoDoc.open(path, fs=fs)
        fs.arm("fsync", "EIO")          # every fsync fails until cleared
        fs.arm("write", "ENOSPC", count=1)  # exactly the next write
        fs.clear()                      # all faults off

    Ops: ``write``, ``truncate``, ``fsync``, ``replace``, ``sync_dir``,
    ``read``. Every injected fault counts
    ``chaos.injected{kind=disk_<op>}`` so a chaos soak can assert its
    faults actually fired.

    ``read`` is special: armed with the sentinel err ``"BITFLIP"`` it
    models silent bit rot — ``read_bytes`` returns the file's bytes with
    one bit flipped instead of raising, which is exactly the fault class
    only a checksum (the integrity scrub) can catch. Armed with a real
    errno name it raises like any other op."""

    FAULTABLE = ("write", "truncate", "fsync", "replace", "sync_dir",
                 "read")

    def __init__(self, base=None):
        if base is None:
            from .journal import OS_FS

            base = OS_FS
        self.base = base
        self._lock = threading.Lock()
        self._armed: Dict[str, List] = {}  # op -> [errno_name, remaining]

    # -- arming ---------------------------------------------------------------

    def arm(self, op: str, err: str = "EIO", count: int = -1) -> None:
        """Fail the next ``count`` calls of ``op`` (-1 = until cleared)
        with the named errno (``"EIO"``, ``"ENOSPC"``, ...)."""
        if op not in self.FAULTABLE:
            raise ValueError(f"unknown faultable op {op!r}")
        if err != "BITFLIP" and not hasattr(_errno, err):
            raise ValueError(f"unknown errno name {err!r}")
        if err == "BITFLIP" and op != "read":
            raise ValueError("BITFLIP is only meaningful on read")
        with self._lock:
            self._armed[op] = [err, int(count)]

    def clear(self, op: Optional[str] = None) -> None:
        with self._lock:
            if op is None:
                self._armed.clear()
            else:
                self._armed.pop(op, None)

    def armed(self) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            return {op: (e, n) for op, (e, n) in self._armed.items()}

    def _maybe_fail(self, op: str) -> None:
        if not self._armed:  # unarmed fast path, no lock
            return
        with self._lock:
            entry = self._armed.get(op)
            if entry is None:
                return
            err, remaining = entry
            if remaining == 0:
                self._armed.pop(op, None)
                return
            if remaining > 0:
                entry[1] = remaining - 1
                if entry[1] == 0:
                    self._armed.pop(op, None)
        from .. import obs

        obs.count("chaos.injected", labels={"kind": f"disk_{op}"})
        code = getattr(_errno, err)
        raise OSError(code, f"injected {err} on {op}")

    def _consume(self, op: str):
        """Decrement and return the armed err name for ``op`` (None when
        unarmed) WITHOUT raising — the BITFLIP read path corrupts the
        returned bytes instead of failing the call."""
        with self._lock:
            entry = self._armed.get(op)
            if entry is None:
                return None
            err, remaining = entry
            if remaining == 0:
                self._armed.pop(op, None)
                return None
            if remaining > 0:
                entry[1] = remaining - 1
                if entry[1] == 0:
                    self._armed.pop(op, None)
            return err

    # -- the OsFS interface ---------------------------------------------------

    def open(self, path: str, mode: str):
        f = self.base.open(path, mode)
        return _FaultyFile(self, f)

    def fsync(self, f) -> None:
        self._maybe_fail("fsync")
        self.base.fsync(f._inner if isinstance(f, _FaultyFile) else f)

    def replace(self, src: str, dst: str) -> None:
        self._maybe_fail("replace")
        self.base.replace(src, dst)

    def sync_dir(self, path: str) -> None:
        self._maybe_fail("sync_dir")
        self.base.sync_dir(path)

    def exists(self, path: str) -> bool:
        return self.base.exists(path)

    def getsize(self, path: str) -> int:
        return self.base.getsize(path)

    def read_bytes(self, path: str) -> bytes:
        err = self._consume("read") if self._armed else None
        if err is not None and err != "BITFLIP":
            from .. import obs

            obs.count("chaos.injected", labels={"kind": "disk_read"})
            raise OSError(getattr(_errno, err), f"injected {err} on read")
        data = self.base.read_bytes(path)
        if err == "BITFLIP" and data:
            from .. import obs

            obs.count("chaos.injected", labels={"kind": "disk_read_flip"})
            # flip one mid-file bit: silent rot, not truncation
            i = len(data) // 2
            data = data[:i] + bytes([data[i] ^ 0x40]) + data[i + 1:]
        return data

    def makedirs(self, path: str) -> None:
        self.base.makedirs(path)

    def remove(self, path: str) -> None:
        self.base.remove(path)

    def lock(self, f) -> None:
        self.base.lock(f._inner if isinstance(f, _FaultyFile) else f)
