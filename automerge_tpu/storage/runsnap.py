"""Run-coded snapshot codec ("ARSN"): the StrideRuns column image as the
on-disk format.

The legacy snapshot is a document chunk: hydrating it parses every change,
re-encodes per-change column bytes to recover hashes, and rebuilds the run
tables from scratch — the dominant cost of a cold open.  An ARSN snapshot
instead stores the *resident* representation directly:

* each change's raw chunk bytes verbatim (hash = chunk hash, so digests and
  sync wire bytes are bit-identical to the legacy path), plus its op count so
  ops decode lazily;
* the ``CompressedOpColumns`` run tables per ROW_SPEC/EDGE_SPEC column
  (dense-demoted columns are stored dense, verbatim);
* the scalar-value heap, actor/prop/mark tables, object table, and heads.

Hydration is read + per-section CRC walk + ``np.repeat`` run expansion — no
chunk parse of op columns, no RLE decode, no run re-encode.  The file layout:

    magic "ARSN" | version u8 | flags u8
    repeated sections: tag u8 | ULEB payload_len | payload | CRC32 LE
                       (CRC over tag + length + payload)

Flags bit0 records whether compressed residency was enabled at write time;
when set, hydration re-installs the run tables (and the per-column demotion
decisions) instead of re-deriving them.

Corruption raises :class:`RunSnapError`; callers fall back to the legacy
salvage reader, which carves the embedded change chunks out of SEC_CHANGES
by magic-scan — an ARSN file degrades exactly like a chunk snapshot.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..utils.leb128 import decode_sleb, decode_uleb, sleb_bytes, uleb_bytes
from . import columns as colio
from .change import LazyOps, StoredChange
from .chunk import parse_chunk

MAGIC = b"ARSN"
VERSION = 1
FLAG_COMPRESSED = 0x01

SEC_META = 1
SEC_ACTORS = 2
SEC_HEADS = 3
SEC_CHANGES = 4
SEC_RUNS = 5
SEC_VALUES = 6
SEC_OBJTAB = 7

SECTION_NAMES = {
    SEC_META: "meta",
    SEC_ACTORS: "actors",
    SEC_HEADS: "heads",
    SEC_CHANGES: "changes",
    SEC_RUNS: "runs",
    SEC_VALUES: "values",
    SEC_OBJTAB: "objtab",
}

# column entry kinds inside SEC_RUNS
_K_ABSENT = 0
_K_RUNS = 1
_K_DENSE = 2

# target dtypes for each OpLog slot on decode (mirrors OpLog._finalize)
_COL_DTYPES = {
    "action": np.int32,
    "insert": np.bool_,
    "prop": np.int32,
    "value_tag": np.int32,
    "width": np.int32,
    "expand": np.bool_,
    "mark_name_idx": np.int32,
    "obj_dense": np.int32,
    "id_key": np.int64,
    "obj_key": np.int64,
    "elem_key": np.int64,
    "elem_ref": np.int32,
    "value_int": np.int64,
    "pred_src": np.int32,
    "pred_tgt": np.int32,
    "pred_key": np.int64,
}


class RunSnapError(Exception):
    """ARSN container is malformed or corrupt."""


def enabled() -> bool:
    """Write new snapshots in the run-coded format? (reader is always on)"""
    return os.environ.get("AUTOMERGE_TPU_RUNSNAP", "1") != "0"


def is_runsnap(data: bytes) -> bool:
    return len(data) >= 6 and data[:4] == MAGIC


# -- low-level framing -------------------------------------------------------


def _put_array(out: bytearray, arr: Optional[np.ndarray]) -> None:
    if arr is None:
        out += b"\x00"
        return
    arr = np.ascontiguousarray(arr)
    ds = arr.dtype.str.encode("ascii")
    out += bytes([len(ds)])
    out += ds
    raw = arr.tobytes()
    out += uleb_bytes(len(raw))
    out += raw


def _get_array(data: bytes, pos: int) -> Tuple[Optional[np.ndarray], int]:
    dlen = data[pos]
    pos += 1
    if dlen == 0:
        return None, pos
    ds = data[pos : pos + dlen].decode("ascii")
    pos += dlen
    nbytes, pos = decode_uleb(data, pos)
    if pos + nbytes > len(data):
        raise RunSnapError("array extends past section end")
    # .copy(): frombuffer views are read-only and several consumers
    # (StrideRuns.extend_tail, in-place re-resolution) mutate columns
    arr = np.frombuffer(data, dtype=np.dtype(ds), count=nbytes // np.dtype(ds).itemsize, offset=pos).copy()
    return arr, pos + nbytes


def _put_bytes(out: bytearray, b: bytes) -> None:
    out += uleb_bytes(len(b))
    out += b


def _get_bytes(data: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = decode_uleb(data, pos)
    if pos + n > len(data):
        raise RunSnapError("byte string extends past section end")
    return bytes(data[pos : pos + n]), pos + n


def _emit_section(out: bytearray, tag: int, payload: bytes) -> None:
    frame = bytes([tag]) + uleb_bytes(len(payload)) + payload
    out += frame
    out += zlib.crc32(frame).to_bytes(4, "little")


def _specs():
    from ..ops.compressed import EDGE_SPEC, ROW_SPEC

    return list(ROW_SPEC) + list(EDGE_SPEC)


# -- encoder -----------------------------------------------------------------


def encode_snapshot(log, heads: List[bytes]) -> bytes:
    """Serialize an OpLog (with raw change bytes) as an ARSN container.

    Raises :class:`RunSnapError` when the log cannot be represented (a
    change without ``raw_bytes``); the caller falls back to the legacy
    chunk writer.
    """
    from ..ops import compressed as C

    changes = log.changes
    for ch in changes:
        if ch.raw_bytes is None:
            raise RunSnapError("change without raw chunk bytes")

    comp = None
    flags = 0
    if C.enabled():
        flags |= FLAG_COMPRESSED
        existing = getattr(log, "_comp", None)
        live = [nm for nm, _, _ in _specs() if getattr(log, nm, None) is not None]
        if existing is not None and existing.entries and existing.all_dense(live):
            # every live column already demoted: skip the compressed
            # sync/encode walk entirely and write dense directly
            obs.count("compact.dense_shortcut")
            comp = None
        else:
            comp = log.compressed(sync=True)

    out = bytearray()
    out += MAGIC
    out += bytes([VERSION, flags])

    n = int(log.n)
    q = 0 if getattr(log, "pred_src", None) is None else len(log.pred_src)

    meta = bytearray()
    meta += uleb_bytes(n)
    meta += uleb_bytes(q)
    meta += uleb_bytes(len(changes))
    meta += uleb_bytes(int(getattr(log, "n_objs", 0) or 0))
    _emit_section(out, SEC_META, bytes(meta))

    actors = bytearray()
    actors += uleb_bytes(len(log.actors))
    for a in log.actors:
        _put_bytes(actors, a.bytes if hasattr(a, "bytes") else bytes(a))
    actors += uleb_bytes(len(log.props))
    for p in log.props:
        _put_bytes(actors, p.encode("utf-8"))
    actors += uleb_bytes(len(log.mark_names))
    for m in log.mark_names:
        _put_bytes(actors, m.encode("utf-8"))
    _emit_section(out, SEC_ACTORS, bytes(actors))

    hd = bytearray()
    hd += uleb_bytes(len(heads))
    for h in sorted(heads):
        if len(h) != 32:
            raise RunSnapError("head hash is not 32 bytes")
        hd += h
    _emit_section(out, SEC_HEADS, bytes(hd))

    chs = bytearray()
    chs += uleb_bytes(len(changes))
    for ch in changes:
        chs += uleb_bytes(len(ch.ops))
        _put_bytes(chs, ch.raw_bytes)
    _emit_section(out, SEC_CHANGES, bytes(chs))

    runs = bytearray()
    for name, _mode, _item in _specs():
        arr = getattr(log, name, None)
        if arr is None:
            runs += bytes([_K_ABSENT])
            continue
        rows = len(arr)
        sr = comp.runs_for(name, rows) if comp is not None else None
        if sr is not None:
            runs += bytes([_K_RUNS])
            rflags = (1 if sr.is_sorted else 0) | (2 if sr.stride_mode else 0)
            runs += bytes([rflags])
            ds = np.dtype(sr.dtype).str.encode("ascii")
            runs += bytes([len(ds)])
            runs += ds
            runs += uleb_bytes(len(sr.starts))
            runs += uleb_bytes(rows)
            runs += np.ascontiguousarray(sr.starts, np.int64).tobytes()
            runs += np.ascontiguousarray(sr.vals, np.int64).tobytes()
            runs += np.ascontiguousarray(sr.strides, np.int64).tobytes()
        else:
            runs += bytes([_K_DENSE])
            if arr.dtype == np.bool_:
                dense = np.ascontiguousarray(arr, np.bool_).view(np.int8)
            else:
                dense = arr
            _put_array(runs, dense)
    _emit_section(out, SEC_RUNS, bytes(runs))

    vals = bytearray()
    code, off, ln, raw = _value_heap(log)
    _put_array(vals, code)
    _put_array(vals, off)
    _put_array(vals, ln)
    _put_bytes(vals, raw)
    _emit_section(out, SEC_VALUES, bytes(vals))

    ot = bytearray()
    _put_array(ot, getattr(log, "obj_table", None))
    _emit_section(out, SEC_OBJTAB, bytes(ot))

    return bytes(out)


def _value_heap(log) -> Tuple[np.ndarray, np.ndarray, np.ndarray, bytes]:
    """The (code, off, len, raw) scalar heap for a log, converting an eager
    value list to the lazy layout when needed."""
    vals = log.values
    if vals is None:
        z = np.zeros(0, np.int32)
        return z, np.zeros(0, np.int64), z.copy(), b""
    if hasattr(vals, "code"):  # LazyValues
        return (
            np.asarray(vals.code),
            np.asarray(vals.off),
            np.asarray(vals.ln),
            bytes(vals.raw),
        )
    from .values import encode_raw_value, value_meta

    n = len(vals)
    code = np.zeros(n, np.int32)
    off = np.zeros(n, np.int64)
    ln = np.zeros(n, np.int32)
    raw = bytearray()
    for i, v in enumerate(vals):
        m = value_meta(v)
        code[i] = m & 0x0F
        ln[i] = m >> 4
        off[i] = len(raw)
        encode_raw_value(v, raw)
    return code, off, ln, bytes(raw)


# -- decoder -----------------------------------------------------------------


class _RunCol:
    __slots__ = ("flags", "dtype", "starts", "vals", "strides", "rows")

    def __init__(self, flags, dtype, starts, vals, strides, rows):
        self.flags = flags
        self.dtype = dtype
        self.starts = starts
        self.vals = vals
        self.strides = strides
        self.rows = rows

    def decode(self) -> np.ndarray:
        return self._runs().decode()

    def _runs(self):
        from ..ops.compressed import StrideRuns

        return StrideRuns(
            self.starts, self.vals, self.strides, self.rows, self.dtype,
            bool(self.flags & 1), bool(self.flags & 2),
        )

    @property
    def nbytes(self) -> int:
        return self.starts.nbytes + self.vals.nbytes + self.strides.nbytes


class RunImage:
    """A parsed ARSN container: the device-ready column image plus the raw
    change blob, held between hydrations so warm→hot promotion and the next
    compaction never re-extract columns from changes."""

    __slots__ = (
        "version",
        "flags",
        "n",
        "q",
        "n_changes",
        "n_objs",
        "actors",
        "props",
        "mark_names",
        "heads",
        "cols",
        "values",
        "obj_table",
        "_change_blob",
        "_changes",
        "_hashes",
    )

    def __init__(self):
        self.version = VERSION
        self.flags = 0
        self.n = 0
        self.q = 0
        self.n_changes = 0
        self.n_objs = 0
        self.actors: List[bytes] = []
        self.props: List[str] = []
        self.mark_names: List[str] = []
        self.heads: List[bytes] = []
        self.cols: Dict[str, object] = {}
        self.values = (None, None, None, b"")
        self.obj_table: Optional[np.ndarray] = None
        self._change_blob: Optional[bytes] = None
        self._changes: Optional[List[StoredChange]] = None
        self._hashes: Optional[List[bytes]] = None

    # -- changes -------------------------------------------------------------

    @property
    def changes(self) -> List[StoredChange]:
        if self._changes is None:
            self._changes = _load_changes(self._change_blob)
            self._hashes = [c.hash for c in self._changes]
        return self._changes

    def change_hashes(self) -> List[bytes]:
        if self._hashes is None:
            self.changes
        return list(self._hashes)

    @property
    def nbytes(self) -> int:
        total = len(self._change_blob or b"")
        for ent in self.cols.values():
            if ent is None:
                continue
            total += ent.nbytes
        code, off, ln, raw = self.values
        for a in (code, off, ln):
            if a is not None:
                total += a.nbytes
        total += len(raw)
        if self.obj_table is not None:
            total += self.obj_table.nbytes
        return total

    # -- hydration -----------------------------------------------------------

    def to_oplog(self, changes: Optional[List[StoredChange]] = None):
        """Rebuild a fully-populated OpLog from the image without touching
        change op columns: run tables expand via ``np.repeat``, dense columns
        copy straight in — zero re-encode."""
        from ..ops import compressed as C
        from ..ops.compressed import CompressedOpColumns
        from ..ops.extract import LazyValues
        from ..ops.oplog import ELEM_MISSING, OpLog
        from ..types import ActorId

        log = OpLog()
        log.changes = list(changes) if changes is not None else list(self.changes)
        log.actors = [ActorId(a) for a in self.actors]
        log.props = list(self.props)
        log.mark_names = list(self.mark_names)
        log.n = self.n

        install_comp = bool(self.flags & FLAG_COMPRESSED) and C.enabled()
        comp = CompressedOpColumns() if install_comp else None

        for name, _mode, _item in _specs():
            ent = self.cols.get(name)
            want = _COL_DTYPES[name]
            rows = self.q if name in ("pred_src", "pred_tgt", "pred_key") else self.n
            if ent is None:
                setattr(log, name, None)
                continue
            if isinstance(ent, _RunCol):
                arr = ent.decode()
                if arr.dtype != want:
                    arr = arr.astype(want)
                setattr(log, name, arr)
                if comp is not None:
                    # a fresh StrideRuns copy: extend_tail mutates run arrays
                    # in place, so the image's arrays must never be shared
                    sr = ent._runs()
                    sr.starts = sr.starts.copy()
                    sr.vals = sr.vals.copy()
                    sr.strides = sr.strides.copy()
                    comp.entries[name] = sr
                    comp.covered[name] = rows
            else:
                arr = ent
                if want == np.bool_:
                    arr = arr.astype(np.bool_)
                elif arr.dtype != want:
                    arr = arr.astype(want)
                setattr(log, name, arr)
                if comp is not None:
                    comp.entries[name] = C._DENSE
                    comp.covered[name] = rows
                    comp.demoted[name] = "ratio"

        code, off, ln, raw = self.values
        if code is not None:
            log.values = LazyValues(code.copy(), off.copy(), ln.copy(), raw)
        else:
            log.values = []
        if self.obj_table is not None:
            log.obj_table = self.obj_table.copy()
            log.n_objs = len(log.obj_table)
        if log.elem_ref is not None:
            log.n_miss_elem = int(np.count_nonzero(log.elem_ref == ELEM_MISSING))
        if log.pred_tgt is not None:
            log.n_miss_pred = int(np.count_nonzero(log.pred_tgt < 0))
        log._comp = comp
        return log

    @classmethod
    def from_log(cls, log) -> "RunImage":
        """An in-memory image snapshotting a (about to be released) log's
        columns — used to retain the run tables across hot→warm demotion so
        the next promotion is zero-encode even before any compact()."""
        from ..ops import compressed as C

        img = cls()
        img.flags = FLAG_COMPRESSED if C.enabled() else 0
        img.n = int(log.n)
        img.q = 0 if getattr(log, "pred_src", None) is None else len(log.pred_src)
        img.n_changes = len(log.changes)
        img.actors = [a.bytes if hasattr(a, "bytes") else bytes(a) for a in log.actors]
        img.props = list(log.props)
        img.mark_names = list(log.mark_names)
        comp = getattr(log, "_comp", None) if C.enabled() else None
        for name, _mode, _item in _specs():
            arr = getattr(log, name, None)
            if arr is None:
                img.cols[name] = None
                continue
            ent = comp.runs_for(name, len(arr)) if comp is not None else None
            if ent is not None:
                img.cols[name] = _RunCol(
                    (1 if ent.is_sorted else 0) | (2 if ent.stride_mode else 0),
                    np.dtype(ent.dtype),
                    ent.starts.copy(),
                    ent.vals.copy(),
                    ent.strides.copy(),
                    len(arr),
                )
            else:
                dense = arr.view(np.int8) if arr.dtype == np.bool_ else arr
                img.cols[name] = np.ascontiguousarray(dense).copy()
        img.values = _value_heap(log)
        ot = getattr(log, "obj_table", None)
        img.obj_table = None if ot is None else ot.copy()
        img.n_objs = 0 if img.obj_table is None else len(img.obj_table)
        img._changes = list(log.changes)
        img._hashes = [c.hash for c in img._changes]
        return img


def _load_changes(blob: Optional[bytes]) -> List[StoredChange]:
    """The cheap change loader: raw chunk bytes → StoredChange with lazy ops.

    Parses only the chunk envelope (validating the checksum, which also
    yields the change hash) and the header LEBs; op columns stay as sliced
    bytes inside a LazyOps, exactly like the commit path leaves them."""
    if not blob:
        return []
    out: List[StoredChange] = []
    pos = 0
    n_changes, pos = decode_uleb(blob, pos)
    for _ in range(n_changes):
        n_ops, pos = decode_uleb(blob, pos)
        raw_len, pos = decode_uleb(blob, pos)
        raw = bytes(blob[pos : pos + raw_len])
        if len(raw) != raw_len:
            raise RunSnapError("truncated change record")
        pos += raw_len
        chunk, _end = parse_chunk(raw, 0)
        if not chunk.checksum_valid:
            raise RunSnapError("change chunk checksum mismatch")
        data = chunk.data
        p = 0
        ndeps, p = decode_uleb(data, p)
        deps = [bytes(data[p + 32 * i : p + 32 * i + 32]) for i in range(ndeps)]
        p += 32 * ndeps
        alen, p = decode_uleb(data, p)
        actor = bytes(data[p : p + alen])
        p += alen
        seq, p = decode_uleb(data, p)
        start_op, p = decode_uleb(data, p)
        tsv, p = decode_sleb(data, p)
        mlen, p = decode_uleb(data, p)
        msg = bytes(data[p : p + mlen]).decode("utf-8") if mlen else None
        p += mlen
        nother, p = decode_uleb(data, p)
        others = []
        for _i in range(nother):
            olen, p = decode_uleb(data, p)
            others.append(bytes(data[p : p + olen]))
            p += olen
        metas, p = colio.parse_columns(data, p)
        col_data = colio.slice_column_data(data, metas, p)
        p += colio.total_column_len(metas)
        extra = bytes(data[p:])
        sc = StoredChange(
            dependencies=deps,
            actor=actor,
            other_actors=others,
            seq=seq,
            start_op=start_op,
            timestamp=tsv,
            message=msg,
            ops=LazyOps(dict(col_data), n_ops),
            extra_bytes=extra,
            hash=chunk.hash,
            raw_bytes=raw,
            op_col_data=dict(col_data),
        )
        out.append(sc)
    return out


def _walk_sections(data: bytes):
    """Yield (tag, payload, frame_start) for each CRC-valid section; raise
    RunSnapError at the first malformed/corrupt frame."""
    if not is_runsnap(data):
        raise RunSnapError("not an ARSN container")
    if data[4] != VERSION:
        raise RunSnapError(f"unsupported ARSN version {data[4]}")
    pos = 6
    end = len(data)
    while pos < end:
        start = pos
        if pos + 1 > end:
            raise RunSnapError("truncated section tag")
        tag = data[pos]
        try:
            plen, body = decode_uleb(data, pos + 1)
        except Exception as e:
            raise RunSnapError(f"bad section length: {e}") from None
        if body + plen + 4 > end:
            raise RunSnapError(
                f"section {SECTION_NAMES.get(tag, tag)} extends past EOF"
            )
        frame = data[start : body + plen]
        crc = int.from_bytes(data[body + plen : body + plen + 4], "little")
        if zlib.crc32(frame) != crc:
            raise RunSnapError(
                f"section {SECTION_NAMES.get(tag, tag)} CRC mismatch at offset {start}"
            )
        yield tag, bytes(data[body : body + plen]), start
        pos = body + plen + 4


def parse(data: bytes) -> RunImage:
    """Decode an ARSN container into a RunImage; RunSnapError on corruption."""
    img = RunImage()
    img.flags = data[5] if len(data) > 5 else 0
    seen = set()
    for tag, payload, _start in _walk_sections(data):
        seen.add(tag)
        try:
            _parse_section(img, tag, payload)
        except RunSnapError:
            raise
        except Exception as e:
            raise RunSnapError(
                f"section {SECTION_NAMES.get(tag, tag)} malformed: {e}"
            ) from None
    required = {SEC_META, SEC_ACTORS, SEC_HEADS, SEC_CHANGES, SEC_RUNS, SEC_VALUES, SEC_OBJTAB}
    missing = required - seen
    if missing:
        raise RunSnapError(
            "missing sections: " + ", ".join(sorted(SECTION_NAMES[t] for t in missing))
        )
    return img


def _parse_section(img: RunImage, tag: int, payload: bytes) -> None:
    p = 0
    if tag == SEC_META:
        img.n, p = decode_uleb(payload, p)
        img.q, p = decode_uleb(payload, p)
        img.n_changes, p = decode_uleb(payload, p)
        img.n_objs, p = decode_uleb(payload, p)
    elif tag == SEC_ACTORS:
        na, p = decode_uleb(payload, p)
        for _ in range(na):
            b, p = _get_bytes(payload, p)
            img.actors.append(b)
        np_, p = decode_uleb(payload, p)
        for _ in range(np_):
            b, p = _get_bytes(payload, p)
            img.props.append(b.decode("utf-8"))
        nm, p = decode_uleb(payload, p)
        for _ in range(nm):
            b, p = _get_bytes(payload, p)
            img.mark_names.append(b.decode("utf-8"))
    elif tag == SEC_HEADS:
        nh, p = decode_uleb(payload, p)
        for _ in range(nh):
            if p + 32 > len(payload):
                raise RunSnapError("truncated head hash")
            img.heads.append(bytes(payload[p : p + 32]))
            p += 32
    elif tag == SEC_CHANGES:
        img._change_blob = payload
    elif tag == SEC_RUNS:
        for name, _mode, _item in _specs():
            kind = payload[p]
            p += 1
            rows = img.q if name in ("pred_src", "pred_tgt", "pred_key") else img.n
            if kind == _K_ABSENT:
                img.cols[name] = None
            elif kind == _K_RUNS:
                rflags = payload[p]
                p += 1
                dlen = payload[p]
                p += 1
                ds = payload[p : p + dlen].decode("ascii")
                p += dlen
                nr, p = decode_uleb(payload, p)
                n_rows, p = decode_uleb(payload, p)
                if n_rows != rows:
                    raise RunSnapError(f"column {name}: row count mismatch")
                need = 3 * nr * 8
                if p + need > len(payload):
                    raise RunSnapError(f"column {name}: truncated run arrays")

                def _take(off):
                    return np.frombuffer(payload, np.int64, count=nr, offset=off).copy()

                starts = _take(p)
                vals = _take(p + nr * 8)
                strides = _take(p + 2 * nr * 8)
                p += need
                img.cols[name] = _RunCol(rflags, np.dtype(ds), starts, vals, strides, rows)
            elif kind == _K_DENSE:
                arr, p = _get_array(payload, p)
                if arr is None or len(arr) != rows:
                    raise RunSnapError(f"column {name}: dense row count mismatch")
                img.cols[name] = arr
            else:
                raise RunSnapError(f"column {name}: unknown kind {kind}")
    elif tag == SEC_VALUES:
        code, p = _get_array(payload, p)
        off, p = _get_array(payload, p)
        ln, p = _get_array(payload, p)
        raw, p = _get_bytes(payload, p)
        img.values = (code, off, ln, raw)
    elif tag == SEC_OBJTAB:
        img.obj_table, p = _get_array(payload, p)
    # unknown tags: CRC already validated, skip for forward compatibility


# -- verification ------------------------------------------------------------


def verify_container(data: bytes) -> dict:
    """Per-section CRC walk (plus a chunk-checksum walk inside SEC_CHANGES),
    for `journal-info --verify` / the scrubber.  Returns a plain dict the
    integrity layer wraps into its VerifyReport."""
    total = len(data)
    if not is_runsnap(data):
        return {
            "ok": False, "total_bytes": total, "valid_bytes": 0,
            "first_bad_offset": 0, "units": 0, "reason": "not an ARSN container",
        }
    units = 0
    valid = 6
    try:
        for tag, payload, start in _walk_sections(data):
            if tag == SEC_CHANGES:
                _verify_changes(payload)
            units += 1
            valid = start + 1 + len(uleb_bytes(len(payload))) + len(payload) + 4
    except RunSnapError as e:
        return {
            "ok": False, "total_bytes": total, "valid_bytes": valid,
            "first_bad_offset": valid, "units": units, "reason": str(e),
        }
    # a structural decode catches in-payload corruption CRCs can't (CRC
    # guards bit-rot; this guards writer bugs / truncated inner arrays)
    try:
        parse(data)
    except RunSnapError as e:
        return {
            "ok": False, "total_bytes": total, "valid_bytes": valid,
            "first_bad_offset": 6, "units": units, "reason": str(e),
        }
    return {
        "ok": True, "total_bytes": total, "valid_bytes": total,
        "first_bad_offset": None, "units": units, "reason": None,
    }


def _verify_changes(payload: bytes) -> None:
    pos = 0
    n_changes, pos = decode_uleb(payload, pos)
    for i in range(n_changes):
        _n_ops, pos = decode_uleb(payload, pos)
        raw_len, pos = decode_uleb(payload, pos)
        raw = payload[pos : pos + raw_len]
        if len(raw) != raw_len:
            raise RunSnapError(f"change {i}: truncated record")
        chunk, _ = parse_chunk(raw, 0)
        if not chunk.checksum_valid:
            raise RunSnapError(f"change {i}: chunk checksum mismatch")
        pos += raw_len
