"""Scalar value column codec: a ValueMetadata RLE column + raw value column.

Byte-compatible with the reference (reference:
rust/automerge/src/columnar/column_range/value.rs). The metadata value is
``(byte_length << 4) | type_code`` with type codes 0=null, 1=false, 2=true,
3=uleb uint, 4=sleb int, 5=f64 LE, 6=utf8 string, 7=bytes, 8=counter (sleb of
the start value), 9=timestamp (sleb); any other code is an unknown type whose
raw bytes roundtrip unchanged.
"""

from __future__ import annotations

import struct

from ..types import ScalarValue
from ..utils.codecs import RleEncoder, rle_decode
from ..utils.leb128 import (
    decode_sleb,
    decode_uleb,
    lebsize,
    sleb_bytes,
    uleb_bytes,
    ulebsize,
)


def value_meta(v: ScalarValue) -> int:
    tag = v.tag
    if tag == "null":
        return 0
    if tag == "bool":
        return 2 if v.value else 1
    if tag == "uint":
        return (ulebsize(v.value) << 4) | 3
    if tag == "int":
        return (lebsize(v.value) << 4) | 4
    if tag == "f64":
        return (8 << 4) | 5
    if tag == "str":
        return (len(v.value.encode("utf-8")) << 4) | 6
    if tag == "bytes":
        return (len(v.value) << 4) | 7
    if tag == "counter":
        return (lebsize(v.value) << 4) | 8
    if tag == "timestamp":
        return (lebsize(v.value) << 4) | 9
    if tag == "unknown":
        type_code, raw = v.value
        return (len(raw) << 4) | type_code
    raise ValueError(f"unknown scalar tag {tag!r}")


def encode_raw_value(v: ScalarValue, out: bytearray) -> None:
    tag = v.tag
    if tag in ("null", "bool"):
        return
    if tag == "uint":
        out += uleb_bytes(v.value)
    elif tag in ("int", "counter", "timestamp"):
        out += sleb_bytes(v.value)
    elif tag == "f64":
        out += struct.pack("<d", v.value)
    elif tag == "str":
        out += v.value.encode("utf-8")
    elif tag == "bytes":
        out += v.value
    elif tag == "unknown":
        out += v.value[1]
    else:
        raise ValueError(f"unknown scalar tag {tag!r}")


class ValueEncoder:
    """Builds the (meta, raw) column pair for a sequence of scalars."""

    def __init__(self):
        self._meta = RleEncoder("uint")
        self._raw = bytearray()

    def append(self, v: ScalarValue) -> None:
        self._meta.append_value(value_meta(v))
        encode_raw_value(v, self._raw)

    def finish(self) -> tuple[bytes, bytes]:
        return self._meta.finish(), bytes(self._raw)


def decode_values(meta_buf: bytes, raw_buf: bytes, count: int) -> list[ScalarValue]:
    metas = rle_decode(meta_buf, "uint", count)
    if len(metas) < count:
        raise ValueError("value metadata column shorter than row count")
    out: list[ScalarValue] = []
    pos = 0
    for m in metas:
        if m is None:
            raise ValueError("value metadata column contained a null")
        type_code = m & 0x0F
        length = m >> 4
        raw = raw_buf[pos : pos + length]
        if len(raw) != length:
            raise ValueError("value column: truncated raw data")
        pos += length
        out.append(_decode_one(type_code, raw))
    return out


def _decode_one(type_code: int, raw: bytes) -> ScalarValue:
    if type_code == 0:
        _expect_empty(raw)
        return ScalarValue("null")
    if type_code == 1:
        _expect_empty(raw)
        return ScalarValue("bool", False)
    if type_code == 2:
        _expect_empty(raw)
        return ScalarValue("bool", True)
    if type_code == 3:
        v, end = decode_uleb(raw, 0)
        _expect_consumed(raw, end)
        return ScalarValue("uint", v)
    if type_code == 4:
        v, end = decode_sleb(raw, 0)
        _expect_consumed(raw, end)
        return ScalarValue("int", v)
    if type_code == 5:
        if len(raw) != 8:
            raise ValueError(f"float value should have length 8, had {len(raw)}")
        return ScalarValue("f64", struct.unpack("<d", raw)[0])
    if type_code == 6:
        return ScalarValue("str", raw.decode("utf-8"))
    if type_code == 7:
        return ScalarValue("bytes", bytes(raw))
    if type_code == 8:
        v, end = decode_sleb(raw, 0)
        _expect_consumed(raw, end)
        return ScalarValue("counter", v)
    if type_code == 9:
        v, end = decode_sleb(raw, 0)
        _expect_consumed(raw, end)
        return ScalarValue("timestamp", v)
    return ScalarValue("unknown", (type_code, bytes(raw)))


def _expect_empty(raw: bytes) -> None:
    if raw:
        raise ValueError("zero-length value type had raw bytes")


def _expect_consumed(raw: bytes, end: int) -> None:
    if end != len(raw):
        raise ValueError("value had extra bytes")
