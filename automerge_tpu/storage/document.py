"""Document chunk encode/decode.

Byte-compatible with the reference (reference:
rust/automerge/src/storage/document.rs, document/doc_op_columns.rs,
document/doc_change_columns.rs). Chunk body layout:

    ULEB num_actors, each ULEB length-prefixed actor id (sorted lexicographic)
    ULEB num_heads, 32-byte head hashes (sorted)
    change column metadata
    ops column metadata
    change column data
    ops column data
    per-head ULEB index of the head change in the change list

Actor indices are document-global indices into the sorted actor table — which
makes (counter, actor_index) order identical to Lamport order, the property
the device merge kernel relies on. Ops are sorted by object id, then key,
then Lamport; delete ops are not stored as rows, they exist only as entries
in their predecessors' ``succ`` lists.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..types import Key, OpId, ScalarValue
from ..utils.codecs import (  # noqa: F401
    _bool_runs_col,
    _str_runs_col,
    BooleanEncoder,
    DeltaEncoder,
    MaybeBooleanEncoder,
    RleEncoder,
    boolean_decode,
    delta_decode,
    rle_decode,
)
from ..utils.leb128 import decode_uleb, encode_uleb
from . import columns as C
from .chunk import (
    CHUNK_DOCUMENT,
    DEFLATE_MIN_SIZE,
    DroppedRegion,
    RawChunk,
    parse_chunk,
    scan_chunks,
    write_chunk,
)
from .change import HEAD_STORED, ROOT_STORED
from .values import ValueEncoder, decode_values

# Normalized doc-op column specs
OP_OBJ_ACTOR = C.spec(0, C.TYPE_ACTOR)  # 1
OP_OBJ_CTR = C.spec(0, C.TYPE_INTEGER)  # 2
OP_KEY_ACTOR = C.spec(1, C.TYPE_ACTOR)  # 17
OP_KEY_CTR = C.spec(1, C.TYPE_DELTA)  # 19
OP_KEY_STR = C.spec(1, C.TYPE_STRING)  # 21
OP_ID_ACTOR = C.spec(2, C.TYPE_ACTOR)  # 33
OP_ID_CTR = C.spec(2, C.TYPE_DELTA)  # 35
OP_INSERT = C.spec(3, C.TYPE_BOOLEAN)  # 52
OP_ACTION = C.spec(4, C.TYPE_INTEGER)  # 66
OP_VAL_META = C.spec(5, C.TYPE_VALUE_META)  # 86
OP_VAL_RAW = C.spec(5, C.TYPE_VALUE)  # 87
OP_SUCC_GROUP = C.spec(8, C.TYPE_GROUP)  # 128
OP_SUCC_ACTOR = C.spec(8, C.TYPE_ACTOR)  # 129
OP_SUCC_CTR = C.spec(8, C.TYPE_DELTA)  # 131
OP_EXPAND = C.spec(9, C.TYPE_BOOLEAN)  # 148
OP_MARK_NAME = C.spec(10, C.TYPE_STRING)  # 165

# Normalized doc-change column specs
CH_ACTOR = C.spec(0, C.TYPE_ACTOR)  # 1
CH_SEQ = C.spec(0, C.TYPE_DELTA)  # 3
CH_MAX_OP = C.spec(1, C.TYPE_DELTA)  # 19
CH_TIME = C.spec(2, C.TYPE_DELTA)  # 35
CH_MESSAGE = C.spec(3, C.TYPE_STRING)  # 53
CH_DEPS_GROUP = C.spec(4, C.TYPE_GROUP)  # 64
CH_DEPS_IDX = C.spec(4, C.TYPE_DELTA)  # 67
CH_EXTRA_META = C.spec(5, C.TYPE_VALUE_META)  # 86
CH_EXTRA_RAW = C.spec(5, C.TYPE_VALUE)  # 87


@dataclass
class DocOp:
    """One op row in the document format (actor indices are doc-global)."""

    id: OpId
    obj: OpId  # ROOT_STORED for the root object
    key: Key
    insert: bool
    action: int
    value: ScalarValue
    succ: List[OpId] = field(default_factory=list)
    expand: bool = False
    mark_name: Optional[str] = None


@dataclass
class DocChangeMeta:
    """Change metadata row in the document format."""

    actor: int  # index into the document actor table
    seq: int
    max_op: int
    timestamp: int
    message: Optional[str]
    deps: List[int]  # indices into the change list
    extra: bytes = b""


class ParsedDocument:
    """A parsed document chunk. ``ops`` decodes lazily from the retained
    column bytes — the fast load path (doc_op_arrays) reads
    ``op_col_data`` directly and never materializes DocOp objects."""

    __slots__ = (
        "actors", "heads", "changes", "head_indices", "checksum_valid",
        "op_col_data", "op_arrays", "_ops",
    )

    def __init__(
        self, actors, heads, changes, head_indices, checksum_valid,
        op_col_data=None, ops=None,
    ):
        self.actors = actors
        self.heads = heads
        self.changes = changes
        self.head_indices = head_indices
        self.checksum_valid = checksum_valid
        self.op_col_data = op_col_data
        self.op_arrays = None  # retained native column arrays (fast load)
        self._ops = ops

    @property
    def ops(self) -> List[DocOp]:
        if self._ops is None:
            ops = decode_doc_ops(self.op_col_data or {})
            for i, op in enumerate(ops):
                _check_doc_actor_bounds(op, i, len(self.actors))
            self._ops = ops
        return self._ops


def encode_doc_ops(ops: List[DocOp]) -> List[Tuple[int, bytes]]:
    obj_actor = RleEncoder("uint")
    obj_ctr = RleEncoder("uint")
    key_actor = RleEncoder("uint")
    key_ctr = DeltaEncoder()
    key_str = RleEncoder("str")
    id_actor = RleEncoder("uint")
    id_ctr = DeltaEncoder()
    insert = BooleanEncoder()
    action = RleEncoder("uint")
    val = ValueEncoder()
    succ_num = RleEncoder("uint")
    succ_actor = RleEncoder("uint")
    succ_ctr = DeltaEncoder()
    expand = MaybeBooleanEncoder()
    mark_name = RleEncoder("str")

    for op in ops:
        # Counter 0 identifies root/HEAD regardless of sentinel actor value
        # (accepts both types.ROOT/HEAD (0,0) and storage (0,-1)).
        if op.obj[0] == 0:
            obj_actor.append_null()
            obj_ctr.append_null()
        else:
            obj_actor.append_value(op.obj[1])
            obj_ctr.append_value(op.obj[0])
        if op.key.prop is not None:
            key_actor.append_null()
            key_ctr.append(None)
            key_str.append_value(op.key.prop)
        elif op.key.elem[0] == 0:
            key_actor.append_null()
            key_ctr.append(0)
            key_str.append_null()
        else:
            key_actor.append_value(op.key.elem[1])
            key_ctr.append(op.key.elem[0])
            key_str.append_null()
        id_actor.append_value(op.id[1])
        id_ctr.append(op.id[0])
        insert.append(op.insert)
        action.append_value(op.action)
        val.append(op.value)
        succ_num.append_value(len(op.succ))
        for s in op.succ:
            succ_actor.append_value(s[1])
            succ_ctr.append(s[0])
        expand.append(op.expand)
        if op.mark_name is None:
            mark_name.append_null()
        else:
            mark_name.append_value(op.mark_name)

    val_meta, val_raw = val.finish()
    return [
        (OP_OBJ_ACTOR, obj_actor.finish()),
        (OP_OBJ_CTR, obj_ctr.finish()),
        (OP_KEY_ACTOR, key_actor.finish()),
        (OP_KEY_CTR, key_ctr.finish()),
        (OP_KEY_STR, key_str.finish()),
        (OP_ID_ACTOR, id_actor.finish()),
        (OP_ID_CTR, id_ctr.finish()),
        (OP_INSERT, insert.finish()),
        (OP_ACTION, action.finish()),
        (OP_VAL_META, val_meta),
        (OP_VAL_RAW, val_raw),
        (OP_SUCC_GROUP, succ_num.finish()),
        (OP_SUCC_ACTOR, succ_actor.finish()),
        (OP_SUCC_CTR, succ_ctr.finish()),
        (OP_EXPAND, expand.finish()),
        (OP_MARK_NAME, mark_name.finish()),
    ]


def encode_doc_ops_arrays(a) -> List[Tuple[int, bytes]]:
    """Array-native doc-op column encode: byte-identical to
    ``encode_doc_ops`` over the materialized DocOp list, built from numpy
    columns (the fast save path, core/document._doc_op_cols_fast).

    ``a`` fields, all length n in document order with save-time actor
    indices: obj_ctr/obj_actor/obj_mask, key_str_ids (+key_str_table),
    key_ctr/key_ctr_mask/key_actor/key_actor_mask, id_ctr/id_actor,
    insert (u8), action, val_meta, val_raw (bytes), succ_num,
    succ_ctr/succ_actor (flat), expand (u8), mark_ids (+mark_table).
    """
    import numpy as np

    from .. import native

    n = len(a["action"])
    ones = np.ones(n, np.uint8)
    ones_s = np.ones(len(a["succ_ctr"]), np.uint8)
    return [
        (OP_OBJ_ACTOR, native.rle_encode_array(a["obj_actor"], a["obj_mask"], False)),
        (OP_OBJ_CTR, native.rle_encode_array(a["obj_ctr"], a["obj_mask"], False)),
        (OP_KEY_ACTOR, native.rle_encode_array(a["key_actor"], a["key_actor_mask"], False)),
        (OP_KEY_CTR, native.delta_encode_array(a["key_ctr"], a["key_ctr_mask"])),
        (OP_KEY_STR, _str_runs_col(a["key_str_ids"], a["key_str_table"], RleEncoder("str"))),
        (OP_ID_ACTOR, native.rle_encode_array(a["id_actor"], ones, False)),
        (OP_ID_CTR, native.delta_encode_array(a["id_ctr"], ones)),
        (OP_INSERT, native.bool_encode_array(a["insert"])),
        (OP_ACTION, native.rle_encode_array(a["action"], ones, False)),
        (OP_VAL_META, native.rle_encode_array(a["val_meta"], ones, False)),
        (OP_VAL_RAW, a["val_raw"]),
        (OP_SUCC_GROUP, native.rle_encode_array(a["succ_num"], ones, False)),
        (OP_SUCC_ACTOR, native.rle_encode_array(a["succ_actor"], ones_s, False)),
        (OP_SUCC_CTR, native.delta_encode_array(a["succ_ctr"], ones_s)),
        (OP_EXPAND, _bool_runs_col(a["expand"], MaybeBooleanEncoder())),
        (OP_MARK_NAME, _str_runs_col(a["mark_ids"], a["mark_table"], RleEncoder("str"))),
    ]


def decode_doc_ops(col_data: dict[int, bytes]) -> List[DocOp]:
    def col(s):
        return col_data.get(s, b"")

    actions = rle_decode(col(OP_ACTION), "uint")
    id_ctr = delta_decode(col(OP_ID_CTR))
    key_str = rle_decode(col(OP_KEY_STR), "str")
    key_ctr = delta_decode(col(OP_KEY_CTR))
    n = max(len(actions), len(id_ctr), len(key_str), len(key_ctr))
    actions = _pad(actions, n)
    insert = boolean_decode(col(OP_INSERT), n)
    obj_actor = _pad(rle_decode(col(OP_OBJ_ACTOR), "uint"), n)
    obj_ctr = _pad(rle_decode(col(OP_OBJ_CTR), "uint"), n)
    key_actor = _pad(rle_decode(col(OP_KEY_ACTOR), "uint"), n)
    key_ctr = _pad(key_ctr, n)
    key_str = _pad(key_str, n)
    id_actor = _pad(rle_decode(col(OP_ID_ACTOR), "uint"), n)
    id_ctr = _pad(id_ctr, n)
    values = decode_values(col(OP_VAL_META), col(OP_VAL_RAW), n)
    succ_num = _pad(rle_decode(col(OP_SUCC_GROUP), "uint"), n)
    total_succ = sum(s or 0 for s in succ_num)
    succ_actor = rle_decode(col(OP_SUCC_ACTOR), "uint", total_succ)
    succ_ctr = delta_decode(col(OP_SUCC_CTR), total_succ)
    expand = boolean_decode(col(OP_EXPAND), n)
    mark_name = _pad(rle_decode(col(OP_MARK_NAME), "str"), n)

    ops: List[DocOp] = []
    si = 0
    for i in range(n):
        if actions[i] is None:
            raise ValueError(f"doc op {i}: missing action")
        if id_ctr[i] is None or id_actor[i] is None:
            raise ValueError(f"doc op {i}: missing op id")
        if obj_ctr[i] is None and obj_actor[i] is None:
            obj = ROOT_STORED
        elif obj_ctr[i] is None or obj_actor[i] is None:
            raise ValueError(f"doc op {i}: half-null object id")
        else:
            obj = (obj_ctr[i], obj_actor[i])
        if key_str[i] is not None:
            key = Key.map(key_str[i])
        elif key_ctr[i] == 0 and key_actor[i] is None:
            key = Key.seq(HEAD_STORED)
        elif key_ctr[i] is not None and key_actor[i] is not None:
            key = Key.seq((key_ctr[i], key_actor[i]))
        else:
            raise ValueError(f"doc op {i}: neither map key nor elem id present")
        ns = succ_num[i] or 0
        succ = []
        for _ in range(ns):
            if si >= len(succ_ctr) or succ_ctr[si] is None or succ_actor[si] is None:
                raise ValueError(f"doc op {i}: truncated succ column")
            succ.append((succ_ctr[si], succ_actor[si]))
            si += 1
        ops.append(
            DocOp(
                id=(id_ctr[i], id_actor[i]),
                obj=obj,
                key=key,
                insert=insert[i],
                action=actions[i],
                value=values[i],
                succ=succ,
                expand=expand[i],
                mark_name=mark_name[i],
            )
        )
    return ops


def encode_doc_changes(changes: List[DocChangeMeta]) -> List[Tuple[int, bytes]]:
    actor = RleEncoder("uint")
    seq = DeltaEncoder()
    max_op = DeltaEncoder()
    time = DeltaEncoder()
    message = RleEncoder("str")
    deps_num = RleEncoder("uint")
    deps_idx = DeltaEncoder()
    extra = ValueEncoder()
    for ch in changes:
        actor.append_value(ch.actor)
        seq.append(ch.seq)
        max_op.append(ch.max_op)
        time.append(ch.timestamp)
        message.append(ch.message)
        deps_num.append_value(len(ch.deps))
        for d in ch.deps:
            deps_idx.append(d)
        extra.append(ScalarValue("bytes", ch.extra))
    extra_meta, extra_raw = extra.finish()
    return [
        (CH_ACTOR, actor.finish()),
        (CH_SEQ, seq.finish()),
        (CH_MAX_OP, max_op.finish()),
        (CH_TIME, time.finish()),
        (CH_MESSAGE, message.finish()),
        (CH_DEPS_GROUP, deps_num.finish()),
        (CH_DEPS_IDX, deps_idx.finish()),
        (CH_EXTRA_META, extra_meta),
        (CH_EXTRA_RAW, extra_raw),
    ]


def decode_doc_changes(col_data: dict[int, bytes]) -> List[DocChangeMeta]:
    def col(s):
        return col_data.get(s, b"")

    actors = rle_decode(col(CH_ACTOR), "uint")
    n = len(actors)
    seq = _pad(delta_decode(col(CH_SEQ)), n)
    max_op = _pad(delta_decode(col(CH_MAX_OP)), n)
    time = _pad(delta_decode(col(CH_TIME)), n)
    message = _pad(rle_decode(col(CH_MESSAGE), "str"), n)
    deps_num = _pad(rle_decode(col(CH_DEPS_GROUP), "uint"), n)
    total_deps = sum(d or 0 for d in deps_num)
    deps_idx = delta_decode(col(CH_DEPS_IDX), total_deps)
    extras = (
        decode_values(col(CH_EXTRA_META), col(CH_EXTRA_RAW), n)
        if col(CH_EXTRA_META)
        else [ScalarValue("bytes", b"")] * n
    )

    out: List[DocChangeMeta] = []
    di = 0
    for i in range(n):
        if actors[i] is None:
            raise ValueError(f"doc change {i}: null actor")
        nd = deps_num[i] or 0
        deps = []
        for _ in range(nd):
            if di >= len(deps_idx) or deps_idx[di] is None:
                raise ValueError(f"doc change {i}: truncated deps")
            if deps_idx[di] < 0:
                raise ValueError(f"doc change {i}: negative dep index")
            deps.append(deps_idx[di])
            di += 1
        extra = extras[i].value if extras[i].tag == "bytes" else b""
        out.append(
            DocChangeMeta(
                actor=actors[i],
                seq=seq[i] if seq[i] is not None else 0,
                max_op=max_op[i] if max_op[i] is not None else 0,
                timestamp=time[i] if time[i] is not None else 0,
                message=message[i],
                deps=deps,
                extra=extra,
            )
        )
    return out


def _pad(lst: list, n: int) -> list:
    if len(lst) < n:
        lst.extend([None] * (n - len(lst)))
    return lst


def build_document(
    actors: List[bytes],
    heads_with_indices: List[Tuple[bytes, int]],
    ops: List[DocOp],
    changes: List[DocChangeMeta],
    deflate: bool = True,
    op_cols: Optional[List[Tuple[int, bytes]]] = None,
) -> bytes:
    """Encode a document chunk. ``actors`` must already be sorted."""
    if sorted(actors) != list(actors):
        raise ValueError("document actor table must be sorted")
    data = bytearray()
    encode_uleb(len(actors), data)
    for a in actors:
        encode_uleb(len(a), data)
        data += a
    encode_uleb(len(heads_with_indices), data)
    for h, _ in heads_with_indices:
        if len(h) != 32:
            raise ValueError("head hash must be 32 bytes")
        data += h

    change_cols = encode_doc_changes(changes)
    if op_cols is None:
        op_cols = encode_doc_ops(ops)
    threshold = DEFLATE_MIN_SIZE if deflate else None
    # Metadata for both column groups precedes both data blocks, so encode
    # them to scratch buffers first.
    change_block = bytearray()
    C.write_columns(change_cols, change_block, threshold)
    op_block = bytearray()
    C.write_columns(op_cols, op_block, threshold)
    data += change_block_meta_and_data(change_block, op_block)
    for _, idx in heads_with_indices:
        encode_uleb(idx, data)
    return write_chunk(CHUNK_DOCUMENT, bytes(data))


def change_block_meta_and_data(change_block: bytearray, op_block: bytearray) -> bytes:
    """Interleave [change meta][op meta][change data][op data].

    ``write_columns`` produces meta+data contiguously, so split each block.
    """
    cm, cd = _split_meta(change_block)
    om, od = _split_meta(op_block)
    return bytes(cm + om + cd + od)


def _split_meta(block: bytearray) -> tuple[bytes, bytes]:
    metas, pos = C.parse_columns(block, 0)
    return bytes(block[:pos]), bytes(block[pos:])


def parse_document(buf: bytes, pos: int = 0) -> tuple[ParsedDocument, int]:
    chunk, end = parse_chunk(buf, pos)
    if chunk.chunk_type != CHUNK_DOCUMENT:
        raise ValueError(f"expected document chunk, got type {chunk.chunk_type}")
    if not chunk.checksum_valid:
        raise ValueError("document chunk checksum mismatch")
    return (_parse_document_body(chunk), end)


def _parse_document_body(chunk: "RawChunk") -> ParsedDocument:
    data = chunk.data
    p = 0
    nactors, p = decode_uleb(data, p)
    actors = []
    for _ in range(nactors):
        alen, p = decode_uleb(data, p)
        if p + alen > len(data):
            raise ValueError("truncated actor table")
        actors.append(bytes(data[p : p + alen]))
        p += alen
    nheads, p = decode_uleb(data, p)
    heads = []
    for _ in range(nheads):
        if p + 32 > len(data):
            raise ValueError("truncated heads")
        heads.append(bytes(data[p : p + 32]))
        p += 32
    change_metas, p = C.parse_columns(data, p)
    op_metas, p = C.parse_columns(data, p)
    change_data = C.slice_column_data(data, change_metas, p)
    p += C.total_column_len(change_metas)
    op_data = C.slice_column_data(data, op_metas, p)
    p += C.total_column_len(op_metas)
    head_indices = []
    if p < len(data):
        for _ in range(nheads):
            idx, p = decode_uleb(data, p)
            head_indices.append(idx)

    changes = decode_doc_changes(change_data)
    for i, ch in enumerate(changes):
        if ch.actor >= nactors:
            raise ValueError(f"doc change {i} references missing actor {ch.actor}")
    parsed = ParsedDocument(
        actors=actors,
        heads=heads,
        changes=changes,
        head_indices=head_indices,
        checksum_valid=chunk.checksum_valid,
        op_col_data=dict(op_data),
    )
    # op-column validation: native array decode when available (arrays are
    # retained for the fast reconstruction); per-op python decode otherwise.
    # Either way malformed op columns are rejected HERE, as before.
    from .. import native as _native

    validated = False
    if _native.available():
        from ..ops.extract import ExtractError, doc_op_arrays, validate_doc_arrays

        try:
            arrs = doc_op_arrays(parsed.op_col_data)
            validate_doc_arrays(arrs, nactors)
            parsed.op_arrays = arrs
            validated = True
        except ExtractError:
            pass  # irregular shape: the python decoder is the authority
    if not validated:
        parsed.ops  # noqa: B018 — decode + per-op bounds checks, may raise
    return parsed


# ---------------------------------------------------------------------------
# salvage loading: recover the valid chunks from a damaged save


@dataclass
class DroppedChunk:
    """One unrecoverable byte span in a damaged save."""

    offset: int
    end: int
    reason: str
    checksum: bytes  # stored 4-byte checksum (the original hash prefix), or b""
    computed_hash: bytes  # hash of the bytes as found (b"" if unparseable)


@dataclass
class SalvageReport:
    """What a salvage load kept and what it had to drop."""

    scanned_bytes: int = 0
    applied_chunks: int = 0
    dropped: List[DroppedChunk] = field(default_factory=list)

    @property
    def dropped_checksums(self) -> List[bytes]:
        """The stored checksums of dropped chunks — each is the first 4
        bytes of the original (pre-corruption) chunk hash, so callers can
        name exactly which changes were lost."""
        return [d.checksum for d in self.dropped if d.checksum]

    def summary(self) -> str:
        return (
            f"salvaged {self.applied_chunks} chunk(s), "
            f"dropped {len(self.dropped)} span(s) over {self.scanned_bytes} bytes"
        )


def salvage_scan(buf: bytes) -> tuple[List[RawChunk], SalvageReport]:
    """Split a (possibly damaged) save into verifiable chunks + a report.

    Checksum-invalid and unparseable spans become ``DroppedChunk`` records;
    the scan resynchronises on the next ``MAGIC_BYTES`` occurrence (see
    ``scan_chunks``). ``applied_chunks`` is left 0 — the loader fills it in
    after it knows how many chunks actually applied.
    """
    report = SalvageReport(scanned_bytes=len(buf))
    chunks: List[RawChunk] = []
    for item in scan_chunks(buf):
        if isinstance(item, DroppedRegion):
            report.dropped.append(
                DroppedChunk(
                    offset=item.offset,
                    end=item.end,
                    reason=item.reason,
                    checksum=item.checksum,
                    computed_hash=item.hash,
                )
            )
        else:
            chunks.append(item)
    return chunks, report


def parse_document_chunk(chunk: RawChunk) -> ParsedDocument:
    """Parse an already-framed-and-verified document chunk (the body of
    ``parse_document``, reusable from the salvage path)."""
    return _parse_document_body(chunk)


def _check_doc_actor_bounds(op: DocOp, i: int, n_actors: int) -> None:
    refs = [op.id[1]]
    if op.obj != ROOT_STORED:
        refs.append(op.obj[1])
    if op.key.elem is not None and op.key.elem != HEAD_STORED:
        refs.append(op.key.elem[1])
    refs.extend(s[1] for s in op.succ)
    for a in refs:
        if a < 0 or a >= n_actors:
            raise ValueError(f"doc op {i} references missing actor index {a}")
